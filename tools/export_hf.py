#!/usr/bin/env python
"""Native checkpoint -> HF LlamaForCausalLM exporter (the converter's inverse).

The reference ships only HF -> DeepSpeed (convert2ckpt.py); going back
required hand-written scripts. Here trained weights round-trip into the HF
ecosystem directly:

    python tools/export_hf.py --checkpoint_dir /ckpts/run1 --output_dir /hf/out
"""

from __future__ import annotations

import argparse
import os
import sys

# invocable as a script from anywhere: the package lives next to tools/
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def export(checkpoint_dir: str, output_dir: str, step: int | None = None) -> None:
    import torch
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import load_module_checkpoint
    from llama_pipeline_parallel_tpu.models.llama.hf import hf_state_dict_from_params

    params, cfg, _, step = load_module_checkpoint(checkpoint_dir, step)
    sd = {k: torch.from_numpy(v) for k, v in
          hf_state_dict_from_params(params, cfg).items()}

    hf_cfg = HFLlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_hidden_layers,
        num_attention_heads=cfg.num_attention_heads,
        num_key_value_heads=cfg.kv_heads,
        max_position_embeddings=cfg.max_position_embeddings,
        rms_norm_eps=cfg.rms_norm_eps, rope_theta=cfg.rope_theta,
        tie_word_embeddings=cfg.tie_word_embeddings)
    model = LlamaForCausalLM(hf_cfg)
    missing, unexpected = model.load_state_dict(sd, strict=False)
    if [m for m in missing if "rotary" not in m] or unexpected:
        raise RuntimeError(f"state mismatch: missing={missing} unexpected={unexpected}")
    model.save_pretrained(output_dir, safe_serialization=True)
    # carry tokenizer files along (convert_hf.py places them next to the
    # native checkpoint precisely so the round trip is self-contained)
    import shutil

    for name in os.listdir(checkpoint_dir):
        if "token" in name or name in ("special_tokens_map.json", "vocab.json",
                                       "merges.txt", "spiece.model"):
            shutil.copy2(os.path.join(checkpoint_dir, name),
                         os.path.join(output_dir, name))
    print(f"exported checkpoint-{step} to {output_dir}")


def main(argv: list[str] | None = None) -> None:
    # standalone CLI: conversion is host-side work — never wait on accelerators
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint_dir", required=True)
    p.add_argument("--output_dir", required=True)
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: latest)")
    args = p.parse_args(argv)
    export(args.checkpoint_dir, args.output_dir, args.step)


if __name__ == "__main__":
    sys.exit(main())

"""Summarize a jax.profiler trace: top ops by accumulated duration.

The per-op breakdown the MFU hunt needs (SURVEY.md §5.1) without opening
TensorBoard/Perfetto: point it at a `BENCH_PROFILE=<dir>` output or a
trainer `profile_steps` window (`<output_dir>/profile`) and it aggregates
the Chrome-trace complete events from the newest capture.

Usage:
  python tools/trace_summary.py <trace_dir> [--top 15] [--track SUBSTR]

`--track` filters to processes whose name contains SUBSTR (e.g. "TPU" to
see only device tracks; default keeps every track and prints each track's
total so device vs host time is visible side by side).
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os


def load_latest_trace(trace_dir: str) -> tuple[str, dict]:
    """Newest capture under `trace_dir`, gzipped or plain (some exporters
    and hand-saved Perfetto sessions write uncompressed *.trace.json).
    A missing capture raises FileNotFoundError; an unreadable or torn one
    (killed mid-capture) raises SystemExit with a readable message — the
    CLI prints it instead of a traceback."""
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(trace_dir, "**", "*.trace.json"),
                    recursive=True),
        key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json.gz (or *.trace.json) under {trace_dir} (is "
            f"this a jax.profiler output dir? expected "
            f"plugins/profile/<ts>/*.trace.json.gz)")
    path = paths[-1]
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rt") as f:
            trace = json.load(f)
    except (OSError, ValueError) as e:
        raise SystemExit(
            f"could not parse trace capture {path}: {e}\n(partial capture "
            f"from an interrupted profile window? delete it and re-capture)")
    if not isinstance(trace, dict):
        raise SystemExit(f"trace capture {path} is not a Chrome-trace JSON "
                         f"object (got {type(trace).__name__})")
    return path, trace


def summarize(trace: dict, track_filter: str | None = None):
    """-> (per-track total us, per-track op->us Counter, per-track op->count
    Counter)."""
    proc_names: dict = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc_names[e.get("pid")] = e.get("args", {}).get(
                "name", str(e.get("pid")))

    track_total: collections.Counter = collections.Counter()
    op_dur: dict = collections.defaultdict(collections.Counter)
    op_count: dict = collections.defaultdict(collections.Counter)
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        track = proc_names.get(e.get("pid"), str(e.get("pid")))
        if track_filter and track_filter.lower() not in track.lower():
            continue
        dur = float(e.get("dur", 0.0))
        name = e.get("name", "?")
        track_total[track] += dur
        op_dur[track][name] += dur
        op_count[track][name] += 1
    return track_total, op_dur, op_count


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("trace_dir")
    p.add_argument("--top", type=int, default=15)
    p.add_argument("--track", default=None,
                   help="only tracks whose process name contains this")
    args = p.parse_args(argv)

    try:
        path, trace = load_latest_trace(args.trace_dir)
    except FileNotFoundError as e:
        # empty/wrong dir: a readable verdict, not a traceback
        raise SystemExit(str(e))
    print(f"trace: {path}")
    track_total, op_dur, op_count = summarize(trace, args.track)
    if not track_total:
        raise SystemExit("no complete events matched "
                         f"(--track {args.track!r}); try without --track")
    for track, total in sorted(track_total.items(), key=lambda kv: -kv[1]):
        print(f"\n== {track}: {total / 1e3:.2f} ms total ==")
        for name, dur in op_dur[track].most_common(args.top):
            pct = 100 * dur / total if total else 0.0
            print(f"  {dur / 1e3:10.2f} ms  {pct:5.1f}%  "
                  f"x{op_count[track][name]:<5d} {name}")


if __name__ == "__main__":
    main()

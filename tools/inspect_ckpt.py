#!/usr/bin/env python
"""Inspect a checkpoint directory: steps, completeness, manifest, layout.

Usage: python tools/inspect_ckpt.py <output_dir> [--step N]

The operational counterpart of the reference's ad-hoc `ls` +
`latest`-tag-reading workflow (reference convert2ckpt.py:76-77,
trainer_base_ds_mp.py:452-455): answers "what can I resume from, under
which topology, with which optimizer layout" without loading any arrays.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def describe(root: str, step: int | None = None) -> dict:
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager

    if not os.path.isdir(root):
        raise FileNotFoundError(f"no such directory: {root}")
    mgr = CheckpointManager(root)
    steps = mgr.list_steps()
    out = {
        "root": os.path.abspath(root),
        "latest_tag": mgr.latest_tag_value(),
        "latest_complete_step": mgr.latest_step(),
        "steps": {
            s: ("complete" if mgr.is_complete(s)
                else "INCOMPLETE (no meta.json — interrupted save, ignored "
                     "by resume)")
            for s in steps
        },
    }
    inspect_step = step if step is not None else mgr.latest_step()
    if step is not None and step not in steps:
        raise ValueError(f"step {step} not found under {root}; "
                         f"available: {steps or 'none'}")
    if inspect_step is not None and not mgr.is_complete(inspect_step):
        out["checkpoint"] = {
            "step": inspect_step,
            "status": "INCOMPLETE — no meta.json (interrupted save); "
                      "arrays may be partial, resume ignores this step",
        }
        return out
    if inspect_step is not None and inspect_step in steps:
        meta = mgr.load_meta(inspect_step)
        man = meta.get("manifest", {})
        out["checkpoint"] = {
            "step": meta.get("step"),
            "stage_partition": (man.get("layer_counts")
                                or f"even: {man.get('num_layers')} layers / "
                                   f"{man.get('num_stages')} stages"),
            "model_config": meta.get("model_config"),
            "optimizer_state": (
                "none (module-only / converter output)"
                if not meta.get("has_optimizer_state") else
                meta.get("opt_layout", "fused (optax)")),
            "format_version": meta.get("format_version"),
            "items_on_disk": sorted(
                d for d in os.listdir(mgr.step_dir(inspect_step))
                if os.path.isdir(os.path.join(mgr.step_dir(inspect_step), d))),
        }
    return out


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("root", help="checkpoint output_dir")
    p.add_argument("--step", type=int, default=None,
                   help="inspect a specific step (default: latest complete)")
    args = p.parse_args(argv)
    print(json.dumps(describe(args.root, args.step), indent=2, default=str))


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Inspect a checkpoint directory: steps, completeness, manifest, layout.

Usage: python tools/inspect_ckpt.py <output_dir> [--step N] [--verify]

The operational counterpart of the reference's ad-hoc `ls` +
`latest`-tag-reading workflow (reference convert2ckpt.py:76-77,
trainer_base_ds_mp.py:452-455): answers "what can I resume from, under
which topology, with which optimizer layout" without loading any arrays.

`--verify` recomputes every file's sha256 against the digests the commit
recorded in meta.json (docs/RESILIENCE.md integrity layer) and reports
per-file status: OK, MISMATCH (bit rot / torn write), missing-on-disk
(recorded but gone), or missing-from-meta (on disk but never recorded —
a stray or post-commit write). Exits nonzero when anything is not OK.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def describe(root: str, step: int | None = None) -> dict:
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager

    if not os.path.isdir(root):
        raise FileNotFoundError(f"no such directory: {root}")
    mgr = CheckpointManager(root)
    steps = mgr.list_steps()
    out = {
        "root": os.path.abspath(root),
        "latest_tag": mgr.latest_tag_value(),
        "latest_complete_step": mgr.latest_step(),
        "steps": {
            s: ("complete" if mgr.is_complete(s)
                else "INCOMPLETE (no meta.json — interrupted save, ignored "
                     "by resume)")
            for s in steps
        },
    }
    inspect_step = step if step is not None else mgr.latest_step()
    if step is not None and step not in steps:
        raise ValueError(f"step {step} not found under {root}; "
                         f"available: {steps or 'none'}")
    if inspect_step is not None and not mgr.is_complete(inspect_step):
        out["checkpoint"] = {
            "step": inspect_step,
            "status": "INCOMPLETE — no meta.json (interrupted save); "
                      "arrays may be partial, resume ignores this step",
        }
        return out
    if inspect_step is not None and inspect_step in steps:
        meta = mgr.load_meta(inspect_step)
        man = meta.get("manifest", {})
        # the partition label: the manifest's explicit counts, else the
        # topology block's layer_counts (recorded since the generated-ladder
        # era so partition-changing resizes are named, not silent), else the
        # even split derived from the manifest
        topo = meta.get("topology") or {}
        out["checkpoint"] = {
            "step": meta.get("step"),
            "stage_partition": (man.get("layer_counts")
                                or topo.get("layer_counts")
                                or f"even: {man.get('num_layers')} layers / "
                                   f"{man.get('num_stages')} stages"),
            "model_config": meta.get("model_config"),
            "optimizer_state": (
                "none (module-only / converter output)"
                if not meta.get("has_optimizer_state") else
                meta.get("opt_layout", "fused (optax)")),
            "format_version": meta.get("format_version"),
            # elastic-resume metadata (docs/RESILIENCE.md "Elastic resume"):
            # the mesh the checkpoint was written at (any topology restores
            # it — this is provenance, not a constraint) and the sampler
            # position an O(1) resume repositions from
            "source_topology": meta.get("topology")
                               or "none (pre-elastic format)",
            "data_state": meta.get("data_state")
                          or "none (pre-elastic format; resume positions "
                             "by step count)",
            "items_on_disk": sorted(
                d for d in os.listdir(mgr.step_dir(inspect_step))
                if os.path.isdir(os.path.join(mgr.step_dir(inspect_step), d))),
        }
    return out


def verify_digests(root: str, step: int) -> dict:
    """Per-file sha256 status for one checkpoint against its meta.json
    digests. Walks the step dir so files the commit never recorded
    (missing-from-meta) surface too; meta.json itself is excluded (the
    digests live inside it — it cannot record its own hash)."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import (
        CheckpointManager,
        _file_digest,
    )

    mgr = CheckpointManager(root)
    if not mgr.is_complete(step):
        return {"step": step, "status": "INCOMPLETE",
                "detail": "no meta.json (interrupted save) — nothing to "
                          "verify against"}
    meta = mgr.load_meta(step)
    integrity = meta.get("integrity") or {}
    recorded: dict = integrity.get("files") or {}
    if not recorded:
        return {"step": step, "status": "NO_DIGESTS",
                "detail": "meta.json carries no integrity digests "
                          "(pre-integrity format, or LPT_CKPT_DIGESTS=0)"}
    step_dir = mgr.step_dir(step)
    on_disk = set()
    for dirpath, _, files in os.walk(step_dir):
        for name in files:
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, step_dir).replace(os.sep, "/")
            if rel != "meta.json":
                on_disk.add(rel)
    files: dict[str, str] = {}
    for rel, want in sorted(recorded.items()):
        full = os.path.join(step_dir, rel)
        if rel not in on_disk:
            files[rel] = "missing-on-disk"
        else:
            files[rel] = "OK" if _file_digest(full) == want else "MISMATCH"
    for rel in sorted(on_disk - set(recorded)):
        files[rel] = "missing-from-meta"
    counts: dict[str, int] = {}
    for status in files.values():
        counts[status] = counts.get(status, 0) + 1
    return {"step": step, "algo": integrity.get("algo", "sha256"),
            "status": "OK" if set(counts) == {"OK"} else "FAILED",
            "counts": counts, "files": files}


def sizes(root: str, step: int) -> dict:
    """Measured on-disk bytes per tree (top-level dir under the step) next
    to the byte model's stage-weight terms — "is the checkpoint the size
    the model says the state is". Degrades on a pre-elastic meta (no
    model_config / layer_counts): measured bytes still report, the model
    side says why it cannot."""
    import dataclasses as _dc

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager

    gib = 1 << 30
    mgr = CheckpointManager(root)
    step_dir = mgr.step_dir(step)
    trees: dict[str, dict] = {}
    total = 0
    for dirpath, _, files in os.walk(step_dir):
        for name in files:
            full = os.path.join(dirpath, name)
            try:
                n = os.path.getsize(full)
            except OSError:  # racing a delete — count what's readable
                continue
            rel = os.path.relpath(full, step_dir).replace(os.sep, "/")
            tree = rel.split("/", 1)[0] if "/" in rel else "(root)"
            t = trees.setdefault(tree, {"bytes": 0, "files": 0})
            t["bytes"] += n
            t["files"] += 1
            total += n
    out: dict = {
        "step": step,
        "total_gib": round(total / gib, 3),
        "trees": {k: {"gib": round(v["bytes"] / gib, 3),
                      "bytes": v["bytes"], "files": v["files"]}
                  for k, v in sorted(trees.items())},
    }
    meta = mgr.load_meta(step) if mgr.is_complete(step) else {}
    mc = meta.get("model_config")
    if not isinstance(mc, dict):
        out["model"] = ("unavailable — meta.json carries no model_config "
                        "(pre-elastic format, or incomplete step); measured "
                        "bytes only")
        return out
    try:
        import numpy as np

        from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
        from llama_pipeline_parallel_tpu.utils.metrics import param_count

        known = {f.name for f in _dc.fields(LlamaConfig)}
        cfg = LlamaConfig(**{k: v for k, v in mc.items() if k in known})
        itemsize = np.dtype(cfg.dtype).itemsize
        n_params = param_count(cfg)
        model: dict = {
            "param_count": n_params,
            "param_dtype": str(cfg.dtype),
            # checkpointed params in the model dtype; optimizer state is
            # two fp32 Adam moments per param (optax adamw)
            "params_gib": round(n_params * itemsize / gib, 3),
        }
        if meta.get("has_optimizer_state"):
            model["opt_state_gib"] = round(n_params * 2 * 4 / gib, 3)
        man = meta.get("manifest") or {}
        topo = meta.get("topology") or {}
        counts = man.get("layer_counts") or topo.get("layer_counts")
        if isinstance(counts, (list, tuple)) and counts:
            # per-stage weight terms, the same split preflight's byte model
            # charges each pipeline stage: per-layer params plus embedding
            # on the first stage, head + final norm on the last
            d, f_, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
            kv_dim = cfg.kv_heads * cfg.head_dim
            per_layer = d * d * 2 + d * kv_dim * 2 + 3 * d * f_ + 2 * d
            stage_gib = []
            for i, layers in enumerate(counts):
                p = int(layers) * per_layer
                if i == 0:
                    p += V * d
                if i == len(counts) - 1:
                    p += V * d + d
                stage_gib.append(round(p * itemsize / gib, 3))
            model["stage_weight_gib"] = stage_gib
        out["model"] = model
    except Exception as e:  # a foreign/garbage model_config must degrade
        out["model"] = f"unavailable — model_config not loadable ({e})"
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("root", help="checkpoint output_dir")
    p.add_argument("--step", type=int, default=None,
                   help="inspect a specific step (default: latest complete)")
    p.add_argument("--verify", action="store_true",
                   help="recompute per-file sha256 digests against meta.json "
                        "and report OK/MISMATCH/missing per file")
    p.add_argument("--sizes", action="store_true",
                   help="per-tree on-disk bytes next to the byte model's "
                        "stage-weight terms (degrades to measured-only on "
                        "pre-elastic meta)")
    args = p.parse_args(argv)
    out = describe(args.root, args.step)
    rc = 0
    if args.sizes:
        step = (args.step if args.step is not None
                else out.get("latest_complete_step"))
        if step is None:
            out["sizes"] = {"status": "NO_CHECKPOINT",
                            "detail": "no complete checkpoint to size"}
        else:
            out["sizes"] = sizes(args.root, step)
    if args.verify:
        step = (args.step if args.step is not None
                else out.get("latest_complete_step"))
        if step is None:
            out["verify"] = {"status": "NO_CHECKPOINT",
                             "detail": "no complete checkpoint to verify"}
            rc = 1
        else:
            out["verify"] = verify_digests(args.root, step)
            rc = 0 if out["verify"]["status"] == "OK" else 1
    print(json.dumps(out, indent=2, default=str))
    return rc


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Fleet actuator daemon: alert edges in, supervised actions out
(docs/RESILIENCE.md "Actuation").

The read side of the loop is tools/fleetd.py — it aggregates the pod into
`fleet_status.json` and appends alert edges to `alerts.jsonl`. This tool
closes the loop: it watches that status file and drives the two journaled
actuators in `utils/actions.py` against it:

- **autoscale** — a sustained serve-side SLO breach (ttft_p95 /
  queue_wait_p95) borrows training devices: the trainer's supervisor
  (running with --actuate) is asked via an atomic `action.request` to pin
  a smaller ladder rung; `scale_up_cmd` launches the extra serve replica
  on the freed devices. Sustained quiet hands them back.
- **deploy** — serve replicas tail the trainer's latest VERIFIED
  checkpoint, gated by each checkpoint's recorded eval_loss; a deployed
  regression rolls back to the previous verified step.

Every action is journaled in `<fleet-root>/actions.jsonl` as an intent
row before any side effect and an outcome row after — SIGKILL this
process at any point and the next start reconciles the open intents from
on-disk evidence (complete or safely void; see ActionJournal). Run it
like fleetd:

  python tools/fleetctl.py --fleet-root /runs/fleet1 --interval 2 \
      --actions '{"autoscale": {"trainer_dir": "/runs/train1",
                  "borrow_rung": "dp1", "restore_rung": "dp2"}}'

`--actions` takes inline JSON or `@/path/to/actions.json` (unknown keys
rejected — the config-block house rule). `--once` reconciles, runs one
tick, prints the ids taken, and exits (tests / cron). Without `--actions`
(or with an empty block) the tool actuates nothing — inert by default,
like every actuation path in this repo.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llama_pipeline_parallel_tpu.utils.actions import (  # noqa: E402
    ActionJournal,
    ActionsConfig,
    Autoscaler,
    Deployer,
    reconcile_open_intents,
)
from llama_pipeline_parallel_tpu.utils.fleet import (  # noqa: E402
    STATUS_NAME,
    FileWatcher,
)


def parse_actions(spec: str | None) -> ActionsConfig:
    """Inline JSON or @file -> validated ActionsConfig (fleetd's --alerts
    convention)."""
    if not spec:
        return ActionsConfig()
    raw = spec.strip()
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            node = json.load(f)
    else:
        node = json.loads(raw)
    return ActionsConfig.from_cfg(node)


class FleetActuator:
    """The tick harness the daemon loop and the tests share: builds the
    journal + configured actuators over a fleet root, reconciles the
    crash-recovery worklist once at startup, then evaluates every tick
    against the newest `fleet_status.json` snapshot."""

    def __init__(self, fleet_root: str, cfg: ActionsConfig):
        self.fleet_root = fleet_root
        self.journal = ActionJournal(fleet_root)
        self._status = FileWatcher(os.path.join(fleet_root, STATUS_NAME))
        self.autoscaler = (Autoscaler(cfg.autoscale, self.journal,
                                      fleet_root)
                           if cfg.autoscale is not None else None)
        self.deployer = (Deployer(cfg.deploy, self.journal)
                         if cfg.deploy is not None else None)

    def reconcile(self) -> list[tuple]:
        return reconcile_open_intents(self.journal, self.autoscaler,
                                      self.deployer)

    def tick(self, now: float | None = None) -> list[str]:
        if now is None:
            now = time.time()
        status = self._status.poll()
        taken: list[str] = []
        if self.autoscaler is not None:
            taken += self.autoscaler.tick(status, now)
        if self.deployer is not None:
            taken += self.deployer.tick(status, now)
        return taken


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--fleet-root", required=True,
                   help="the fleet home tools/fleetd.py aggregates into "
                        "(fleet_status.json in, actions.jsonl out)")
    p.add_argument("--actions", default=None,
                   help="actuation config: inline JSON or @/path/to/"
                        "actions.json with actions.* keys "
                        "(docs/RESILIENCE.md 'Actuation')")
    p.add_argument("--interval", type=float, default=2.0,
                   help="tick cadence in seconds (match fleetd's "
                        "--refresh-s; each tick is one status read)")
    p.add_argument("--once", action="store_true",
                   help="reconcile + one tick, print action ids, exit")
    args = p.parse_args(argv)

    try:
        cfg = parse_actions(args.actions)
    except (OSError, ValueError) as e:
        raise SystemExit(f"fleetctl: bad --actions: {e}")
    act = FleetActuator(args.fleet_root, cfg)

    # crash recovery FIRST: an intent left open by a killed predecessor
    # must resolve before any fresh action can race its side effects
    for action_id, kind, outcome in act.reconcile():
        print(f"[fleetctl] reconciled {action_id} ({kind}): {outcome}",
              flush=True)

    if args.once:
        taken = act.tick()
        print(json.dumps({"actions": taken}))
        return 0

    configured = [name for name, a in (("autoscale", act.autoscaler),
                                       ("deploy", act.deployer))
                  if a is not None]
    print(f"[fleetctl] watching {args.fleet_root} every "
          f"{args.interval:.1f}s — actuators: "
          f"{', '.join(configured) or 'none (inert)'}", flush=True)

    stop = threading.Event()

    def _stop(signum, _frame):
        print(f"[fleetctl] signal {signum}: exiting after this tick",
              flush=True)
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _stop)
        except ValueError:  # not the main thread (in-process tests)
            pass

    while not stop.is_set():
        for action_id in act.tick():
            print(f"[fleetctl] action {action_id} journaled", flush=True)
        stop.wait(args.interval)
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Offline serving-SLO summary from a serve run directory.

Reads the telemetry a `tools/serve.py` process left behind —
`serve_request` spans in spans.jsonl (per-request TTFT/TPOT/queue-wait),
serving metrics lines in metrics.jsonl, health.json — and prints the SLO
picture: request/token counts, p50/p95/p99 latency tables, throughput over
the busy window, and the slot/queue occupancy the last metrics line saw.

    python tools/serving_report.py /runs/serve1

Degrades instead of tracebacking on missing/torn files (the
goodput_report.py contract): a crashed replica's directory must still
report whatever it managed to record.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llama_pipeline_parallel_tpu.serve.telemetry import (  # noqa: E402
    SERVE_COUNTER_KEYS,
    percentiles_ms,
)


def load_jsonl(path: str) -> list[dict]:
    """Parseable dict rows only — `perf.read_jsonl`, the one spelling of
    the tolerant reader (a torn tail or garbage line is skipped)."""
    from llama_pipeline_parallel_tpu.utils.perf import read_jsonl

    return read_jsonl(path)


def build_report(output_dir: str) -> dict:
    spans = load_jsonl(os.path.join(output_dir, "spans.jsonl"))
    requests = [s for s in spans if s.get("name") == "serve_request"]
    metrics = [m for m in load_jsonl(os.path.join(output_dir, "metrics.jsonl"))
               if m.get("serving")]
    try:
        with open(os.path.join(output_dir, "health.json")) as f:
            health = json.load(f)
        health = health if isinstance(health, dict) else {}
    except (OSError, ValueError):
        health = {}

    ttft = [s["ttft"] for s in requests if isinstance(s.get("ttft"), (int, float))]
    tpot = [s["tpot"] for s in requests if isinstance(s.get("tpot"), (int, float))]
    qwait = [s["queue_wait"] for s in requests
             if isinstance(s.get("queue_wait"), (int, float))]
    tokens = sum(int(s.get("tokens", 0)) for s in requests)

    busy = None
    if requests:
        t0 = min(s["ts"] for s in requests)
        t1 = max(s.get("end", s["ts"]) for s in requests)
        busy = max(t1 - t0, 1e-9)
    return {
        "output_dir": output_dir,
        "requests": len(requests),
        "tokens": tokens,
        "busy_seconds": busy,
        "tokens_per_sec": (tokens / busy) if busy else None,
        "ttft": percentiles_ms(ttft, "ttft"),
        "tpot": percentiles_ms(tpot, "tpot"),
        "queue_wait": percentiles_ms(qwait, "queue_wait"),
        "max_ttft_ms": round(1000 * max(ttft), 3) if ttft else None,
        "mean_tokens_per_request": round(tokens / len(requests), 2)
        if requests else None,
        "last_metrics": metrics[-1] if metrics else None,
        "role": health.get("role"),
        "health_goodput": health.get("goodput"),
    }


def _latency_row(name: str, table: dict, values_key: str) -> str:
    cells = " ".join(f"p{q}={table.get(f'{values_key}_p{q}_ms', '—')}"
                     for q in (50, 95, 99))
    return f"  {name:<12} {cells} (ms)"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("output_dir")
    args = p.parse_args(argv)
    rep = build_report(args.output_dir)

    print(f"== serving report: {rep['output_dir']} ==")
    if not rep["requests"] and rep["last_metrics"] is None:
        print("  no serve_request spans or serving metrics found — nothing "
              "served yet, or the directory is not a serve run")
        return 1
    print(f"  {rep['requests']} requests, {rep['tokens']} tokens"
          + (f", {rep['tokens_per_sec']:.1f} tok/s over "
             f"{rep['busy_seconds']:.2f} s busy window"
             if rep["tokens_per_sec"] is not None else ""))
    if rep["mean_tokens_per_request"] is not None:
        print(f"  {rep['mean_tokens_per_request']} tokens/request mean")
    print("\n== SLO percentiles (spans.jsonl serve_request) ==")
    print(_latency_row("ttft", rep["ttft"], "ttft"))
    print(_latency_row("tpot", rep["tpot"], "tpot"))
    print(_latency_row("queue_wait", rep["queue_wait"], "queue_wait"))
    last = rep["last_metrics"]
    if last:
        print("\n== last serving metrics line ==")
        # the shared counter set (telemetry.SERVE_COUNTER_KEYS — the one
        # spelling goodput_report renders too) plus this report's
        # occupancy extras
        occupancy = {k: last.get(k) for k in
                     SERVE_COUNTER_KEYS
                     + ("active_slots", "queue_depth", "slot_allocations",
                        "decode_steps") if k in last}
        print("  " + " ".join(f"{k}={v}" for k, v in occupancy.items()))
        if last.get("kv_cache") == "paged":
            # the paged-capacity picture next to the SLOs: pool occupancy,
            # worst-case reservations, the admission-refusal counter, and
            # the prefill-chunk cadence (docs/SERVING.md "Paged KV cache")
            # requests_page_refused moved up into the counter line above
            pages = {k: last.get(k) for k in
                     ("pages_used", "pages_reserved", "pages_total",
                      "page_size", "kv_quant", "page_allocations")
                     if k in last}
            print("  page pool: " + " ".join(f"{k}={v}"
                                             for k, v in pages.items()))
            chunks = {k: last.get(k) for k in
                      ("prefill_chunks_last_tick", "prefill_chunks_total",
                       "prefill_tokens_total", "prefilling") if k in last}
            if chunks:
                print("  prefill:   " + " ".join(f"{k}={v}"
                                                 for k, v in chunks.items()))
            if last.get("prefix_cache"):
                # the prefix-cache picture (docs/SERVING.md "Prefix
                # caching"): hit rate, tokens/pages served from shared
                # pages, CoW forks, and the cached-page / eviction churn
                prefix = {k: last.get(k) for k in
                          ("prefix_hit_rate", "prefix_hits",
                           "prefix_misses", "prefix_cached_tokens",
                           "prefix_shared_pages", "prefix_cow_forks",
                           "pages_cached", "prefix_evictions")
                          if k in last}
                print("  prefix:    " + " ".join(f"{k}={v}"
                                                 for k, v in prefix.items()))
        tenants = last.get("tenants")
        if isinstance(tenants, dict) and tenants:
            # per-tenant attribution (serve/telemetry.py _TenantStats);
            # the full per-request story lives in tools/request_report.py
            for name in sorted(tenants):
                snap = tenants[name]
                if isinstance(snap, dict):
                    cells = " ".join(
                        f"{k}={v}" for k, v in sorted(snap.items()))
                    print(f"  tenant {name}: {cells}")
    if os.path.exists(os.path.join(rep["output_dir"],
                                   "request_trace.jsonl")):
        print("\n  per-request span trees found: render waterfalls with "
              f"tools/request_report.py {rep['output_dir']}")
    if rep["health_goodput"] is not None:
        print(f"\n  serve goodput (health.json): "
              f"{100 * rep['health_goodput']:.1f}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Supervised elastic restart for the trainer (docs/RESILIENCE.md).

A watchdog that launches the training command, watches the run's
`health.json` heartbeat (utils/trace.Heartbeat: `time` stale => process
dead or wedged), and restarts crashed or hung incarnations within a
bounded budget — the recovery half of the fault-tolerance story whose
detection half PR 1's telemetry built. Each incarnation is appended to the
goodput ledger `<output_dir>/incarnations.jsonl`, which
tools/goodput_report.py folds into its report, so restart badput is
visible next to the buckets it depresses.

Usage:
  python tools/supervisor.py --output-dir /runs/exp1 [options] -- \\
      python train.py --config conf/llama_7b_pp4.yaml output_dir=/runs/exp1

The watchdog is workload-agnostic: a serving replica (tools/serve.py)
heartbeats the same health.json (with a `role: serve` label that lands in
the ledger), so multi-replica serving is N supervisors each watching one
serve process from a shared checkpoint — docs/SERVING.md "Supervised
replicas".

Behavior:
- exit 0 from the child ends supervision (clean completion; the trainer's
  own preemption save counts — it exits 0).
- non-zero exit / signal death restarts the child, up to --max-restarts.
- a heartbeat stale for --hang-timeout-s (or never appearing for that
  long) marks the incarnation HUNG: SIGTERM (the trainer's graceful
  checkpoint-and-exit path), --grace-s to comply, then SIGKILL.
- crash-loop detection: --crash-loop-threshold consecutive failures each
  younger than --crash-loop-window-s abort supervision (exit 3) — a
  deterministic crash must page a human, not burn the restart budget.
- SIGTERM/SIGINT to the supervisor forward to the child and stop the
  restart loop (the pod-preemption path: the trainer saves, everyone
  exits).

Elastic fallback ladder (docs/RESILIENCE.md "Elastic resume"): with
--layout-ladder, each (re)launch first probes the available device count
and walks the ladder — an ordered list of layouts, each with the minimum
devices it needs and the config overrides that select it:

  --layout-ladder '[{"name": "dp4", "devices": 32, "overrides": []},
                    {"name": "dp2", "devices": 16,
                     "overrides": ["mesh.dp=2",
                                   "gradient_accumulation_steps=16"]},
                    {"name": "dp1", "devices": 8,
                     "overrides": ["mesh.dp=1",
                                   "gradient_accumulation_steps=32"]}]'

(inline JSON or @/path/to/ladder.json). The first rung whose `devices`
fits launches; its overrides are appended to the training command, the
trainer's elastic restore reshards the checkpoint onto the new mesh, and
the resize is recorded in the incarnation ledger (`layout`, `devices`,
`resized` fields). Keep every rung's GLOBAL batch identical (compensate a
dp shrink with more accumulation steps) for sample-exact data continuity.
The probe order is: an injected `device_probe` fault verdict (chaos
tests) > $LPT_DEVICE_COUNT > --probe-cmd > `python -c "import jax;
print(jax.device_count())"` in a fresh process. When no rung fits, the
supervisor aborts with exit 4 — running a layout the hardware cannot hold
would just crash-loop.

Fleet observatory (docs/OBSERVABILITY.md "Fleet"): the supervisor writes
its OWN heartbeat to `<output_dir>/supervisor_health.json` (role=
supervisor, restart count, consecutive-failure crash-loop state, current
child pid) — watchdog staleness is as observable as the child's. With
--fleet-root, the supervisor and every child (re)launch are registered in
`<fleet-root>/registry.jsonl` (role/replica/output_dir/pid/incarnation/
layout), the discovery contract tools/fleetd.py aggregates a whole pod
from.

Exit codes: 0 child completed; 2 restart budget exhausted; 3 crash loop;
4 no ladder rung fits the available devices; when the supervisor itself
is stopped, the child's own exit code (a signal death maps to the shell
convention 128+N).

Resume correctness is the trainer's job (checkpoint integrity + fallback,
O(1) data repositioning); the supervisor only guarantees a fresh
incarnation gets launched with a command line whose layout the surviving
hardware can actually run.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LEDGER_NAME = "incarnations.jsonl"
HEALTH_NAME = "health.json"
# actuation state persisted across SUPERVISOR restarts (pinned ladder
# rung / pinned serve checkpoint step): a restarted watchdog must keep
# honoring an applied action, not silently revert the pod
ACTION_STATE_NAME = "action_state.json"


def _now() -> float:
    return time.time()


def read_health(output_dir: str) -> dict | None:
    """The run's health.json, or None when absent/torn/not-a-dict (the
    writer is atomic, but the supervisor must survive any on-disk state)."""
    try:
        with open(os.path.join(output_dir, HEALTH_NAME)) as f:
            health = json.load(f)
    except (OSError, ValueError):
        return None
    return health if isinstance(health, dict) else None


@dataclasses.dataclass
class LayoutRung:
    """One rung of the elastic fallback ladder: the minimum device count
    this layout needs and the config overrides that select it."""

    devices: int
    overrides: tuple = ()
    name: str = ""

    def label(self) -> str:
        return self.name or (" ".join(self.overrides) or "base")


def parse_ladder(spec: str | None) -> list[LayoutRung] | None:
    """--layout-ladder value: inline JSON or `@/path/to/ladder.json`, a list
    of {"devices": int, "overrides": [str, ...], "name": str?} objects,
    ordered best-first."""
    if not spec:
        return None
    raw = spec.strip()
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            data = json.load(f)
    else:
        data = json.loads(raw)
    if not isinstance(data, list) or not data:
        raise ValueError("--layout-ladder must be a non-empty JSON list")
    rungs = []
    for i, entry in enumerate(data):
        if not isinstance(entry, dict) or "devices" not in entry:
            raise ValueError(f"ladder rung #{i} must be an object with a "
                             f"'devices' key, got {entry!r}")
        unknown = set(entry) - {"devices", "overrides", "name"}
        if unknown:
            raise ValueError(f"ladder rung #{i}: unknown keys {sorted(unknown)}")
        rungs.append(LayoutRung(devices=int(entry["devices"]),
                                overrides=tuple(entry.get("overrides") or ()),
                                name=str(entry.get("name", ""))))
    return rungs


@dataclasses.dataclass
class SupervisorConfig:
    output_dir: str
    max_restarts: int = 5
    hang_timeout_s: float = 300.0
    grace_s: float = 30.0
    crash_loop_threshold: int = 3
    crash_loop_window_s: float = 120.0
    poll_s: float = 1.0
    ladder: list | None = None      # LayoutRungs, best-first (None = inelastic)
    probe_cmd: str | None = None    # shell command printing the device count
    # fleet observatory (docs/OBSERVABILITY.md "Fleet"): every launch is
    # registered in <fleet_root>/registry.jsonl so tools/fleetd.py can
    # discover and tail this member; role/replica label the registration
    # (role is otherwise learned from the child's own health.json)
    fleet_root: str | None = None
    role: str | None = None
    replica: str | None = None
    # alert-driven actuation (docs/RESILIENCE.md "Actuation"): consume
    # atomic `action.request` files an actuator (tools/fleetctl.py) drops
    # into output_dir — resize pins a ladder rung, deploy pins a serve
    # checkpoint step; both gracefully restart the child WITHOUT ending
    # supervision. Off by default: without it the supervisor's behavior
    # is byte-identical to the pre-actuation watchdog.
    actuate: bool = False


class Supervisor:
    """Launch/watch/restart loop. Separated from main() so chaos tests can
    drive it in-process with fast timeouts."""

    def __init__(self, cmd: list[str], cfg: SupervisorConfig,
                 env: dict[str, str] | None = None):
        if not cmd:
            raise ValueError("supervisor needs a command to run")
        self.cmd = cmd
        self.cfg = cfg
        self.env = env
        self._child: subprocess.Popen | None = None
        self._stop_signal: int | None = None
        self._ledger_path = os.path.join(cfg.output_dir, LEDGER_NAME)
        os.makedirs(cfg.output_dir, exist_ok=True)
        # previous incarnation's rung label, seeded from the persisted
        # ledger so a resize across a SUPERVISOR restart (new process, same
        # output_dir) is still recorded as resized
        self._last_layout: str | None = self._last_ledger_layout()
        # the watchdog's OWN heartbeat (supervisor_health.json, started in
        # run()): watchdog staleness must be as observable as the child's —
        # a fleet whose supervisor died silently cannot restart anything
        self._hb = None
        self._hb_state: dict[str, Any] = {
            "incarnation": None, "child_pid": None, "restarts": 0,
            "consecutive_failures": 0, "last_outcome": None, "layout": None}
        # actuation (--actuate): pinned layout rung / serve checkpoint
        # step, persisted in action_state.json so a supervisor restart
        # keeps honoring an applied action; the action currently stopping
        # the child (its clean exit must NOT end supervision)
        self._pinned_rung: str | None = None
        self._pinned_step: int | None = None
        self._action_pending: dict | None = None
        self._action_state_path = os.path.join(cfg.output_dir,
                                               ACTION_STATE_NAME)
        if cfg.actuate:
            state = self._read_json(self._action_state_path)
            if state:
                if isinstance(state.get("rung"), str):
                    self._pinned_rung = state["rung"]
                if isinstance(state.get("step"), int):
                    self._pinned_step = state["step"]

    def _heartbeat_start(self) -> None:
        try:
            from llama_pipeline_parallel_tpu.utils import fleet, trace
        except Exception as e:  # the watchdog must run even half-installed
            print(f"[supervisor] own heartbeat unavailable ({e!r})",
                  flush=True)
            return
        try:
            self._hb = trace.Heartbeat(
                self.cfg.output_dir,
                interval=min(10.0, max(self.cfg.poll_s, 0.5)),
                static={"role": "supervisor", "pid": os.getpid(),
                        "watched_dir": os.path.abspath(self.cfg.output_dir),
                        "max_restarts": self.cfg.max_restarts},
                extra=self._hb_state,
                filename=fleet.SUPERVISOR_HEALTH_NAME)
        except OSError as e:
            print(f"[supervisor] own heartbeat unavailable ({e!r})",
                  flush=True)
        try:
            if self.cfg.fleet_root:
                fleet.register_member(
                    self.cfg.fleet_root, output_dir=self.cfg.output_dir,
                    role="supervisor", pid=os.getpid(),
                    replica=self.cfg.replica,
                    health_file=fleet.SUPERVISOR_HEALTH_NAME)
        except Exception as e:
            # registration is telemetry; a full fleet disk must not stop
            # the watchdog from launching anything (_register_incarnation's
            # rule, applied to the supervisor's own row too)
            print(f"[supervisor] fleet registration failed: {e!r}",
                  flush=True)

    def _register_incarnation(self, incarnation: int, pid: int,
                              layout: dict | None) -> None:
        """Fleet registry contract: one row per LAUNCH, so the aggregator
        sees a fresh pid/incarnation the moment the child exists (and its
        registration vouches liveness until the first health.json write)."""
        if not self.cfg.fleet_root:
            return
        try:
            from llama_pipeline_parallel_tpu.utils import fleet

            fleet.register_member(
                self.cfg.fleet_root, output_dir=self.cfg.output_dir,
                role=self.cfg.role, replica=self.cfg.replica,
                pid=pid, incarnation=incarnation,
                supervisor_pid=os.getpid(), **(layout or {}))
        except Exception as e:
            # registration is telemetry; a full fleet disk must not stop
            # the restart loop
            print(f"[supervisor] fleet registration failed: {e!r}",
                  flush=True)

    def _register_abort(self, reason: str) -> None:
        """Terminal registry rows for BOTH member keys (child + the
        supervisor's own) when supervision gives up (crash loop, budget,
        no rung): the aggregator stops counting them as fresh the moment
        it reads the row, instead of waiting out heartbeat_stale_s on a
        pod nothing will ever restart."""
        if not self.cfg.fleet_root:
            return
        try:
            from llama_pipeline_parallel_tpu.utils import fleet

            fleet.register_member(
                self.cfg.fleet_root, output_dir=self.cfg.output_dir,
                role=self.cfg.role, replica=self.cfg.replica,
                pid=os.getpid(), outcome="aborted", reason=reason)
            fleet.register_member(
                self.cfg.fleet_root, output_dir=self.cfg.output_dir,
                role="supervisor", replica=self.cfg.replica,
                pid=os.getpid(), health_file=fleet.SUPERVISOR_HEALTH_NAME,
                outcome="aborted", reason=reason)
        except Exception as e:  # telemetry; the exit code must still land
            print(f"[supervisor] abort registration failed: {e!r}",
                  flush=True)

    # -- actuation (--actuate) -----------------------------------------------

    @staticmethod
    def _read_json(path: str) -> dict | None:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return None
        return data if isinstance(data, dict) else None

    @staticmethod
    def _write_json_atomic(path: str, payload: dict) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2)
        os.replace(tmp, path)

    def _apply_step_override(self, cmd: list[str]) -> list[str]:
        """The pinned serve checkpoint step, spliced into the child
        command (any existing --step is replaced — the pin IS the
        deployment state)."""
        if self._pinned_step is None:
            return cmd
        out, i = [], 0
        while i < len(cmd):
            if cmd[i] == "--step" and i + 1 < len(cmd):
                i += 2
                continue
            if cmd[i].startswith("--step="):
                i += 1
                continue
            out.append(cmd[i])
            i += 1
        return out + ["--step", str(self._pinned_step)]

    def _consume_action_request(self, child: "subprocess.Popen | None"
                                ) -> dict | None:
        """One actuation RPC step: read + validate `action.request`,
        apply the pin, persist it, write the ack (the actuator's
        reconciliation evidence), remove the request, then gracefully
        stop the child (it saves/drains and exits 0 — the same contract
        as preemption). Crash-safe at every seam: the request file is
        removed LAST, so a supervisor killed mid-apply re-consumes an
        identical request on restart (pins are idempotent)."""
        from llama_pipeline_parallel_tpu.utils import actions

        req_path = os.path.join(self.cfg.output_dir,
                                actions.ACTION_REQUEST_NAME)
        req = self._read_json(req_path)
        if req is None:
            if os.path.exists(req_path):
                # torn/garbage request: drop it, or it wedges the
                # actuator's skip-if-present writer forever
                print(f"[supervisor] removing unreadable action request "
                      f"{req_path}", flush=True)
                try:
                    os.remove(req_path)
                except OSError:
                    pass
            return None
        action = req.get("action")
        if action == "resize":
            rung = req.get("rung")
            self._pinned_rung = rung if isinstance(rung, str) else None
            # the trainer's own boundary poll (actions.resize_on_request):
            # a labeled resize file it consumes at the next step boundary
            # — the SIGTERM below covers children that don't poll
            try:
                self._write_json_atomic(
                    os.path.join(self.cfg.output_dir,
                                 actions.RESIZE_REQUEST_NAME),
                    {"ts": _now(), "id": req.get("id"),
                     "rung": self._pinned_rung})
            except OSError:
                pass
        elif action == "deploy":
            try:
                self._pinned_step = int(req["step"])
            except (KeyError, TypeError, ValueError):
                print(f"[supervisor] deploy request without a valid step: "
                      f"{req!r}; ignoring", flush=True)
                try:
                    os.remove(req_path)
                except OSError:
                    pass
                return None
        else:
            print(f"[supervisor] unknown action {action!r}; ignoring",
                  flush=True)
            try:
                os.remove(req_path)
            except OSError:
                pass
            return None
        try:
            self._write_json_atomic(
                self._action_state_path,
                {"rung": self._pinned_rung, "step": self._pinned_step,
                 "last_id": req.get("id"), "ts": _now()})
            self._write_json_atomic(
                os.path.join(self.cfg.output_dir, actions.ACTION_ACK_NAME),
                {"ts": _now(), "id": req.get("id"), "action": action,
                 "rung": self._pinned_rung, "step": self._pinned_step})
        except OSError as e:
            print(f"[supervisor] could not persist action state: {e!r}",
                  flush=True)
        try:
            os.remove(req_path)
        except OSError:
            pass
        print(f"[supervisor] action {req.get('id')}: {action} "
              f"(rung={self._pinned_rung} step={self._pinned_step}); "
              f"restarting child gracefully", flush=True)
        if child is not None and child.poll() is None:
            try:
                child.terminate()  # trainer saves at a boundary, serve
            except OSError:        # drains — both exit 0
                pass
        return req

    def _last_ledger_layout(self) -> str | None:
        try:
            with open(self._ledger_path) as f:
                lines = [l for l in f if l.strip()]
            return json.loads(lines[-1]).get("layout") if lines else None
        except (OSError, ValueError, AttributeError):
            return None  # fresh run, torn tail, or a pre-elastic ledger

    # -- ledger ------------------------------------------------------------

    def _log_incarnation(self, rec: dict[str, Any]) -> None:
        """Append one incarnation row to the goodput ledger. Plain append:
        the supervisor is the file's only writer."""
        with open(self._ledger_path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    # -- signal forwarding ---------------------------------------------------

    def _forward_signal(self, sig, _frame) -> None:
        self._stop_signal = sig
        child = self._child
        if child is not None and child.poll() is None:
            try:
                child.send_signal(sig)
            except OSError:
                pass

    # -- elastic layout selection --------------------------------------------

    def _probe_devices(self, incarnation: int) -> int | None:
        """Available device count for the next launch, or None when unknown
        (treated as "assume the full topology"). Probe order: an injected
        `device_probe` fault verdict (chaos plans simulate losing chips at
        restart) > $LPT_DEVICE_COUNT > --probe-cmd > this interpreter
        importing jax in a fresh process (the dead child's devices are
        released by then)."""
        try:
            from llama_pipeline_parallel_tpu.utils import faults

            verdict = faults.fire("device_probe",
                                  tag=f"incarnation-{incarnation}")
        except Exception:
            verdict = None
        if verdict and verdict.startswith("device_loss:"):
            n = int(verdict.split(":", 1)[1])
            print(f"[supervisor] injected device loss: probe reports {n} "
                  f"device(s)", flush=True)
            return n
        env_count = (self.env or os.environ).get("LPT_DEVICE_COUNT")
        if env_count:
            try:
                return int(env_count)
            except ValueError:
                # the supervisor exists to survive faults — garbage in the
                # env falls through to the next probe, never a traceback
                print(f"[supervisor] ignoring malformed LPT_DEVICE_COUNT="
                      f"{env_count!r}", flush=True)
        probe_cmd = self.cfg.probe_cmd or (
            f"{sys.executable} -c 'import jax; print(jax.device_count())'")
        try:
            out = subprocess.run(probe_cmd, shell=True, env=self.env,
                                 capture_output=True, text=True, timeout=300)
            return int(out.stdout.strip().splitlines()[-1])
        except Exception as e:
            print(f"[supervisor] device probe failed ({e!r}); assuming the "
                  f"full topology", flush=True)
            return None

    def _select_rung(self, incarnation: int
                     ) -> tuple["LayoutRung | None", int | None]:
        """(rung, probed device count) for this launch; (None, n) when no
        rung fits. Without a ladder: (None, None) — inelastic, base command."""
        if not self.cfg.ladder:
            return None, None
        available = self._probe_devices(incarnation)
        if self._pinned_rung is not None:
            for rung in self.cfg.ladder:
                if rung.label() == self._pinned_rung:
                    # an applied resize action overrides best-fit: a
                    # BORROW deliberately runs a smaller rung than the
                    # probe would pick (the freed devices host a serve
                    # replica), so availability does not re-promote it
                    return rung, available
            print(f"[supervisor] pinned rung {self._pinned_rung!r} not in "
                  f"the ladder; falling back to best-fit", flush=True)
        for rung in self.cfg.ladder:
            if available is None or available >= rung.devices:
                return rung, available
        return None, available

    # -- one incarnation -----------------------------------------------------

    def _heartbeat_age(self, started_at: float) -> float:
        """Seconds since the run last proved liveness: health.json's `time`
        field when present, else the incarnation launch (covers the init
        window before the Heartbeat thread exists — size --hang-timeout-s
        for the model-build+restore+compile phase, not just step cadence)."""
        health = read_health(self.cfg.output_dir)
        last = started_at
        if health is not None:
            try:
                t = float(health.get("time", 0.0))
            except (TypeError, ValueError):
                t = 0.0
            # a stale file from a PREVIOUS incarnation must not vouch for
            # this one before it ever writes
            if t > started_at:
                last = t
        return _now() - last

    def _kill_hung(self, child: subprocess.Popen) -> None:
        """SIGTERM (the trainer checkpoints and exits cleanly), grace, then
        SIGKILL."""
        try:
            child.terminate()
        except OSError:
            return
        try:
            child.wait(timeout=self.cfg.grace_s)
        except subprocess.TimeoutExpired:
            try:
                child.kill()
            except OSError:
                pass
            child.wait()

    def _run_once(self, incarnation: int, cmd: list[str] | None = None,
                  layout: dict | None = None) -> dict:
        cmd = cmd if cmd is not None else self.cmd
        started = _now()
        print(f"[supervisor] incarnation {incarnation}: {' '.join(cmd)}",
              flush=True)
        child = subprocess.Popen(cmd, env=self.env)
        self._child = child
        self._register_incarnation(incarnation, child.pid, layout)
        self._hb_state.update(incarnation=incarnation, child_pid=child.pid,
                              layout=(layout or {}).get("layout"))
        if self._hb is not None:
            try:
                self._hb.write()
            except OSError:  # full disk must not orphan the fresh child
                pass
        outcome = "clean"
        while True:
            rc = child.poll()
            if rc is not None:
                if self._stop_signal is not None:
                    outcome = "supervisor_stopped"
                elif rc != 0:
                    outcome = "crash"
                break
            if self.cfg.actuate and self._stop_signal is None \
                    and self._action_pending is None:
                self._action_pending = self._consume_action_request(child)
            if self._stop_signal is None \
                    and self._heartbeat_age(started) > self.cfg.hang_timeout_s:
                print(f"[supervisor] incarnation {incarnation} heartbeat "
                      f"stale > {self.cfg.hang_timeout_s:.0f}s; killing "
                      f"(SIGTERM, {self.cfg.grace_s:.0f}s grace, SIGKILL)",
                      flush=True)
                self._kill_hung(child)
                rc = child.returncode
                outcome = "hang"
                break
            time.sleep(self.cfg.poll_s)
        self._child = None
        ended = _now()
        if outcome == "crash":
            # OOM forensics (utils/memwatch.py): the trainer's allocation-
            # failure handler dumps a snapshot into <output_dir>/oom/
            # before re-raising. A snapshot newer than THIS incarnation's
            # launch means memory pressure killed it — labeled distinctly
            # so goodput_report separates capacity problems (every restart
            # will OOM again) from transient crashes (a restart may help).
            from llama_pipeline_parallel_tpu.utils import memwatch
            oom_mtime = memwatch.latest_oom_mtime(self.cfg.output_dir)
            if oom_mtime is not None and oom_mtime > started:
                outcome = "oom"
        health = read_health(self.cfg.output_dir) or {}
        # a health.json the DEAD PREVIOUS incarnation wrote must not label
        # this one (same staleness rule as _heartbeat_age): an incarnation
        # that died before its first heartbeat gets None fields, not the
        # old topology/step
        try:
            fresh = float(health.get("time", 0.0)) > started
        except (TypeError, ValueError):
            fresh = False
        rec = {
            "incarnation": incarnation,
            "start": started,
            "end": ended,
            "duration_s": round(ended - started, 3),
            "exit_code": rc,
            "outcome": outcome,
            "last_step": health.get("last_step"),
            "goodput": health.get("goodput"),
            # the trainer's own view of its mesh (health.json `topology`,
            # written by the Heartbeat) — the ledger's authoritative label
            "trainer_topology": health.get("topology") if fresh else None,
        }
        # serve processes (tools/serve.py) heartbeat a `role` so the ledger
        # and goodput report can tell a serving incarnation from a training
        # one; absent for trainers, so their rows are unchanged
        if fresh and health.get("role"):
            rec["role"] = health.get("role")
        if layout is not None:
            rec.update(layout)
        if self._action_pending is not None:
            # the ledger shows WHY this incarnation ended: an applied
            # action, not a fault (outcome stays "clean" so goodput and
            # crash-loop accounting are untouched)
            rec["action"] = {"id": self._action_pending.get("id"),
                             "action": self._action_pending.get("action")}
        self._log_incarnation(rec)
        print(f"[supervisor] incarnation {incarnation} ended: "
              f"outcome={outcome} exit={rc} last_step={rec['last_step']}",
              flush=True)
        return rec

    # -- the loop ------------------------------------------------------------

    def run(self) -> int:
        prev_handlers = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(sig, self._forward_signal)
            except ValueError:  # not the main thread (in-process tests)
                pass
        self._heartbeat_start()
        try:
            failures: list[dict] = []  # consecutive non-clean incarnations
            for incarnation in range(self.cfg.max_restarts + 1):
                if self.cfg.actuate and self._action_pending is None:
                    # a request that arrived while no child was running
                    # (or survived a supervisor crash mid-apply): apply
                    # the pin BEFORE launching, then clear — there is no
                    # child to stop, so it is not a pending restart
                    self._consume_action_request(None)
                rung, available = self._select_rung(incarnation)
                cmd, layout = self.cmd, None
                if self.cfg.ladder:
                    if rung is None:
                        print(f"[supervisor] no ladder rung fits "
                              f"{available} available device(s); aborting "
                              f"(a layout the hardware cannot hold would "
                              f"only crash-loop)", flush=True)
                        self._register_abort("no_rung_fits")
                        return 4
                    cmd = self.cmd + list(rung.overrides)
                    resized = (self._last_layout is not None
                               and rung.label() != self._last_layout)
                    if resized:
                        print(f"[supervisor] topology resize: "
                              f"{self._last_layout} -> {rung.label()} "
                              f"({available} device(s) available)",
                              flush=True)
                    layout = {"layout": rung.label(), "devices": available,
                              "overrides": list(rung.overrides),
                              "resized": resized}
                    self._last_layout = rung.label()
                cmd = self._apply_step_override(cmd)
                rec = self._run_once(incarnation, cmd=cmd, layout=layout)
                self._hb_state.update(
                    last_outcome=rec["outcome"], restarts=incarnation,
                    consecutive_failures=(
                        0 if rec["outcome"] in ("clean", "supervisor_stopped")
                        else self._hb_state["consecutive_failures"] + 1))
                if rec["outcome"] == "clean":
                    if self._action_pending is not None:
                        # an applied action stopped the child (resize/
                        # deploy): its clean exit is a RESTART boundary,
                        # not the end of supervision — relaunch on the
                        # pinned state
                        self._action_pending = None
                        failures.clear()
                        continue
                    return 0
                if rec["outcome"] == "supervisor_stopped":
                    # pod preemption of the supervisor itself: the child was
                    # told, saved, and exited; propagate its code. A signal
                    # death maps to the shell convention 128+N — a raw
                    # negative returncode through sys.exit() would come out
                    # as an unrelated status (e.g. -15 -> 241)
                    rc = rec["exit_code"] or 0
                    return 128 - rc if rc < 0 else rc
                failures.append(rec)
                tail = failures[-self.cfg.crash_loop_threshold:]
                if (len(tail) >= self.cfg.crash_loop_threshold
                        and all(f["duration_s"] < self.cfg.crash_loop_window_s
                                for f in tail)):
                    print(f"[supervisor] crash loop: last {len(tail)} "
                          f"incarnations each died within "
                          f"{self.cfg.crash_loop_window_s:.0f}s; giving up",
                          flush=True)
                    self._register_abort("crash_loop")
                    return 3
            print(f"[supervisor] restart budget exhausted "
                  f"({self.cfg.max_restarts} restarts)", flush=True)
            self._register_abort("budget_exhausted")
            return 2
        finally:
            if self._hb is not None:
                try:  # final state: last outcome + restart count
                    self._hb.stop()
                except OSError:
                    pass  # heartbeat is telemetry; handlers must restore
            for sig, handler in prev_handlers.items():
                signal.signal(sig, handler)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--output-dir", required=True,
                   help="the trainer's output_dir (health.json + the "
                        "incarnations.jsonl ledger live here)")
    p.add_argument("--max-restarts", type=int, default=5,
                   help="restarts after the first launch (default 5)")
    p.add_argument("--hang-timeout-s", type=float, default=300.0,
                   help="heartbeat staleness that declares a hang; must "
                        "cover the init+compile window (default 300)")
    p.add_argument("--grace-s", type=float, default=30.0,
                   help="SIGTERM->SIGKILL grace for a hung child (default 30)")
    p.add_argument("--crash-loop-threshold", type=int, default=3,
                   help="consecutive fast failures that abort (default 3)")
    p.add_argument("--crash-loop-window-s", type=float, default=120.0,
                   help="a failure younger than this counts toward the "
                        "crash loop (default 120)")
    p.add_argument("--poll-s", type=float, default=1.0,
                   help="watchdog poll interval (default 1)")
    p.add_argument("--layout-ladder", default=None,
                   help="elastic fallback ladder: JSON list of {devices, "
                        "overrides, name} rungs, best-first (inline or "
                        "@/path/to/ladder.json); each launch probes the "
                        "available devices and runs the first rung that "
                        "fits (exit 4 when none does)")
    p.add_argument("--probe-cmd", default=None,
                   help="shell command printing the available device count "
                        "(default: this interpreter importing jax in a "
                        "fresh process); only used with --layout-ladder")
    p.add_argument("--fleet-root", default=None,
                   help="fleet observatory home: register this member and "
                        "every (re)launch in <fleet-root>/registry.jsonl "
                        "for tools/fleetd.py (docs/OBSERVABILITY.md "
                        "'Fleet')")
    p.add_argument("--role", default=None,
                   help="registry role label (trainer/serve); default: "
                        "learned from the child's health.json")
    p.add_argument("--replica", default=None,
                   help="registry replica id; default: the output dir's "
                        "basename")
    p.add_argument("--actuate", action="store_true",
                   help="consume atomic action.request files an actuator "
                        "(tools/fleetctl.py) drops into the output dir: "
                        "resize pins a ladder rung, deploy pins a serve "
                        "checkpoint step; both restart the child "
                        "gracefully without ending supervision "
                        "(docs/RESILIENCE.md 'Actuation'). Off by "
                        "default — without it behavior is identical to "
                        "the plain watchdog")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="the training command, after `--`")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        p.error("no training command given (append `-- python train.py ...`)")
    sup = Supervisor(cmd, SupervisorConfig(
        output_dir=args.output_dir, max_restarts=args.max_restarts,
        hang_timeout_s=args.hang_timeout_s, grace_s=args.grace_s,
        crash_loop_threshold=args.crash_loop_threshold,
        crash_loop_window_s=args.crash_loop_window_s, poll_s=args.poll_s,
        ladder=parse_ladder(args.layout_ladder), probe_cmd=args.probe_cmd,
        fleet_root=args.fleet_root, role=args.role, replica=args.replica,
        actuate=args.actuate))
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())

"""Supervised elastic restart for the trainer (docs/RESILIENCE.md).

A watchdog that launches the training command, watches the run's
`health.json` heartbeat (utils/trace.Heartbeat: `time` stale => process
dead or wedged), and restarts crashed or hung incarnations within a
bounded budget — the recovery half of the fault-tolerance story whose
detection half PR 1's telemetry built. Each incarnation is appended to the
goodput ledger `<output_dir>/incarnations.jsonl`, which
tools/goodput_report.py folds into its report, so restart badput is
visible next to the buckets it depresses.

Usage:
  python tools/supervisor.py --output-dir /runs/exp1 [options] -- \\
      python train.py --config conf/llama_7b_pp4.yaml output_dir=/runs/exp1

Behavior:
- exit 0 from the child ends supervision (clean completion; the trainer's
  own preemption save counts — it exits 0).
- non-zero exit / signal death restarts the child, up to --max-restarts.
- a heartbeat stale for --hang-timeout-s (or never appearing for that
  long) marks the incarnation HUNG: SIGTERM (the trainer's graceful
  checkpoint-and-exit path), --grace-s to comply, then SIGKILL.
- crash-loop detection: --crash-loop-threshold consecutive failures each
  younger than --crash-loop-window-s abort supervision (exit 3) — a
  deterministic crash must page a human, not burn the restart budget.
- SIGTERM/SIGINT to the supervisor forward to the child and stop the
  restart loop (the pod-preemption path: the trainer saves, everyone
  exits).

Exit codes: 0 child completed; 2 restart budget exhausted; 3 crash loop;
when the supervisor itself is stopped, the child's own exit code (a
signal death maps to the shell convention 128+N).

Resume correctness is the trainer's job (checkpoint integrity + fallback,
loader fast-forward); the supervisor only guarantees a fresh incarnation
gets launched with the same command line.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any

LEDGER_NAME = "incarnations.jsonl"
HEALTH_NAME = "health.json"


def _now() -> float:
    return time.time()


def read_health(output_dir: str) -> dict | None:
    """The run's health.json, or None when absent/torn/not-a-dict (the
    writer is atomic, but the supervisor must survive any on-disk state)."""
    try:
        with open(os.path.join(output_dir, HEALTH_NAME)) as f:
            health = json.load(f)
    except (OSError, ValueError):
        return None
    return health if isinstance(health, dict) else None


@dataclasses.dataclass
class SupervisorConfig:
    output_dir: str
    max_restarts: int = 5
    hang_timeout_s: float = 300.0
    grace_s: float = 30.0
    crash_loop_threshold: int = 3
    crash_loop_window_s: float = 120.0
    poll_s: float = 1.0


class Supervisor:
    """Launch/watch/restart loop. Separated from main() so chaos tests can
    drive it in-process with fast timeouts."""

    def __init__(self, cmd: list[str], cfg: SupervisorConfig,
                 env: dict[str, str] | None = None):
        if not cmd:
            raise ValueError("supervisor needs a command to run")
        self.cmd = cmd
        self.cfg = cfg
        self.env = env
        self._child: subprocess.Popen | None = None
        self._stop_signal: int | None = None
        self._ledger_path = os.path.join(cfg.output_dir, LEDGER_NAME)
        os.makedirs(cfg.output_dir, exist_ok=True)

    # -- ledger ------------------------------------------------------------

    def _log_incarnation(self, rec: dict[str, Any]) -> None:
        """Append one incarnation row to the goodput ledger. Plain append:
        the supervisor is the file's only writer."""
        with open(self._ledger_path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    # -- signal forwarding ---------------------------------------------------

    def _forward_signal(self, sig, _frame) -> None:
        self._stop_signal = sig
        child = self._child
        if child is not None and child.poll() is None:
            try:
                child.send_signal(sig)
            except OSError:
                pass

    # -- one incarnation -----------------------------------------------------

    def _heartbeat_age(self, started_at: float) -> float:
        """Seconds since the run last proved liveness: health.json's `time`
        field when present, else the incarnation launch (covers the init
        window before the Heartbeat thread exists — size --hang-timeout-s
        for the model-build+restore+compile phase, not just step cadence)."""
        health = read_health(self.cfg.output_dir)
        last = started_at
        if health is not None:
            try:
                t = float(health.get("time", 0.0))
            except (TypeError, ValueError):
                t = 0.0
            # a stale file from a PREVIOUS incarnation must not vouch for
            # this one before it ever writes
            if t > started_at:
                last = t
        return _now() - last

    def _kill_hung(self, child: subprocess.Popen) -> None:
        """SIGTERM (the trainer checkpoints and exits cleanly), grace, then
        SIGKILL."""
        try:
            child.terminate()
        except OSError:
            return
        try:
            child.wait(timeout=self.cfg.grace_s)
        except subprocess.TimeoutExpired:
            try:
                child.kill()
            except OSError:
                pass
            child.wait()

    def _run_once(self, incarnation: int) -> dict:
        started = _now()
        print(f"[supervisor] incarnation {incarnation}: {' '.join(self.cmd)}",
              flush=True)
        child = subprocess.Popen(self.cmd, env=self.env)
        self._child = child
        outcome = "clean"
        while True:
            rc = child.poll()
            if rc is not None:
                if self._stop_signal is not None:
                    outcome = "supervisor_stopped"
                elif rc != 0:
                    outcome = "crash"
                break
            if self._stop_signal is None \
                    and self._heartbeat_age(started) > self.cfg.hang_timeout_s:
                print(f"[supervisor] incarnation {incarnation} heartbeat "
                      f"stale > {self.cfg.hang_timeout_s:.0f}s; killing "
                      f"(SIGTERM, {self.cfg.grace_s:.0f}s grace, SIGKILL)",
                      flush=True)
                self._kill_hung(child)
                rc = child.returncode
                outcome = "hang"
                break
            time.sleep(self.cfg.poll_s)
        self._child = None
        ended = _now()
        health = read_health(self.cfg.output_dir) or {}
        rec = {
            "incarnation": incarnation,
            "start": started,
            "end": ended,
            "duration_s": round(ended - started, 3),
            "exit_code": rc,
            "outcome": outcome,
            "last_step": health.get("last_step"),
            "goodput": health.get("goodput"),
        }
        self._log_incarnation(rec)
        print(f"[supervisor] incarnation {incarnation} ended: "
              f"outcome={outcome} exit={rc} last_step={rec['last_step']}",
              flush=True)
        return rec

    # -- the loop ------------------------------------------------------------

    def run(self) -> int:
        prev_handlers = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(sig, self._forward_signal)
            except ValueError:  # not the main thread (in-process tests)
                pass
        try:
            failures: list[dict] = []  # consecutive non-clean incarnations
            for incarnation in range(self.cfg.max_restarts + 1):
                rec = self._run_once(incarnation)
                if rec["outcome"] == "clean":
                    return 0
                if rec["outcome"] == "supervisor_stopped":
                    # pod preemption of the supervisor itself: the child was
                    # told, saved, and exited; propagate its code. A signal
                    # death maps to the shell convention 128+N — a raw
                    # negative returncode through sys.exit() would come out
                    # as an unrelated status (e.g. -15 -> 241)
                    rc = rec["exit_code"] or 0
                    return 128 - rc if rc < 0 else rc
                failures.append(rec)
                tail = failures[-self.cfg.crash_loop_threshold:]
                if (len(tail) >= self.cfg.crash_loop_threshold
                        and all(f["duration_s"] < self.cfg.crash_loop_window_s
                                for f in tail)):
                    print(f"[supervisor] crash loop: last {len(tail)} "
                          f"incarnations each died within "
                          f"{self.cfg.crash_loop_window_s:.0f}s; giving up",
                          flush=True)
                    return 3
            print(f"[supervisor] restart budget exhausted "
                  f"({self.cfg.max_restarts} restarts)", flush=True)
            return 2
        finally:
            for sig, handler in prev_handlers.items():
                signal.signal(sig, handler)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--output-dir", required=True,
                   help="the trainer's output_dir (health.json + the "
                        "incarnations.jsonl ledger live here)")
    p.add_argument("--max-restarts", type=int, default=5,
                   help="restarts after the first launch (default 5)")
    p.add_argument("--hang-timeout-s", type=float, default=300.0,
                   help="heartbeat staleness that declares a hang; must "
                        "cover the init+compile window (default 300)")
    p.add_argument("--grace-s", type=float, default=30.0,
                   help="SIGTERM->SIGKILL grace for a hung child (default 30)")
    p.add_argument("--crash-loop-threshold", type=int, default=3,
                   help="consecutive fast failures that abort (default 3)")
    p.add_argument("--crash-loop-window-s", type=float, default=120.0,
                   help="a failure younger than this counts toward the "
                        "crash loop (default 120)")
    p.add_argument("--poll-s", type=float, default=1.0,
                   help="watchdog poll interval (default 1)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="the training command, after `--`")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        p.error("no training command given (append `-- python train.py ...`)")
    sup = Supervisor(cmd, SupervisorConfig(
        output_dir=args.output_dir, max_restarts=args.max_restarts,
        hang_timeout_s=args.hang_timeout_s, grace_s=args.grace_s,
        crash_loop_threshold=args.crash_loop_threshold,
        crash_loop_window_s=args.crash_loop_window_s, poll_s=args.poll_s))
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Fleet observatory daemon: the live rollup of a supervised pod
(docs/OBSERVABILITY.md "Fleet").

Points a `utils/fleet.FleetAggregator` at a `--fleet-root` (where every
supervisor launched with the same flag registers its members), refreshes
it on a fixed cadence — each refresh tails the members' health/metrics/
incarnation streams INCREMENTALLY, rewrites `fleet_status.json`
atomically, evaluates the `alerts.*` rules, appends firing/resolved edges
to `alerts.jsonl`, and drops `capture.trigger` files into alerting
members' output dirs — and serves the rollup live over stdlib HTTP (the
serve/frontend.py style: dependency-free, runs on a bare TPU VM image):

  GET /fleet     the full fleet_status.json payload (latest refresh)
  GET /healthz   {"time", "refresh_count", "members", "alerts_firing"}

Usage:
  python tools/fleetd.py --fleet-root /runs/fleet1 --port 8900 \
      --refresh-s 2 --alerts '{"heartbeat_stale_s": 30, "ttft_p95_ms": 500}'

`--alerts` takes inline JSON or `@/path/to/alerts.json` (unknown keys
rejected — the config-block house rule). A `tenant_ttft_p95_ms`
threshold fans out per tenant: one `tenant_ttft_p95:<tenant>` rule
instance per tenant found in a member's serving snapshot, all sharing
the one configured threshold. `--once` performs a single
refresh, prints the status JSON, and exits (cron / CI probes).
SIGTERM/SIGINT exit cleanly after the current refresh. Alert edges are
echoed to stdout as they happen, so a supervisor-of-supervisors log shows
the pod's incident timeline without opening a file.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llama_pipeline_parallel_tpu.utils.actions import (  # noqa: E402
    ACTIONS_NAME,
)
from llama_pipeline_parallel_tpu.utils.fleet import (  # noqa: E402
    AlertRules,
    FleetAggregator,
    JsonlTailer,
)


class _FleetHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.0"
    server_version = "lpt-fleetd/1"

    def log_message(self, fmt, *args):  # stdlib default spams stderr
        pass

    def _send_json(self, code: int, payload: dict) -> None:
        body = (json.dumps(payload, indent=2) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        status = self.server.fleet_status()  # type: ignore[attr-defined]
        if self.path == "/fleet":
            if status is None:
                return self._send_json(503, {"error": "no refresh yet"})
            return self._send_json(200, status)
        if self.path == "/healthz":
            if status is None:
                return self._send_json(200, {"time": time.time(),
                                             "refresh_count": 0,
                                             "members": 0,
                                             "alerts_firing": []})
            return self._send_json(200, {
                "time": status["time"],
                "refresh_count": status["refresh_count"],
                "members": len(status["members"]),
                "alerts_firing": status["pod"]["alerts_firing"]})
        return self._send_json(404, {"error": f"no route {self.path}"})


def make_server(agg: FleetAggregator, host: str = "127.0.0.1",
                port: int = 0) -> ThreadingHTTPServer:
    """Bound (not yet serving) HTTP server over the aggregator's LATEST
    snapshot — handler threads never touch the aggregator itself (it is
    single-threaded); they read the last refresh under a lock."""
    server = ThreadingHTTPServer((host, port), _FleetHandler)
    server.daemon_threads = True
    lock = threading.Lock()

    def fleet_status():
        with lock:
            return agg.last_status

    server.fleet_status = fleet_status  # type: ignore[attr-defined]
    server.status_lock = lock           # type: ignore[attr-defined]
    return server


def _parse_alerts(spec: str | None) -> AlertRules:
    if not spec:
        return AlertRules()
    raw = spec.strip()
    if raw.startswith("@"):
        with open(raw[1:]) as f:
            node = json.load(f)
    else:
        node = json.loads(raw)
    return AlertRules.from_cfg(node)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--fleet-root", required=True,
                   help="the registry/status/alerts home every supervisor "
                        "was pointed at with its own --fleet-root")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 picks an ephemeral port (printed at startup)")
    p.add_argument("--refresh-s", type=float, default=2.0,
                   help="aggregation cadence (each refresh is incremental)")
    p.add_argument("--alerts", default=None,
                   help="alert thresholds: inline JSON or @/path/to/"
                        "alerts.json with alerts.* keys "
                        "(docs/OBSERVABILITY.md 'Fleet')")
    p.add_argument("--no-capture", action="store_true",
                   help="evaluate alerts but never drop capture.trigger "
                        "files into member dirs")
    p.add_argument("--once", action="store_true",
                   help="one refresh, print the status JSON, exit")
    args = p.parse_args(argv)

    try:
        rules = _parse_alerts(args.alerts)
    except (OSError, ValueError) as e:
        raise SystemExit(f"fleetd: bad --alerts: {e}")
    agg = FleetAggregator(args.fleet_root, rules,
                          capture_on_alert=not args.no_capture)

    if args.once:
        status = agg.refresh()
        print(json.dumps(status, indent=2))
        return 0

    server = make_server(agg, args.host, args.port)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="fleetd-http").start()
    print(f"[fleetd] watching {args.fleet_root} — GET http://{args.host}:"
          f"{port}/fleet every {args.refresh_s:.1f}s", flush=True)

    stop = threading.Event()

    def _stop(signum, _frame):
        print(f"[fleetd] signal {signum}: exiting after this refresh",
              flush=True)
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _stop)
        except ValueError:  # not the main thread (in-process tests)
            pass

    # tools/fleetctl.py's action journal, echoed next to the alert edges
    # that caused the actions — the pod's incident AND response timeline in
    # one log. No actuator running -> no file -> poll() is a no-op.
    actions_tail = JsonlTailer(os.path.join(args.fleet_root, ACTIONS_NAME))
    try:
        while not stop.is_set():
            with server.status_lock:  # type: ignore[attr-defined]
                status = agg.refresh()
            for edge in status["alert_edges_last_refresh"]:
                print(f"[fleetd] alert {edge['state'].upper()}: "
                      f"{edge['alert']} on {edge['member']} "
                      f"(value={edge['value']} threshold={edge['threshold']})",
                      flush=True)
            for row in actions_tail.poll():
                if row.get("phase") == "intent":
                    print(f"[fleetd] action INTENT: {row.get('kind')} "
                          f"{row.get('id')} params={row.get('params')}"
                          + (f" alert={row['alert']}"
                             if row.get("alert") else ""), flush=True)
                elif row.get("phase") == "outcome":
                    print(f"[fleetd] action "
                          f"{str(row.get('outcome', '?')).upper()}: "
                          f"{row.get('kind')} {row.get('id')}", flush=True)
            stop.wait(args.refresh_s)
    finally:
        server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Generate text from a native checkpoint (KV-cache batched decoding).

The reference has no predict/generate path at all (its `prediction_cfg`
names an absent evaluator class, reference conf yaml:107-115; SURVEY.md
§2.4). This tool closes that hole:

    python tools/generate.py --checkpoint_dir /ckpts/run1 \
        --prompt "Once upon a time" --prompt "def main():" \
        --max_new_tokens 64 --temperature 0.8 --top_k 40 --top_p 0.95

Prompts are left-padded into one batch and decoded in a single jitted
`lax.scan` loop (models/llama/decode.py). The pad target is a BUCKET
length (--bucket_sizes, smallest bucket holding the longest prompt), not
the longest prompt itself: `generate` compiles per `[b, P]` shape, so
without bucketing every distinct prompt length pays a fresh XLA compile —
left padding is invisible to the model (positions/kv masks absorb it), so
the extra pad columns only cost prefill FLOPs. A run summary with
tokens/s goes to stderr (stdout stays the decoded text).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048)


def bucket_length(longest: int, buckets=DEFAULT_BUCKETS) -> int:
    """Smallest bucket >= the longest prompt; a prompt past the last bucket
    falls back to its own length (correct, but compiles per shape)."""
    for b in sorted(buckets):
        if b >= longest:
            return b
    return longest


def run(args: argparse.Namespace) -> list[str]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from transformers import AutoTokenizer

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import load_module_checkpoint
    from llama_pipeline_parallel_tpu.data.tokenization import expand_special_tokenizer
    from llama_pipeline_parallel_tpu.models.llama.decode import (
        GenerationConfig,
        generate,
    )

    params, cfg, _, _ = load_module_checkpoint(args.checkpoint_dir, args.step)

    tok_path = args.tokenizer_path or args.checkpoint_dir
    tokenizer = AutoTokenizer.from_pretrained(tok_path)
    added = expand_special_tokenizer(tokenizer)
    # This tool cannot resize the checkpoint's embeddings: any id at or past
    # the model vocab would gather garbage silently (JAX clamps OOB indices).
    if added > 0 or len(tokenizer) > cfg.vocab_size:
        raise ValueError(
            f"tokenizer has {len(tokenizer)} tokens ({added} just added) but "
            f"the checkpoint's vocab is {cfg.vocab_size}; re-convert with "
            f"tools/convert_hf.py (vocab expansion is its default) so the "
            f"embeddings match")

    tokenizer.padding_side = "left"
    if tokenizer.pad_token is None:  # max_length padding needs a pad token
        tokenizer.pad_token = tokenizer.eos_token or tokenizer.unk_token
    lengths = [len(ids) for ids in tokenizer(list(args.prompt))["input_ids"]]
    bucket_arg = getattr(args, "bucket_sizes", None)  # optional for callers
    buckets = (tuple(int(b) for b in bucket_arg.split(","))
               if bucket_arg else DEFAULT_BUCKETS)
    bucket = bucket_length(max(lengths), buckets)
    enc = tokenizer(list(args.prompt), return_tensors="np",
                    padding="max_length", max_length=bucket, truncation=False)
    gen = GenerationConfig(
        max_new_tokens=args.max_new_tokens, temperature=args.temperature,
        top_k=args.top_k, top_p=getattr(args, "top_p", 1.0),
        eos_token_id=tokenizer.eos_token_id,
        pad_token_id=tokenizer.pad_token_id or 0)
    t0 = time.perf_counter()
    out = generate(params, jnp.asarray(enc["input_ids"], jnp.int32),
                   jnp.asarray(enc["attention_mask"], jnp.int32), cfg, gen,
                   rng=jax.random.PRNGKey(args.seed))
    n_tokens = int(np.asarray(out["tokens"]).size)  # blocks on the result
    dt = time.perf_counter() - t0
    print(f"[generate] {len(lengths)} prompt(s) (longest {max(lengths)}) "
          f"padded to bucket {bucket}; {n_tokens} tokens in {dt:.2f}s = "
          f"{n_tokens / max(dt, 1e-9):.1f} tok/s (first call includes "
          f"compile; rerun at any prompt length <= {bucket} reuses it)",
          file=sys.stderr, flush=True)

    texts = []
    for row in np.asarray(out["tokens"]):
        ids = row.tolist()
        if gen.eos_token_id is not None and gen.eos_token_id in ids:
            ids = ids[:ids.index(gen.eos_token_id)]  # truncate at FIRST eos
        texts.append(tokenizer.decode(ids, skip_special_tokens=True))
    return texts


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. 'cpu'); default: the "
                        "image's platform (TPU when available)")
    p.add_argument("--checkpoint_dir", required=True)
    p.add_argument("--tokenizer_path", default=None,
                   help="defaults to checkpoint_dir (convert_hf.py places "
                        "tokenizer files there)")
    p.add_argument("--step", type=int, default=None)
    p.add_argument("--prompt", action="append", required=True,
                   help="repeatable; prompts batch together")
    p.add_argument("--max_new_tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top_k", type=int, default=0)
    p.add_argument("--top_p", type=float, default=1.0,
                   help="nucleus sampling mass (1.0 disables)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--bucket_sizes", default=None,
                   help="comma-separated ascending prompt pad buckets "
                        f"(default {','.join(map(str, DEFAULT_BUCKETS))}); "
                        "distinct buckets, not distinct prompt lengths, "
                        "determine recompiles")
    args = p.parse_args(argv)
    if args.platform:
        import jax

        # env JAX_PLATFORMS is not enough on images whose sitecustomize
        # force-registers an accelerator platform; re-pin via config.
        jax.config.update("jax_platforms", args.platform)
    for prompt, text in zip(args.prompt, run(args)):
        print(f"=== {prompt!r}\n{text}\n")


if __name__ == "__main__":
    main()

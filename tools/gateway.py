"""Run the request-durable gateway tier in front of N serve replicas.

The supervised-process wrapper over serve/gateway.py (docs/SERVING.md
"Gateway & failover"): discovers replicas live from the PR 15 fleet
registry (role="serve" rows) and/or explicitly named output dirs,
journals every accepted request to `<output_dir>/gateway_journal.jsonl`
before dispatch, reconciles orphaned intents left by a previous
incarnation at startup, and serves:

  POST /v1/generate   the serve front-end's API, routed + durable:
                      health-aware replica choice, bounded retry with
                      Retry-After honored, bit-exact replay + stream
                      splice when a replica dies mid-request, optional
                      hedged dispatch (--hedge).
  GET  /healthz       gateway gauges (telemetry.GATEWAY_COUNTER_KEYS) +
                      per-replica routing state.
  GET  /replicas      the routing table alone.

Telemetry follows the serve replica's shape: `gateway.json` (atomic;
pid/host/port/started), a health.json heartbeat (role="gateway"), and
periodic metrics.jsonl lines marked `"gateway": 1` the fleet aggregator
rolls up (utils/fleet._GATEWAY_FIELDS). SIGTERM drains: new submits shed
with 503 + Retry-After while in-flight requests finish.

Example:

  python tools/gateway.py --output_dir /tmp/gw \\
      --fleet_root /tmp/fleet --port 8100 --hedge auto
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from llama_pipeline_parallel_tpu.serve.gateway import (  # noqa: E402
    GATEWAY_JSON_NAME,
    Gateway,
    ReplicaDirectory,
    make_gateway_server,
)
from llama_pipeline_parallel_tpu.utils import fleet, trace  # noqa: E402
from llama_pipeline_parallel_tpu.utils.metrics import MetricsWriter  # noqa: E402
from llama_pipeline_parallel_tpu.utils.retry import RetryPolicy  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output_dir", required=True,
                   help="gateway home: journal/metrics/health/gateway.json")
    p.add_argument("--fleet_root", default=None,
                   help="fleet registry root; serve members are discovered "
                        "live from its registry.jsonl")
    p.add_argument("--replica_dirs", default=None,
                   help="comma-separated serve output dirs (instead of, or "
                        "in addition to, --fleet_root discovery)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 picks an ephemeral port (recorded in "
                        "gateway.json)")
    p.add_argument("--replica", default=None,
                   help="fleet member name (default: output dir basename)")
    p.add_argument("--stale_s", type=float, default=15.0,
                   help="replica heartbeat age beyond which it is not "
                        "routed to (<=0 disables the check)")
    p.add_argument("--hedge", default="off",
                   help="'off', 'auto' (p95-derived delay), or a fixed "
                        "delay in seconds for the second attempt")
    p.add_argument("--retry_attempts", type=int, default=4,
                   help="total dispatch tries per request (backoffs and "
                        "mid-stream deaths both draw on this budget)")
    p.add_argument("--retry_base_delay_s", type=float, default=0.05)
    p.add_argument("--request_timeout_s", type=float, default=120.0)
    p.add_argument("--watermark_every", type=int, default=8,
                   help="journal a tokens-delivered watermark row every N "
                        "streamed tokens")
    p.add_argument("--no_reconcile", action="store_true",
                   help="skip startup reconciliation of orphaned WAL "
                        "intents (they stay orphaned)")
    p.add_argument("--drain_s", type=float, default=10.0)
    p.add_argument("--health_interval", type=float, default=5.0)
    p.add_argument("--metrics_every_s", type=float, default=2.0)
    args = p.parse_args(argv)

    replica_dirs = tuple(d for d in (args.replica_dirs or "").split(",")
                         if d.strip())
    if not args.fleet_root and not replica_dirs:
        p.error("need --fleet_root and/or --replica_dirs")

    t_start = time.time()
    os.makedirs(args.output_dir, exist_ok=True)
    directory = ReplicaDirectory(fleet_root=args.fleet_root,
                                 replica_dirs=replica_dirs,
                                 stale_s=args.stale_s)
    hedge: str | float = args.hedge
    if hedge not in ("off", "auto"):
        hedge = float(hedge)
    gw = Gateway(
        args.output_dir, directory,
        policy=RetryPolicy.from_env(max_attempts=args.retry_attempts,
                                    base_delay_s=args.retry_base_delay_s,
                                    max_delay_s=5.0),
        hedge=hedge, watermark_every=args.watermark_every,
        request_timeout_s=args.request_timeout_s)

    directory.poll()
    if not args.no_reconcile:
        # a previous incarnation's orphaned intents get their terminal
        # outcome BEFORE new traffic: re-polled from replica traces when
        # the request finished without us, replayed headless otherwise
        reconciled = gw.reconcile()
        if reconciled:
            print(f"[gateway] reconciled {len(reconciled)} orphaned "
                  f"intent(s): "
                  + ", ".join(f"{r['gid']}={r['outcome']}"
                              for r in reconciled), flush=True)

    server = make_gateway_server(gw, args.host, args.port)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="gateway-http").start()

    name = args.replica or os.path.basename(
        os.path.normpath(args.output_dir))
    fleet.write_json_atomic(
        os.path.join(args.output_dir, GATEWAY_JSON_NAME),
        {"pid": os.getpid(), "host": args.host, "port": port,
         "fleet_root": args.fleet_root, "replica_dirs": list(replica_dirs),
         "started": t_start})
    if args.fleet_root:
        fleet.register_member(args.fleet_root, output_dir=args.output_dir,
                              role="gateway", replica=name,
                              pid=os.getpid())
    hb = trace.Heartbeat(args.output_dir, interval=args.health_interval,
                         static={"role": "gateway", "port": port})
    writer = MetricsWriter(args.output_dir)

    stop = threading.Event()

    def _stop(signum, _frame):
        print(f"[gateway] signal {signum}: draining to clean exit",
              flush=True)
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _stop)

    known = len(directory.all())
    print(f"[gateway] ready on {args.host}:{port} — {known} replica(s) "
          f"known, hedge={args.hedge}, "
          f"retry_attempts={args.retry_attempts}", flush=True)

    def metrics_line() -> dict:
        snap = gw.healthz()
        snap.pop("replicas", None)  # nested routing table: /healthz only
        snap.pop("inflight", None)
        return snap

    tick = 0
    try:
        while not stop.is_set():
            directory.poll()
            tick += 1
            hb.beat(tick)
            writer.log(tick, metrics_line())
            stop.wait(max(args.metrics_every_s, 0.1))
        # drain: shed new submits with an honest 503 while in-flight
        # streams (and their replays) finish
        gw.draining = True
        deadline = time.monotonic() + args.drain_s
        while (gw.stats.snapshot().get("inflight_total", 0)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        left = gw.stats.snapshot().get("inflight_total", 0)
        if left:
            print(f"[gateway] drain window ({args.drain_s:.0f}s) expired "
                  f"with {left} dispatch(es) in flight", flush=True)
    finally:
        server.shutdown()
        writer.log(tick + 1, metrics_line())
        writer.close()
        gw.close()
        hb.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

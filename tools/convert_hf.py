#!/usr/bin/env python
"""Offline HF -> native checkpoint converter.

TPU-native replacement for the reference's `convert2ckpt.py` (whole file):
loads an HF LLaMA, optionally expands vocab for added special tokens
(reference convert2ckpt.py:60-63), and writes a module-only checkpoint in the
canonical Orbax layout plus tokenizer/config alongside (reference :79-80),
with a `latest` tag (reference :76-77).

Usage:
    python tools/convert_hf.py --model_name_or_path <hf-dir> --output_dir <dir>
"""

from __future__ import annotations

import argparse
import os
import sys

# invocable as a script from anywhere: the package lives next to tools/
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def convert(model_name_or_path: str, output_dir: str, expand_vocab: bool = True) -> None:
    import jax.numpy as jnp
    from transformers import AutoTokenizer, LlamaForCausalLM

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.data.tokenization import expand_special_tokenizer
    from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
    from llama_pipeline_parallel_tpu.models.llama.hf import params_from_hf_state_dict
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.parallel.pipeline import stack_stages

    try:
        tokenizer = AutoTokenizer.from_pretrained(model_name_or_path)
    except Exception as e:  # weights-only model dirs have no tokenizer files
        print(f"warning: no loadable tokenizer at {model_name_or_path} ({e!r}); "
              f"skipping vocab expansion and tokenizer export", file=sys.stderr)
        tokenizer = None
    model = LlamaForCausalLM.from_pretrained(model_name_or_path)
    if expand_vocab and tokenizer is not None:
        num_added = expand_special_tokenizer(tokenizer)
        if num_added:
            model.resize_token_embeddings(len(tokenizer))

    cfg = LlamaConfig.from_hf_config(model.config, dtype=jnp.bfloat16)
    params = params_from_hf_state_dict(model.state_dict(), cfg)
    # Canonical layout is PP-agnostic; save through the trivial 1-stage manifest.
    manifest = StageManifest(num_layers=cfg.num_hidden_layers, num_stages=1)
    mgr = CheckpointManager(output_dir)
    path = mgr.save(step=0, params_stacked=stack_stages(params, manifest),
                    manifest=manifest, cfg=cfg, opt_state=None)
    if tokenizer is not None:
        tokenizer.save_pretrained(output_dir)
    model.config.save_pretrained(output_dir)
    print(f"wrote module-only checkpoint to {path}")


def main(argv: list[str] | None = None) -> None:
    # standalone CLI: conversion is host-side work — never wait on accelerators
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model_name_or_path", required=True)
    p.add_argument("--output_dir", required=True)
    p.add_argument("--no_expand_vocab", action="store_true",
                   help="skip special-token vocab expansion")
    args = p.parse_args(argv)
    convert(args.model_name_or_path, args.output_dir, expand_vocab=not args.no_expand_vocab)


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Continuous-batching inference service over a native checkpoint.

The serving counterpart of train.py (docs/SERVING.md): loads a checkpoint
(the train->serve handoff — any training checkpoint's canonical layout
loads straight into the decode stack via `load_module_checkpoint`), builds
a `serve.ServeEngine`, exposes the JSON HTTP endpoint, and emits the SAME
run telemetry as a trainer — spans.jsonl (TTFT/TPOT/queue-wait per
request), metrics.jsonl (serving SLO percentile lines), and the
health.json heartbeat — so `tools/supervisor.py` supervises a serving
replica with zero changes and `tools/goodput_report.py` /
`tools/serving_report.py` read its run directory like any other.

    python tools/serve.py --checkpoint_dir /ckpts/run1 \
        --output_dir /runs/serve1 --port 8000 --max_slots 8 --max_len 2048

Multi-replica serving is N supervisors each watching one of these
processes from a shared checkpoint:

    python tools/supervisor.py --output-dir /runs/serve1 -- \
        python tools/serve.py --checkpoint_dir /ckpts/run1 \
            --output_dir /runs/serve1 --port 8000

The engine loop runs on the MAIN thread (serve_prefill/serve_decode_step
spans feed the RunClock's `serve` bucket — goodput for a serve process is
the fraction of wall-clock spent producing tokens); HTTP handler threads
only block on request handles. SIGTERM/SIGINT stop ADMISSIONS, drain
in-flight and queued requests for up to --drain_s (size it inside the
supervisor's --grace-s), then exit 0 — the preemption contract: a routine
stop must not 500 the requests already decoding.

`serve.json` in the output dir records the bound port + pid atomically, so
clients (and the multi-replica chaos test) can find a restarted replica.
LPT_SERVE_STEP_DELAY_S stretches every decode step (chaos hook: gives the
kill-mid-decode test a deterministic window; never set it in production).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def write_serve_json(output_dir: str, payload: dict) -> None:
    """Atomic `serve.json` rewrite: a polling client never reads a torn
    file. Reuses the checkpoint layer's crash-safe writer (tmp + fsync +
    os.replace under the storage retry policy) instead of a third
    hand-rolled copy."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import _write_file_atomic

    _write_file_atomic(os.path.join(output_dir, "serve.json"),
                       json.dumps(payload, indent=2))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. 'cpu'); default: the "
                        "image's platform (TPU when available)")
    p.add_argument("--checkpoint_dir", required=True)
    p.add_argument("--step", type=int, default=None)
    p.add_argument("--output_dir", required=True,
                   help="telemetry home: spans/metrics/health/serve.json")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 picks an ephemeral port (recorded in serve.json)")
    p.add_argument("--max_slots", type=int, default=8)
    p.add_argument("--max_len", type=int, default=2048,
                   help="per-slot KV capacity (prompt bucket + new tokens)")
    p.add_argument("--buckets", default="64,128,256,512,1024",
                   help="ascending prompt bucket lengths (one prefill "
                        "compile each)")
    p.add_argument("--max_queue", type=int, default=64)
    p.add_argument("--kv_cache", default="dense", choices=("dense", "paged"),
                   help="paged: fixed-size KV pages + slot->page table — "
                        "HBM tracks tokens actually generated "
                        "(docs/SERVING.md)")
    p.add_argument("--page_size", type=int, default=64,
                   help="tokens per KV page (paged only)")
    p.add_argument("--num_pages", type=int, default=None,
                   help="page-pool size; default = dense-equivalent "
                        "(max_slots * max_len / page_size)")
    p.add_argument("--kv_quant", default="fp", choices=("fp", "int8"),
                   help="int8: quantized KV pages with per-page scales, "
                        "fp32 dequant on read (paged only)")
    p.add_argument("--prefill_chunk_tokens", type=int, default=0,
                   help="per-tick prefill token budget; buckets above it "
                        "prefill in chunks interleaved with decode ticks "
                        "(paged only; 0 = whole-prompt admissions)")
    p.add_argument("--prefix_cache", action="store_true",
                   help="share physical KV pages between requests with "
                        "identical prompt prefixes: cache hits skip the "
                        "shared span's prefill and reserve only their new "
                        "pages (paged only; docs/SERVING.md 'Prefix "
                        "caching')")
    p.add_argument("--metrics_every", type=int, default=16,
                   help="completed requests per serving metrics line")
    p.add_argument("--health_interval", type=float, default=10.0,
                   help="health.json heartbeat cadence in seconds (size "
                        "fleet heartbeat_stale_s alerts above this)")
    p.add_argument("--idle_poll_s", type=float, default=0.02)
    p.add_argument("--drain_s", type=float, default=15.0,
                   help="after SIGTERM/SIGINT: seconds to finish in-flight "
                        "and queued requests before failing the remainder "
                        "(keep below the supervisor's --grace-s)")
    p.add_argument("--timeline", action="store_true",
                   help="write a per-tick timeline.jsonl (prefill-chunk vs "
                        "decode-step wall split — the serving half of the "
                        "schedule observatory, docs/OBSERVABILITY.md "
                        "'Timelines')")
    p.add_argument("--slo_ttft_ms", type=float, default=None,
                   help="TTFT SLO in ms: breaches count on the metrics "
                        "line and fire a bounded profiler capture under "
                        "<output_dir>/captures (docs/OBSERVABILITY.md "
                        "'Triggered capture')")
    p.add_argument("--slo_queue_wait_ms", type=float, default=None,
                   help="queue-wait SLO in ms (same breach handling)")
    p.add_argument("--capture_max", type=int, default=3,
                   help="retention cap for SLO-breach profiler captures")
    p.add_argument("--request_trace", action="store_true",
                   help="per-request span trees to request_trace.jsonl "
                        "(one line per completed/shed request) + the "
                        "slowest-K exemplar snapshot — the request "
                        "observatory (docs/SERVING.md 'Request tracing'); "
                        "off by default: OFF adds no per-token cost")
    p.add_argument("--trace_exemplars", type=int, default=8,
                   help="slowest-K requests kept with full span trees in "
                        "request_trace_exemplars.json (--request_trace)")
    args = p.parse_args(argv)

    if args.platform:
        import jax

        # env JAX_PLATFORMS is not enough on images whose sitecustomize
        # force-registers an accelerator platform; re-pin via config.
        jax.config.update("jax_platforms", args.platform)

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import (
        load_module_checkpoint,
    )
    from llama_pipeline_parallel_tpu.serve import (
        ServeConfig,
        ServeEngine,
    )
    from llama_pipeline_parallel_tpu.serve.frontend import make_server
    from llama_pipeline_parallel_tpu.utils import trace
    from llama_pipeline_parallel_tpu.utils.metrics import MetricsWriter

    t_start = time.time()
    os.makedirs(args.output_dir, exist_ok=True)
    trace.configure(args.output_dir)
    clock = trace.RunClock(prior=trace.load_health(args.output_dir))
    trace.recorder().add_listener(clock.on_span)

    params, cfg, manifest, step = load_module_checkpoint(
        args.checkpoint_dir, args.step)
    serve_cfg = ServeConfig(
        max_slots=args.max_slots, max_len=args.max_len,
        prompt_buckets=tuple(int(b) for b in args.buckets.split(",")),
        max_queue=args.max_queue, metrics_every=args.metrics_every,
        kv_cache=args.kv_cache, page_size=args.page_size,
        num_pages=args.num_pages, kv_quant=args.kv_quant,
        prefill_chunk_tokens=args.prefill_chunk_tokens,
        prefix_cache=args.prefix_cache)
    writer = MetricsWriter(args.output_dir)
    tl_writer = None
    if args.timeline:
        from llama_pipeline_parallel_tpu.utils.timeline import TimelineWriter

        tl_writer = TimelineWriter(
            os.path.join(args.output_dir, "timeline.jsonl"))
    from llama_pipeline_parallel_tpu.utils.profiler import (
        CaptureConfig,
        TriggeredProfiler,
    )

    # the profiler is ALWAYS armed: without SLO thresholds it captures
    # nothing on its own, but its capture.trigger poll is what lets a
    # fleet-level alert (tools/fleetd.py) reach into this replica for a
    # bounded trace (docs/OBSERVABILITY.md "Fleet")
    prof = TriggeredProfiler(
        CaptureConfig(zscore=0.0, max_captures=args.capture_max,
                      window_steps=8),
        args.output_dir)
    slo = None
    if args.slo_ttft_ms is not None or args.slo_queue_wait_ms is not None:
        from llama_pipeline_parallel_tpu.serve.telemetry import SLOThresholds

        slo = SLOThresholds(
            ttft_s=(args.slo_ttft_ms / 1000.0
                    if args.slo_ttft_ms is not None else None),
            queue_wait_s=(args.slo_queue_wait_ms / 1000.0
                          if args.slo_queue_wait_ms is not None else None))
    reqtrace_rec = None
    if args.request_trace:
        from llama_pipeline_parallel_tpu.serve.reqtrace import (
            RequestTraceRecorder,
        )

        reqtrace_rec = RequestTraceRecorder(
            args.output_dir, exemplar_k=args.trace_exemplars)
    engine = ServeEngine(params, cfg, serve_cfg, metrics_writer=writer,
                         timeline=tl_writer, profiler=prof, slo=slo,
                         reqtrace=reqtrace_rec)

    server = make_server(engine, args.host, args.port)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="serve-http").start()
    write_serve_json(args.output_dir, {
        "pid": os.getpid(), "host": args.host, "port": port,
        "checkpoint_dir": args.checkpoint_dir, "checkpoint_step": step,
        "kv_cache": serve_cfg.kv_cache, "started": t_start})

    # init window accounted like the trainer's: everything before the loop
    trace.recorder().emit("init", ts=t_start, dur=time.time() - t_start)
    hb_serve_cfg = {"max_slots": serve_cfg.max_slots,
                    "max_len": serve_cfg.max_len,
                    "prompt_buckets": list(serve_cfg.prompt_buckets),
                    "kv_cache": serve_cfg.kv_cache}
    if serve_cfg.kv_cache == "paged":
        hb_serve_cfg.update(
            page_size=serve_cfg.page_size,
            num_pages=serve_cfg.resolved_num_pages,
            kv_quant=serve_cfg.kv_quant,
            prefill_chunk_tokens=serve_cfg.prefill_chunk_tokens,
            prefix_cache=serve_cfg.prefix_cache)
    hb = trace.Heartbeat(
        args.output_dir, clock, interval=args.health_interval,
        static={"role": "serve", "port": port,
                "checkpoint_step": step,
                "serve_config": hb_serve_cfg})

    stop = threading.Event()

    def _stop(signum, _frame):
        print(f"[serve] signal {signum}: draining to clean exit", flush=True)
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _stop)

    step_delay = float(os.environ.get("LPT_SERVE_STEP_DELAY_S", "0") or 0)
    kv_desc = f"{serve_cfg.max_slots} slots x {serve_cfg.max_len} kv"
    if serve_cfg.kv_cache == "paged":
        kv_desc = (f"{serve_cfg.max_slots} slots over "
                   f"{serve_cfg.resolved_num_pages} x "
                   f"{serve_cfg.page_size}-token {serve_cfg.kv_quant} pages"
                   + (f", prefill chunk {serve_cfg.prefill_chunk_tokens}"
                      if serve_cfg.prefill_chunk_tokens else "")
                   + (", prefix cache" if serve_cfg.prefix_cache else ""))
    print(f"[serve] ready on {args.host}:{port} — checkpoint step {step}, "
          f"{kv_desc}, buckets {serve_cfg.prompt_buckets}", flush=True)
    try:
        while not stop.is_set():
            did_work = engine.step()
            if did_work:
                hb.beat(engine.steps)
                if step_delay:
                    time.sleep(step_delay)
            else:
                # an idle replica must still honor a fleet capture trigger
                # AND advance an open capture window (the engine only does
                # either inside work ticks — without this, an idle-started
                # capture would trace nothing, unbounded, until traffic)
                prof.observe_step(engine.steps)
                engine._work.wait(args.idle_poll_s)
        # graceful drain: HTTP stays UP but every new submit sheds with a
        # coherent 429 + honest Retry-After (degraded-mode admission,
        # docs/RESILIENCE.md "Actuation") while in-flight and queued
        # requests finish — the stop contract; whatever outlives the
        # window is failed by engine.shutdown() below
        engine.set_degraded("draining")
        deadline = time.monotonic() + args.drain_s
        while ((engine.slots.active_count or engine.queue_depth())
               and time.monotonic() < deadline):
            if engine.step():
                hb.beat(engine.steps)
            else:  # unreachable in practice; never busy-spin the drain
                time.sleep(0.01)
        if engine.slots.active_count or engine.queue_depth():
            print(f"[serve] drain window ({args.drain_s:.0f}s) expired with "
                  f"{engine.slots.active_count} active / "
                  f"{engine.queue_depth()} queued; failing them", flush=True)
    finally:
        server.shutdown()
        engine.shutdown()
        snap = engine.metrics_snapshot()
        if engine.stats.completed:
            writer.log(engine.stats.completed, snap)
            # the serve loop's perf-ledger contribution: measured SLO
            # latencies (no analytic halves yet — the pairing the serving
            # cost models of a future PR will fill in)
            from llama_pipeline_parallel_tpu.utils import perf

            perf.append_rows(
                os.path.join(args.output_dir, "perf.jsonl"),
                [perf.make_row(f"serve:{k}", measured=snap[k], unit="ms",
                               source="serve", run=args.output_dir)
                 for k in ("ttft_p50_ms", "ttft_p95_ms", "tpot_p50_ms",
                           "queue_wait_p95_ms") if k in snap])
        writer.close()
        if tl_writer is not None:
            tl_writer.close()
        if reqtrace_rec is not None:
            reqtrace_rec.close()
        hb.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

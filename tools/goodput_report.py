"""Per-run goodput/badput report from a trainer output dir.

Joins the span stream (`spans.jsonl`, written by utils/trace.py) with the
scalar stream (`metrics.jsonl`) into the operational picture a TPU run
lives or dies on: where wall-clock went (time-bucket table), which logging
windows were slowest, and how bad the input-pipeline stalls were — offline,
after the run, no profiler capture needed (the Perfetto window covers a few
steps; the span stream covers the whole run).

Usage:
  python tools/goodput_report.py <output_dir> [--top 5] [--json]

Follows tools/trace_summary.py's track-summary conventions: one `== section ==`
per table, durations in ms/s with percentages against the section total.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_jsonl(path: str) -> list[dict]:
    """THE tolerant jsonl reader — `perf.read_jsonl`, spelled once for the
    whole repo: a crashed writer (the whole reason this tool exists) can
    leave a truncated tail or garbage line in any run artifact, and every
    reader must degrade to whatever parses, never traceback."""
    from llama_pipeline_parallel_tpu.utils.perf import read_jsonl

    return read_jsonl(path)


def wall_window(spans: list[dict]) -> tuple[float, float]:
    """(first span start, last span end) over MAIN-THREAD spans — the run's
    observed wall window. The trainer emits a retroactive `init` span from
    trace.configure(), so the window opens at run start; background spans
    (async checkpoint commits) are excluded, matching the bucket rules."""
    main = [s for s in spans if s.get("main_thread", True)]
    if not main:
        raise SystemExit("no spans to report on")
    return (min(s["ts"] for s in main),
            max(s.get("end", s["ts"] + s.get("dur", 0.0)) for s in main))


def bucket_table(spans: list[dict]) -> dict[str, float]:
    """Seconds per RunClock bucket, recomputed from the span stream with the
    SAME rules the live clock applies (top-level, main-thread spans only —
    utils/trace.SPAN_BUCKETS), plus `untracked` as the wall remainder, so
    the table's sum IS the run's wall-clock."""
    from llama_pipeline_parallel_tpu.utils.trace import SPAN_BUCKETS

    t0, t1 = wall_window(spans)
    buckets: dict[str, float] = {}
    for s in spans:
        if s.get("depth") != 0 or not s.get("main_thread", True):
            continue
        bucket = SPAN_BUCKETS.get(s["name"])
        if bucket is not None:
            buckets[bucket] = buckets.get(bucket, 0.0) + s["dur"]
    buckets["untracked"] = max((t1 - t0) - sum(buckets.values()), 0.0)
    return buckets


def slowest_windows(spans: list[dict], metrics: list[dict], top: int
                    ) -> list[dict]:
    """Logging windows ranked by per-step wall time: `device_step` spans
    (one per boundary; `steps` counts the window's steps) joined with the
    metrics line logged at the same step for loss/goodput context."""
    # a step can carry several lines (the train scalars, then an eval_loss
    # line at the same boundary) — merge them so neither shadows the other
    by_step: dict = {}
    for m in metrics:
        by_step.setdefault(m.get("step"), {}).update(m)
    windows = []
    for s in spans:
        if s["name"] != "device_step":
            continue
        steps = max(int(s.get("steps", 1)), 1)
        m = by_step.get(s.get("step"), {})
        windows.append({
            "step": s.get("step"),
            "steps": steps,
            "block_s": s["dur"],
            "per_step_s": s["dur"] / steps,
            "step_time": m.get("step_time"),
            "loss": m.get("loss"),
        })
    windows.sort(key=lambda w: -(w["step_time"] or w["per_step_s"]))
    return windows[:top]


def stall_histogram(spans: list[dict], name: str = "data_wait"
                    ) -> list[tuple[str, int, float]]:
    """(label, count, total seconds) per duration decade for one span name.
    `data_wait` and the nested `prefetch_stall` are histogrammed SEPARATELY —
    a prefetch stall happens inside its data_wait, so summing both would
    double-count the stalled seconds."""
    edges = [(0.001, "<1ms"), (0.01, "1-10ms"), (0.1, "10-100ms"),
             (1.0, "0.1-1s"), (float("inf"), ">=1s")]
    hist = {label: [0, 0.0] for _, label in edges}
    for s in spans:
        if s["name"] != name:
            continue
        for hi, label in edges:
            if s["dur"] < hi:
                hist[label][0] += 1
                hist[label][1] += s["dur"]
                break
    return [(label, n, total) for label, (n, total) in hist.items()]


def _num(value) -> float | None:
    """A float, or None for anything a half-written file might hold."""
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


def load_health(output_dir: str) -> tuple[dict, str]:
    """(health dict, status) where status is ok|missing|corrupt. The report
    must DEGRADE on a missing or partially-written/garbage health.json (a
    crashed run is exactly when this tool gets pointed at a dir), never
    traceback — non-dict JSON ("null", a list) counts as corrupt too."""
    path = os.path.join(output_dir, "health.json")
    if not os.path.exists(path):
        return {}, "missing"
    try:
        with open(path) as f:
            health = json.load(f)
    except (OSError, ValueError):
        return {}, "corrupt"
    if not isinstance(health, dict):
        return {}, "corrupt"
    return health, "ok"


def _incarnation_label(row: dict) -> str | None:
    """Topology label for one ledger row: the trainer's own health.json
    topology when it ran long enough to write one, else the supervisor's
    ladder-rung label, else None (an inelastic pre-elastic ledger)."""
    topo = row.get("trainer_topology")
    if isinstance(topo, dict) and topo.get("layout"):
        return topo["layout"]
    return row.get("layout")


def incarnation_summary(output_dir: str) -> dict | None:
    """Roll-up of the supervisor's goodput ledger (incarnations.jsonl, one
    row per launch — tools/supervisor.py), or None when the run was never
    supervised. Restart badput = wall seconds spent in incarnations that
    did not end cleanly; `resize_lost_seconds` is a SIBLING bucket — the
    failed-incarnation time that forced each topology resize plus the
    probe/relaunch gap before the resized launch (the gap is wall-clock
    lost_seconds never counts) — so elastic downgrades are visible next to
    plain restarts."""
    rows = load_jsonl(os.path.join(output_dir, "incarnations.jsonl"))
    rows = [r for r in rows if isinstance(r, dict)]
    if not rows:
        return None
    # supervisor_stopped = the supervisor itself was preempted and the child
    # checkpointed + exited cleanly — productive time, not restart badput
    failed = [r for r in rows
              if r.get("outcome") not in ("clean", "supervisor_stopped", None)]
    resize_lost = 0.0
    resizes = 0
    for prev, cur in zip(rows, rows[1:]):
        if not cur.get("resized"):
            continue
        resizes += 1
        # the failed incarnation that forced this resize, plus the
        # probe/relaunch gap before the resized one came up
        if prev in failed:
            resize_lost += _num(prev.get("duration_s")) or 0.0
        start, end = _num(cur.get("start")), _num(prev.get("end"))
        if start is not None and end is not None:
            resize_lost += max(start - end, 0.0)
    return {
        "incarnations": len(rows),
        "restarts": max(len(rows) - 1, 0),
        "crashes": sum(1 for r in failed if r.get("outcome") == "crash"),
        "hangs": sum(1 for r in failed if r.get("outcome") == "hang"),
        # the supervisor labels an allocation-failure death distinctly
        # (crash + fresh oom/ snapshot — tools/supervisor.py): a capacity
        # problem every relaunch will hit again, unlike a transient crash
        "ooms": sum(1 for r in failed if r.get("outcome") == "oom"),
        "lost_seconds": sum(_num(r.get("duration_s")) or 0.0 for r in failed),
        "resize_events": resizes,
        "resize_lost_seconds": round(resize_lost, 3),
        "last_outcome": rows[-1].get("outcome"),
        "layouts": [{"incarnation": r.get("incarnation"),
                     "outcome": r.get("outcome"),
                     "layout": _incarnation_label(r),
                     "devices": r.get("devices"),
                     "resized": bool(r.get("resized"))} for r in rows],
    }


def supervisor_summary(output_dir: str) -> dict | None:
    """Roll-up of the watchdog's OWN heartbeat (supervisor_health.json,
    tools/supervisor.py), or None when the run is unsupervised — so the
    report labels the directory's supervisor distinctly instead of
    treating every health file as the trainer's."""
    import time

    path = os.path.join(output_dir, "supervisor_health.json")
    try:
        with open(path) as f:
            health = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(health, dict):
        return None
    age = None
    t = _num(health.get("time"))
    if t is not None:
        age = round(time.time() - t, 1)
    return {"pid": health.get("pid"),
            "heartbeat_age_s": age,
            "restarts": health.get("restarts"),
            "consecutive_failures": health.get("consecutive_failures"),
            "last_outcome": health.get("last_outcome"),
            "child_pid": health.get("child_pid"),
            "layout": health.get("layout")}


# the p95-ish latency + capacity gauges this report shows NEXT to the
# shared counter set (telemetry.SERVE_COUNTER_KEYS, the one spelling)
_SERVE_GAUGE_KEYS = ("ttft_p95_ms", "tpot_p50_ms", "queue_wait_p95_ms",
                     "pages_used", "pages_free", "pages_reserved",
                     "reserved_unbacked", "page_fragmentation",
                     "reserved_gap_bytes", "prefilling",
                     "prefill_chunks_total", "prefill_tokens_total")


def serve_counter_summary(metrics: list[dict]) -> dict | None:
    """Last serving metrics line's counter/gauge picture, or None for a
    run that never served."""
    from llama_pipeline_parallel_tpu.serve.telemetry import SERVE_COUNTER_KEYS

    serving = [m for m in metrics if isinstance(m, dict) and m.get("serving")]
    if not serving:
        return None
    last = serving[-1]
    return {k: last[k] for k in SERVE_COUNTER_KEYS + _SERVE_GAUGE_KEYS
            if k in last}


def oom_summary(output_dir: str, top: int = 5) -> dict | None:
    """Roll-up of the OOM forensics snapshots (`<output_dir>/oom/`, written
    by the trainer's allocation-failure handler — utils/memwatch.py), or
    None when the run never OOMed. Tolerant like every other reader: a
    torn/garbage snapshot contributes nothing, the parseable rest still
    reports."""
    from llama_pipeline_parallel_tpu.utils import memwatch

    snaps = memwatch.read_oom_snapshots(output_dir)
    if not snaps:
        return None
    out = {"snapshots": len(snaps), "events": []}
    for s in snaps[:top]:
        live = s.get("live") if isinstance(s.get("live"), dict) else {}
        peak = _num(live.get("device_peak_bytes"))
        out["events"].append({"step": s.get("step"),
                              "time": s.get("time"),
                              "error": str(s.get("error", ""))[:160],
                              "device_peak_gib": (round(peak / (1 << 30), 2)
                                                  if peak is not None
                                                  else None)})
    return out


def numerics_summary(output_dir: str, top: int = 5) -> dict | None:
    """Roll-up of the numerics observatory's stream (numerics.jsonl, one row
    per step — utils/numerics.py), or None when the run had numerics off.
    Folds the anomaly timeline into the run report so a goodput dip can be
    read next to the loss spike / nonfinite step that caused the restart."""
    rows = [r for r in load_jsonl(os.path.join(output_dir, "numerics.jsonl"))
            if isinstance(r, dict) and "step" in r]
    if not rows:
        return None
    # last record per step: resumes re-run steps past their checkpoint and
    # append a second record — only the surviving timeline counts
    by_step: dict = {}
    for r in rows:
        by_step[r["step"]] = r
    rows = [by_step[s] for s in sorted(by_step)]
    anomalies = [r for r in rows if r.get("anomaly")]
    nonfinite = [r for r in rows if r.get("nonfinite")]
    return {
        "records": len(rows),
        "nonfinite_steps": len(nonfinite),
        "anomaly_count": len(anomalies),
        "first_nonfinite_step": nonfinite[0]["step"] if nonfinite else None,
        "anomalies": [{"step": r["step"], "kinds": r["anomaly"]}
                      for r in anomalies[:top]],
    }


def build_report(output_dir: str, top: int = 5) -> dict:
    spans = load_jsonl(os.path.join(output_dir, "spans.jsonl"))
    metrics = load_jsonl(os.path.join(output_dir, "metrics.jsonl"))
    health, health_status = load_health(output_dir)
    t0, t1 = wall_window(spans)
    buckets = bucket_table(spans)
    wall = t1 - t0
    return {
        "output_dir": output_dir,
        "wall_seconds": wall,
        "buckets": buckets,
        # either workload's useful-work bucket (a process runs one of them)
        "goodput": (buckets.get("train", 0.0) + buckets.get("serve", 0.0))
        / max(wall, 1e-9),
        "health_status": health_status,
        "cumulative_goodput": _num(health.get("goodput")),
        "last_step": health.get("last_step"),
        # serve replicas heartbeat a role; a trainer's health has none
        "role": health.get("role") or "trainer",
        "serve_counters": serve_counter_summary(metrics),
        "supervisor": supervisor_summary(output_dir),
        "incarnations": incarnation_summary(output_dir),
        "numerics": numerics_summary(output_dir, top),
        "oom": oom_summary(output_dir, top),
        "slowest_windows": slowest_windows(spans, metrics, top),
        "stall_histogram": stall_histogram(spans, "data_wait"),
        "prefetch_stalls": {
            "count": sum(1 for s in spans if s["name"] == "prefetch_stall"),
            "seconds": sum(s["dur"] for s in spans
                           if s["name"] == "prefetch_stall"),
        },
        "spans": len(spans),
        "metrics_lines": len(metrics),
    }


def print_report(rep: dict) -> None:
    wall = rep["wall_seconds"]
    print(f"run: {rep['output_dir']}  (role {rep.get('role', 'trainer')}, "
          f"{rep['spans']} spans, {rep['metrics_lines']} metrics lines, "
          f"last step {rep['last_step']})")
    if rep.get("health_status") != "ok":
        print(f"  (health.json {rep['health_status']} — cumulative goodput / "
              f"last-step fields degraded)")

    sup = rep.get("supervisor")
    if sup:
        loop = (f", {sup['consecutive_failures']} consecutive failure(s)"
                if sup.get("consecutive_failures") else "")
        age = (f", heartbeat {sup['heartbeat_age_s']:.0f}s old"
               if sup.get("heartbeat_age_s") is not None else "")
        print(f"\n== supervisor (watchdog heartbeat) ==\n"
              f"  pid {sup.get('pid')}, {sup.get('restarts') or 0} "
              f"restart(s){loop}, last outcome "
              f"{sup.get('last_outcome')}{age}")

    inc = rep.get("incarnations")
    if inc:
        ooms = (f", {inc['ooms']} oom(s)" if inc.get("ooms") else "")
        print(f"\n== incarnations (supervisor ledger) ==\n"
              f"  {inc['incarnations']} launch(es), {inc['restarts']} "
              f"restart(s): {inc['crashes']} crash(es), {inc['hangs']} "
              f"hang(s){ooms}; {inc['lost_seconds']:.1f} s lost to failed "
              f"incarnations; last outcome: {inc['last_outcome']}")
        if inc.get("resize_events"):
            # crash duration + relaunch gap around each resize — the gap is
            # not part of lost_seconds (which counts only failed-incarnation
            # wall time), so this is a sibling bucket, not a subset
            print(f"  {inc['resize_events']} topology resize(s); "
                  f"{inc['resize_lost_seconds']:.1f} s of crash + relaunch "
                  f"downtime bought a smaller layout (resize badput)")
        if any(l.get("layout") for l in inc.get("layouts", [])):
            for l in inc["layouts"]:
                mark = " <- resized" if l.get("resized") else ""
                devices = (f", {l['devices']} device(s)"
                           if l.get("devices") is not None else "")
                print(f"    #{l['incarnation']}: {l['layout'] or '?'}"
                      f"{devices}  [{l['outcome']}]{mark}")

    oom = rep.get("oom")
    if oom:
        print(f"\n== oom forensics ({oom['snapshots']} snapshot(s), "
              f"newest first) ==")
        for e in oom["events"]:
            peak = (f"  device peak {e['device_peak_gib']} GiB"
                    if e.get("device_peak_gib") is not None else "")
            print(f"    step {e.get('step')}: {e.get('error')}{peak}")
        print("  (full snapshots: <output_dir>/oom/)")

    num = rep.get("numerics")
    if num:
        print(f"\n== numerics (anomaly timeline) ==\n"
              f"  {num['records']} records: {num['nonfinite_steps']} "
              f"nonfinite step(s), {num['anomaly_count']} anomaly(ies)"
              + (f"; first nonfinite at step {num['first_nonfinite_step']}"
                 if num["first_nonfinite_step"] is not None else ""))
        for a in num["anomalies"]:
            print(f"    step {a['step']:<6} {','.join(a['kinds'])}")
        if num["anomaly_count"]:
            print("  (details: python tools/numerics_report.py "
                  f"{rep['output_dir']})")

    serve = rep.get("serve_counters")
    if serve:
        print("\n== serving counters (last metrics line) ==")
        print("  " + " ".join(f"{k}={serve[k]}" for k in serve))

    print(f"\n== time buckets: {wall:.2f} s wall ==")
    for name, secs in sorted(rep["buckets"].items(), key=lambda kv: -kv[1]):
        pct = 100 * secs / wall if wall else 0.0
        print(f"  {secs:10.2f} s  {pct:5.1f}%  {name}")
    print(f"  {sum(rep['buckets'].values()):10.2f} s  total (goodput "
          f"{100 * rep['goodput']:.1f}%"
          + (f", cumulative incl. prior incarnations "
             f"{100 * rep['cumulative_goodput']:.1f}%"
             if rep["cumulative_goodput"] is not None else "") + ")")

    if rep["slowest_windows"]:
        print("\n== slowest logging windows (per-step wall time) ==")
        for w in rep["slowest_windows"]:
            step_time = w["step_time"]
            shown = step_time if step_time is not None else w["per_step_s"]
            loss = f"  loss {w['loss']:.4g}" if w["loss"] is not None else ""
            print(f"  {1e3 * shown:10.2f} ms/step  @step {w['step']:<6} "
                  f"({w['steps']} steps, value-fetch block "
                  f"{1e3 * w['block_s']:.2f} ms){loss}")

    total_stall = sum(t for _, _, t in rep["stall_histogram"])
    print(f"\n== input-wait histogram (data_wait): {total_stall:.3f} s total ==")
    for label, n, secs in rep["stall_histogram"]:
        print(f"  {label:>8}  x{n:<6d} {secs:10.3f} s")
    ps = rep["prefetch_stalls"]
    print(f"  of which prefetch buffer-empty stalls: x{ps['count']} "
          f"{ps['seconds']:.3f} s")


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("output_dir", help="trainer output dir (holds spans.jsonl)")
    p.add_argument("--top", type=int, default=5,
                   help="slowest logging windows to list")
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON instead of tables")
    args = p.parse_args(argv)
    rep = build_report(args.output_dir, top=args.top)
    if args.json:
        print(json.dumps(rep, indent=2))
    else:
        print_report(rep)


if __name__ == "__main__":
    main()

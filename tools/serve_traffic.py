#!/usr/bin/env python
"""Synthetic serving traffic: Poisson arrivals, prompt/output length mixes.

Turns the serving tier's SLO claims into measured curves: a seeded,
deterministic request trace (exponential inter-arrival gaps at `--rate`
requests/s; prompt and output lengths drawn from weighted mixes like
`"64:0.7,256:0.3"`) is replayed against a live `ServeEngine` in-process,
and the run summary reports what the engine actually did under load —
completions, page/queue refusals, TTFT/TPOT percentiles, prefill-chunk
cadence. bench.py's `extra:serve-prefill-*` row and
tests/test_serve_traffic.py drive the same library functions
(`poisson_trace` / `run_trace`), so the mix recorded in a bench row's
metadata is exactly what generated its load.

    python tools/serve_traffic.py --checkpoint_dir /ckpts/run1 \
        --rate 8 --requests 64 --prompt_mix 64:0.6,256:0.4 \
        --output_mix 16:0.5,64:0.5 --kv_cache paged --page_size 64 \
        --prefill_chunk_tokens 256

Determinism: the trace depends only on (seed, rate, n, mixes) — two runs
against the same checkpoint see identical arrivals, prompts, and sampling
seeds. Wall-clock replay obviously isn't deterministic; the trace is.

`--gateway URL` replays the SAME trace over HTTP through the routing tier
(tools/gateway.py) instead of an in-process engine — no checkpoint load,
no jax in this process — and the summary gains the gateway's per-request
attempt/replay/hedge counts. `--chaos kill:<t_s>` pairs with it: SIGKILL
the replica named by `--chaos_target` (its serve.json pid) at trace
offset t_s, turning the run into the failover acceptance drill — the
summary then shows how many requests were replayed to a survivor.
Gateway mode adds NO RNG draws: arrivals, prompts, and seeds come from
the identical `poisson_trace` stream, so a gateway run and an in-process
run of the same (seed, rate, n, mixes) serve identical requests.
"""

from __future__ import annotations

import argparse
import dataclasses
import http.client
import json
import os
import re
import signal
import sys
import threading
import time
import zlib

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class TrafficRequest:
    arrival_s: float        # offset from trace start
    prompt_len: int         # tail length when a prefix class is stamped
    max_new_tokens: int
    seed: int
    tenant: str | None = None   # SLO class (per-tenant attribution)
    # shared-prefix workload class (--prefix_mix): requests in the same
    # class share a seeded common prefix of `prefix_len` tokens ahead of
    # their per-seed tail — the prefix-cache hit population
    prefix: str | None = None
    prefix_len: int = 0


def parse_mix(spec: str) -> tuple[tuple[int, float], ...]:
    """`"64:0.7,256:0.3"` -> ((64, 0.7), (256, 0.3)), weights normalized.
    A bare `"64"` means a single length at weight 1."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        length, _, weight = part.partition(":")
        out.append((int(length), float(weight) if weight else 1.0))
    if not out:
        raise ValueError(f"empty length mix {spec!r}")
    total = sum(w for _, w in out)
    if total <= 0 or any(w < 0 for _, w in out) or any(n < 1 for n, _ in out):
        raise ValueError(f"mix {spec!r} needs positive lengths and "
                         f"non-negative weights summing > 0")
    return tuple((n, w / total) for n, w in out)


def mix_label(mix: tuple[tuple[int, float], ...]) -> str:
    """Canonical `len:weight` string — the form bench rows record."""
    return ",".join(f"{n}:{round(w, 4)}" for n, w in mix)


def parse_tenant_mix(spec: str) -> tuple[tuple[str, float], ...]:
    """`"free:0.8,paid:0.2"` -> (("free", 0.8), ("paid", 0.2)), weights
    normalized — the tenant counterpart of `parse_mix`. A bare `"paid"`
    means one tenant at weight 1."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, weight = part.partition(":")
        if not name:
            raise ValueError(f"tenant mix {spec!r} has an empty tenant name")
        out.append((name, float(weight) if weight else 1.0))
    if not out:
        raise ValueError(f"empty tenant mix {spec!r}")
    total = sum(w for _, w in out)
    if total <= 0 or any(w < 0 for _, w in out):
        raise ValueError(f"tenant mix {spec!r} needs non-negative weights "
                         f"summing > 0")
    return tuple((name, w / total) for name, w in out)


def tenant_mix_label(mix: tuple[tuple[str, float], ...]) -> str:
    return ",".join(f"{name}:{round(w, 4)}" for name, w in mix)


def parse_prefix_mix(spec: str) -> tuple[tuple[str, int, float], ...]:
    """`"sys512:0.9,cold:0.1"` -> (("sys512", 512, 0.9), ("cold", 0, 0.1)):
    trailing digits in an entry name are its shared-prefix token count
    (every request in that class gets the SAME seeded prefix of that many
    tokens ahead of its per-request tail); a digitless name like `cold`
    is a no-prefix class. Weights normalize like the other mixes."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, weight = part.partition(":")
        if not name:
            raise ValueError(f"prefix mix {spec!r} has an empty class name")
        m = re.search(r"(\d+)$", name)
        out.append((name, int(m.group(1)) if m else 0,
                    float(weight) if weight else 1.0))
    if not out:
        raise ValueError(f"empty prefix mix {spec!r}")
    total = sum(w for _, _, w in out)
    if total <= 0 or any(w < 0 for _, _, w in out):
        raise ValueError(f"prefix mix {spec!r} needs non-negative weights "
                         f"summing > 0")
    return tuple((name, n, w / total) for name, n, w in out)


def prefix_mix_label(mix: tuple[tuple[str, int, float], ...]) -> str:
    return ",".join(f"{name}:{round(w, 4)}" for name, _, w in mix)


def prefix_ids(name: str, length: int, vocab: int,
               low: int = 3) -> list[int]:
    """The shared prefix token ids of class `name`: seeded by the class
    name alone, so every request in the class — across traces and runs —
    shares the exact same tokens (a system prompt, in effect)."""
    rs = np.random.RandomState(zlib.crc32(name.encode()) & 0x7FFFFFFF)
    return rs.randint(low, vocab, size=length).tolist()


def poisson_trace(seed: int, rate_rps: float, n_requests: int,
                  prompt_mix, output_mix, tenant_mix=None,
                  prefix_mix=None) -> list[TrafficRequest]:
    """A deterministic Poisson arrival trace: exponential inter-arrival
    gaps at `rate_rps`, lengths drawn independently from the two mixes.
    Each request carries its own sampling seed (derived from the trace
    seed), so replaying a trace is reproducible end-to-end. `tenant_mix`
    (parse_tenant_mix) additionally stamps each request with a weighted
    tenant draw — all tenant draws happen AFTER the whole length/seed
    stream, so a tenantless trace is bit-identical to one generated
    before tenants existed and stamping tenants changes ONLY the tenant
    field. `prefix_mix` (parse_prefix_mix) stamps a shared-prefix class
    the same way — its draws come AFTER the tenant stream, so untenanted,
    unprefixed traces stay tuple-identical across all three vintages."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    rs = np.random.RandomState(seed)
    gaps = rs.exponential(1.0 / rate_rps, size=n_requests)
    arrivals = np.cumsum(gaps) - gaps[0]          # first request at t=0
    p_lens = [n for n, _ in prompt_mix]
    p_w = [w for _, w in prompt_mix]
    o_lens = [n for n, _ in output_mix]
    o_w = [w for _, w in output_mix]
    draws = []
    for i in range(n_requests):
        prompt_len = int(rs.choice(p_lens, p=p_w))
        max_new = int(rs.choice(o_lens, p=o_w))
        req_seed = int(rs.randint(0, 2**31 - 1))
        draws.append((prompt_len, max_new, req_seed))
    if tenant_mix:
        t_names = [name for name, _ in tenant_mix]
        t_w = [w for _, w in tenant_mix]
        tenants = [str(rs.choice(t_names, p=t_w))
                   for _ in range(n_requests)]
    else:
        tenants = [None] * n_requests
    if prefix_mix:
        p_names = list(range(len(prefix_mix)))
        p_pw = [w for _, _, w in prefix_mix]
        picks = [int(rs.choice(p_names, p=p_pw)) for _ in range(n_requests)]
        prefixes = [(prefix_mix[j][0], prefix_mix[j][1]) for j in picks]
    else:
        prefixes = [(None, 0)] * n_requests
    return [TrafficRequest(arrival_s=float(arrivals[i]), prompt_len=pl,
                           max_new_tokens=mn, seed=sd, tenant=tenants[i],
                           prefix=prefixes[i][0],
                           prefix_len=prefixes[i][1])
            for i, (pl, mn, sd) in enumerate(draws)]


def run_trace(engine, trace_requests, time_scale: float = 1.0,
              prompt_token_low: int = 3,
              result_timeout_s: float = 300.0,
              collect_tokens: bool = False) -> dict:
    """Replay a trace against a live engine (a ServeLoop is started for
    the duration): submit each request at its (scaled) arrival offset,
    count refusals by kind, wait for every accepted request, and return
    the run summary. Prompt token ids are drawn deterministically from
    the request's seed; a TrafficRequest's tenant is stamped onto the
    ServeRequest, so per-tenant SLO slices and request traces attribute
    it. `collect_tokens=True` adds `tokens` to the summary — one entry
    per trace request, index-aligned (None for refused requests) — the
    fixture the tracing-ON/OFF parity twin compares bit-for-bit."""
    from llama_pipeline_parallel_tpu.models.llama.decode import (
        GenerationConfig,
    )
    from llama_pipeline_parallel_tpu.serve import (
        RequestRejected,
        ServeLoop,
        ServeOverloaded,
        ServePagesExhausted,
        ServeRequest,
    )

    vocab = engine.cfg.vocab_size
    handles = []                 # (trace index, handle)
    refused_pages = refused_overload = rejected = 0
    submitted_by_tenant: dict[str, int] = {}
    t0 = time.monotonic()
    with ServeLoop(engine, idle_wait_s=0.002):
        for i, tr in enumerate(trace_requests):
            target = t0 + tr.arrival_s * time_scale
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            prompt = np.random.RandomState(tr.seed).randint(
                prompt_token_low, vocab, size=tr.prompt_len).tolist()
            if tr.prefix_len:
                # shared-prefix class: the class prefix ahead of the
                # per-seed tail — same tail-length class means same total
                # length, same bucket pad, real page sharing
                prompt = prefix_ids(tr.prefix, tr.prefix_len, vocab,
                                    prompt_token_low) + prompt
            req = ServeRequest(
                input_ids=prompt,
                gen=GenerationConfig(max_new_tokens=tr.max_new_tokens),
                seed=tr.seed, tenant=tr.tenant)
            try:
                handles.append((i, engine.submit(req)))
                if tr.tenant:
                    submitted_by_tenant[tr.tenant] = \
                        submitted_by_tenant.get(tr.tenant, 0) + 1
            except ServePagesExhausted:
                refused_pages += 1
            except ServeOverloaded:
                refused_overload += 1
            except RequestRejected:
                rejected += 1
        tokens_by_index: dict[int, list] = {}
        for i, h in handles:
            try:
                tokens_by_index[i] = h.result(timeout=result_timeout_s)
            except Exception:
                pass  # counted via the engine's failed/rejected counters
    wall = time.monotonic() - t0
    snap = engine.metrics_snapshot()
    summary = {
        "requests": len(trace_requests),
        "submitted": len(handles),
        "refused_pages": refused_pages,
        "refused_overload": refused_overload,
        "rejected_shape": rejected,
        "wall_s": round(wall, 3),
        **{k: snap[k] for k in snap
           if k.startswith(("ttft_", "tpot_", "queue_wait_", "prefix_"))
           or k in ("requests_completed", "requests_failed",
                    "tokens_generated", "prefill_chunks_total",
                    "prefill_tokens_total", "pages_total")},
    }
    if submitted_by_tenant:
        summary["submitted_by_tenant"] = dict(
            sorted(submitted_by_tenant.items()))
    if any(tr.prefix is not None for tr in trace_requests):
        # per-class hit rate: what fraction of each prefix class's
        # SUBMITTED requests were served a cached prefix (the engine-side
        # counters aggregate across classes; this is the mix breakdown)
        per: dict[str, dict] = {}
        for i, h in handles:
            name = trace_requests[i].prefix or "cold"
            d = per.setdefault(name, {"submitted": 0, "hits": 0,
                                      "cached_tokens": 0})
            d["submitted"] += 1
            if h.prefix_cached_tokens > 0:
                d["hits"] += 1
                d["cached_tokens"] += h.prefix_cached_tokens
        for d in per.values():
            d["hit_rate"] = round(d["hits"] / d["submitted"], 4)
        summary["prefix_classes"] = dict(sorted(per.items()))
    if "tenants" in snap:
        summary["tenants"] = snap["tenants"]
    if collect_tokens:
        summary["tokens"] = [tokens_by_index.get(i)
                             for i in range(len(trace_requests))]
    if wall > 0:
        summary["tokens_per_sec"] = round(
            snap.get("tokens_generated", 0) / wall, 2)
    return summary


def parse_chaos(spec: str) -> tuple[str, float]:
    """`"kill:2.5"` -> ("kill", 2.5): SIGKILL the --chaos_target replica
    at trace offset 2.5s (scaled by --time_scale like arrivals)."""
    kind, _, at = spec.partition(":")
    if kind != "kill" or not at:
        raise ValueError(f"chaos spec {spec!r}: expected 'kill:<t_s>'")
    t_s = float(at)
    if t_s < 0:
        raise ValueError(f"chaos offset must be >= 0, got {t_s}")
    return kind, t_s


def kill_replica(replica_dir: str) -> int | None:
    """SIGKILL the serve process whose serve.json lives in `replica_dir`;
    returns the pid killed, or None when there is nothing to kill (the
    chaos drill racing a supervisor relaunch is expected, not an error)."""
    try:
        with open(os.path.join(replica_dir, "serve.json")) as f:
            pid = int(json.load(f)["pid"])
        os.kill(pid, signal.SIGKILL)
        return pid
    except (OSError, ValueError, KeyError):
        return None


def _gateway_addr(url: str) -> tuple[str, int]:
    hostport = url.split("//", 1)[-1].rstrip("/")
    host, _, port = hostport.partition(":")
    return host or "127.0.0.1", int(port or 80)


def _gateway_one(host: str, port: int, body: dict, timeout_s: float,
                 results: list, i: int) -> None:
    """One streamed request through the gateway; results[i] gets
    {"status", "tokens", "attempts", "replays", "hedges"} or
    {"status", "error"} — connection death (the gateway itself dying,
    not a replica: replica deaths are absorbed by replay) is an error."""
    out: dict = {"status": 0}
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
        conn.request("POST", "/v1/generate", json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        out["status"] = resp.status
        if resp.status != 200:
            try:
                out["error"] = json.loads(resp.read() or b"{}").get("error")
            except ValueError:
                out["error"] = f"http {resp.status}"
            return
        tokens, tail = [], None
        while True:
            raw = resp.readline()
            if not raw:
                break
            line = json.loads(raw)
            if line.get("done"):
                tail = line
                break
            tokens.append(line["token"])
        if tail is None:
            out.update(status=0, error="stream ended without done line")
            return
        if "error" in tail:
            out.update(status=500, error=tail["error"])
            return
        out.update(tokens=tail.get("tokens", tokens),
                   attempts=int(tail.get("attempts", 1)),
                   replays=int(tail.get("replays", 0)),
                   hedges=int(tail.get("hedges", 0)))
    except (OSError, ValueError) as e:
        out.setdefault("error", repr(e))
        out["status"] = out.get("status") or 0
    finally:
        results[i] = out


def gateway_healthz(gateway_url: str, timeout_s: float = 5.0) -> dict:
    host, port = _gateway_addr(gateway_url)
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    conn.request("GET", "/healthz")
    return json.loads(conn.getresponse().read())


def run_trace_gateway(gateway_url: str, trace_requests, vocab: int,
                      time_scale: float = 1.0, prompt_token_low: int = 3,
                      result_timeout_s: float = 300.0,
                      collect_tokens: bool = False,
                      chaos: tuple[str, float] | None = None,
                      chaos_target: str | None = None) -> dict:
    """Replay a trace through the gateway tier over HTTP: one streaming
    POST per request at its (scaled) arrival offset, each read to its
    done line on a worker thread. Prompts are drawn exactly as
    `run_trace` draws them — same RandomState(seed) stream — so the two
    modes serve identical requests. `chaos=("kill", t_s)` SIGKILLs the
    `chaos_target` replica at trace offset t_s; requests in flight on it
    are the gateway's replay population, and the summary's `replayed` /
    `attempts_total` report what the failover actually did."""
    host, port = _gateway_addr(gateway_url)
    n = len(trace_requests)
    results: list = [None] * n
    threads: list[threading.Thread] = []
    t0 = time.monotonic()
    chaos_timer = None
    if chaos is not None:
        if not chaos_target:
            raise ValueError("chaos needs a chaos_target replica dir")
        kind, t_s = chaos
        chaos_timer = threading.Timer(t_s * time_scale, kill_replica,
                                      args=(chaos_target,))
        chaos_timer.daemon = True
        chaos_timer.start()
    for i, tr in enumerate(trace_requests):
        target = t0 + tr.arrival_s * time_scale
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        prompt = np.random.RandomState(tr.seed).randint(
            prompt_token_low, vocab, size=tr.prompt_len).tolist()
        if tr.prefix_len:
            prompt = prefix_ids(tr.prefix, tr.prefix_len, vocab,
                                prompt_token_low) + prompt
        body = {"input_ids": prompt, "seed": tr.seed, "stream": True,
                "max_new_tokens": tr.max_new_tokens}
        if tr.tenant:
            body["tenant"] = tr.tenant
        t = threading.Thread(target=_gateway_one,
                             args=(host, port, body, result_timeout_s,
                                   results, i), daemon=True)
        t.start()
        threads.append(t)
    deadline = time.monotonic() + result_timeout_s
    for t in threads:
        t.join(timeout=max(0.1, deadline - time.monotonic()))
    if chaos_timer is not None:
        chaos_timer.cancel()
    wall = time.monotonic() - t0
    done = [r or {"status": 0, "error": "no response"} for r in results]
    completed = [r for r in done if r["status"] == 200 and "error" not in r]
    summary = {
        "requests": n,
        "submitted": sum(1 for r in done if r["status"] == 200),
        "completed": len(completed),
        "failed": sum(1 for r in done
                      if r["status"] in (0, 500)
                      or (r["status"] == 200 and "error" in r)),
        "refused_overload": sum(1 for r in done
                                if r["status"] in (429, 503)),
        "rejected_shape": sum(1 for r in done if r["status"] == 400),
        "attempts_total": sum(r.get("attempts", 0) for r in completed),
        "replayed": sum(1 for r in completed if r.get("replays", 0) > 0),
        "hedged": sum(1 for r in completed if r.get("hedges", 0) > 0),
        "wall_s": round(wall, 3),
    }
    try:
        snap = gateway_healthz(gateway_url)
        summary["gateway"] = {k: snap[k] for k in (
            "requests_routed", "requests_retried", "requests_replayed",
            "requests_hedged", "hedge_wins", "wasted_hedge_tokens",
            "replay_skipped_tokens", "requests_completed",
            "requests_failed", "requests_shed", "ttft_p50_ms",
            "ttft_p95_ms", "replicas_known", "replicas_healthy")
            if k in snap}
    except (OSError, ValueError):
        pass  # gateway gone at drain time: the per-request view stands
    if collect_tokens:
        summary["tokens"] = [r.get("tokens") for r in done]
    return summary


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--platform", default=None)
    p.add_argument("--checkpoint_dir", default=None,
                   help="required unless --gateway drives a remote tier")
    p.add_argument("--gateway", default=None, metavar="URL",
                   help="replay the trace over HTTP through a gateway "
                        "(tools/gateway.py) instead of an in-process "
                        "engine — no checkpoint load in this process")
    p.add_argument("--vocab", type=int, default=32000,
                   help="vocab size for prompt draws in --gateway mode "
                        "(in-process mode reads it off the checkpoint)")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="failure drill in --gateway mode: 'kill:<t_s>' "
                        "SIGKILLs the --chaos_target replica at trace "
                        "offset t_s (scaled by --time_scale)")
    p.add_argument("--chaos_target", default=None,
                   help="replica output dir whose serve.json pid the "
                        "--chaos drill kills")
    p.add_argument("--step", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--rate", type=float, default=4.0, help="requests/s")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--prompt_mix", default="64:0.7,256:0.3")
    p.add_argument("--output_mix", default="16:0.5,64:0.5")
    p.add_argument("--tenant_mix", default=None,
                   help="weighted tenant mix like 'free:0.8,paid:0.2': "
                        "stamps each generated request's tenant for "
                        "per-tenant SLO slices and request traces")
    p.add_argument("--prefix_mix", default=None,
                   help="shared-prefix workload mix like "
                        "'sys512:0.9,cold:0.1': trailing digits are the "
                        "class's common seeded prefix length in tokens "
                        "ahead of each request's tail (digitless = no "
                        "prefix); pair with --prefix_cache to measure "
                        "hit-rate TTFT wins")
    p.add_argument("--prefix_cache", action="store_true",
                   help="enable the engine's prefix cache (paged only)")
    p.add_argument("--time_scale", type=float, default=1.0,
                   help="replay arrivals at 1/time_scale speed")
    p.add_argument("--output_dir", default=None,
                   help="where --request_trace artifacts land (optional "
                        "otherwise)")
    p.add_argument("--request_trace", action="store_true",
                   help="attach a RequestTraceRecorder to the engine: "
                        "request_trace.jsonl + exemplars in --output_dir "
                        "(requires --output_dir)")
    p.add_argument("--trace_exemplars", type=int, default=8)
    # engine shape (mirrors tools/serve.py)
    p.add_argument("--max_slots", type=int, default=8)
    p.add_argument("--max_len", type=int, default=2048)
    p.add_argument("--buckets", default="64,128,256,512,1024")
    p.add_argument("--max_queue", type=int, default=64)
    p.add_argument("--kv_cache", default="dense", choices=("dense", "paged"))
    p.add_argument("--page_size", type=int, default=64)
    p.add_argument("--num_pages", type=int, default=None)
    p.add_argument("--kv_quant", default="fp", choices=("fp", "int8"))
    p.add_argument("--prefill_chunk_tokens", type=int, default=0)
    args = p.parse_args(argv)

    prompt_mix = parse_mix(args.prompt_mix)
    output_mix = parse_mix(args.output_mix)
    tenant_mix = (parse_tenant_mix(args.tenant_mix)
                  if args.tenant_mix else None)
    prefix_mix = (parse_prefix_mix(args.prefix_mix)
                  if args.prefix_mix else None)
    if args.request_trace and not args.output_dir:
        p.error("--request_trace requires --output_dir")
    if args.chaos and not args.gateway:
        p.error("--chaos is a --gateway mode drill")
    if args.chaos and not args.chaos_target:
        p.error("--chaos requires --chaos_target")

    if args.gateway:
        # gateway mode: same trace, over HTTP — this process never
        # touches jax or the checkpoint
        trace_requests = poisson_trace(args.seed, args.rate, args.requests,
                                       prompt_mix, output_mix,
                                       tenant_mix=tenant_mix,
                                       prefix_mix=prefix_mix)
        summary = run_trace_gateway(
            args.gateway, trace_requests, vocab=args.vocab,
            time_scale=args.time_scale,
            chaos=parse_chaos(args.chaos) if args.chaos else None,
            chaos_target=args.chaos_target)
        summary["mix"] = {"prompt": mix_label(prompt_mix),
                          "output": mix_label(output_mix),
                          "rate_rps": args.rate, "seed": args.seed}
        print(json.dumps(summary, indent=2))
        return 0

    if not args.checkpoint_dir:
        p.error("--checkpoint_dir is required without --gateway")
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import (
        load_module_checkpoint,
    )
    from llama_pipeline_parallel_tpu.serve import ServeConfig, ServeEngine

    params, cfg, _, step = load_module_checkpoint(args.checkpoint_dir,
                                                  args.step)
    reqtrace_rec = None
    if args.request_trace:
        from llama_pipeline_parallel_tpu.serve.reqtrace import (
            RequestTraceRecorder,
        )

        reqtrace_rec = RequestTraceRecorder(
            args.output_dir, exemplar_k=args.trace_exemplars)
    engine = ServeEngine(params, cfg, ServeConfig(
        max_slots=args.max_slots, max_len=args.max_len,
        prompt_buckets=tuple(int(b) for b in args.buckets.split(",")),
        max_queue=args.max_queue, kv_cache=args.kv_cache,
        page_size=args.page_size, num_pages=args.num_pages,
        kv_quant=args.kv_quant,
        prefill_chunk_tokens=args.prefill_chunk_tokens,
        prefix_cache=args.prefix_cache),
        reqtrace=reqtrace_rec)
    trace_requests = poisson_trace(args.seed, args.rate, args.requests,
                                   prompt_mix, output_mix,
                                   tenant_mix=tenant_mix,
                                   prefix_mix=prefix_mix)
    summary = run_trace(engine, trace_requests, time_scale=args.time_scale)
    summary["mix"] = {"prompt": mix_label(prompt_mix),
                      "output": mix_label(output_mix),
                      "rate_rps": args.rate, "seed": args.seed}
    if tenant_mix is not None:
        summary["mix"]["tenant"] = tenant_mix_label(tenant_mix)
    if prefix_mix is not None:
        summary["mix"]["prefix"] = prefix_mix_label(prefix_mix)
    summary["checkpoint_step"] = step
    engine.shutdown()
    if reqtrace_rec is not None:
        reqtrace_rec.close()
    print(json.dumps(summary, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Offline whole-pod report from a fleet root (docs/OBSERVABILITY.md
"Fleet").

The post-mortem counterpart of tools/fleetd.py: where the daemon shows
the pod NOW, this tells the pod's story after the fact — which members
ran, every incarnation of every role on one wall-clock timeline with
restart/resize markers, the alert firing/resolved timeline next to it,
the serve tier's SLO picture, and the checkpoint-lag table (how far each
replica trailed the trainer's latest verified checkpoint).

    python tools/fleet_report.py /runs/fleet1 [--json]

Reads `<fleet-root>/registry.jsonl` + `alerts.jsonl` and each registered
member's health.json / metrics.jsonl / incarnations.jsonl. Degrades on
missing/torn/garbage files like every other report in tools/ — a pod that
just burned down is exactly when this gets run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llama_pipeline_parallel_tpu.utils.actions import (  # noqa: E402
    read_actions,
)
from llama_pipeline_parallel_tpu.utils.fleet import (  # noqa: E402
    HEALTH_NAME,
    FleetAggregator,
    _num,
    load_registry,
    read_alerts,
)
from llama_pipeline_parallel_tpu.utils.perf import read_jsonl  # noqa: E402


def build_report(fleet_root: str) -> dict:
    """One refresh of the aggregator (it already knows how to roll a
    member up; offline we just never write status/alerts/triggers) plus
    the cross-member timelines only hindsight can draw."""
    registry = load_registry(fleet_root)
    agg = FleetAggregator(fleet_root, capture_on_alert=False)
    status = agg.refresh(write=False) if registry else {
        "members": {}, "pod": {"members": 0, "alerts_firing": []}}

    # every incarnation of every member on one timeline
    seen_dirs = []
    for row in registry:
        if row["output_dir"] not in seen_dirs:
            seen_dirs.append(row["output_dir"])
    # one event stream per OUTPUT DIR: the supervisor member shares its
    # child's dir (and ledger), so iterating members would print every
    # incarnation twice — label each dir with its child (non-supervisor)
    # member when one exists
    dir_label: dict[str, str] = {}
    for member_id, member in status["members"].items():
        out = member["output_dir"]
        if out not in dir_label or member["role"] != "supervisor":
            dir_label[out] = member_id
    events = []
    for out, member_id in dir_label.items():
        rows = read_jsonl(os.path.join(out, "incarnations.jsonl"),
                          keep=lambda r: "incarnation" in r)
        for row in rows:
            events.append({
                "member": member_id,
                "incarnation": row.get("incarnation"),
                "start": _num(row.get("start")),
                "end": _num(row.get("end")),
                "duration_s": _num(row.get("duration_s")),
                "outcome": row.get("outcome"),
                "layout": row.get("layout"),
                "resized": bool(row.get("resized")),
                "last_step": row.get("last_step"),
            })
    events.sort(key=lambda e: e["start"] or 0.0)

    alerts = read_alerts(fleet_root)
    actions = read_actions(fleet_root)
    t0_candidates = ([e["start"] for e in events if e["start"]]
                     + [_num(a.get("ts")) for a in alerts
                        if _num(a.get("ts"))]
                     + [_num(r.get("ts")) for r in actions
                        if _num(r.get("ts"))]
                     + [_num(r.get("ts")) for r in registry
                        if _num(r.get("ts"))])
    t0 = min(t0_candidates) if t0_candidates else None

    # serve SLO + checkpoint-lag tables straight off the member rollups
    slo_rows, lag_rows, gateway_rows = [], [], []
    trainer_step = status.get("pod", {}).get("trainer_step")
    for member_id, member in status["members"].items():
        if member["role"] == "gateway":
            # the routing tier's own counters (serve/gateway.py): routing /
            # retry / replay / hedge volume plus gateway-observed TTFT
            gateway_rows.append({k: member.get(k) for k in (
                "replica", "requests_routed", "requests_completed",
                "requests_retried", "requests_replayed", "requests_hedged",
                "hedge_wins", "wasted_hedge_tokens", "replay_skipped_tokens",
                "requests_shed", "requests_failed", "ttft_p50_ms",
                "ttft_p95_ms", "replicas_healthy", "replicas_known")})
        if member["role"] != "serve":
            continue
        slo_rows.append({k: member.get(k) for k in (
            "replica", "requests_completed", "tokens_generated",
            "ttft_p50_ms", "ttft_p95_ms", "tpot_p50_ms",
            "queue_wait_p95_ms", "slo_breaches", "requests_page_refused",
            "requests_failed")})
        lag_rows.append({"replica": member.get("replica"),
                         "checkpoint_step": member.get("checkpoint_step"),
                         "checkpoint_lag": member.get("checkpoint_lag")})
    return {"fleet_root": fleet_root, "t0": t0,
            "registered_members": len(status["members"]),
            "registered_dirs": seen_dirs,
            "members": status["members"], "pod": status.get("pod", {}),
            "incarnation_timeline": events, "alert_timeline": alerts,
            "action_timeline": actions,
            "slo_table": slo_rows, "gateway_table": gateway_rows,
            "checkpoint_lag": {"trainer_step": trainer_step,
                               "replicas": lag_rows}}


def _rel(ts, t0) -> str:
    if ts is None or t0 is None:
        return "?"
    return f"t+{ts - t0:8.1f}s"


def print_report(rep: dict) -> None:
    print(f"fleet: {rep['fleet_root']}  ({rep['registered_members']} "
          f"member(s))")
    pod = rep.get("pod", {})
    if pod.get("goodput") is not None:
        print(f"  pod goodput (elapsed-weighted, incarnations included): "
              f"{100 * pod['goodput']:.1f}%")
    if pod.get("alerts_firing"):
        print(f"  STILL FIRING: {', '.join(pod['alerts_firing'])}")

    print("\n== members ==")
    for member_id, m in rep["members"].items():
        bits = [f"{m.get('incarnations', 1) or 1} incarnation(s)"]
        if m.get("last_step") is not None:
            bits.append(f"last step {m['last_step']}")
        if m.get("latest_verified_step") is not None:
            bits.append(f"latest verified ckpt {m['latest_verified_step']}")
        if m.get("checkpoint_step") is not None:
            bits.append(f"serving ckpt step {m['checkpoint_step']}")
        if m.get("goodput") is not None:
            bits.append(f"goodput {100 * m['goodput']:.1f}%")
        if m.get("health_status") not in ("ok", None):
            bits.append(f"health {m['health_status']}")
        print(f"  {member_id:<24} {', '.join(bits)}")

    t0 = rep["t0"]
    if rep["incarnation_timeline"]:
        print("\n== incarnation timeline (all roles) ==")
        for e in rep["incarnation_timeline"]:
            mark = ""
            if e["resized"]:
                mark = "  <- resized"
            elif e["outcome"] not in ("clean", None):
                mark = f"  <- {e['outcome']}"
            layout = f" [{e['layout']}]" if e.get("layout") else ""
            dur = (f"{e['duration_s']:7.1f}s"
                   if e["duration_s"] is not None else "      ?")
            print(f"  {_rel(e['start'], t0)}  {e['member']:<24} "
                  f"#{e['incarnation']} {dur}  {e['outcome'] or '?'}"
                  f"{layout}{mark}")

    if rep["alert_timeline"]:
        print("\n== alert timeline ==")
        for a in rep["alert_timeline"]:
            print(f"  {_rel(_num(a.get('ts')), t0)}  "
                  f"{str(a.get('state', '?')).upper():<9} {a.get('alert')} "
                  f"on {a.get('member')} (value={a.get('value')} "
                  f"threshold={a.get('threshold')})")

    if rep.get("action_timeline"):
        # the actuation story (tools/fleetctl.py): every action's intent
        # and outcome row, interleaved with the alert edges that caused
        # them — one merged clock, so cause sits right above effect
        print("\n== actions timeline (interleaved with alert edges) ==")
        merged = ([("alert", _num(a.get("ts")), a)
                   for a in rep["alert_timeline"]]
                  + [("action", _num(r.get("ts")), r)
                     for r in rep["action_timeline"]])
        merged.sort(key=lambda item: item[1] or 0.0)
        for tag, ts, row in merged:
            if tag == "alert":
                print(f"  {_rel(ts, t0)}  alert  "
                      f"{str(row.get('state', '?')).upper():<9} "
                      f"{row.get('alert')} on {row.get('member')}")
            elif row.get("phase") == "intent":
                cause = f"  <- {row['alert']}" if row.get("alert") else ""
                print(f"  {_rel(ts, t0)}  action INTENT    "
                      f"{row.get('kind')} {row.get('id')} "
                      f"params={row.get('params')}{cause}")
            else:
                print(f"  {_rel(ts, t0)}  action "
                      f"{str(row.get('outcome', '?')).upper():<9} "
                      f"{row.get('kind')} {row.get('id')}")

    if rep["slo_table"]:
        print("\n== serve tier SLOs (last metrics line per replica) ==")
        for r in rep["slo_table"]:
            cells = " ".join(f"{k}={r[k]}" for k in (
                "requests_completed", "ttft_p50_ms", "ttft_p95_ms",
                "tpot_p50_ms", "queue_wait_p95_ms", "slo_breaches",
                "requests_page_refused", "requests_failed")
                if r.get(k) is not None)
            cells = cells or "(no serving metrics recorded)"
            print(f"  {str(r.get('replica')):<16} {cells}")

    if rep.get("gateway_table"):
        print("\n== gateway tier (last metrics line per gateway) ==")
        for r in rep["gateway_table"]:
            cells = " ".join(f"{k}={r[k]}" for k in (
                "requests_routed", "requests_completed", "requests_retried",
                "requests_replayed", "requests_hedged", "hedge_wins",
                "wasted_hedge_tokens", "replay_skipped_tokens",
                "requests_shed", "requests_failed", "ttft_p50_ms",
                "ttft_p95_ms") if r.get(k) is not None)
            if r.get("replicas_known") is not None:
                cells += (f" replicas={r.get('replicas_healthy')}"
                          f"/{r.get('replicas_known')} healthy")
            cells = cells.strip() or "(no gateway metrics recorded)"
            print(f"  {str(r.get('replica')):<16} {cells}")

    lag = rep["checkpoint_lag"]
    if lag["replicas"]:
        print(f"\n== checkpoint lag (trainer latest verified: "
              f"{lag['trainer_step']}) ==")
        for r in lag["replicas"]:
            lag_s = (f"{r['checkpoint_lag']} step(s) behind"
                     if r.get("checkpoint_lag") is not None
                     else "lag unknown")
            print(f"  {str(r.get('replica')):<16} serving step "
                  f"{r.get('checkpoint_step')}  ({lag_s})")
    if not rep["members"]:
        print("\n  no members registered — is this a fleet root? "
              f"(expected {os.path.join(rep['fleet_root'], 'registry.jsonl')}"
              f"; members heartbeat {HEALTH_NAME} in their own dirs)")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("fleet_root", help="the --fleet-root the supervisors "
                                      "and fleetd were pointed at")
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON instead of tables")
    args = p.parse_args(argv)
    rep = build_report(args.fleet_root)
    if args.json:
        print(json.dumps(rep, indent=2))
    else:
        print_report(rep)
    return 0


if __name__ == "__main__":
    sys.exit(main())

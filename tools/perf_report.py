#!/usr/bin/env python
"""Model-vs-measured calibration report over the perf ledger
(docs/OBSERVABILITY.md "Perf ledger & calibration").

Reads perf.jsonl rows (utils/perf.py schema — written by train.py with
`timeline.enabled`, by `bench.py --perf-ledger/--full-trajectory`, and by
tools/serve.py) plus archived bench rounds (BENCH_r0*.json, error rounds
included), and prints:

- the **calibration table**: per metric, the analytic/model value next to
  its measured counterpart, the model error %, and the measured drift
  across runs;
- the **failure summary**: reason-tagged rows ("N rounds unreachable" —
  the standing TPU gap, summarized instead of silently dropped);
- with `--emit-calibration PATH`: the measured-constants JSON
  (`mfu`, `host_bw_gibps`, `ici_bw_gibps`, `mem_scale` — whichever the
  ledger holds) that `tools/preflight.py --select --calibration PATH`
  consumes to re-rank the layout/schedule frontier from measurements
  instead of CLI guesses — the analytic half of ROADMAP's "measured
  re-selection". `mem_scale` (live peak / byte-model peak, from the
  memory observatory's `mem_peak_gib` rows) scales the selector's
  est_peak_gib feasibility test.

Degrades, never tracebacks: missing/torn/garbage ledgers and archives
contribute whatever parses (the goodput_report house rule).

Usage:
  python tools/perf_report.py <run_dir_or_perf.jsonl> ... \
      [--bench BENCH_r01.json ...] [--bench-glob 'BENCH_r0*.json'] \
      [--emit-calibration perf-calib.json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llama_pipeline_parallel_tpu.utils import perf  # noqa: E402


def collect_rows(paths: list[str], bench: list[str]) -> list[dict]:
    rows: list[dict] = []
    for p in paths:
        ledger = p if p.endswith(".jsonl") else os.path.join(p, "perf.jsonl")
        got = perf.read_ledger(ledger)
        if not got:
            print(f"note: no parseable rows under {ledger}", file=sys.stderr)
        rows.extend(got)
    for b in bench:
        rows.extend(perf.rows_from_bench_file(b))
    return rows


def _fmt(x: float | None, width: int = 10) -> str:
    if x is None:
        return "-".rjust(width)
    if x == 0 or 1e-3 <= abs(x) < 1e5:
        return f"{x:.4g}".rjust(width)
    return f"{x:.3e}".rjust(width)


def print_table(rows: list[dict]) -> None:
    summary = perf.summarize(rows)
    metrics = summary["metrics"]
    if metrics:
        print(f"{'metric':40s} {'model':>10s} {'measured':>10s} "
              f"{'err%':>8s} {'n':>4s} {'drift':>10s} {'unit':>6s}")
        for name in sorted(metrics):
            m = metrics[name]
            model = statistics.median(m["models"]) if m["models"] else None
            meas = statistics.median(m["measured"]) if m["measured"] else None
            err = ""
            if m["pairs"]:
                # median relative model error over rows carrying both halves
                errs = [(mo - me) / me * 100.0
                        for mo, me in m["pairs"] if me]
                if errs:
                    err = f"{statistics.median(errs):+.1f}"
            drift = None
            if len(m["measured"]) > 1:
                drift = statistics.pstdev(m["measured"])
            n = max(len(m["measured"]), len(m["models"]))
            print(f"{name[:40]:40s} {_fmt(model)} {_fmt(meas)} "
                  f"{err:>8s} {n:>4d} {_fmt(drift)} {m['unit']:>6s}")
    else:
        print("no model/measured rows")
    failures = summary["failures"]
    if failures:
        by_run: dict[str, str] = {}
        for row in failures:
            by_run.setdefault(row.get("run") or "?", str(row.get("reason")))
        print(f"\n{len(by_run)} round(s) produced no live number:")
        for run in sorted(by_run):
            print(f"  {run}: {by_run[run][:120]}")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("runs", nargs="*",
                   help="run output dirs (or perf.jsonl paths)")
    p.add_argument("--bench", nargs="*", default=[],
                   help="bench summary JSON file(s) (bench.py output or "
                        "BENCH_r0*.json archives; error rounds summarize "
                        "as failures)")
    p.add_argument("--bench-glob", default=None,
                   help="glob of bench archives, e.g. 'BENCH_r0*.json'")
    p.add_argument("--emit-calibration", default=None, metavar="PATH",
                   help="write the measured-constants JSON for "
                        "`preflight --select --calibration PATH`")
    args = p.parse_args(argv)

    bench = list(args.bench)
    if args.bench_glob:
        bench += sorted(glob.glob(args.bench_glob))
    if not args.runs and not bench:
        p.error("nothing to read: pass run dirs and/or --bench/--bench-glob")
    rows = collect_rows(args.runs, bench)
    print_table(rows)

    if args.emit_calibration:
        calib = perf.derive_calibration(rows)
        usable = {k: v for k, v in calib.items()
                  if k in ("mfu", "host_bw_gibps", "ici_bw_gibps",
                           "mem_scale")}
        with open(args.emit_calibration, "w") as f:
            json.dump(calib, f, indent=2)
        if usable:
            print(f"\ncalibration written: {args.emit_calibration} "
                  f"({', '.join(f'{k}={v}' for k, v in usable.items())}) — "
                  f"feed it to `tools/preflight.py --select --calibration`")
        else:
            print(f"\ncalibration written: {args.emit_calibration} — no "
                  f"measured constants yet (no offload-bw/mfu rows in the "
                  f"ledger); preflight will keep its CLI assumptions")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Offline tail-latency forensics from a request-trace directory.

Reads the artifacts a tracing-enabled serve run left behind —
`request_trace.jsonl` (one span tree per finished request, written by
serve/reqtrace.py) and `request_trace_exemplars.json` (the slowest-K
snapshot) — and answers "which request paid the p99 and WHERE":

- the p99-TTFT exemplar's waterfall (queue wait, admission verdict, each
  prefill chunk, first token, decode-tick summary), rendered against the
  request's own arrival time;
- a tail-attribution table decomposing tail TTFT into its phases —
  queue wait, the request's OWN prefill chunks, and the gap between them
  (time spent waiting behind a chunking neighbor's prefill ticks);
- per-tenant tables (counts, tokens, TTFT/TPOT percentiles) when the
  trace carries tenants;
- with `--gateway DIR`, the gateway-tier join: the routing tier's WAL
  (`gateway_journal.jsonl`, serve/gateway.py) joined to the replica's
  trace records by trace_id — one request's full dispatch history
  (every routed attempt, the replay after a replica died, the hedge that
  lost) next to the replica-side spans it produced, plus replay/hedge
  spans in the exemplar waterfall.

    python tools/request_report.py /runs/serve1
    python tools/request_report.py /runs/serve1 --json
    python tools/request_report.py /runs/serve1 --gateway /runs/gw

Degrades instead of tracebacking on missing/torn files (the
goodput_report.py contract): a crashed replica's directory must still
report whatever it managed to record.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llama_pipeline_parallel_tpu.serve.reqtrace import (  # noqa: E402
    EXEMPLARS_NAME,
    REQUEST_TRACE_NAME,
)
from llama_pipeline_parallel_tpu.serve.telemetry import (  # noqa: E402
    percentiles_ms,
)


def load_trace(output_dir: str) -> list[dict]:
    """Parseable dict rows only — `perf.read_jsonl`, the one spelling of
    the tolerant reader (a torn tail or garbage line is skipped)."""
    from llama_pipeline_parallel_tpu.utils.perf import read_jsonl

    return read_jsonl(os.path.join(output_dir, REQUEST_TRACE_NAME))


def load_exemplars(output_dir: str) -> dict:
    try:
        with open(os.path.join(output_dir, EXEMPLARS_NAME)) as f:
            snap = json.load(f)
        return snap if isinstance(snap, dict) else {}
    except (OSError, ValueError):
        return {}


def _num(v) -> float | None:
    return float(v) if isinstance(v, (int, float)) else None


def load_gateway_journal(gateway_dir: str) -> dict[str, dict]:
    """gid -> collapsed WAL state from a gateway dir's
    gateway_journal.jsonl (serve/gateway.py schema): intent ts + trace_id,
    every routed attempt, the high-water delivered mark, and the FIRST
    terminal row (the journal writer enforces exactly one; a torn rewrite
    never un-decides an outcome). perf.read_jsonl keeps this tolerant of
    torn tails — a crashed gateway's journal still reports."""
    from llama_pipeline_parallel_tpu.utils.perf import read_jsonl

    from llama_pipeline_parallel_tpu.serve.gateway import JOURNAL_NAME

    by_gid: dict[str, dict] = {}
    for r in read_jsonl(os.path.join(gateway_dir, JOURNAL_NAME)):
        gid, kind = r.get("gid"), r.get("kind")
        if not isinstance(gid, str) or not isinstance(kind, str):
            continue
        st = by_gid.setdefault(gid, {
            "gid": gid, "trace_id": None, "intent_ts": None,
            "routed": [], "watermark": 0, "terminal": None})
        if kind == "intent":
            st["trace_id"] = r.get("trace_id")
            st["intent_ts"] = _num(r.get("ts"))
        elif kind == "routed":
            st["routed"].append({k: r.get(k) for k in
                                 ("replica", "attempt", "hedge", "ts")})
        elif kind == "watermark":
            st["watermark"] = max(st["watermark"],
                                  int(r.get("delivered") or 0))
        elif kind == "terminal" and st["terminal"] is None:
            st["terminal"] = {k: r.get(k) for k in
                              ("outcome", "tokens", "ts", "replays",
                               "hedges", "via") if r.get(k) is not None}
    return by_gid


def gateway_tables(by_gid: dict[str, dict],
                   records: list[dict]) -> dict:
    """The gateway join: WAL state keyed by gid, replica trace records
    attached by trace_id (a replayed request has ONE gid and trace_id but
    several replica records — the dead attempt's partial trace and the
    survivor's full one both join)."""
    recs_by_trace: dict[str, list[dict]] = {}
    for rec in records:
        tid = rec.get("trace_id")
        if isinstance(tid, str):
            recs_by_trace.setdefault(tid, []).append(rec)
    outcomes: dict[str, int] = {}
    replayed = hedged = orphans = joined = 0
    exemplar = None
    rows = []
    for gid in sorted(by_gid):
        st = by_gid[gid]
        term = st["terminal"]
        if term is None:
            orphans += 1
        else:
            outcomes[term["outcome"]] = outcomes.get(term["outcome"], 0) + 1
            if term.get("replays"):
                replayed += 1
            if term.get("hedges"):
                hedged += 1
        replica_recs = recs_by_trace.get(st["trace_id"], [])
        joined += bool(replica_recs)
        row = {**st, "replica_records": len(replica_recs),
               "replicas": sorted({r.get("replica") for r in st["routed"]
                                   if r.get("replica")})}
        rows.append(row)
        # the exemplar: the request with the busiest dispatch history
        # (most attempts; replays beat hedges at a tie) — the one whose
        # waterfall shows the failover machinery actually working
        busy = (len(st["routed"]),
                int((term or {}).get("replays") or 0))
        if st["routed"] and (exemplar is None or busy > exemplar[0]):
            exemplar = (busy, row, replica_recs)
    return {"requests": len(by_gid), "outcomes": dict(sorted(
                outcomes.items())),
            "replayed": replayed, "hedged": hedged, "orphans": orphans,
            "joined": joined, "rows": rows,
            "exemplar": None if exemplar is None
            else {"wal": exemplar[1], "records": exemplar[2]}}


def gateway_waterfall(wal: dict, replica_recs: list[dict]) -> list[str]:
    """Render one gateway request's dispatch history: each routed attempt
    as a span at its offset from the WAL intent row, replay/hedge marked,
    with the replica-side record (outcome + TTFT) it joins to."""
    t0 = wal.get("intent_ts")
    term = wal.get("terminal") or {}
    lines = [f"  gateway {wal['gid']} trace {wal.get('trace_id')} "
             f"outcome={term.get('outcome', 'ORPHANED')} "
             f"tokens={term.get('tokens')} watermark={wal.get('watermark')}"]
    by_attempt = {}
    for rec in replica_recs:
        att = (rec.get("gateway") or {}).get("attempt")
        if att is not None:
            by_attempt.setdefault(att, rec)
    for r in wal["routed"]:
        ts = _num(r.get("ts"))
        off = (f"+{1000 * (ts - t0):8.1f} ms"
               if ts is not None and t0 is not None else "        ?")
        kind = "hedge " if r.get("hedge") else ("replay" if r["attempt"] > 1
                                                else "route ")
        rec = by_attempt.get(r.get("attempt"))
        side = ""
        if rec is not None:
            ttft = _num(rec.get("ttft_s"))
            side = (f"  -> replica outcome={rec.get('outcome')}"
                    + (f" ttft={1000 * ttft:.1f} ms" if ttft else ""))
        lines.append(f"    {off}  attempt {r.get('attempt')} "
                     f"{kind} -> {r.get('replica')}{side}")
    ts = _num(term.get("ts"))
    if ts is not None and t0 is not None:
        extras = " ".join(f"{k}={term[k]}" for k in
                          ("replays", "hedges", "via") if k in term)
        lines.append(f"    +{1000 * (ts - t0):8.1f} ms  terminal "
                     f"{term.get('outcome')} {extras}".rstrip())
    return lines


def ttft_breakdown(rec: dict) -> dict | None:
    """Decompose one record's TTFT into queue / own-prefill / interleave.

    queue is the recorded queue-wait, prefill is the sum of the request's
    own chunk durations (`prefill_s`), and interleave is whatever remains
    of TTFT — under chunked batched prefill that remainder is the time
    the request's chunks spent parked behind a neighbor's turn on the
    shared tick, the "prefill-behind-chunked-neighbor" phase.
    """
    ttft = _num(rec.get("ttft_s"))
    if ttft is None or ttft <= 0:
        return None
    queue = _num(rec.get("queue_wait_s")) or 0.0
    prefill = _num(rec.get("prefill_s")) or 0.0
    interleave = max(ttft - queue - prefill, 0.0)
    decode = max((_num(rec.get("wall_s")) or ttft) - ttft, 0.0)
    out = {"ttft_s": ttft,
           "queue_s": round(queue, 6),
           "prefill_s": round(prefill, 6),
           "interleave_s": round(interleave, 6),
           "decode_s": round(decode, 6),
           "queue_pct": round(100 * queue / ttft, 1),
           "prefill_pct": round(100 * prefill / ttft, 1),
           "interleave_pct": round(100 * interleave / ttft, 1)}
    cached = rec.get("prefix_cached_tokens")
    if isinstance(cached, int) and cached > 0:
        # prefix_cache_hit component: tokens the prefix cache served from
        # shared pages — prefill work this request never paid (the span of
        # the same name in the waterfall carries pages/cow detail)
        out["prefix_cached_tokens"] = cached
        out["prefix_shared_pages"] = int(rec.get("prefix_shared_pages") or 0)
        out["prefix_cow_fork"] = bool(rec.get("prefix_cow_fork"))
    return out


def tail_attribution(records: list[dict], quantile: float = 99.0) -> dict:
    """Aggregate breakdown over the TTFT tail (records at or above the
    given TTFT percentile): where does tail TTFT actually go?"""
    timed = [(r, _num(r.get("ttft_s"))) for r in records]
    timed = [(r, t) for r, t in timed if t is not None and t > 0]
    if not timed:
        return {}
    values = sorted(t for _, t in timed)
    idx = min(int(len(values) * quantile / 100.0), len(values) - 1)
    cut = values[idx]
    tail = [r for r, t in timed if t >= cut]
    queue = sum((_num(r.get("queue_wait_s")) or 0.0) for r in tail)
    prefill = sum((_num(r.get("prefill_s")) or 0.0) for r in tail)
    ttft = sum(t for _, t in timed if t >= cut)
    interleave = max(ttft - queue - prefill, 0.0)
    return {"quantile": quantile, "cut_ttft_s": round(cut, 6),
            "requests": len(tail),
            "queue_pct": round(100 * queue / ttft, 1),
            "prefill_pct": round(100 * prefill / ttft, 1),
            "interleave_pct": round(100 * interleave / ttft, 1)}


def tenant_tables(records: list[dict]) -> dict:
    """Per-tenant slices of the trace (completed outcomes drive the
    latency percentiles; shed/abandoned are counted separately)."""
    tenants: dict[str, dict] = {}
    for rec in records:
        tenant = rec.get("tenant")
        if not isinstance(tenant, str):
            continue
        t = tenants.setdefault(tenant, {"completed": 0, "shed": 0,
                                        "abandoned": 0, "failed": 0,
                                        "tokens": 0, "_ttft": [], "_tpot": []})
        outcome = rec.get("outcome")
        if outcome in t:
            t[outcome] += 1
        if outcome == "completed":
            t["tokens"] += int(rec.get("tokens") or 0)
            for metric in ("ttft", "tpot"):
                v = _num(rec.get(f"{metric}_s"))
                if v is not None:
                    t[f"_{metric}"].append(v)
    out = {}
    for name in sorted(tenants):
        t = tenants[name]
        row = {k: v for k, v in t.items() if not k.startswith("_")}
        row.update(percentiles_ms(t["_ttft"], "ttft", qs=(50, 95, 99)))
        row.update(percentiles_ms(t["_tpot"], "tpot", qs=(50, 95, 99)))
        out[name] = row
    return out


def exemplar_waterfall(rec: dict) -> list[str]:
    """Render one record's span tree as offset/duration lines relative to
    the request's arrival — the human-readable waterfall."""
    arrival = _num(rec.get("arrival"))
    lines = [f"  request {rec.get('request_id')} trace {rec.get('trace_id')}"
             f" tenant={rec.get('tenant')} outcome={rec.get('outcome')}"
             f" tokens={rec.get('tokens')}"]
    gw = rec.get("gateway")
    if isinstance(gw, dict):
        # gateway attribution (serve/gateway.py pass-through): which
        # dispatch attempt produced THIS replica-side record
        lines.append(
            f"  gateway attempt {gw.get('attempt')}"
            + (" (replay)" if gw.get("replay") else "")
            + (" (hedge)" if gw.get("hedge") else ""))
    bd = ttft_breakdown(rec)
    if bd:
        lines.append(
            f"  ttft {1000 * bd['ttft_s']:.1f} ms = "
            f"{bd['queue_pct']}% queue + {bd['prefill_pct']}% own prefill "
            f"+ {bd['interleave_pct']}% prefill-behind-chunked-neighbor; "
            f"decode {1000 * bd['decode_s']:.1f} ms")
        if bd.get("prefix_cached_tokens"):
            lines.append(
                f"  prefix cache hit: {bd['prefix_cached_tokens']} tokens "
                f"from {bd['prefix_shared_pages']} shared page(s)"
                + (", CoW fork" if bd.get("prefix_cow_fork") else ""))
    for span in rec.get("spans") or []:
        if not isinstance(span, dict):
            continue
        name = span.get("name", "?")
        ts = _num(span.get("ts"))
        off = (f"+{1000 * (ts - arrival):8.1f} ms" if ts is not None
               and arrival is not None else f"tick {span.get('tick', '?')}")
        dur = _num(span.get("dur"))
        dur_s = f" for {1000 * dur:7.1f} ms" if dur is not None else ""
        extras = " ".join(f"{k}={span[k]}" for k in
                          ("slot", "bucket", "verdict", "offset", "tokens",
                           "pages", "cow") if k in span)
        lines.append(f"    {off}{dur_s}  {name:<14} {extras}".rstrip())
    decode = rec.get("decode")
    if isinstance(decode, dict):
        lines.append(f"    decode: ticks {decode.get('first_tick')}.."
                     f"{decode.get('last_tick')} ({decode.get('ticks')} "
                     f"total), shared_with={decode.get('shared_with')}")
    if rec.get("slo_breach"):
        lines.append(f"    SLO breach: {rec['slo_breach']}"
                     + (f" -> capture {rec['capture']}"
                        if rec.get("capture") else ""))
    return lines


def build_report(output_dir: str, gateway_dir: str | None = None) -> dict:
    records = load_trace(output_dir)
    exemplars = load_exemplars(output_dir)
    gateway = (gateway_tables(load_gateway_journal(gateway_dir), records)
               if gateway_dir else None)
    completed = [r for r in records if r.get("outcome") == "completed"]
    shed = [r for r in records if r.get("outcome") == "shed"]
    ttft = [v for r in completed
            if (v := _num(r.get("ttft_s"))) is not None]
    tpot = [v for r in completed
            if (v := _num(r.get("tpot_s"))) is not None]
    timed = [(r, t) for r in completed
             if (t := _num(r.get("ttft_s"))) is not None]
    p99_exemplar = max(timed, key=lambda it: it[1])[0] if timed else None
    hits = [r for r in records
            if isinstance(r.get("prefix_cached_tokens"), int)
            and r["prefix_cached_tokens"] > 0]
    prefix = {
        "hits": len(hits),
        "cached_tokens": sum(r["prefix_cached_tokens"] for r in hits),
        "shared_pages": sum(int(r.get("prefix_shared_pages") or 0)
                            for r in hits),
        "cow_forks": sum(1 for r in hits if r.get("prefix_cow_fork")),
    } if hits else None
    return {"output_dir": output_dir,
            "gateway": gateway,
            "prefix": prefix,
            "records": len(records),
            "completed": len(completed),
            "shed": len(shed),
            "abandoned": sum(1 for r in records
                             if r.get("outcome") == "abandoned"
                             or r.get("abandoned")),
            "ttft": percentiles_ms(ttft, "ttft", qs=(50, 95, 99)),
            "tpot": percentiles_ms(tpot, "tpot", qs=(50, 95, 99)),
            "tail": tail_attribution(completed),
            "tenants": tenant_tables(records),
            "p99_exemplar": p99_exemplar,
            "exemplars": {m: [r.get("request_id") for r in recs
                              if isinstance(r, dict)]
                          for m, recs in exemplars.items()
                          if isinstance(recs, list)}}


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("output_dir")
    p.add_argument("--gateway", default=None, metavar="DIR",
                   help="gateway output dir: join its "
                        "gateway_journal.jsonl to the replica trace by "
                        "trace_id (dispatch attempts, replays, hedges)")
    p.add_argument("--json", action="store_true",
                   help="emit the full report as one JSON object")
    args = p.parse_args(argv)
    rep = build_report(args.output_dir, gateway_dir=args.gateway)
    if args.json:
        print(json.dumps(rep, indent=2))
        return 0 if rep["records"] else 1

    print(f"== request trace report: {rep['output_dir']} ==")
    if not rep["records"]:
        print(f"  no {REQUEST_TRACE_NAME} records found — tracing was off, "
              "or the directory is not a traced serve run")
        return 1
    print(f"  {rep['records']} records: {rep['completed']} completed, "
          f"{rep['shed']} shed, {rep['abandoned']} abandoned")
    if rep["prefix"]:
        px = rep["prefix"]
        print(f"  prefix cache: {px['hits']} hit(s), "
              f"{px['cached_tokens']} cached tokens, "
              f"{px['shared_pages']} shared page(s), "
              f"{px['cow_forks']} CoW fork(s)")
    for metric in ("ttft", "tpot"):
        table = rep[metric]
        cells = " ".join(f"p{q}={table.get(f'{metric}_p{q}_ms', '—')}"
                         for q in (50, 95, 99))
        print(f"  {metric:<6} {cells} (ms)")
    tail = rep["tail"]
    if tail:
        print(f"\n== tail attribution (TTFT >= p{tail['quantile']:g} = "
              f"{1000 * tail['cut_ttft_s']:.1f} ms, "
              f"{tail['requests']} request(s)) ==")
        print(f"  tail TTFT = {tail['queue_pct']}% queue + "
              f"{tail['prefill_pct']}% own prefill + "
              f"{tail['interleave_pct']}% prefill-behind-chunked-neighbor")
    if rep["p99_exemplar"] is not None:
        print("\n== slowest-TTFT exemplar waterfall ==")
        for line in exemplar_waterfall(rep["p99_exemplar"]):
            print(line)
    gw = rep.get("gateway")
    if gw:
        print(f"\n== gateway join ({gw['requests']} journalled "
              f"request(s)) ==")
        cells = " ".join(f"{k}={v}" for k, v in gw["outcomes"].items())
        print(f"  outcomes: {cells or '(none terminal)'}; "
              f"{gw['replayed']} replayed, {gw['hedged']} hedged, "
              f"{gw['orphans']} orphaned")
        print(f"  {gw['joined']} of {gw['requests']} joined to replica "
              f"trace records by trace_id")
        if gw["exemplar"]:
            print("\n== busiest dispatch waterfall (most attempts) ==")
            for line in gateway_waterfall(gw["exemplar"]["wal"],
                                          gw["exemplar"]["records"]):
                print(line)
    if rep["tenants"]:
        print("\n== per-tenant ==")
        for name, row in rep["tenants"].items():
            counts = " ".join(f"{k}={row[k]}" for k in
                              ("completed", "shed", "abandoned", "failed",
                               "tokens") if row.get(k))
            lat = " ".join(f"{k.replace('_ms', '')}="
                           f"{row[k]}" for k in row if k.endswith("_ms")
                           and row[k] is not None)
            print(f"  {name:<12} {counts}")
            if lat:
                print(f"  {'':<12} {lat} (ms)")
    if rep["exemplars"]:
        print("\n== exemplar snapshot (request_trace_exemplars.json) ==")
        for metric, ids in rep["exemplars"].items():
            print(f"  slowest by {metric}: {ids}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/bin/bash
# The first-live-chip hour, scripted (VERDICT round-4 task 1): the moment the
# TPU tunnel answers, capture — in strictly-decreasing-value order, each step
# timeout-bounded so a re-wedge mid-sprint keeps everything already banked —
#   1. the headline bench sweep + the 65B-path extras   -> sprint/bench.json
#   2. a profiler trace of the winning config           -> sprint/trace/
#      + the offline top-op table                       -> sprint/top_ops.txt
#   3. the preflight TPU-vs-CPU memory calibration      -> sprint/calibrate.txt
# Run from the repo root:  bash tools/chip_sprint.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-sprint}"
mkdir -p "$OUT"
# Persistent XLA compile cache: a sprint aborted by a re-wedge leaves its
# compiled programs behind, so the NEXT attempt skips straight to execution
# (the sweep's ~9 compiles are most of its chip time).
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}"
echo "chip sprint start: $(date -u +%FT%TZ)" | tee "$OUT/log.txt"

# 1+2. bench with profiling in ONE sweep: bench.py prints (banks) the result
# JSON before the BENCH_PROFILE block runs, and that block carries its own
# 600s wedge guard — a wedge during profiling can no longer cost the
# measurement, and no headline config compiles twice.
BENCH_PROFILE="$OUT/trace" timeout 1800 python bench.py \
    > "$OUT/bench.json" 2> "$OUT/bench.stderr"
rc=$?
echo "bench rc=$rc: $(head -c 300 "$OUT/bench.json")" | tee -a "$OUT/log.txt"
# a wedge mid-sweep exits nonzero with the every-config-failed sentinel
# (which still contains "value": 0.0) — test for the error key, not "value"
if grep -q '"error"' "$OUT/bench.json" 2>/dev/null \
        || ! grep -q '"value"' "$OUT/bench.json" 2>/dev/null; then
    echo "bench reported an error or nothing; chip likely re-wedged — " \
         "stopping (partial results, if any, are banked)" | tee -a "$OUT/log.txt"
    exit 1
fi

if [ -d "$OUT/trace" ]; then
    timeout 300 python tools/trace_summary.py --top 10 "$OUT/trace" \
        > "$OUT/top_ops.txt" 2>&1
    echo "top-op table -> $OUT/top_ops.txt" | tee -a "$OUT/log.txt"
fi

# 3. memory-estimate calibration (AOT compiles only). TPU backend only:
# the cpu half costs ~25 min of XLA-CPU compile on this 1-core host and is
# obtainable offline anytime — don't spend the live-chip window on it.
CALIBRATE_BACKENDS=tpu timeout 1100 python tools/preflight.py --calibrate \
    > "$OUT/calibrate.txt" 2>&1
echo "calibrate rc=$?" | tee -a "$OUT/log.txt"

echo "chip sprint done: $(date -u +%FT%TZ)" | tee -a "$OUT/log.txt"

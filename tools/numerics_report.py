"""Offline view of a run's numerics stream (`<output_dir>/numerics.jsonl`).

The training-dynamics counterpart of tools/goodput_report.py: where that
tool answers "where did wall-clock go", this one answers "what did the
optimization do" — per-stage norm trajectories, the anomaly timeline, and
first-nonfinite localization to a pipeline stage / layer-group (from the
per-step records plus the `numerics-snapshot-<step>.json` the monitor
dumps on each anomaly — utils/numerics.py, docs/OBSERVABILITY.md).

Usage:
  python tools/numerics_report.py <output_dir> [--json] [--top 5]

Follows the track-summary conventions of the sibling tools: one
`== section ==` per table; degrades (never tracebacks) on torn/missing
artifacts from a crashed run.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _num(v) -> float:
    """jsonl stat value -> float, via the writer's own codec (the monitor
    spells nonfinite floats as 'inf'/'-inf'/'nan' strings); junk from a
    torn line degrades to NaN."""
    from llama_pipeline_parallel_tpu.utils.numerics import stat_to_float

    try:
        return stat_to_float(v)
    except (TypeError, ValueError):
        return math.nan


def load_records(output_dir: str) -> list[dict]:
    from goodput_report import load_jsonl  # same torn-line-tolerant reader

    recs = [r for r in load_jsonl(os.path.join(output_dir, "numerics.jsonl"))
            if isinstance(r, dict) and "step" in r]
    # numerics.jsonl appends across incarnations: a resume re-runs the steps
    # after its checkpoint, so a step can have several records. The LAST one
    # is the surviving timeline (the run the checkpoints continue from) —
    # keep it, like the metrics/incarnation readers treat their streams.
    by_step: dict = {}
    for r in recs:
        by_step[r["step"]] = r
    return [by_step[s] for s in sorted(by_step)]


def stage_trajectories(records: list[dict],
                       field: str = "grad_norm_per_stage") -> list[dict]:
    """Per-stage summary of one per-stage field over the run: first/last/
    max finite value + nonfinite step count."""
    series: dict[int, list] = {}
    for r in records:
        vals = r.get(field)
        if not isinstance(vals, list):
            continue
        for s, v in enumerate(vals):
            series.setdefault(s, []).append((r["step"], _num(v)))
    out = []
    for s in sorted(series):
        pts = series[s]
        finite = [v for _, v in pts if math.isfinite(v)]
        out.append({
            "stage": s,
            "steps": len(pts),
            "first": finite[0] if finite else None,
            "last": finite[-1] if finite else None,
            "max": max(finite) if finite else None,
            "nonfinite_steps": sum(1 for _, v in pts if not math.isfinite(v)),
        })
    return out


def anomaly_timeline(records: list[dict]) -> list[dict]:
    return [{"step": r["step"], "kinds": r.get("anomaly"),
             "z_loss": r.get("z_loss"), "z_grad": r.get("z_grad"),
             "loss": r.get("loss"), "grad_norm": r.get("grad_norm")}
            for r in records if r.get("anomaly")]


def first_nonfinite(records: list[dict], output_dir: str) -> dict | None:
    """Localize the FIRST nonfinite step to a pipeline stage (from the
    per-stage vectors in the step record) and, when the anomaly snapshot
    exists, to the layer-groups whose gradients went nonfinite."""
    for r in records:
        if not r.get("nonfinite"):
            continue
        loc: dict = {"step": r["step"]}
        stages = set()
        for field in ("grad_norm_per_stage", "act_absmax_per_stage"):
            vals = r.get(field)
            if isinstance(vals, list):
                stages |= {s for s, v in enumerate(vals)
                           if not math.isfinite(_num(v))}
        loc["stages"] = sorted(stages)
        snap_path = os.path.join(output_dir, f"numerics-snapshot-{r['step']}.json")
        if os.path.exists(snap_path):
            try:
                with open(snap_path) as f:
                    snap = json.load(f)
            except (OSError, ValueError):
                snap = None
            if isinstance(snap, dict):
                groups = []
                for name, vals in (snap.get("grad_absmax_per_group") or {}).items():
                    if isinstance(vals, list) and any(
                            not math.isfinite(_num(v)) for v in vals):
                        groups.append(name)
                for name, v in (snap.get("replicated_groups") or {}).items():
                    if not math.isfinite(_num(v)):
                        groups.append(name)
                loc["groups"] = sorted(groups)
                loc["snapshot"] = os.path.basename(snap_path)
        return loc
    return None


def build_report(output_dir: str, top: int = 5) -> dict:
    records = load_records(output_dir)
    if not records:
        raise SystemExit(
            f"no numerics records under {output_dir} (numerics.jsonl missing "
            f"or empty — was the run started with numerics.enabled: false?)")
    anomalies = anomaly_timeline(records)
    return {
        "output_dir": output_dir,
        "records": len(records),
        "first_step": records[0]["step"],
        "last_step": records[-1]["step"],
        "nonfinite_steps": sum(1 for r in records if r.get("nonfinite")),
        "anomaly_count": len(anomalies),
        "anomalies": anomalies[:top],
        "first_nonfinite": first_nonfinite(records, output_dir),
        "grad_norm_per_stage": stage_trajectories(records, "grad_norm_per_stage"),
        "param_norm_per_stage": stage_trajectories(records, "param_norm_per_stage"),
        "act_rms_per_stage": stage_trajectories(records, "act_rms_per_stage"),
        "act_absmax_per_stage": stage_trajectories(records, "act_absmax_per_stage"),
        "snapshots": sorted(os.path.basename(p) for p in glob.glob(
            os.path.join(output_dir, "numerics-snapshot-*.json"))),
    }


def _fmt(v) -> str:
    return "-" if v is None else f"{v:.4g}"


def print_report(rep: dict) -> None:
    print(f"run: {rep['output_dir']}  ({rep['records']} numerics records, "
          f"steps {rep['first_step']}..{rep['last_step']})")
    print(f"  nonfinite steps: {rep['nonfinite_steps']}   anomalies: "
          f"{rep['anomaly_count']}")

    loc = rep.get("first_nonfinite")
    if loc:
        stages = ",".join(map(str, loc.get("stages", []))) or "?"
        groups = ",".join(loc.get("groups", [])) or "(no snapshot detail)"
        print(f"\n== first nonfinite ==\n  step {loc['step']}: pipeline "
              f"stage(s) {stages}; layer-group(s) {groups}")

    if rep["anomalies"]:
        print("\n== anomaly timeline ==")
        for a in rep["anomalies"]:
            zs = " ".join(f"{k}={a[k]}" for k in ("z_loss", "z_grad")
                          if a.get(k) is not None)
            print(f"  step {a['step']:<6} {','.join(a['kinds']):<24} "
                  f"loss={a['loss']} grad_norm={a['grad_norm']} {zs}")

    for field in ("grad_norm_per_stage", "param_norm_per_stage",
                  "act_rms_per_stage", "act_absmax_per_stage"):
        rows = rep.get(field)
        if not rows:
            continue
        print(f"\n== {field}: first -> last (max) ==")
        for row in rows:
            nf = (f"  NONFINITE x{row['nonfinite_steps']}"
                  if row["nonfinite_steps"] else "")
            print(f"  stage {row['stage']}:  {_fmt(row['first'])} -> "
                  f"{_fmt(row['last'])}  (max {_fmt(row['max'])}){nf}")

    if rep["snapshots"]:
        print(f"\n== anomaly snapshots ==\n  " + "\n  ".join(rep["snapshots"]))


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("output_dir", help="trainer output dir (holds numerics.jsonl)")
    p.add_argument("--top", type=int, default=5,
                   help="anomalies to list in the timeline")
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON instead of tables")
    args = p.parse_args(argv)
    rep = build_report(args.output_dir, top=args.top)
    if args.json:
        print(json.dumps(rep, indent=2))
    else:
        print_report(rep)


if __name__ == "__main__":
    main()

"""AOT memory preflight: compile the full train step for a big config on a
VIRTUAL device mesh and report XLA's per-device memory analysis vs the HBM
budget — no hardware needed.

This backs the BASELINE ladder's large configs (conf/llama_65b_pp8_tp2_dp2.yaml,
conf/codellama_34b_16k.yaml, conf/llama2_70b_pp4_tp4_dp2.yaml) with a
checked artifact instead of hand-computed HBM comments: the same technique
tests/test_pipeline.py::test_1f1b_memory_bounded_in_microbatches uses to pin
the 1F1B memory bound. The reference had no equivalent — its 65B memory
story is a README sentence (reference README.md:70-71).

Caveats (printed with the report): the analysis is XLA-CPU's compilation of
the SPMD program — TPU layouts/padding and Mosaic (flash) kernel VMEM differ,
so treat the numbers as an estimate with margin, not a guarantee.

Usage:
  python tools/preflight.py --config conf/llama_65b_pp8_tp2_dp2.yaml \
      [--hbm-gb 95] [key=value ...]
Exit code 1 when the estimate exceeds the budget.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mesh_product(config_path: str, overrides: list[str]) -> int:
    """Device count from the yaml's mesh block WITHOUT importing the package
    (jax must see XLA_FLAGS before its first import)."""
    import yaml

    with open(config_path) as f:
        raw = yaml.safe_load(f)
    mesh = dict(raw.get("mesh") or {})
    for ov in overrides:
        key, _, val = ov.lstrip("-").partition("=")
        if key.startswith("mesh."):
            mesh[key[len("mesh."):]] = int(val)
    n = 1
    for axis in ("pp", "dp", "tp", "sp"):
        n *= int(mesh.get(axis, 1))
    return n


def preflight(cfg: dict, hbm_gb: float) -> dict:
    """Lower + compile the training step ABSTRACTLY (no arrays materialize:
    65B fp32 masters never exist) and return the per-device byte breakdown."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding

    from llama_pipeline_parallel_tpu.models.llama import model as llama
    from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel import train_step as ts
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh
    from llama_pipeline_parallel_tpu.train import (
        build_manifest,
        build_model_config,
        build_pipeline_config,
        select_attention,
    )

    if cfg.get("optimizer_offload_zero2") and not cfg.get("optimizer_offload"):
        # mirror the trainer's rejection (train.py) — preflight passing a
        # config the real run refuses defeats its purpose
        raise ValueError("optimizer_offload_zero2 requires optimizer_offload: "
                         "true")
    mesh_cfg = MeshConfig(**cfg.get("mesh", {}))
    mesh = make_mesh(mesh_cfg)
    model_cfg = build_model_config(cfg["model"])
    # the trainer's own builders: the preflight must compile the SAME program
    manifest = build_manifest(cfg, model_cfg, mesh_cfg.pp)
    pcfg = build_pipeline_config(cfg, mesh_cfg, manifest)

    # the trainer probes the collator for the real row length; the synthetic
    # dataset's seq_length is that probe's answer for these configs
    data_cfg = cfg.get("dataset") or {}
    if not data_cfg or data_cfg.get("synthetic"):
        seq = data_cfg.get("seq_length", cfg.get("max_seq_length", 512))
    else:
        seq = cfg.get("max_seq_length", 512)
    # `auto` would try to TIME kernels — preflight must stay measurement-free.
    # Resolve it to EXACT, the conservative choice: at runtime auto may pick
    # either backend, and exact's O(L^2) score tensors are the memory
    # worst case (a flash-compiled estimate would under-count runs where
    # auto picks exact). Configs that pin `attention: flash` compile flash.
    impl = cfg.get("attention", "auto")
    attn_fn = select_attention("exact" if impl == "auto" else impl, seq, mesh,
                               sequence_parallel=pcfg.sequence_parallel,
                               packed=pcfg.packed)

    ocfg = OptimizerConfig(learning_rate=cfg.get("learning_rate", 1e-6),
                           total_steps=10, warmup_steps=1)
    tx, sched = make_optimizer(ocfg)

    # abstract, sharding-annotated state: eval_shape never runs the init
    def build(rng):
        return pl.stack_stages(llama.init_params(rng, model_cfg), manifest)

    stacked_abs = jax.eval_shape(build, jax.random.PRNGKey(0))
    shardings = ts.state_shardings(mesh, tx, stacked_abs)

    def annotate(tree_abs, tree_shard):
        return jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            tree_abs, tree_shard)

    opt_abs = jax.eval_shape(tx.init, stacked_abs)
    state_abs = ts.TrainState(
        step=jax.ShapeDtypeStruct((), jax.numpy.int32, sharding=shardings.step),
        params=annotate(stacked_abs, shardings.params),
        opt_state=annotate(opt_abs, shardings.opt_state))

    import jax.numpy as jnp

    # NOT multiplied by packing_factor: the loader feeds micro*accum*pack
    # EXAMPLES per replica, but the packed collator emits examples //
    # pack_factor ROWS (data/collator.py) — the device program sees
    # micro*accum rows either way
    global_batch = (cfg.get("per_device_train_batch_size", 1)
                    * pcfg.num_microbatches * mesh_cfg.dp)
    b_specs = pl.batch_specs(mesh)
    batch_abs = {
        k: jax.ShapeDtypeStruct((global_batch, seq), jnp.int32,
                                sharding=NamedSharding(mesh, b_specs[k]))
        for k in ("input_ids", "attention_mask", "position_ids", "labels")
    }

    if cfg.get("optimizer_offload"):
        # The offload path's DEVICE program is loss+grad only: bf16 working
        # params in, fp32 grads out; masters + Adam moments live in host
        # DRAM (optim/offload.py) exactly like the reference's 65B
        # ZeRO-offload run (reference conf yaml:160-162, README.md:70-71).
        # Under optimizer_offload_zero2 the grads leave the device
        # dp-sharded (reduce-scatter), matching the trainer's program.
        param_specs = pl.stage_param_specs(stacked_abs,
                                           tp=mesh.shape["tp"] > 1)
        bf16_abs = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(
                a.shape, model_cfg.dtype, sharding=NamedSharding(mesh, s)),
            stacked_abs, param_specs)
        out_shardings = None
        if cfg.get("optimizer_offload_zero2") and mesh_cfg.dp > 1:
            out_shardings = (None, ts.specs_to_shardings(
                mesh, ts.zero2_param_specs(stacked_abs, mesh)))
        grad_fn = jax.jit(pl.make_pipeline_loss_and_grad(
            mesh, model_cfg, pcfg, stacked_abs, attn_fn=attn_fn),
            out_shardings=out_shardings)
        compiled = grad_fn.lower(bf16_abs, batch_abs).compile()
    else:
        step = ts.make_train_step(mesh, model_cfg, pcfg, tx, sched, stacked_abs,
                                  attn_fn=attn_fn)
        compiled = step.lower(state_abs, batch_abs).compile()
    ma = compiled.memory_analysis()
    if ma is None:
        raise RuntimeError("backend exposes no compile-time memory analysis")

    gib = 1 << 30
    arg = getattr(ma, "argument_size_in_bytes", 0)
    out = getattr(ma, "output_size_in_bytes", 0)
    temp = getattr(ma, "temp_size_in_bytes", 0)
    alias = getattr(ma, "alias_size_in_bytes", 0)
    # donated state aliases into the outputs: alias bytes are counted once
    peak = arg + out + temp - alias
    report = {
        "compiled_path": "offload_loss_and_grad" if cfg.get("optimizer_offload")
                         else "fused_train_step",
        "devices": int(np.prod(list(mesh.shape.values()))),
        "global_batch_rows": global_batch,
        "seq": seq,
        "schedule": pcfg.schedule,
        "arguments_gib": round(arg / gib, 2),
        "outputs_gib": round(out / gib, 2),
        "temp_gib": round(temp / gib, 2),
        "aliased_gib": round(alias / gib, 2),
        "per_device_peak_gib": round(peak / gib, 2),
        "hbm_budget_gib": hbm_gb,
        "fits": peak / gib <= hbm_gb,
    }
    if pcfg.schedule == "zb1":
        # The zb1 split backward stashes a (chunk input, ring cotangent)
        # residual per queued W unit (docs/SCHEDULES.md "W-stash memory
        # bound"). XLA's peak above already counts these buffers — the
        # explicit term names the schedule's memory tax and sizes the
        # remedy when it blows the headroom (see the FAIL message in
        # main()): accum_chunks divides the per-flush queue.
        mb_rows = int(cfg.get("per_device_train_batch_size", 1))
        dtype_bytes = jax.numpy.dtype(model_cfg.dtype).itemsize
        stash = pl.wgrad_stash_bytes(
            pcfg, mb_rows, seq // max(mesh_cfg.sp, 1),
            model_cfg.hidden_size, dtype_bytes)
        report["wgrad_queue_depth"] = pl.wgrad_queue_peak(pcfg)
        report["wgrad_stash_gib"] = round(stash / gib, 2)
        headroom = hbm_gb - (peak - stash) / gib
        if stash / gib > max(headroom, 0.0):
            report["wgrad_stash_verdict"] = (
                f"W-stash {report['wgrad_stash_gib']} GiB exceeds the "
                f"{round(max(headroom, 0.0), 2)} GiB headroom left by the "
                f"rest of the step — raise gradient_accumulation_chunks "
                f"(halves the per-flush W-queue per doubling) or fall back "
                f"to pipeline_schedule: interleaved_1f1b")
        else:
            report["wgrad_stash_verdict"] = "fits within headroom"
    if cfg.get("optimizer_offload"):
        # host side: fp32 masters + two fp32 Adam moments, sharded per
        # process (optim/offload.py keeps only each host's device shards)
        n_params = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(stacked_abs))
        report["host_dram_total_gib"] = round(n_params * 12 / gib, 1)
    return report


def calibrate() -> dict:
    """Compile the BENCH config's single-chip train step on BOTH backends —
    the real TPU (via the axon platform) and XLA-CPU — and report both
    `memory_analysis()` peaks side by side. This puts an error bar on every
    XLA-CPU preflight verdict (the tool's own caveat: TPU layouts/padding and
    Mosaic VMEM differ). Run it whenever a chip is reachable; record the
    margin in docs/PREFLIGHT.md. AOT only — no arrays materialize, so it
    needs the tunnel for compilation RPCs but never runs a step."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from __graft_entry__ import _bench_config  # repo root on sys.path (module top)

    from llama_pipeline_parallel_tpu.models.llama import model as llama
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel import train_step as ts
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg = _bench_config()
    manifest = StageManifest.for_config(cfg, 1)
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-4,
                                               total_steps=1000, warmup_steps=10))
    gib = 1 << 30
    out: dict = {"model": "bench-550m", "batch": 8, "seq": 512}
    # cpu FIRST: a wedged TPU tunnel hangs the tpu compile, and the caller's
    # timeout should still have the cpu half on stdout by then. The cpu half
    # alone costs ~25 min of XLA-CPU compile on a 1-core host, so callers
    # racing a live-chip window (tools/chip_sprint.sh) can select backends:
    # CALIBRATE_BACKENDS=tpu skips it (the cpu number is obtainable offline).
    backends = tuple(b.strip().lower()
                     for b in os.environ.get("CALIBRATE_BACKENDS",
                                             "cpu,tpu").split(",")
                     if b.strip())
    if "tpu" not in backends:
        # cpu-only run: pin the platform so jax never initializes the axon
        # TPU client at all (a wedged/failing tunnel otherwise poisons even
        # the jax.devices("cpu") lookup)
        jax.config.update("jax_platforms", "cpu")
    for backend in backends:
        try:
            devices = jax.devices(backend)
        except RuntimeError as e:
            out[backend] = f"backend unavailable: {e}"
            continue
        mesh = make_mesh(MeshConfig(), devices=devices[:1])
        stacked_abs = jax.eval_shape(
            lambda rng: pl.stack_stages(llama.init_params(rng, cfg), manifest),
            jax.random.PRNGKey(0))
        shardings = ts.state_shardings(mesh, tx, stacked_abs)
        opt_abs = jax.eval_shape(tx.init, stacked_abs)

        def annotate(tree_abs, tree_shard):
            return jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
                tree_abs, tree_shard)

        state_abs = ts.TrainState(
            step=jax.ShapeDtypeStruct((), jnp.int32, sharding=shardings.step),
            params=annotate(stacked_abs, shardings.params),
            opt_state=annotate(opt_abs, shardings.opt_state))
        b_spec = NamedSharding(mesh, pl.batch_specs(mesh)["input_ids"])
        batch_abs = {k: jax.ShapeDtypeStruct((8, 512), jnp.int32, sharding=b_spec)
                     for k in ("input_ids", "attention_mask", "position_ids",
                               "labels")}
        pcfg = pl.PipelineConfig(num_stages=1, num_microbatches=1, remat=False)
        step = ts.make_train_step(mesh, cfg, pcfg, tx, sched, stacked_abs)
        ma = step.lower(state_abs, batch_abs).compile().memory_analysis()
        if ma is None:
            out[backend] = "no memory analysis exposed"
            continue
        arg = getattr(ma, "argument_size_in_bytes", 0)
        o = getattr(ma, "output_size_in_bytes", 0)
        temp = getattr(ma, "temp_size_in_bytes", 0)
        alias = getattr(ma, "alias_size_in_bytes", 0)
        out[backend] = {"arguments_gib": round(arg / gib, 3),
                        "outputs_gib": round(o / gib, 3),
                        "temp_gib": round(temp / gib, 3),
                        "peak_gib": round((arg + o + temp - alias) / gib, 3)}
        print(f"calibrate[{backend}]: {out[backend]}", flush=True)
    if isinstance(out.get("tpu"), dict) and isinstance(out.get("cpu"), dict):
        cpu_peak, tpu_peak = out["cpu"]["peak_gib"], out["tpu"]["peak_gib"]
        out["tpu_over_cpu"] = round(tpu_peak / cpu_peak, 3) if cpu_peak else None
    return out


def resume_compat(cfg: dict) -> dict | None:
    """Elastic-resume preflight (docs/RESILIENCE.md "Elastic resume"): when
    the config's output_dir already holds a checkpoint this run would
    resume, compare its recorded source topology and data contract against
    the config — BEFORE burning a compile on a resume that will warn about
    (or silently accept) a changed global batch. Returns None when there is
    nothing to resume; never fails the preflight (topology changes are
    legal — that is the point of elastic restore)."""
    import json as _json

    out_dir = cfg.get("output_dir")
    if not out_dir or not os.path.isdir(out_dir) or not cfg.get("resume", True):
        return None
    # read meta.json directly (no CheckpointManager: the preflight must not
    # create dirs or spin up Orbax just to peek at a marker file)
    latest = None
    try:
        import re as _re

        for d in os.listdir(out_dir):
            m = _re.match(r"^checkpoint-(\d+)$", d)
            if m and os.path.isfile(os.path.join(out_dir, d, "meta.json")):
                latest = max(latest or 0, int(m.group(1)))
        if latest is None:
            return None
        with open(os.path.join(out_dir, f"checkpoint-{latest}",
                               "meta.json")) as f:
            meta = _json.load(f)
    except (OSError, ValueError):
        return None  # torn/corrupt meta: the trainer's quarantine handles it
    mesh = dict(cfg.get("mesh") or {})
    current = {"pp": int(mesh.get("pp", 1)), "dp": int(mesh.get("dp", 1)),
               "tp": int(mesh.get("tp", 1)), "sp": int(mesh.get("sp", 1)),
               "schedule": cfg.get("pipeline_schedule", "1f1b"),
               "virtual_stages": int(cfg.get("virtual_stages", 1) or 1)}
    report: dict = {"resume_step": latest}
    source = meta.get("topology")
    if source:
        changed = sorted(k for k in current if source.get(k) != current[k])
        report["source_topology"] = source.get("layout", source)
        report["topology_changed"] = changed or "no"
        if source.get("schedule") != current["schedule"]:
            # a schedule change is as restore-relevant as a topology one:
            # the stacked layout changes (flat [S,k] vs chunked [S,v,k])
            # even though the canonical checkpoint restores into either
            report["schedule_changed"] = (
                f"{source.get('schedule', '1f1b')} -> {current['schedule']} "
                f"(layout re-stacks from the canonical checkpoint; "
                f"docs/SCHEDULES.md)")
    data_state = meta.get("data_state")
    if data_state:
        packing = int(cfg.get("packing_factor", 1) or 1)
        g_now = (current["dp"] * int(cfg.get("per_device_train_batch_size", 1))
                 * int(cfg.get("gradient_accumulation_steps", 1)) * packing)
        g_ckpt = data_state.get("global_batch_examples")
        report["global_batch_examples"] = {"checkpoint": g_ckpt,
                                           "config": g_now}
        report["data_contract"] = (
            "exact (O(1) reposition, zero dropped/duplicated samples)"
            if g_ckpt == g_now else
            "REMAPPED — global batch changed; re-trains at most one partial "
            "batch and shifts the lr-schedule/epoch mapping "
            "(docs/RESILIENCE.md)")
    return report


def _run_all(patterns: list[str], hbm_gb: float, overrides: list[str]) -> None:
    """Preflight every config matching `patterns` in its own subprocess (each
    needs a different virtual device count, fixed at jax import) and print a
    pass/fail table — one command reproduces docs/PREFLIGHT.md."""
    import glob as globmod
    import re
    import subprocess

    paths = sorted({p for pat in patterns for p in globmod.glob(pat)})
    if not paths:
        raise SystemExit(f"no configs match {patterns!r}")
    rows, any_fail = [], False
    for path in paths:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--config", path,
             "--hbm-gb", str(hbm_gb), *overrides],
            capture_output=True, text=True)
        peak = "?"
        m = re.search(r"per_device_peak_gib: ([0-9.]+)", proc.stdout)
        if m:
            peak = m.group(1)
        ok = proc.returncode == 0
        any_fail |= not ok
        rows.append((path, peak, "OK" if ok else "FAIL"))
        print(f"{'OK  ' if ok else 'FAIL'} {path}: peak {peak} GiB",
              flush=True)
        if not ok and not m:  # compile error, not a budget miss: show why
            print((proc.stdout + proc.stderr).strip()[-800:], flush=True)
    print(f"\n{'config':<40} {'peak GiB':>9}  verdict")
    for path, peak, verdict in rows:
        print(f"{path:<40} {peak:>9}  {verdict}")
    if any_fail:
        sys.exit(1)


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default=None,
                   help="one config yaml (or use --all for a sweep)")
    p.add_argument("--hbm-gb", type=float, default=95.0,
                   help="per-chip HBM budget in GiB (TPU v5p: 95)")
    p.add_argument("--all", dest="all_globs", nargs="*", default=None,
                   metavar="GLOB",
                   help="preflight every config matching the GLOB pattern(s) "
                        "(default conf/*.yaml; unquoted shell-expanded paths "
                        "work too), one subprocess each (XLA device counts "
                        "differ per config), and print a summary table; "
                        "exit 1 if any fails")
    p.add_argument("--calibrate", action="store_true",
                   help="compile the bench config on the real TPU and/or "
                        "XLA-CPU (CALIBRATE_BACKENDS=cpu,tpu — default "
                        "both; cpu alone costs ~25 min of XLA-CPU compile) "
                        "and print each memory_analysis() peak — the error "
                        "bar for every CPU-estimate verdict (tpu needs the "
                        "tunnel; AOT only, runs nothing)")
    p.add_argument("overrides", nargs="*", help="key=value config overrides")
    args, unknown = p.parse_known_args(argv)
    bad = [u for u in unknown if not (u.startswith("--") and "=" in u)]
    if bad:
        p.error(f"unrecognized arguments: {' '.join(bad)}")
    args.overrides += unknown

    if args.calibrate:
        import json

        print(json.dumps(calibrate(), indent=2))
        return
    if args.all_globs is not None:
        if args.config:
            p.error("--config and --all are mutually exclusive")
        # nargs='*' greedily consumes trailing key=value overrides too:
        # route anything that isn't a yaml path/glob back to overrides
        globs = [g for g in args.all_globs
                 if g.endswith((".yaml", ".yml")) or "*" in g]
        stray = [g for g in args.all_globs if g not in globs]
        if any("=" not in s for s in stray):
            p.error(f"--all takes .yaml globs; got {stray}")
        _run_all(globs or ["conf/*.yaml"], args.hbm_gb,
                 stray + args.overrides)
        return
    if args.config is None:
        p.error("--config is required (or pass --all for a sweep)")

    n_devices = _mesh_product(args.config, args.overrides)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")  # sitecustomize pins TPU otherwise

    from llama_pipeline_parallel_tpu.utils.config import load_config

    cfg = load_config(args.config, args.overrides)
    print(f"preflight: {args.config} on {n_devices} virtual devices "
          f"(XLA-CPU estimate; TPU layouts/Mosaic VMEM differ — keep margin)")
    report = preflight(cfg, args.hbm_gb)
    for k, v in report.items():
        print(f"  {k}: {v}")
    resume = resume_compat(cfg)
    if resume:
        print("resume preflight (elastic — docs/RESILIENCE.md):")
        for k, v in resume.items():
            print(f"  {k}: {v}")
    if not report["fits"]:
        print(f"preflight FAIL: per-device peak {report['per_device_peak_gib']} GiB "
              f"exceeds the {args.hbm_gb} GiB budget")
        if "wgrad_queue_depth" in report:  # zb1 configs, even a tiny stash
            # actionable zb1 guidance: the W-stash is the schedule's own
            # memory tax, and accum_chunks is its dial (docs/SCHEDULES.md)
            print(f"  zb1 W-stash: {report['wgrad_stash_gib']} GiB across "
                  f"{report['wgrad_queue_depth']} queued units — raise "
                  f"gradient_accumulation_chunks to shrink the per-flush "
                  f"W-queue, or fall back to pipeline_schedule: "
                  f"interleaved_1f1b")
        sys.exit(1)
    print("preflight OK")


if __name__ == "__main__":
    main()

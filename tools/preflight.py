"""AOT memory preflight: compile the full train step for a big config on a
VIRTUAL device mesh and report XLA's per-device memory analysis vs the HBM
budget — no hardware needed.

This backs the BASELINE ladder's large configs (conf/llama_65b_pp8_tp2_dp2.yaml,
conf/codellama_34b_16k.yaml, conf/llama2_70b_pp4_tp4_dp2.yaml) with a
checked artifact instead of hand-computed HBM comments: the same technique
tests/test_pipeline.py::test_1f1b_memory_bounded_in_microbatches uses to pin
the 1F1B memory bound. The reference had no equivalent — its 65B memory
story is a README sentence (reference README.md:70-71).

Caveats (printed with the report): the analysis is XLA-CPU's compilation of
the SPMD program — TPU layouts/padding and Mosaic (flash) kernel VMEM differ,
so treat the numbers as an estimate with margin, not a guarantee.

Usage:
  python tools/preflight.py --config conf/llama_65b_pp8_tp2_dp2.yaml \
      [--hbm-gb 95] [key=value ...]
Exit code 1 when the estimate exceeds the budget.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mesh_product(config_path: str, overrides: list[str]) -> int:
    """Device count from the yaml's mesh block WITHOUT importing the package
    (jax must see XLA_FLAGS before its first import)."""
    import yaml

    with open(config_path) as f:
        raw = yaml.safe_load(f)
    mesh = dict(raw.get("mesh") or {})
    for ov in overrides:
        key, _, val = ov.lstrip("-").partition("=")
        if key.startswith("mesh."):
            mesh[key[len("mesh."):]] = int(val)
    n = 1
    for axis in ("pp", "dp", "tp", "sp"):
        n *= int(mesh.get(axis, 1))
    return n


def _host_transfers_enabled() -> bool:
    from llama_pipeline_parallel_tpu.utils import host_stash

    return host_stash.transfers_enabled()


def counted_device_terms_gib(pcfg, dims: tuple) -> float:
    """GiB a GATED-OFF compile (no host memory space) keeps device-resident
    for the schedule's ring/stash stores: the full buffers, plus the host
    rings' garbage slots for the stores marked tiered — what must be
    subtracted from an anchored compile's peak before re-adding the real
    shape's terms (see preflight()'s anchored-compile mode)."""
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl

    mb_rows, local_seqlen, hidden_size, dtype_bytes = dims
    slot = mb_rows * local_seqlen * hidden_size * dtype_bytes
    total = (pl.activation_ring_bytes(pcfg, *dims)
             + pl.wgrad_stash_bytes(pcfg, *dims))
    if pl.wgrad_partition(pcfg)[1]:
        total += 2 * slot
    if pcfg.offload_activations and pl.activation_ring_slots(pcfg):
        total += slot
    return total / (1 << 30)


def _step_compute_seconds(model_cfg, mesh_cfg, pcfg, mb_rows: int, seq: int,
                          mfu: float, chip_flops: float | None) -> float:
    """Modeled per-device compute seconds of one training step: the
    overlap budget the offload traffic must hide inside. Uses the same
    train_flops_per_token the bench MFU math uses; each device sees its dp
    shard's tokens through 1/(pp*tp*sp) of the model."""
    from llama_pipeline_parallel_tpu.utils.metrics import (
        detect_chip_peak_flops,
        train_flops_per_token,
    )

    peak = chip_flops or detect_chip_peak_flops() or 197e12
    tokens = mb_rows * pcfg.num_microbatches * seq
    shards = mesh_cfg.pp * mesh_cfg.tp * mesh_cfg.sp
    return train_flops_per_token(model_cfg, seq) * tokens / shards / (
        peak * max(mfu, 1e-6))


def offload_traffic_bytes(pcfg, dims: tuple) -> int:
    """Host-link bytes ONE STEP moves for the enabled offload knobs, both
    directions (every tiered residual goes D2H once at stash time and H2D
    once at consume time; accum_chunks shifts WHEN, not how much): the
    zb1 W queue moves 2 buffers per unit x Mv units x 2 directions, the
    activation ring 1 buffer per unit x 2 directions."""
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl

    mb_rows, local_seqlen, hidden_size, dtype_bytes = dims
    slot = mb_rows * local_seqlen * hidden_size * dtype_bytes
    units = pcfg.num_microbatches * pcfg.virtual_stages
    total = 0
    # W-residual link traffic. A MIXED per-unit vector is charged the FULL
    # per-flush unit count, not just the tiered subset: the interpreter's
    # tick-uniform SPMD body pushes the host buffer every B tick (the
    # predicate only redirects non-tiered units to the garbage slot — the
    # D2H copy still moves) and where-selects every W pop from both
    # buffers (one H2D per unit either way). Selective offload's win is
    # host RESIDENCY (few slots live), never link bytes — the model must
    # not promise hiding the hardware won't deliver.
    hbm_slots, host_slots = pl.wgrad_partition(pcfg)
    if host_slots:
        wgrad_units = (pl.wgrad_offloaded_units(pcfg) if hbm_slots == 0
                       else units // pcfg.accum_chunks)
        total += 4 * wgrad_units * pcfg.accum_chunks * slot
    if pcfg.offload_activations and pl.activation_ring_slots(pcfg):
        total += 2 * units * slot
    return total


def offload_feasibility(pcfg, dims: tuple, step_compute_s: float,
                        host_bw_gibps: float) -> dict:
    """The bandwidth half of the memory model: modeled transfer seconds
    over modeled compute seconds (`offload_hide_ratio`). Ratios <= 1 can
    in principle hide entirely behind compute (XLA's async copies overlap
    the scan phases — parallel/pipeline.py); ratios above it WILL stall
    the step no matter how the copies are scheduled."""
    gib = 1 << 30
    traffic = offload_traffic_bytes(pcfg, dims)
    transfer_s = traffic / (host_bw_gibps * gib)
    return {
        "offload_traffic_gib_per_step": round(traffic / gib, 2),
        "offload_transfer_s_model": round(transfer_s, 3),
        "offload_compute_s_model": round(step_compute_s, 3),
        "offload_hide_ratio": round(transfer_s / max(step_compute_s, 1e-9),
                                    3),
    }


# ---------------------------------------------------------------------------
# Schedule selection: enumerate (schedule, v, accum, offload) candidates
# against the budget and pick analytically (OptPipe-style: solve for the
# schedule/memory trade instead of hand-picking it — PAPERS.md 2510.05186)
# ---------------------------------------------------------------------------

def _stash_device_bytes(hbm_slots: int, host_slots: int, slot: int) -> int:
    """Device-resident bytes of a W queue's slot split: the full HBM-side
    buffers plus, when anything tiers to host, the in-flight transfer
    slots (2 per buffer direction, capped at 4 slot-equivalents). ONE
    spelling shared by candidate_device_terms_gib and solver_candidates'
    binary-search estimator so the two can never drift."""
    return 2 * hbm_slots * slot + (min(2 * host_slots * slot, 4 * slot)
                                   if host_slots else 0)


def candidate_device_terms_gib(pcfg, dims: tuple, vocab: int | None = None
                               ) -> dict:
    """The schedule-DEPENDENT device-memory terms of one candidate, GiB:
    the stage-input ring buffer and (zb1) the W stash — each replaced by
    two in-flight transfer slots when its store tiers to host — plus, when
    `vocab` is given, the last stage's loss-head term (the live fp32
    logits block + chunked-backward dh accumulator of the XLA path; ~0 for
    `kernels.ce: pallas` — pl.loss_head_bytes). Everything else in the
    step (weights, grads, optimizer, transient activations) is
    schedule-independent at fixed batch shape, which is what lets selection
    anchor on ONE compiled peak (see select_schedule)."""
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl

    gib = 1 << 30
    mb_rows, local_seqlen, hidden_size, dtype_bytes = dims
    slot = mb_rows * local_seqlen * hidden_size * dtype_bytes
    ring = pl.activation_ring_bytes(pcfg, *dims)
    ring_dev = min(ring, 2 * slot) if pcfg.offload_activations else ring
    hbm_slots, host_slots = pl.wgrad_partition(pcfg)
    stash_dev = _stash_device_bytes(hbm_slots, host_slots, slot)
    head = (pl.loss_head_bytes(pcfg, mb_rows, local_seqlen, hidden_size,
                               vocab) if vocab else 0)
    return {"ring_gib": ring_dev / gib, "stash_gib": stash_dev / gib,
            "host_gib": pl.host_stash_bytes(pcfg, *dims) / gib,
            "loss_head_gib": head / gib}


def enumerate_candidates(num_stages: int, microbatches: int, num_layers: int,
                         max_virtual: int = 4,
                         accum_options: tuple = (1, 2, 4, 8),
                         ce_options: tuple | None = None,
                         layer_counts: tuple | None = None) -> list:
    """Every valid PipelineConfig in the selection grid: schedule x
    virtual_stages (layer-divisible) x accum_chunks (microbatch-divisible)
    x offload tiers (wgrad for zb1, activations for all hand-written
    backwards) x — when `ce_options` is given — the loss-head axis, each
    entry a (loss_chunks, kernel_ce) pair (docs/KERNELS.md; the default
    keeps the legacy grid so the axis is opt-in). Validity delegates to
    PipelineConfig's own constructor — one source of truth for the
    divisibility rules.

    `layer_counts`: an UNEQUAL stage partition (from
    StageManifest.balanced at layer-indivisible pp — the layout lane's
    cost-balancing). Offered to the flat and zb1-v1 schedules only (the
    round-robin chunk layout has no uneven form); their bubble_fraction is
    then counted with per-stage unit costs (parallel/schedule.py)."""
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl

    uneven = (layer_counts is not None and len(set(layer_counts)) != 1)
    ce_axis = tuple(ce_options) if ce_options else ((1, False),)
    cands = []
    for schedule in ("1f1b", "interleaved_1f1b", "zb1"):
        if schedule == "1f1b":
            vs = (1,)
        elif uneven:
            vs = (1,) if schedule == "zb1" else ()
        else:
            vs = tuple(v for v in (1, 2, 4)
                       if v <= max_virtual
                       and num_layers % (num_stages * v) == 0)
        for v in vs:
            for c in accum_options:
                offloads = [(False, False), (False, True)]
                if schedule == "zb1":
                    offloads += [(True, False), (True, True)]
                for ow, oa in offloads:
                    for ce_chunks, ce_kernel in ce_axis:
                        try:
                            cands.append(pl.PipelineConfig(
                                num_stages=num_stages,
                                num_microbatches=microbatches,
                                schedule=schedule, virtual_stages=v,
                                accum_chunks=c, offload_wgrad=ow,
                                offload_activations=oa,
                                loss_chunks=ce_chunks,
                                kernel_ce=ce_kernel,
                                layer_counts=layer_counts))
                        except ValueError:
                            continue
    return cands


def solver_candidates(num_stages: int, microbatches: int, num_layers: int,
                      base_gib: float, dims: tuple, hbm_gb: float,
                      max_virtual: int = 4,
                      accum_options: tuple = (1, 2, 4, 8),
                      head_gib: float = 0.0,
                      mem_scale: float = 1.0) -> list:
    """Solver-EMITTED sequences as selection candidates (the list-scheduling
    search beyond the three canonical shapes — docs/SCHEDULES.md 'Solver
    schedules'). For each split-backward (v, accum, W-placement) grid
    point the list scheduler emits a sequence, then sizes its per-unit
    offload decision vector against the budget: tier the MINIMUM number
    of residual units for base + ring + remaining HBM stash slots to fit
    (fewest tiered bytes at the canonical bubble — strictly better than
    the all-or-nothing boolean whenever 0 < k < n fits). The k=0 and
    k=n_units boundary points reproduce `offload.wgrad_stash` off/on
    exactly. Candidates that cannot fit even fully tiered are emitted
    fully tiered and left for select_schedule to refuse with the others."""
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel import schedule as usched

    import numpy as np

    gib = 1 << 30
    mb_rows, local_seqlen, hidden_size, dtype_bytes = dims
    slot = mb_rows * local_seqlen * hidden_size * dtype_bytes
    cands = []
    vs = tuple(v for v in (1, 2, 4)
               if v <= max_virtual and num_layers % (num_stages * v) == 0)
    for v in vs:
        for c in accum_options:
            if microbatches % c:
                continue
            m_flush = microbatches // c
            if v > 1 and m_flush % num_stages:
                continue
            for placement in ("trailing", "drain"):
                try:
                    seq = usched.list_schedule(m_flush, num_stages, v,
                                               w_placement=placement)
                except usched.ScheduleError:
                    continue

                def build(vector):
                    s = usched.with_offload(seq, vector)
                    return pl.PipelineConfig(
                        num_stages=num_stages, num_microbatches=microbatches,
                        schedule="solver", virtual_stages=v, accum_chunks=c,
                        unit_schedule=s)

                # the ring term is offload-vector-invariant: hoist it out
                # of the binary search
                ring = seq.ring_slots * slot if bool(seq.has_f.any()) else 0

                def est(vector):
                    # must mirror select_schedule's scoring — candidate_
                    # device_terms_gib for a no-activation-offload solver
                    # config (the stash term via the SHARED
                    # _stash_device_bytes spelling) — including the
                    # loss-head term it charges when a vocab is in play
                    # (`head_gib` — solver rows run the as-written dense
                    # head; a vector sized without it would come up short
                    # at exactly the tight budgets this lane exists for).
                    # Computed from the slot assignment DIRECTLY (not via
                    # a PipelineConfig, whose constructor re-validates the
                    # whole sequence — the binary search probes this a
                    # dozen times per grid point, and the layout lane runs
                    # the grid per mesh)
                    s = usched.with_offload(seq, vector)
                    stash = _stash_device_bytes(s.wq_hbm_slots,
                                                s.wq_host_slots, slot)
                    # mem_scale: the calibrated live/model peak ratio
                    # (perf.derive_calibration) — the SAME scaling
                    # select_schedule applies, or the vector would be
                    # sized against a different budget than it's scored by
                    return (base_gib + (ring + stash) / gib
                            + head_gib) * mem_scale

                n = seq.n_units
                if est(np.zeros(n, bool)) <= hbm_gb:
                    k = 0
                else:
                    # minimal k: tier the earliest-scheduled units first
                    # (their transfers start streaming soonest); binary
                    # search on the actual slot assignment, not the
                    # arithmetic guess — drain placements reuse slots
                    lo, hi = 1, n
                    while lo < hi:
                        mid = (lo + hi) // 2
                        vec = np.zeros(n, bool)
                        vec[:mid] = True
                        if est(vec) <= hbm_gb:
                            hi = mid
                        else:
                            lo = mid + 1
                    k = lo
                vec = np.zeros(n, bool)
                vec[:k] = True
                cands.append(build(vec))
    return cands


def select_schedule(candidates: list, base_gib: float, dims: tuple,
                    hbm_gb: float, host_bw_gibps: float,
                    step_compute_fn, hide_max: float = 1.0,
                    vocab: int | None = None,
                    mem_scale: float = 1.0) -> tuple:
    """Score every candidate against the HBM budget AND the host-bandwidth
    bound, and pick the feasible one with the lowest analytic bubble
    (ties: lower host residency first — never move bytes for nothing —
    then lower device peak; the ce axis resolves through the peak, since
    the loss-head term is the only byte it moves). `base_gib` is the
    schedule-independent anchor: the as-written config's compiled device
    peak minus ITS ring/stash (and, with `vocab`, loss-head) terms.
    `step_compute_fn(pcfg) -> seconds` models the overlap budget
    (accum_chunks does not change it — same flops, more flushes).
    `mem_scale` (measured live peak / byte-model peak, from the memory
    observatory via `--calibration`) scales every candidate's estimate —
    a >1 ratio tightens the feasibility cut to what the live telemetry
    actually saw, re-ranking the frontier from measurement.
    Returns (winner_row_or_None, all_rows)."""
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl

    rows = []
    for pcfg in candidates:
        terms = candidate_device_terms_gib(pcfg, dims, vocab)
        est = (base_gib + terms["ring_gib"] + terms["stash_gib"]
               + terms["loss_head_gib"]) * mem_scale
        feas = offload_feasibility(pcfg, dims, step_compute_fn(pcfg),
                                   host_bw_gibps)
        fits_hbm = est <= hbm_gb
        hides = feas["offload_hide_ratio"] <= hide_max
        row_extra = {}
        if pcfg.schedule == "solver":
            us = pcfg.unit_schedule
            row_extra = {"label": us.label,
                         "wgrad_offload_units": us.offloaded_units,
                         "wgrad_units_total": us.n_units,
                         "_pcfg": pcfg}
        rows.append({
            "schedule": pcfg.schedule, "virtual_stages": pcfg.virtual_stages,
            "accum_chunks": pcfg.accum_chunks,
            "offload_wgrad": pcfg.offload_wgrad,
            "offload_activations": pcfg.offload_activations,
            "loss_chunks": pcfg.loss_chunks,
            "kernel_ce": pcfg.kernel_ce,
            **row_extra,
            "est_peak_gib": round(est, 2) + 0.0,  # normalize -0.0
            "host_stash_gib": round(terms["host_gib"], 2) + 0.0,
            "loss_head_gib": round(terms["loss_head_gib"], 2) + 0.0,
            "bubble_fraction": round(pl.bubble_fraction(pcfg), 4),
            "hide_ratio": feas["offload_hide_ratio"],
            "feasible": fits_hbm and hides,
            "why_not": None if fits_hbm and hides else
                       ("exceeds HBM budget" if not fits_hbm else
                        "offload traffic cannot hide behind compute"),
        })
    feasible = [r for r in rows if r["feasible"]]
    winner = min(feasible, key=lambda r: (r["bubble_fraction"],
                                          r["host_stash_gib"],
                                          r["est_peak_gib"]),
                 default=None)
    return winner, rows


def ce_axis_options(loss_chunks: int, vocab: int, tp: int) -> tuple | None:
    """The loss-head axis --select scores (docs/KERNELS.md): the as-written
    chunking, an 8-way chunked XLA head where the vocab divides, and ONE
    Pallas option at the kernel's own VMEM sizing — lane-exact 128-wide
    vocab tiles (V/128 chunks), per pallas_ce_sum_count's contract. The
    XLA-scale chunk counts are never offered for the kernel: its
    [d, V/chunks] weight tile at 8 chunks is tens of MiB against ~16 MiB
    VMEM, a Mosaic refusal interpret-mode CI cannot see. None at tp>1: the
    head is already vocab-parallel there and the trainer REJECTS
    loss_chunks/kernels.ce overrides, so selection must not emit them."""
    if tp > 1:
        return None
    opts = {(loss_chunks, False)}
    if vocab % 8 == 0:
        opts.add((8, False))
    if vocab % 128 == 0:
        opts.add((vocab // 128, True))
    return tuple(sorted(opts))


# ---------------------------------------------------------------------------
# Layout auto-selection: grow the OUTER (pp, tp, dp, sp) axes for a device
# count, re-evaluate the memory model per candidate mesh, rank the frontier
# by an analytic step-time score, and emit the supervisor ladder as DATA
# (ROADMAP item 3: the hand-written --layout-ladder becomes generated).
# ---------------------------------------------------------------------------

def _divisors(n: int) -> tuple:
    return tuple(d for d in range(1, n + 1) if n % d == 0)


def enumerate_layouts(devices: int, model_cfg, seq: int,
                      global_batch_examples: int, mb_rows: int,
                      max_tp: int = 8, max_sp: int = 4) -> list[dict]:
    """Every (pp, tp, dp, sp) mesh of EXACTLY `devices` chips the model and
    batch shape admit, each with its microbatch count at the PRESERVED
    global batch (the elastic data contract: a dp change is compensated in
    gradient_accumulation_steps, never in examples/step) and its stage
    partition (even where layers divide, StageManifest.balanced counts
    where they don't — the unequal-stage lever SkipPipe/MPMD-PP open).

    The divisibility rules mirror the trainer's own validation
    (parallel/pipeline.py make_pipeline_loss_and_grad, mesh.MeshConfig):
    anything emitted here must survive the launch line."""
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest

    layouts = []
    for pp in _divisors(devices):
        if pp > model_cfg.num_hidden_layers:
            continue
        for tp in _divisors(devices // pp):
            if tp > max_tp:
                continue
            if (model_cfg.num_attention_heads % tp
                    or model_cfg.kv_heads % tp
                    or model_cfg.intermediate_size % tp
                    or model_cfg.vocab_size % tp):
                continue
            for sp in _divisors(devices // (pp * tp)):
                if sp > max_sp or seq % sp:
                    continue
                dp = devices // (pp * tp * sp)
                micro, rem = divmod(global_batch_examples, mb_rows * dp)
                if rem or micro < 1:
                    continue
                if model_cfg.num_hidden_layers % pp == 0:
                    counts = None
                else:
                    counts = StageManifest.balanced(
                        model_cfg, pp).stage_layer_counts
                layouts.append({"pp": pp, "tp": tp, "dp": dp, "sp": sp,
                                "microbatches": micro,
                                "layer_counts": counts})
    return layouts


def layout_device_gib(model_cfg, pp: int, tp: int, dp: int,
                      layer_counts: tuple | None = None,
                      optimizer_offload: bool = True,
                      zero2: bool = True) -> float:
    """Schedule-INDEPENDENT analytic device memory of a layout, GiB: the
    bf16 working params of one stage's (padded) layer slots at the tp
    shard width plus the replicated embed / final norm / vocab-parallel
    lm-head, the fp32 gradient trees the step holds live (accumulator +
    per-tick grads + returned grads — the returned tree dp-sharded under
    ZeRO-2's reduce-scatter), and — on the fused path — the fp32 masters +
    dp-sharded Adam moments. The schedule-dependent ring/stash/loss-head
    terms are NOT here: candidate_device_terms_gib adds them per schedule
    candidate, exactly as the fixed-mesh selection does.

    This is a model, not a compile: --select calibrates it against the one
    compiled peak it already paid for (the residual covers transient
    activations and XLA slack, scaled to each layout's per-tick work) and
    the verdicts inherit the usual CPU-estimate caveat."""
    import numpy as np

    d = model_cfg.hidden_size
    kv_dim = model_cfg.kv_heads * model_cfg.head_dim
    matmul = (2 * d * d + 2 * d * kv_dim
              + 3 * d * model_cfg.intermediate_size)
    k_max = (max(layer_counts) if layer_counts
             else -(-model_cfg.num_hidden_layers // pp))
    stage = k_max * (matmul / tp + 2 * d)
    shared = (model_cfg.vocab_size * d            # embed, replicated
              + model_cfg.vocab_size * d / tp     # lm-head, vocab-parallel
              + d)                                # final norm
    n = stage + shared
    dtype_b = np.dtype(model_cfg.dtype).itemsize
    weights = n * dtype_b
    if optimizer_offload:
        grads = n * 4 * (2 + (1.0 / dp if zero2 else 1.0))
        opt = 0.0
    else:
        grads = n * 4 * 2
        opt = n * 4 + n * 8 / dp  # fp32 masters + ZeRO-1 dp-sharded moments
    return (weights + grads + opt) / (1 << 30)


def layout_step_seconds(model_cfg, lay: dict, bubble: float, mb_rows: int,
                        seq: int, mfu: float, chip_flops: float | None,
                        ici_bw_gibps: float, zero2: bool = True) -> float:
    """Analytic per-step seconds of a layout running its chosen schedule —
    the RANKING score of the frontier (absolute accuracy is not the point;
    bench.py's extra:layout-* rows put the measured number next to it):

      compute/(1-bubble)           the lockstep pipeline wall (compute is
                                   layout-invariant at fixed devices — the
                                   whole model's flops spread over all
                                   chips — so bubble and collectives are
                                   what separate layouts)
    + tp allreduces                4 per layer per microbatch of the
                                   [mb, seq/sp, d] block (Megatron f/g),
                                   ring-allreduce 2(tp-1)/tp bytes
    + dp gradient reduction        the stage's fp32 grads, reduce-scatter
                                   (dp-1)/dp under ZeRO-2, allreduce
                                   2(dp-1)/dp otherwise
    + pp ring handoff              one [mb, seq/sp, d] slab per unit each
                                   direction
    + sp ring-attention rotation   (sp-1) k/v-slab hops per layer per
                                   microbatch, ~3x for fwd+bwd

    Collectives are charged SERIALLY at --ici-bw-gibps — conservative (XLA
    overlaps some of them), which is the right bias for a ranking that
    must not over-promise exotic layouts."""
    import numpy as np

    from llama_pipeline_parallel_tpu.utils.metrics import (
        detect_chip_peak_flops,
        train_flops_per_token,
    )

    pp, tp, dp, sp = lay["pp"], lay["tp"], lay["dp"], lay["sp"]
    micro = lay["microbatches"]
    devices = pp * tp * dp * sp
    peak = chip_flops or detect_chip_peak_flops() or 197e12
    tokens = mb_rows * micro * dp * seq
    t_comp = (train_flops_per_token(model_cfg, seq) * tokens / devices
              / (peak * max(mfu, 1e-6)))
    wall = t_comp / max(1.0 - bubble, 1e-6)

    d = model_cfg.hidden_size
    dtype_b = np.dtype(model_cfg.dtype).itemsize
    bw = ici_bw_gibps * (1 << 30)
    slab = mb_rows * (seq // sp) * d * dtype_b
    counts = lay.get("layer_counts")
    k_max = max(counts) if counts else -(-model_cfg.num_hidden_layers // pp)
    t_tp = (2 * (tp - 1) / tp) * 4 * k_max * micro * slab / bw if tp > 1 \
        else 0.0
    kv_dim = model_cfg.kv_heads * model_cfg.head_dim
    matmul = 2 * d * d + 2 * d * kv_dim + 3 * d * model_cfg.intermediate_size
    stage_grads = k_max * (matmul / tp) * 4
    dp_factor = (dp - 1) / dp if zero2 else 2 * (dp - 1) / dp
    t_dp = dp_factor * stage_grads / bw if dp > 1 else 0.0
    t_pp = 2 * micro * slab / bw if pp > 1 else 0.0
    kv_slab = 2 * mb_rows * (seq // sp) * kv_dim * dtype_b
    t_sp = 3 * (sp - 1) * k_max * micro * kv_slab / bw if sp > 1 else 0.0
    return wall + t_tp + t_dp + t_pp + t_sp


def layout_frontier(model_cfg, devices: int, mb_rows: int, seq: int,
                    global_batch_examples: int, base_gib_aw: float,
                    aw_layout: tuple, hbm_gb: float,
                    host_bw_gibps: float = 30.0, mfu: float = 0.45,
                    chip_flops: float | None = None,
                    ici_bw_gibps: float = 90.0, hide_max: float = 1.0,
                    optimizer_offload: bool = True, zero2: bool = True,
                    loss_chunks_aw: int = 1, vocab_enabled: bool = True,
                    solver_lane: bool = True,
                    max_virtual: int = 4, mem_scale: float = 1.0) -> tuple:
    """The full (pp, tp, dp, sp) frontier at `devices` chips: per layout,
    re-run the schedule/offload/ce selection against the memory model at
    THAT mesh (base re-derived analytically, calibrated by the residual
    between the as-written layout's compiled base `base_gib_aw` and its
    analytic model; the residual — transients + XLA slack — scales with
    each layout's per-tick tp/sp shard width), then rank the feasible
    survivors by layout_step_seconds. Returns (winner_row, rows) ordered
    best-first. Pure arithmetic: the one compile was already paid for."""
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig

    pp_aw, tp_aw, dp_aw, sp_aw = aw_layout
    residual = base_gib_aw - layout_device_gib(
        model_cfg, pp_aw, tp_aw, dp_aw,
        optimizer_offload=optimizer_offload, zero2=zero2)
    rows = []
    for lay in enumerate_layouts(devices, model_cfg, seq,
                                 global_batch_examples, mb_rows):
        pp, tp, dp, sp = lay["pp"], lay["tp"], lay["dp"], lay["sp"]
        micro = lay["microbatches"]
        dims = pl.stash_dims(mb_rows, seq, sp, model_cfg.hidden_size,
                             model_cfg.dtype)
        base = (layout_device_gib(model_cfg, pp, tp, dp,
                                  layer_counts=lay["layer_counts"],
                                  optimizer_offload=optimizer_offload,
                                  zero2=zero2)
                + residual * (tp_aw * sp_aw) / (tp * sp))
        ce_axis = (ce_axis_options(loss_chunks_aw, model_cfg.vocab_size, tp)
                   if vocab_enabled else None)
        vocab = (model_cfg.vocab_size if vocab_enabled and tp <= 1 else None)
        cands = enumerate_candidates(pp, micro, model_cfg.num_hidden_layers,
                                     max_virtual=max_virtual,
                                     ce_options=ce_axis,
                                     layer_counts=lay["layer_counts"])
        if solver_lane and lay["layer_counts"] is None:
            solver_head = 0.0
            if vocab:
                solver_head = pl.loss_head_bytes(
                    pl.PipelineConfig(num_stages=pp, num_microbatches=micro),
                    *dims[:3], vocab) / (1 << 30)
            cands += solver_candidates(pp, micro,
                                       model_cfg.num_hidden_layers, base,
                                       dims, hbm_gb, max_virtual=max_virtual,
                                       head_gib=solver_head,
                                       mem_scale=mem_scale)
        mesh_cfg = MeshConfig(pp=pp, tp=tp, dp=dp, sp=sp)
        compute_fn = lambda c, _mc=mesh_cfg: _step_compute_seconds(
            model_cfg, _mc, c, mb_rows, seq, mfu, chip_flops)
        sched_winner, _ = select_schedule(cands, base, dims, hbm_gb,
                                          host_bw_gibps, compute_fn,
                                          hide_max=hide_max, vocab=vocab,
                                          mem_scale=mem_scale)
        row = {"pp": pp, "tp": tp, "dp": dp, "sp": sp,
               "layout": f"pp{pp}xtp{tp}xdp{dp}xsp{sp}",
               "microbatches": micro,
               "layer_counts": (list(lay["layer_counts"])
                                if lay["layer_counts"] else None),
               "base_gib": round(base, 2)}
        if sched_winner is None:
            row.update({"feasible": False, "score_s": None,
                        "why_not": "no schedule fits this layout's memory "
                                   "model"})
        else:
            score = layout_step_seconds(model_cfg, lay,
                                        sched_winner["bubble_fraction"],
                                        mb_rows, seq, mfu, chip_flops,
                                        ici_bw_gibps, zero2=zero2)
            row.update({"feasible": True, "score_s": round(score, 4),
                        "_score": score,
                        "why_not": None, "sched": sched_winner,
                        "est_peak_gib": sched_winner["est_peak_gib"],
                        "bubble_fraction": sched_winner["bubble_fraction"]})
        rows.append(row)
    rows.sort(key=lambda r: (not r["feasible"],
                             r.get("_score", float("inf")), r["layout"]))
    winner = rows[0] if rows and rows[0]["feasible"] else None
    return winner, rows


def layout_overrides(row: dict, schedule_file: str | None = None) -> list:
    """One frontier row as the override LIST a supervisor ladder rung (or
    an operator's launch line) appends to the training command — the mesh
    axes, the preserved-global-batch microbatch count, the explicit stage
    partition when uneven, and the chosen schedule's own overrides. Every
    string here must round-trip train.py's config validation
    (tests/test_layout_select.py pins the grid)."""
    parts = [f"mesh.pp={row['pp']}", f"mesh.tp={row['tp']}",
             f"mesh.dp={row['dp']}", f"mesh.sp={row['sp']}",
             f"gradient_accumulation_steps={row['microbatches']}"]
    if row.get("layer_counts"):
        parts.append("layer_counts=[" +
                     ",".join(str(c) for c in row["layer_counts"]) + "]")
    parts += select_overrides(row["sched"], schedule_file=schedule_file).split()
    return parts


def build_ladder(model_cfg, devices: int, mb_rows: int, seq: int,
                 global_batch_examples: int, base_gib_aw: float,
                 aw_layout: tuple, hbm_gb: float, top_k: int = 3,
                 schedule_file_for=None, **frontier_kw) -> tuple:
    """The generated supervisor ladder: the top-k frontier survivors at
    `devices` chips, then the single best survivor at each HALVED device
    count (the elastic-resize rungs: lose half the pod, walk down a rung,
    keep the global batch) — best-first, tools/supervisor.py's
    --layout-ladder format verbatim ({name, devices, overrides}).
    `schedule_file_for(rung_name, pcfg) -> path` serializes a solver
    winner's unit sequence and returns the path its rung references (None
    = restrict rungs to canonical schedules). Returns (rungs, frontiers)
    where frontiers maps device count -> the scored rows."""
    rungs, frontiers = [], {}
    n = devices
    while n >= 1:
        kw = dict(frontier_kw)
        if schedule_file_for is None:
            kw["solver_lane"] = False  # a solver rung needs its sequence file
        _, rows = layout_frontier(model_cfg, n, mb_rows, seq,
                                  global_batch_examples, base_gib_aw,
                                  aw_layout, hbm_gb, **kw)
        frontiers[n] = rows
        feasible = [r for r in rows if r["feasible"]]
        for r in feasible[:top_k if n == devices else 1]:
            name = f"{r['layout']}-{r['sched']['schedule']}"
            if any(rg["name"] == name for rg in rungs):
                name += f"-c{r['sched']['accum_chunks']}"
            sfile = None
            if r["sched"]["schedule"] == "solver":
                sfile = schedule_file_for(name, r["sched"]["_pcfg"])
            rungs.append({"name": name, "devices": n,
                          "overrides": layout_overrides(
                              r, schedule_file=sfile)})
        if n == 1:
            break
        n //= 2
    return rungs, frontiers


def select_overrides(row: dict, schedule_file: str | None = None) -> str:
    """The winning candidate as `key=value` config overrides — what the
    operator (or the supervisor's layout ladder) pastes onto the launch
    line to run the chosen schedule. A solver winner additionally needs
    its emitted sequence file (`--emit-schedule` writes it; without one
    the override line carries a placeholder to fill in)."""
    parts = [f"pipeline_schedule={row['schedule']}",
             f"virtual_stages={row['virtual_stages']}",
             f"gradient_accumulation_chunks={row['accum_chunks']}"]
    if row["schedule"] == "solver":
        parts.append(
            f"schedule_file={schedule_file or '<path from --emit-schedule>'}")
    if row["offload_wgrad"]:
        parts.append("offload.wgrad_stash=true")
    if row["offload_activations"]:
        parts.append("offload.activations=true")
    if row.get("loss_chunks", 1) > 1:
        parts.append(f"loss_vocab_chunks={row['loss_chunks']}")
    if row.get("kernel_ce"):
        parts.append("kernels.ce=pallas")
    return " ".join(parts)


def _as_written_pcfg(cfg: dict):
    """The as-written config's PipelineConfig, rebuilt with the trainer's
    own builders (preflight() constructs the same thing internally but
    does not return it) — shared by the --emit-schedule and FAIL-remedies
    paths in main()."""
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig
    from llama_pipeline_parallel_tpu.train import (
        build_manifest,
        build_model_config,
        build_pipeline_config,
    )

    mesh_cfg = MeshConfig(**cfg.get("mesh", {}))
    model_cfg = build_model_config(cfg["model"])
    return build_pipeline_config(
        cfg, mesh_cfg, build_manifest(cfg, model_cfg, mesh_cfg.pp))


def stash_remedies(pcfg) -> str:
    """Remedies for a blown W-stash, DERIVED from emitted sequences instead
    of a hard-coded list of schedule names: the queue depth comes from the
    sequence's slot accounting, and each fallback is named with its bubble
    counted from ITS canonical sequence's idle ticks at this exact shape —
    so the error text can never drift from what the interpreter runs."""
    import dataclasses as _dc

    from llama_pipeline_parallel_tpu.parallel import pipeline as pl

    depth = pl.wgrad_queue_peak(pcfg)
    own_b = pl.bubble_fraction(pcfg)
    parts = [f"raise gradient_accumulation_chunks (the per-flush W-queue "
             f"holds {depth} residual units; each doubling halves it)",
             "tier residuals to host DRAM (offload.wgrad_stash, or a "
             "solver sequence's per-unit offload vector via --select)"]
    alts = []
    for name, v in (("interleaved_1f1b", pcfg.virtual_stages), ("1f1b", 1)):
        try:
            alt = _dc.replace(pcfg, schedule=name, virtual_stages=v,
                              offload_wgrad=False, unit_schedule=None)
            alts.append((pl.bubble_fraction(alt), name))
        except ValueError:
            continue
    if alts:
        b, name = min(alts)
        parts.append(
            f"fall back to pipeline_schedule: {name} (no W stash; bubble "
            f"{100 * b:.2f}% vs {100 * own_b:.2f}% here — both counted "
            f"from the schedules' emitted sequences)")
    return "; ".join(parts)


def _compile_abstract(cfg: dict, mesh, mesh_cfg, model_cfg, manifest, pcfg):
    """Lower + compile the trainer's own program ABSTRACTLY (eval_shape
    state, ShapeDtypeStruct batch — no arrays materialize) and return
    ``(compiled, seq)``. Shared by preflight() and memory_audit(): both
    must compile the SAME program the real run executes, at whatever
    accum shape their caller baked into ``cfg``/``pcfg``."""
    import jax
    from jax.sharding import NamedSharding

    from llama_pipeline_parallel_tpu.models.llama import model as llama
    from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel import train_step as ts
    from llama_pipeline_parallel_tpu.train import select_attention

    # the trainer probes the collator for the real row length; the synthetic
    # dataset's seq_length is that probe's answer for these configs
    data_cfg = cfg.get("dataset") or {}
    if not data_cfg or data_cfg.get("synthetic"):
        seq = data_cfg.get("seq_length", cfg.get("max_seq_length", 512))
    else:
        seq = cfg.get("max_seq_length", 512)
    # `auto` would try to TIME kernels — preflight must stay measurement-free.
    # Resolve it to EXACT, the conservative choice: at runtime auto may pick
    # either backend, and exact's O(L^2) score tensors are the memory
    # worst case (a flash-compiled estimate would under-count runs where
    # auto picks exact). Configs that pin `attention: flash` compile flash.
    impl = cfg.get("attention", "auto")
    attn_fn = select_attention("exact" if impl == "auto" else impl, seq, mesh,
                               sequence_parallel=pcfg.sequence_parallel,
                               packed=pcfg.packed)

    ocfg = OptimizerConfig(learning_rate=cfg.get("learning_rate", 1e-6),
                           total_steps=10, warmup_steps=1)
    tx, sched = make_optimizer(ocfg)

    # abstract, sharding-annotated state: eval_shape never runs the init
    def build(rng):
        return pl.stack_stages(llama.init_params(rng, model_cfg), manifest)

    stacked_abs = jax.eval_shape(build, jax.random.PRNGKey(0))
    shardings = ts.state_shardings(mesh, tx, stacked_abs)

    def annotate(tree_abs, tree_shard):
        return jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            tree_abs, tree_shard)

    opt_abs = jax.eval_shape(tx.init, stacked_abs)
    state_abs = ts.TrainState(
        step=jax.ShapeDtypeStruct((), jax.numpy.int32, sharding=shardings.step),
        params=annotate(stacked_abs, shardings.params),
        opt_state=annotate(opt_abs, shardings.opt_state))

    import jax.numpy as jnp

    # NOT multiplied by packing_factor: the loader feeds micro*accum*pack
    # EXAMPLES per replica, but the packed collator emits examples //
    # pack_factor ROWS (data/collator.py) — the device program sees
    # micro*accum rows either way
    global_batch = (cfg.get("per_device_train_batch_size", 1)
                    * pcfg.num_microbatches * mesh_cfg.dp)
    b_specs = pl.batch_specs(mesh)
    batch_abs = {
        k: jax.ShapeDtypeStruct((global_batch, seq), jnp.int32,
                                sharding=NamedSharding(mesh, b_specs[k]))
        for k in ("input_ids", "attention_mask", "position_ids", "labels")
    }

    if cfg.get("optimizer_offload"):
        # The offload path's DEVICE program is loss+grad only: bf16 working
        # params in, fp32 grads out; masters + Adam moments live in host
        # DRAM (optim/offload.py) exactly like the reference's 65B
        # ZeRO-offload run (reference conf yaml:160-162, README.md:70-71).
        # Under optimizer_offload_zero2 the grads leave the device
        # dp-sharded (reduce-scatter), matching the trainer's program.
        param_specs = pl.stage_param_specs(stacked_abs,
                                           tp=mesh.shape["tp"] > 1)
        bf16_abs = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(
                a.shape, model_cfg.dtype, sharding=NamedSharding(mesh, s)),
            stacked_abs, param_specs)
        out_shardings = None
        if cfg.get("optimizer_offload_zero2") and mesh_cfg.dp > 1:
            out_shardings = (None, ts.specs_to_shardings(
                mesh, ts.zero2_param_specs(stacked_abs, mesh)))
        grad_fn = jax.jit(pl.make_pipeline_loss_and_grad(
            mesh, model_cfg, pcfg, stacked_abs, attn_fn=attn_fn),
            out_shardings=out_shardings)
        compiled = grad_fn.lower(bf16_abs, batch_abs).compile()
    else:
        step = ts.make_train_step(mesh, model_cfg, pcfg, tx, sched, stacked_abs,
                                  attn_fn=attn_fn)
        compiled = step.lower(state_abs, batch_abs).compile()
    return compiled, seq


def preflight(cfg: dict, hbm_gb: float, host_bw_gibps: float = 30.0,
              mfu: float = 0.45, hide_max: float = 1.0,
              chip_flops: float | None = None) -> dict:
    """Lower + compile the training step ABSTRACTLY (no arrays materialize:
    65B fp32 masters never exist) and return the per-device byte breakdown."""
    import jax
    import numpy as np

    from llama_pipeline_parallel_tpu.models.llama import model as llama
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh
    from llama_pipeline_parallel_tpu.train import (
        build_manifest,
        build_model_config,
        build_pipeline_config,
    )

    if cfg.get("optimizer_offload_zero2") and not cfg.get("optimizer_offload"):
        # mirror the trainer's rejection (train.py) — preflight passing a
        # config the real run refuses defeats its purpose
        raise ValueError("optimizer_offload_zero2 requires optimizer_offload: "
                         "true")
    mesh_cfg = MeshConfig(**cfg.get("mesh", {}))
    mesh = make_mesh(mesh_cfg)
    model_cfg = build_model_config(cfg["model"])
    # the trainer's own builders: the preflight must compile the SAME program
    manifest = build_manifest(cfg, model_cfg, mesh_cfg.pp)
    pcfg = build_pipeline_config(cfg, mesh_cfg, manifest)

    # Anchored-compile mode for host-offload configs on backends that
    # cannot express host memory (utils/host_stash.py gating — XLA-CPU,
    # i.e. every CLI preflight): the gated-off compile holds the tiered
    # stash DEVICE-resident, and XLA-CPU additionally over-counts stash
    # buffers past 2^31 elements (~2.4x at the 65B micro-8 shape, where
    # the same program at micro 2 — exactly 2^31 — and the whole 7B grid
    # match the closed-form model to the 0.1 GiB). So the device peak is
    # estimated from a compile of the SAME program at the smallest valid
    # M (queue shrunk under the cliff), with the schedule's ring/stash
    # terms swapped to the real shape analytically — every other term is
    # M-independent (ring slots cap at 2vS-1; scan trip counts are free).
    pcfg_real, anchor_m = pcfg, None
    if ((pcfg.offload_wgrad or pcfg.offload_activations)
            and not _host_transfers_enabled()):
        m_min = pcfg.num_stages * pcfg.accum_chunks
        if m_min < pcfg.num_microbatches:
            anchor_m = m_min
            cfg = {**cfg, "gradient_accumulation_steps": m_min}
            pcfg = build_pipeline_config(cfg, mesh_cfg, manifest)

    compiled, seq = _compile_abstract(cfg, mesh, mesh_cfg, model_cfg,
                                      manifest, pcfg)
    ma = compiled.memory_analysis()
    if ma is None:
        raise RuntimeError("backend exposes no compile-time memory analysis")

    gib = 1 << 30
    arg = getattr(ma, "argument_size_in_bytes", 0)
    out = getattr(ma, "output_size_in_bytes", 0)
    temp = getattr(ma, "temp_size_in_bytes", 0)
    alias = getattr(ma, "alias_size_in_bytes", 0)
    # donated state aliases into the outputs: alias bytes are counted once
    peak = arg + out + temp - alias
    mb_rows = int(cfg.get("per_device_train_batch_size", 1))
    dims = pl.stash_dims(mb_rows, seq, mesh_cfg.sp, model_cfg.hidden_size,
                         model_cfg.dtype)
    # Device-peak estimate for offload configs: a GATED-OFF compile holds
    # the tiered stash in regular memory (one flat address space on that
    # backend), so the modeled host bytes are subtracted — via the anchored
    # mode above when it applies, directly otherwise. When transfers are
    # REAL (pinned_host exists), the compile already placed the stash in
    # the host space and the raw peak is taken as-is — subtracting there
    # would double-count the relief and understate device HBM by the whole
    # stash (whether memory_analysis excludes host-space buffers is a
    # calibration question; taking the raw number can only overstate).
    host_bytes = pl.host_stash_bytes(pcfg_real, *dims)
    if anchor_m:
        terms_real = candidate_device_terms_gib(pcfg_real, dims)
        peak_device_gib = (peak / gib - counted_device_terms_gib(pcfg, dims)
                           + terms_real["ring_gib"] + terms_real["stash_gib"])
    elif host_bytes and not _host_transfers_enabled():
        peak_device_gib = (peak - host_bytes) / gib
    else:
        peak_device_gib = peak / gib
    report = {
        "compiled_path": "offload_loss_and_grad" if cfg.get("optimizer_offload")
                         else "fused_train_step",
        "devices": int(np.prod(list(mesh.shape.values()))),
        "global_batch_rows": mb_rows * pcfg_real.num_microbatches
                             * mesh_cfg.dp,
        "seq": seq,
        "schedule": pcfg_real.schedule,
        "arguments_gib": round(arg / gib, 2),
        "outputs_gib": round(out / gib, 2),
        "temp_gib": round(temp / gib, 2),
        "aliased_gib": round(alias / gib, 2),
        "per_device_peak_gib": round(peak_device_gib, 2),
        "hbm_budget_gib": hbm_gb,
        "fits": peak_device_gib <= hbm_gb,
    }
    # The loss head's live term (pl.loss_head_bytes): the [tokens, V/chunks]
    # fp32 logits block + chunked-backward dh accumulator of the XLA path,
    # ~0 under kernels.ce=pallas (docs/KERNELS.md) — named so the operator
    # can see what the ce axis of --select is trading. Under tp the head is
    # vocab-PARALLEL (each shard's logits block is [tokens, V/tp]; the
    # loss_chunks/kernels.ce knobs are rejected there), so the shard width
    # is the vocab the term sees.
    report["loss_head_gib"] = round(
        pl.loss_head_bytes(pcfg_real, *dims[:3],
                           model_cfg.vocab_size // max(mesh_cfg.tp, 1))
        / gib, 2)
    kernels_on = [n for n, on in (("ce", pcfg_real.kernel_ce),
                                  ("prologue", pcfg_real.kernel_prologue))
                  if on]
    if kernels_on:
        report["kernels"] = "+".join(kernels_on)
    if anchor_m:
        report["anchor_microbatches"] = anchor_m
        report["anchor_peak_gib"] = round(peak / gib, 2)
        report["anchor_note"] = (
            f"device peak estimated from an M={anchor_m} compile of the "
            f"same program (this backend cannot express host memory, so a "
            f"full-M compile would hold the tiered stash device-resident, "
            f"and XLA-CPU over-counts stash buffers past 2^31 elements); "
            f"ring/stash terms re-added analytically at "
            f"M={pcfg_real.num_microbatches}")
    hbm_slots, host_slots = pl.wgrad_partition(pcfg_real)
    if host_bytes:
        if not anchor_m and not _host_transfers_enabled():
            report["xla_raw_peak_gib"] = round(peak / gib, 2)
        report["host_stash_gib"] = round(host_bytes / gib, 2)
        wgrad_tier = "wgrad_stash"
        if (pcfg_real.schedule == "solver" and host_slots
                and hbm_slots):  # selective vector: name the split
            wgrad_tier = (f"wgrad_stash"
                          f"[{pl.wgrad_offloaded_units(pcfg_real)}"
                          f"/{pcfg_real.unit_schedule.n_units}]")
        report["offload"] = "+".join(
            n for n, on in ((wgrad_tier, host_slots > 0),
                            ("activations", pcfg_real.offload_activations))
            if on)
    if pl.wgrad_queue_peak(pcfg_real):
        # The split backward stashes a (chunk input, ring cotangent)
        # residual per queued W unit (docs/SCHEDULES.md "W-stash memory
        # bound"). The explicit term names the schedule's memory tax and
        # sizes the remedies when it blows the headroom (see the FAIL
        # message in main()): accum_chunks divides the per-flush queue;
        # offload.wgrad_stash (or a solver sequence's per-unit vector)
        # tiers it to host DRAM. Only the HBM-RESIDENT portion counts
        # against headroom — a solver vector's host slots already left.
        stash = pl.wgrad_stash_bytes(pcfg_real, *dims)
        slot_b = dims[0] * dims[1] * dims[2] * dims[3]
        stash_hbm = 2 * hbm_slots * slot_b
        report["wgrad_queue_depth"] = pl.wgrad_queue_peak(pcfg_real)
        report["wgrad_stash_gib"] = round(stash / gib, 2)
        if host_slots and not hbm_slots:
            report["wgrad_stash_verdict"] = (
                "tiered to host DRAM (offload.wgrad_stash or an all-host "
                "sequence vector) — HBM holds only the in-flight transfer "
                "slots")
        else:
            headroom = hbm_gb - (peak_device_gib - stash_hbm / gib)
            if stash_hbm / gib > max(headroom, 0.0):
                report["wgrad_stash_verdict"] = (
                    f"HBM-resident W-stash {round(stash_hbm / gib, 2)} GiB "
                    f"exceeds the {round(max(headroom, 0.0), 2)} GiB "
                    f"headroom left by the rest of the step — "
                    f"{stash_remedies(pcfg_real)}")
            else:
                report["wgrad_stash_verdict"] = "fits within headroom"
    if pcfg_real.offload_activations or host_slots:
        # Host-bandwidth feasibility (the PipeOffload bound): the stash
        # traffic must stream behind the step's compute, or the offload
        # trades an OOM for a stall — rejected HERE, analytically, not
        # discovered on device.
        feas = offload_feasibility(
            pcfg_real, dims,
            _step_compute_seconds(model_cfg, mesh_cfg, pcfg_real, mb_rows,
                                  seq, mfu, chip_flops),
            host_bw_gibps)
        report.update(feas)
        if feas["offload_hide_ratio"] > hide_max:
            report["fits"] = False
            report["offload_bw_verdict"] = (
                f"offload traffic cannot hide behind compute: modeled "
                f"transfer time is {feas['offload_hide_ratio']:.2f}x the "
                f"step's compute at {host_bw_gibps} GiB/s host bandwidth "
                f"(--host-bw-gibps) and {mfu} MFU — raise "
                f"gradient_accumulation_chunks, shrink the stash, or drop "
                f"the offload")
        else:
            report["offload_bw_verdict"] = "hides behind compute"
    if cfg.get("optimizer_offload"):
        # host side: fp32 masters + two fp32 Adam moments, sharded per
        # process (optim/offload.py keeps only each host's device shards)
        stacked_abs = jax.eval_shape(
            lambda rng: pl.stack_stages(llama.init_params(rng, model_cfg),
                                        manifest),
            jax.random.PRNGKey(0))
        n_params = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(stacked_abs))
        report["host_dram_total_gib"] = round(n_params * 12 / gib, 1)
    return report


def memory_audit(cfg: dict, top_buffers: int = 8) -> dict:
    """Per-buffer evidence behind the anchored-estimate mode: compile the
    SAME program at a ladder of microbatch counts and, per rung, put the
    byte model's candidate terms (candidate_device_terms_gib) next to
    `memory_analysis()`'s raw numbers plus top-buffer attribution
    (utils/memwatch.py). The residual (raw peak minus the model's ring +
    stash terms) is M-independent when XLA counts honestly — a residual
    that JUMPS between rungs localizes the over-count to the buffers the
    attribution lists, which is exactly the 2^31-element XLA-CPU cliff
    the anchored mode in preflight() works around
    (docs/PREFLIGHT.md "Memory audit")."""
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh
    from llama_pipeline_parallel_tpu.train import (
        build_manifest,
        build_model_config,
        build_pipeline_config,
    )
    from llama_pipeline_parallel_tpu.utils import memwatch

    gib = 1 << 30
    mesh_cfg = MeshConfig(**cfg.get("mesh", {}))
    mesh = make_mesh(mesh_cfg)
    model_cfg = build_model_config(cfg["model"])
    manifest = build_manifest(cfg, model_cfg, mesh_cfg.pp)
    pcfg_real = build_pipeline_config(cfg, mesh_cfg, manifest)

    # M-ladder: the smallest valid microbatch count (the anchored mode's
    # compile shape), the as-written M, and a midpoint rung when the two
    # are far apart — three points separate "residual is flat" from
    # "residual jumps at one rung".
    m_min = pcfg_real.num_stages * pcfg_real.accum_chunks
    m_real = pcfg_real.num_microbatches
    ladder = {m for m in (m_min, m_real) if m >= m_min}
    if m_real >= 4 * m_min:
        ladder.add(2 * m_min)
    mb_rows = int(cfg.get("per_device_train_batch_size", 1))

    rungs = []
    for m in sorted(ladder):
        cfg_m = {**cfg, "gradient_accumulation_steps": m}
        try:
            pcfg_m = build_pipeline_config(cfg_m, mesh_cfg, manifest)
            compiled, seq = _compile_abstract(cfg_m, mesh, mesh_cfg,
                                              model_cfg, manifest, pcfg_m)
        except Exception as e:  # invalid rung (schedule constraint) — skip
            rungs.append({"microbatches": m, "error": f"{type(e).__name__}: {e}"})
            continue
        info = memwatch.compiled_memory(compiled, top_buffers=top_buffers,
                                        label=f"M={m}")
        if info is None:
            rungs.append({"microbatches": m,
                          "error": "backend exposes no memory analysis"})
            continue
        dims = pl.stash_dims(mb_rows, seq, mesh_cfg.sp, model_cfg.hidden_size,
                             model_cfg.dtype)
        terms = candidate_device_terms_gib(pcfg_m, dims)
        peak_gib = info["peak_bytes"] / gib
        # flag buffers past the XLA-CPU over-count cliff: 2^31 ELEMENTS
        bufs = []
        for b in info["top_buffers"]:
            elements = 1
            for d in b.get("shape") or ():
                elements *= d
            bufs.append({**b, "gib": round(b["bytes"] / gib, 2),
                         "over_2^31_elements": elements >= (1 << 31)})
        rungs.append({
            "microbatches": m,
            "anchor_rung": m == m_min and m != m_real,
            "as_written": m == m_real,
            "raw_peak_gib": round(peak_gib, 2),
            "arguments_gib": round(info["argument_bytes"] / gib, 2),
            "outputs_gib": round(info["output_bytes"] / gib, 2),
            "temp_gib": round(info["temp_bytes"] / gib, 2),
            "ring_gib": round(terms["ring_gib"], 2),
            "stash_gib": round(terms["stash_gib"], 2),
            "loss_head_gib": round(terms["loss_head_gib"], 2),
            "residual_gib": round(peak_gib - terms["ring_gib"]
                                  - terms["stash_gib"], 2),
            "top_buffers": bufs,
        })
    return {"schedule": pcfg_real.schedule, "anchor_microbatches": m_min,
            "as_written_microbatches": m_real,
            "devices": _prod(mesh.shape.values()),
            "rungs": rungs}


def _prod(vals) -> int:
    out = 1
    for v in vals:
        out *= int(v)
    return out


def print_memory_audit(audit: dict) -> None:
    """The --memory-audit table: one row per ladder rung, residual last —
    a flat residual column validates the byte model's M-scaling; a jump
    names the over-counted rung, and the per-rung buffer attribution
    below names the tensor (docs/PREFLIGHT.md commits these tables for
    the 7B and 65B conf shapes)."""
    print(f"memory audit: schedule {audit['schedule']}, "
          f"anchor M={audit['anchor_microbatches']}, "
          f"as-written M={audit['as_written_microbatches']}")
    hdr = (f"{'M':>6s} {'raw_peak':>9s} {'temp':>8s} {'ring':>7s} "
           f"{'stash':>7s} {'head':>7s} {'residual':>9s}  note")
    print(hdr)
    for r in audit["rungs"]:
        if "error" in r:
            print(f"{r['microbatches']:>6d} {'-':>9s} {'-':>8s} {'-':>7s} "
                  f"{'-':>7s} {'-':>7s} {'-':>9s}  {r['error']}")
            continue
        note = ("anchor" if r.get("anchor_rung")
                else "as-written" if r.get("as_written") else "")
        print(f"{r['microbatches']:>6d} {r['raw_peak_gib']:>9.2f} "
              f"{r['temp_gib']:>8.2f} {r['ring_gib']:>7.2f} "
              f"{r['stash_gib']:>7.2f} {r['loss_head_gib']:>7.2f} "
              f"{r['residual_gib']:>9.2f}  {note}")
    for r in audit["rungs"]:
        if "error" in r or not r.get("top_buffers"):
            continue
        print(f"\ntop buffers at M={r['microbatches']}:")
        for b in r["top_buffers"]:
            flag = "  <-- over 2^31 elements" if b["over_2^31_elements"] else ""
            shape = ",".join(str(d) for d in (b.get("shape") or ()))
            print(f"  {b['gib']:>8.2f} GiB  {b['dtype']}[{shape}]  "
                  f"%{b['name']}{flag}")


def calibrate() -> dict:
    """Compile the BENCH config's single-chip train step on BOTH backends —
    the real TPU (via the axon platform) and XLA-CPU — and report both
    `memory_analysis()` peaks side by side. This puts an error bar on every
    XLA-CPU preflight verdict (the tool's own caveat: TPU layouts/padding and
    Mosaic VMEM differ). Run it whenever a chip is reachable; record the
    margin in docs/PREFLIGHT.md. AOT only — no arrays materialize, so it
    needs the tunnel for compilation RPCs but never runs a step."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from __graft_entry__ import _bench_config  # repo root on sys.path (module top)

    from llama_pipeline_parallel_tpu.models.llama import model as llama
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel import train_step as ts
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg = _bench_config()
    manifest = StageManifest.for_config(cfg, 1)
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-4,
                                               total_steps=1000, warmup_steps=10))
    gib = 1 << 30
    out: dict = {"model": "bench-550m", "batch": 8, "seq": 512}
    # cpu FIRST: a wedged TPU tunnel hangs the tpu compile, and the caller's
    # timeout should still have the cpu half on stdout by then. The cpu half
    # alone costs ~25 min of XLA-CPU compile on a 1-core host, so callers
    # racing a live-chip window (tools/chip_sprint.sh) can select backends:
    # CALIBRATE_BACKENDS=tpu skips it (the cpu number is obtainable offline).
    backends = tuple(b.strip().lower()
                     for b in os.environ.get("CALIBRATE_BACKENDS",
                                             "cpu,tpu").split(",")
                     if b.strip())
    if "tpu" not in backends:
        # cpu-only run: pin the platform so jax never initializes the axon
        # TPU client at all (a wedged/failing tunnel otherwise poisons even
        # the jax.devices("cpu") lookup)
        jax.config.update("jax_platforms", "cpu")
    for backend in backends:
        try:
            devices = jax.devices(backend)
        except RuntimeError as e:
            out[backend] = f"backend unavailable: {e}"
            continue
        mesh = make_mesh(MeshConfig(), devices=devices[:1])
        stacked_abs = jax.eval_shape(
            lambda rng: pl.stack_stages(llama.init_params(rng, cfg), manifest),
            jax.random.PRNGKey(0))
        shardings = ts.state_shardings(mesh, tx, stacked_abs)
        opt_abs = jax.eval_shape(tx.init, stacked_abs)

        def annotate(tree_abs, tree_shard):
            return jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
                tree_abs, tree_shard)

        state_abs = ts.TrainState(
            step=jax.ShapeDtypeStruct((), jnp.int32, sharding=shardings.step),
            params=annotate(stacked_abs, shardings.params),
            opt_state=annotate(opt_abs, shardings.opt_state))
        b_spec = NamedSharding(mesh, pl.batch_specs(mesh)["input_ids"])
        batch_abs = {k: jax.ShapeDtypeStruct((8, 512), jnp.int32, sharding=b_spec)
                     for k in ("input_ids", "attention_mask", "position_ids",
                               "labels")}
        pcfg = pl.PipelineConfig(num_stages=1, num_microbatches=1, remat=False)
        step = ts.make_train_step(mesh, cfg, pcfg, tx, sched, stacked_abs)
        ma = step.lower(state_abs, batch_abs).compile().memory_analysis()
        if ma is None:
            out[backend] = "no memory analysis exposed"
            continue
        arg = getattr(ma, "argument_size_in_bytes", 0)
        o = getattr(ma, "output_size_in_bytes", 0)
        temp = getattr(ma, "temp_size_in_bytes", 0)
        alias = getattr(ma, "alias_size_in_bytes", 0)
        out[backend] = {"arguments_gib": round(arg / gib, 3),
                        "outputs_gib": round(o / gib, 3),
                        "temp_gib": round(temp / gib, 3),
                        "peak_gib": round((arg + o + temp - alias) / gib, 3)}
        print(f"calibrate[{backend}]: {out[backend]}", flush=True)
    if isinstance(out.get("tpu"), dict) and isinstance(out.get("cpu"), dict):
        cpu_peak, tpu_peak = out["cpu"]["peak_gib"], out["tpu"]["peak_gib"]
        out["tpu_over_cpu"] = round(tpu_peak / cpu_peak, 3) if cpu_peak else None
    return out


def resume_compat(cfg: dict) -> dict | None:
    """Elastic-resume preflight (docs/RESILIENCE.md "Elastic resume"): when
    the config's output_dir already holds a checkpoint this run would
    resume, compare its recorded source topology and data contract against
    the config — BEFORE burning a compile on a resume that will warn about
    (or silently accept) a changed global batch. Returns None when there is
    nothing to resume; never fails the preflight (topology changes are
    legal — that is the point of elastic restore)."""
    import json as _json

    out_dir = cfg.get("output_dir")
    if not out_dir or not os.path.isdir(out_dir) or not cfg.get("resume", True):
        return None
    # read meta.json directly (no CheckpointManager: the preflight must not
    # create dirs or spin up Orbax just to peek at a marker file)
    latest = None
    try:
        import re as _re

        for d in os.listdir(out_dir):
            m = _re.match(r"^checkpoint-(\d+)$", d)
            if m and os.path.isfile(os.path.join(out_dir, d, "meta.json")):
                latest = max(latest or 0, int(m.group(1)))
        if latest is None:
            return None
        with open(os.path.join(out_dir, f"checkpoint-{latest}",
                               "meta.json")) as f:
            meta = _json.load(f)
    except (OSError, ValueError):
        return None  # torn/corrupt meta: the trainer's quarantine handles it
    mesh = dict(cfg.get("mesh") or {})
    current = {"pp": int(mesh.get("pp", 1)), "dp": int(mesh.get("dp", 1)),
               "tp": int(mesh.get("tp", 1)), "sp": int(mesh.get("sp", 1)),
               "schedule": cfg.get("pipeline_schedule", "1f1b"),
               "virtual_stages": int(cfg.get("virtual_stages", 1) or 1)}
    report: dict = {"resume_step": latest}
    source = meta.get("topology")
    if source and "layer_counts" in source:
        # mirror the trainer's partition-aware restore labeling: a ladder
        # rung that changes layer_counts is a topology change here too
        try:
            from llama_pipeline_parallel_tpu.train import (
                build_manifest,
                build_model_config,
            )

            man = build_manifest(cfg, build_model_config(cfg["model"]),
                                 current["pp"])
            current["layer_counts"] = (
                f"even/{man.stage_layer_counts[0]}" if man.is_even
                else list(man.stage_layer_counts))
        except Exception:
            pass  # unresolvable model node: skip the partition comparison
    if source:
        changed = sorted(k for k in current if source.get(k) != current[k])
        report["source_topology"] = source.get("layout", source)
        report["topology_changed"] = changed or "no"
        if source.get("schedule") != current["schedule"]:
            # a schedule change is as restore-relevant as a topology one:
            # the stacked layout changes (flat [S,k] vs chunked [S,v,k])
            # even though the canonical checkpoint restores into either
            report["schedule_changed"] = (
                f"{source.get('schedule', '1f1b')} -> {current['schedule']} "
                f"(layout re-stacks from the canonical checkpoint; "
                f"docs/SCHEDULES.md)")
    data_state = meta.get("data_state")
    if data_state:
        packing = int(cfg.get("packing_factor", 1) or 1)
        g_now = (current["dp"] * int(cfg.get("per_device_train_batch_size", 1))
                 * int(cfg.get("gradient_accumulation_steps", 1)) * packing)
        g_ckpt = data_state.get("global_batch_examples")
        report["global_batch_examples"] = {"checkpoint": g_ckpt,
                                           "config": g_now}
        report["data_contract"] = (
            "exact (O(1) reposition, zero dropped/duplicated samples)"
            if g_ckpt == g_now else
            "REMAPPED — global batch changed; re-trains at most one partial "
            "batch and shifts the lr-schedule/epoch mapping "
            "(docs/RESILIENCE.md)")
    return report


def _run_all(patterns: list[str], hbm_gb: float, overrides: list[str]) -> None:
    """Preflight every config matching `patterns` in its own subprocess (each
    needs a different virtual device count, fixed at jax import) and print a
    pass/fail table — one command reproduces docs/PREFLIGHT.md."""
    import glob as globmod
    import re
    import subprocess

    paths = sorted({p for pat in patterns for p in globmod.glob(pat)})
    if not paths:
        raise SystemExit(f"no configs match {patterns!r}")
    rows, any_fail = [], False
    for path in paths:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--config", path,
             "--hbm-gb", str(hbm_gb), *overrides],
            capture_output=True, text=True)
        peak = "?"
        m = re.search(r"per_device_peak_gib: ([0-9.]+)", proc.stdout)
        if m:
            peak = m.group(1)
        ok = proc.returncode == 0
        any_fail |= not ok
        rows.append((path, peak, "OK" if ok else "FAIL"))
        print(f"{'OK  ' if ok else 'FAIL'} {path}: peak {peak} GiB",
              flush=True)
        if not ok and not m:  # compile error, not a budget miss: show why
            print((proc.stdout + proc.stderr).strip()[-800:], flush=True)
    print(f"\n{'config':<40} {'peak GiB':>9}  verdict")
    for path, peak, verdict in rows:
        print(f"{path:<40} {peak:>9}  {verdict}")
    if any_fail:
        sys.exit(1)


CALIBRATION_KEYS = {"mfu": "mfu", "host_bw_gibps": "host_bw_gibps",
                    "ici_bw_gibps": "ici_bw_gibps",
                    "mem_scale": "mem_scale"}


def load_calibration(path: str) -> dict:
    """Read a perf_report --emit-calibration constants file. Raises
    SystemExit with a readable message on unreadable/garbage input — a
    user pointing --calibration at the wrong file must get a verdict, not
    a traceback; a file with no usable keys returns {} (the CLI defaults
    then stand)."""
    import json

    try:
        with open(path) as f:
            calib = json.load(f)
    except (OSError, ValueError) as e:
        raise SystemExit(f"--calibration {path} is not readable JSON: {e}")
    if not isinstance(calib, dict):
        raise SystemExit(f"--calibration {path} is not a JSON object "
                         f"(got {type(calib).__name__})")
    out = {}
    for key in CALIBRATION_KEYS:
        try:
            v = float(calib[key])
        except (KeyError, TypeError, ValueError):
            continue
        if v > 0:
            out[key] = v
    return out


def apply_calibration(args, path: str) -> dict:
    """Override the CLI model constants with the file's measured values
    (only the keys it carries). Returns what was applied — the
    measured-re-selection loop: bench/train measure, perf_report distills,
    --select re-ranks from the measurements."""
    applied = load_calibration(path)
    for key, attr in CALIBRATION_KEYS.items():
        if key in applied:
            setattr(args, attr, applied[key])
    return applied


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default=None,
                   help="one config yaml (or use --all for a sweep)")
    p.add_argument("--hbm-gb", type=float, default=95.0,
                   help="per-chip HBM budget in GiB (TPU v5p: 95)")
    p.add_argument("--all", dest="all_globs", nargs="*", default=None,
                   metavar="GLOB",
                   help="preflight every config matching the GLOB pattern(s) "
                        "(default conf/*.yaml; unquoted shell-expanded paths "
                        "work too), one subprocess each (XLA device counts "
                        "differ per config), and print a summary table; "
                        "exit 1 if any fails")
    p.add_argument("--calibrate", action="store_true",
                   help="compile the bench config on the real TPU and/or "
                        "XLA-CPU (CALIBRATE_BACKENDS=cpu,tpu — default "
                        "both; cpu alone costs ~25 min of XLA-CPU compile) "
                        "and print each memory_analysis() peak — the error "
                        "bar for every CPU-estimate verdict (tpu needs the "
                        "tunnel; AOT only, runs nothing)")
    p.add_argument("--select", action="store_true",
                   help="after the as-written verdict, enumerate "
                        "(schedule, virtual_stages, accum_chunks, offload) "
                        "candidates against the HBM budget + host-bandwidth "
                        "bound and print the analytically chosen config "
                        "(OptPipe-style selection; docs/SCHEDULES.md "
                        "'Host offload')")
    p.add_argument("--emit-schedule", default=None, metavar="PATH",
                   help="dump the selected unit sequence (the --select "
                        "winner's, else the as-written config's canonical "
                        "re-emission) as JSON to PATH and print the "
                        "per-stage ASCII timeline — debug a refused or "
                        "surprising schedule without a TPU; the file feeds "
                        "pipeline_schedule: solver + schedule_file")
    p.add_argument("--layout-devices", type=int, default=None, metavar="N",
                   help="with --select: grow the OUTER (pp, tp, dp, sp) "
                        "axes — enumerate every mesh of N devices (default: "
                        "the as-written world size), re-run the memory "
                        "model + schedule selection per mesh, and rank the "
                        "frontier by the analytic step-time score "
                        "(docs/PREFLIGHT.md 'Layout auto-selection')")
    p.add_argument("--emit-ladder", default=None, metavar="PATH",
                   help="with --select: write the layout frontier's top-k "
                        "survivors (plus the best rung at each halved "
                        "device count — the elastic-resize rungs) as a "
                        "tools/supervisor.py --layout-ladder JSON; solver "
                        "rungs get their unit-sequence files written next "
                        "to PATH")
    p.add_argument("--ladder-top-k", type=int, default=3,
                   help="rungs to emit at the full device count (default "
                        "3 — the set bench.py's extra:layout-* rows "
                        "measure)")
    p.add_argument("--ici-bw-gibps", type=float, default=90.0,
                   help="assumed ICI per-link bandwidth, GiB/s, for the "
                        "layout score's collective terms (v5p ~90)")
    p.add_argument("--host-bw-gibps", type=float, default=30.0,
                   help="assumed host-link bandwidth, GiB/s, for the "
                        "offload feasibility bound (measure the real one "
                        "with bench.py's extra:offload-bw row)")
    p.add_argument("--mfu", type=float, default=0.45,
                   help="assumed MFU for the modeled step-compute time the "
                        "offload traffic must hide inside (higher = "
                        "stricter: faster compute leaves less hiding room)")
    p.add_argument("--hide-ratio-max", type=float, default=1.0,
                   help="reject offload whose modeled transfer/compute "
                        "ratio exceeds this")
    p.add_argument("--mem-scale", type=float, default=1.0,
                   help="measured live-peak / byte-model-peak ratio "
                        "scaling every --select candidate's est_peak_gib "
                        "(1.0 = trust the model; the memory observatory's "
                        "mem_peak_gib rows calibrate it via --calibration)")
    p.add_argument("--memory-audit", action="store_true",
                   help="compile the SAME program at a ladder of "
                        "microbatch counts and print the per-term "
                        "byte-model vs memory_analysis() residual table "
                        "with top-buffer attribution — the per-buffer "
                        "evidence behind the anchored-estimate mode "
                        "(docs/PREFLIGHT.md 'Memory audit')")
    p.add_argument("--chip-flops", type=float, default=None,
                   help="chip peak FLOP/s for the compute model (default: "
                        "detect, else 197e12)")
    p.add_argument("--calibration", default=None, metavar="JSON",
                   help="measured constants file from tools/perf_report.py "
                        "--emit-calibration: keys present there (mfu, "
                        "host_bw_gibps, ici_bw_gibps, mem_scale) override "
                        "the CLI assumptions above, so --select re-ranks "
                        "the frontier from MEASURED bandwidth/MFU/memory "
                        "instead of guesses (docs/PREFLIGHT.md "
                        "'Calibration')")
    p.add_argument("overrides", nargs="*", help="key=value config overrides")
    args, unknown = p.parse_known_args(argv)
    bad = [u for u in unknown if not (u.startswith("--") and "=" in u)]
    if bad:
        p.error(f"unrecognized arguments: {' '.join(bad)}")
    args.overrides += unknown

    if args.calibrate:
        import json

        print(json.dumps(calibrate(), indent=2))
        return
    if args.calibration:
        applied = apply_calibration(args, args.calibration)
        if applied:
            print("calibration: " + ", ".join(
                f"{k}={v}" for k, v in applied.items())
                + f" (measured — {args.calibration})")
        else:
            print(f"calibration: {args.calibration} carries no usable keys; "
                  f"keeping the CLI assumptions")
    if (args.emit_ladder or args.layout_devices) and not args.select:
        p.error("--emit-ladder/--layout-devices extend --select (the layout "
                "lane calibrates against the compiled peak --select anchors "
                "on)")
    if args.all_globs is not None:
        if args.config:
            p.error("--config and --all are mutually exclusive")
        # nargs='*' greedily consumes trailing key=value overrides too:
        # route anything that isn't a yaml path/glob back to overrides
        globs = [g for g in args.all_globs
                 if g.endswith((".yaml", ".yml")) or "*" in g]
        stray = [g for g in args.all_globs if g not in globs]
        if any("=" not in s for s in stray):
            p.error(f"--all takes .yaml globs; got {stray}")
        _run_all(globs or ["conf/*.yaml"], args.hbm_gb,
                 stray + args.overrides)
        return
    if args.config is None:
        p.error("--config is required (or pass --all for a sweep)")

    n_devices = _mesh_product(args.config, args.overrides)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")  # sitecustomize pins TPU otherwise

    from llama_pipeline_parallel_tpu.utils.config import load_config

    cfg = load_config(args.config, args.overrides)
    print(f"preflight: {args.config} on {n_devices} virtual devices "
          f"(XLA-CPU estimate; TPU layouts/Mosaic VMEM differ — keep margin)")
    report = preflight(cfg, args.hbm_gb, host_bw_gibps=args.host_bw_gibps,
                       mfu=args.mfu, hide_max=args.hide_ratio_max,
                       chip_flops=args.chip_flops)
    for k, v in report.items():
        print(f"  {k}: {v}")
    resume = resume_compat(cfg)
    if resume:
        print("resume preflight (elastic — docs/RESILIENCE.md):")
        for k, v in resume.items():
            print(f"  {k}: {v}")
    if args.memory_audit:
        print()
        print_memory_audit(memory_audit(cfg))
    if args.select:
        _print_selection(cfg, report, args)
    elif args.emit_schedule:
        _emit_schedule(args.emit_schedule, None, None,
                       int((cfg.get("mesh") or {}).get("pp", 1)),
                       _as_written_pcfg(cfg))
    if not report["fits"]:
        print(f"preflight FAIL: per-device peak {report['per_device_peak_gib']} GiB "
              f"exceeds the {args.hbm_gb} GiB budget"
              if "offload_bw_verdict" not in report
              or report["offload_hide_ratio"] <= args.hide_ratio_max else
              f"preflight FAIL: {report['offload_bw_verdict']}")
        if "wgrad_queue_depth" in report and not report.get("offload"):
            # actionable split-backward guidance: the W-stash is the
            # schedule's own memory tax; the remedies (and the fallback's
            # bubble) are DERIVED from the emitted sequences at this exact
            # shape, not hard-coded schedule names (docs/SCHEDULES.md)
            print(f"  W-stash: {report['wgrad_stash_gib']} GiB across "
                  f"{report['wgrad_queue_depth']} queued units — "
                  f"{stash_remedies(_as_written_pcfg(cfg))}")
        sys.exit(1)
    print("preflight OK")


def _print_selection(cfg: dict, report: dict, args) -> None:
    """The --select pass: anchor on the compiled peak, enumerate the
    candidate grid, print the scored table + the chosen config (or why
    nothing fits). Pure arithmetic after the one compile the as-written
    report already paid for."""
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig
    from llama_pipeline_parallel_tpu.train import (
        build_manifest,
        build_model_config,
        build_pipeline_config,
    )

    mesh_cfg = MeshConfig(**cfg.get("mesh", {}))
    model_cfg = build_model_config(cfg["model"])
    manifest = build_manifest(cfg, model_cfg, mesh_cfg.pp)
    pcfg = build_pipeline_config(cfg, mesh_cfg, manifest)
    import jax.numpy as jnp

    mb_rows = int(cfg.get("per_device_train_batch_size", 1))
    seq = report["seq"]
    dims = pl.stash_dims(mb_rows, seq, mesh_cfg.sp, model_cfg.hidden_size,
                         model_cfg.dtype)
    # schedule-independent anchor: the compiled DEVICE peak minus the
    # as-written config's own ring/stash/loss-head terms. The ce axis
    # (docs/KERNELS.md) only exists at tp=1: under tp the head is already
    # vocab-parallel and the trainer REJECTS loss_chunks/kernels.ce
    # overrides, so selection must not recommend them (the head term is
    # then candidate-invariant and stays inside the anchor). Pallas
    # candidates are offered CHUNKED only — at loss_chunks=1 the kernel's
    # [d, V] weight block cannot fit VMEM at production vocabs.
    vocab = model_cfg.vocab_size if mesh_cfg.tp <= 1 else None
    mem_scale = getattr(args, "mem_scale", 1.0) or 1.0
    terms = candidate_device_terms_gib(pcfg, dims, vocab)
    base = (report["per_device_peak_gib"] - terms["ring_gib"]
            - terms["stash_gib"] - terms["loss_head_gib"])
    compute_fn = lambda c: _step_compute_seconds(
        model_cfg, mesh_cfg, c, mb_rows, seq, args.mfu, args.chip_flops)
    ce_axis = ce_axis_options(pcfg.loss_chunks, model_cfg.vocab_size,
                              mesh_cfg.tp)
    candidates = enumerate_candidates(mesh_cfg.pp, pcfg.num_microbatches,
                                      model_cfg.num_hidden_layers,
                                      ce_options=ce_axis,
                                      layer_counts=pcfg.layer_counts)
    # the solver lane: list-scheduled sequences with budget-sized per-unit
    # offload vectors, scored in the SAME pass under the same constraints
    # (incl. the dense loss-head term solver rows are charged — they carry
    # the as-written head, never a ce override)
    solver_head = 0.0
    if vocab:
        import dataclasses as _dc

        solver_head = pl.loss_head_bytes(
            _dc.replace(pcfg, loss_chunks=1, kernel_ce=False),
            *dims[:3], vocab) / (1 << 30)
    if pcfg.layer_counts is None or len(set(pcfg.layer_counts)) == 1:
        # the solver lane emits even sequences; on an unequal as-written
        # partition its rows would be scored with uncosted bubbles and
        # unfairly beat the canonical candidates — skip it there (the
        # layout lane already skips uneven layouts the same way)
        candidates += solver_candidates(mesh_cfg.pp, pcfg.num_microbatches,
                                        model_cfg.num_hidden_layers, base,
                                        dims, args.hbm_gb,
                                        head_gib=solver_head,
                                        mem_scale=mem_scale)
    winner, rows = select_schedule(
        candidates, base, dims, args.hbm_gb, args.host_bw_gibps, compute_fn,
        hide_max=args.hide_ratio_max, vocab=vocab, mem_scale=mem_scale)
    scale_note = (f", mem_scale {mem_scale}" if mem_scale != 1.0 else "")
    print(f"schedule selection ({len(rows)} candidates; base "
          f"{round(base, 2)} GiB + per-candidate ring/stash/loss-head; "
          f"bw {args.host_bw_gibps} GiB/s, mfu {args.mfu}{scale_note}):")
    print(f"  {'schedule':<17} {'v':>2} {'c':>2} {'offload':<14} "
          f"{'ce':<10} {'peak GiB':>9} {'host GiB':>9} {'head GiB':>9} "
          f"{'bubble%':>8} {'hide':>6}  verdict")
    for r in sorted(rows, key=lambda r: (not r["feasible"],
                                         r["bubble_fraction"],
                                         r["est_peak_gib"])):
        off = "+".join(n for n, on in (("wgrad", r["offload_wgrad"]),
                                       ("acts", r["offload_activations"]))
                       if on) or "-"
        if r.get("wgrad_offload_units"):
            off = (f"wgrad[{r['wgrad_offload_units']}"
                   f"/{r['wgrad_units_total']}]")
        sched_name = r["schedule"]
        if r.get("label"):
            sched_name = r["label"]
        ce = (f"{'pallas' if r['kernel_ce'] else 'xla'}/"
              f"{r['loss_chunks']}")
        mark = "*" if r is winner else " "
        print(f" {mark}{sched_name:<17} {r['virtual_stages']:>2} "
              f"{r['accum_chunks']:>2} {off:<14} {ce:<10} "
              f"{r['est_peak_gib']:>9} {r['host_stash_gib']:>9} "
              f"{r['loss_head_gib']:>9} "
              f"{100 * r['bubble_fraction']:>8.2f} {r['hide_ratio']:>6} "
              f" {'OK' if r['feasible'] else r['why_not']}")
    if args.layout_devices or args.emit_ladder:
        # the OUTER axes: every (pp, tp, dp, sp) mesh of the device count,
        # each re-scored by the same memory model — runs even when nothing
        # fits the as-written mesh (another layout may be the fix)
        _print_layout_frontier(cfg, args, model_cfg, mesh_cfg, pcfg, base,
                               mb_rows, seq)
    if winner is None:
        print("selection: NO feasible candidate — grow the mesh (tp/pp) or "
              "shrink the batch shape")
        if getattr(args, "emit_schedule", None):
            # the debug-a-refused-schedule case the flag exists for: emit
            # the as-written config's canonical sequence so the operator
            # can read the timeline even though nothing fit
            _emit_schedule(args.emit_schedule, None, None, mesh_cfg.pp, pcfg)
        return
    emitted = None
    if getattr(args, "emit_schedule", None):
        emitted = _emit_schedule(args.emit_schedule, winner.get("_pcfg"),
                                 winner, mesh_cfg.pp, pcfg)
    print(f"selected: {select_overrides(winner, schedule_file=emitted)}  "
          f"(est peak {winner['est_peak_gib']} GiB, bubble "
          f"{100 * winner['bubble_fraction']:.2f}%, host stash "
          f"{winner['host_stash_gib']} GiB)")


def _print_layout_frontier(cfg: dict, args, model_cfg, mesh_cfg, pcfg,
                           base: float, mb_rows: int, seq: int) -> None:
    """The layout lane of --select: print the scored (pp, tp, dp, sp)
    frontier and — with --emit-ladder — write the generated supervisor
    ladder (+ any solver rungs' unit-sequence files). Pure arithmetic on
    top of the one compile the as-written report paid for."""
    import json as _json

    devices = args.layout_devices or mesh_cfg.world_size
    g_examples = mb_rows * pcfg.num_microbatches * mesh_cfg.dp
    aw_layout = (mesh_cfg.pp, mesh_cfg.tp, mesh_cfg.dp, mesh_cfg.sp)
    kw = dict(host_bw_gibps=args.host_bw_gibps, mfu=args.mfu,
              chip_flops=args.chip_flops, ici_bw_gibps=args.ici_bw_gibps,
              hide_max=args.hide_ratio_max,
              optimizer_offload=bool(cfg.get("optimizer_offload")),
              zero2=bool(cfg.get("optimizer_offload_zero2")),
              loss_chunks_aw=pcfg.loss_chunks,
              mem_scale=getattr(args, "mem_scale", 1.0) or 1.0)
    # the display frontier ranks LAYOUTS, and the layout score depends on
    # the bubble, not on where the W residuals live — the canonical lane
    # ranks identically, so the solver refinement (slower: a per-unit
    # binary search per grid point) is saved for the ladder's actual rungs
    winner, rows = layout_frontier(model_cfg, devices, mb_rows, seq,
                                   g_examples, base, aw_layout, args.hbm_gb,
                                   solver_lane=False, **kw)
    print(f"layout frontier ({devices} devices, global batch {g_examples} "
          f"examples preserved; analytic memory model calibrated on the "
          f"compiled as-written peak, score = compute/(1-bubble) + "
          f"collectives at {args.ici_bw_gibps} GiB/s ICI):")
    print(f"  {'layout':<20} {'M':>4} {'partition':<14} {'schedule':<17} "
          f"{'v':>2} {'c':>2} {'peak GiB':>9} {'bubble%':>8} "
          f"{'score s':>8}  verdict")
    for r in rows:
        part = ("even" if not r["layer_counts"]
                else ",".join(str(c) for c in r["layer_counts"]))
        mark = "*" if r is winner else " "
        if r["feasible"]:
            s = r["sched"]
            name = s.get("label") or s["schedule"]
            print(f" {mark}{r['layout']:<20} {r['microbatches']:>4} "
                  f"{part:<14} {name:<17} {s['virtual_stages']:>2} "
                  f"{s['accum_chunks']:>2} {r['est_peak_gib']:>9} "
                  f"{100 * r['bubble_fraction']:>8.2f} {r['score_s']:>8}  OK")
        else:
            print(f" {mark}{r['layout']:<20} {r['microbatches']:>4} "
                  f"{part:<14} {'-':<17} {'-':>2} {'-':>2} "
                  f"{r['base_gib']:>9} {'-':>8} {'-':>8}  {r['why_not']}")
    if winner is not None:
        print(f"layout selected: {winner['layout']} "
              f"(score {winner['score_s']} s, est peak "
              f"{winner['est_peak_gib']} GiB) — overrides: "
              f"{' '.join(layout_overrides(winner))}")
    else:
        print("layout selection: NO feasible layout at this device count — "
              "shrink the batch shape or raise --hbm-gb")
    if args.emit_ladder:
        stem = args.emit_ladder
        if stem.endswith(".json"):
            stem = stem[: -len(".json")]

        def schedule_file_for(rung_name: str, rung_pcfg) -> str:
            from llama_pipeline_parallel_tpu.parallel import schedule as usched

            path = f"{stem}-{rung_name}.schedule.json"
            with open(path, "w") as fh:
                fh.write(usched.to_json(rung_pcfg.unit_schedule))
            return path

        rungs, _ = build_ladder(model_cfg, devices, mb_rows, seq,
                                g_examples, base, aw_layout, args.hbm_gb,
                                top_k=args.ladder_top_k,
                                schedule_file_for=schedule_file_for, **kw)
        with open(args.emit_ladder, "w") as fh:
            _json.dump(rungs, fh, indent=1)
            fh.write("\n")
        print(f"emitted ladder -> {args.emit_ladder} ({len(rungs)} rungs, "
              f"best-first; feed it to tools/supervisor.py "
              f"--layout-ladder @{args.emit_ladder}):")
        for rg in rungs:
            print(f"  {rg['devices']:>5} devices  {rg['name']:<28} "
                  f"{' '.join(rg['overrides'])}")


def _emit_schedule(path: str, winner_pcfg, row: dict | None, pp: int,
                   as_written_pcfg=None) -> str:
    """`--emit-schedule <path>`: serialize the relevant unit sequence
    (the --select winner's, else the as-written config's canonical
    re-emission) as JSON and print the compact per-stage ASCII timeline —
    so a refused or surprising schedule is debuggable without a TPU."""
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel import schedule as usched

    import dataclasses as _dc

    pcfg = winner_pcfg
    if pcfg is None and row is None and as_written_pcfg is not None \
            and as_written_pcfg.schedule == "gpipe":
        print("--emit-schedule: gpipe has no unit sequence (its backward "
              "is AD of the forward scan) — nothing emitted")
        return path
    if pcfg is None and row is not None:
        # a canonical winner: rebuild its pcfg from the row (the winner's
        # grid shares the as-written config's total microbatch count)
        if as_written_pcfg is None:
            raise ValueError("_emit_schedule needs the as-written pcfg to "
                             "size a canonical winner's flush")
        pcfg = pl.PipelineConfig(
            num_stages=pp,
            num_microbatches=as_written_pcfg.num_microbatches,
            schedule=row["schedule"], virtual_stages=row["virtual_stages"],
            accum_chunks=row["accum_chunks"],
            offload_wgrad=row["offload_wgrad"],
            offload_activations=row["offload_activations"])
    if pcfg is None:
        pcfg = as_written_pcfg
    flush_pcfg = _dc.replace(
        pcfg, num_microbatches=pcfg.num_microbatches // pcfg.accum_chunks,
        accum_chunks=1)
    seq = (flush_pcfg.unit_schedule if flush_pcfg.schedule == "solver"
           else usched.canonical_schedule(
               flush_pcfg.schedule, flush_pcfg.num_microbatches,
               flush_pcfg.num_stages, flush_pcfg.virtual_stages,
               offload_wgrad=flush_pcfg.offload_wgrad))
    with open(path, "w") as fh:
        fh.write(usched.to_json(seq))
    idle, wall = usched.bubble_stats(seq)
    print(f"emitted unit sequence -> {path} ({seq.num_ticks} ticks, "
          f"{idle}/{wall} idle units = {idle / wall:.4f} bubble per flush)")
    print(usched.ascii_timeline(seq))
    return path


if __name__ == "__main__":
    main()

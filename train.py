#!/usr/bin/env python
"""CLI entry point: `python train.py --config conf/<name>.yaml [key=value ...]`.

Thin launcher over `llama_pipeline_parallel_tpu.cli` (also installed as the
`lpt-train` console script — see pyproject.toml)."""

from __future__ import annotations

import sys

from llama_pipeline_parallel_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Single-chip training-throughput benchmark.

Runs the real train-step path (pipeline machinery at PP=1, bf16 compute,
fp32 AdamW with ZeRO-1 layout) on a ~550M-param LLaMA-shaped model at the
reference workload shape (seq 512; reference conf yaml:32) and prints ONE
JSON line: {"metric", "value", "unit", "vs_baseline"}.

Sweeps the configuration knobs a user would actually tune on one chip —
remat on/off (HBM is plentiful at this size; recompute is pure overhead when
memory allows), exact vs flash attention, and the vocab-chunked fused CE
(which never materializes the fp32 [tokens, V] logits) — and reports the BEST measured
configuration as the headline, with every config's number in the detail
field. The reference publishes no throughput numbers (BASELINE.md), so
vs_baseline is measured MFU / 0.45 — the 45%-MFU north-star from
BASELINE.json.

After the headline sweep, three NON-headline rows bench the paths the 65B
run of record actually uses (they appear under `all_configs` prefixed
`extra:` but never win the headline — their tokens/s are not
shape-comparable):
- `extra:offload` — the SAME step with the host-offloaded AdamW
  (optim/offload.py, the trainer-default device-norm streaming path)
  instead of the fused optax update; its delta vs the matching fused row is
  the measured offload stall, and the row carries the phase breakdown from
  `host.last_timings` (norm_ms + the streamed d2h/update/h2d span).
- `extra:packed` — a FLAN-shaped packed batch (segment-id masks, ~real
  workload); its tokens/s counts REAL (non-pad) tokens only, the
  `real_tokens_per_sec` headline of packed training.
- `extra:seq2048-flash` — the long-context shape on the flash kernel.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _parse_args(argv=None):
    """CLI surface (env vars keep working; flags win where both exist):

    --full-trajectory  the one-shot runbook: force every `extra:*` row
                       family ON (sched-*, layout-*, offload-*, mem-*,
                       kernel-*, serve-*) regardless of the BENCH_* env
                       toggles and
                       write every row into the perf ledger — the "first
                       reachable-TPU run records everything in one pass"
                       mode, runnable end-to-end on CPU today.
    --perf-ledger P    append utils/perf.py rows (model-vs-measured pairs
                       per row, reason-tagged failure rows for probe
                       failures) to P; defaults to ./perf.jsonl under
                       --full-trajectory.
    --row-budget-s B   per-row wall budget for the extras families: a new
                       family may start only while the extras wall stays
                       within B x rows-completed (+1); families skipped by
                       an exhausted budget land in the ledger as
                       reason-tagged rows, so perf_report can tell
                       "skipped" from "never attempted".
    """
    p = argparse.ArgumentParser(add_help=False)
    p.add_argument("--full-trajectory", action="store_true")
    p.add_argument("--perf-ledger", default=os.environ.get("BENCH_PERF_LEDGER"))
    p.add_argument("--row-budget-s", type=float,
                   default=float(os.environ.get("BENCH_ROW_BUDGET_S", "0") or 0))
    args, _ = p.parse_known_args(argv)
    if args.full_trajectory:
        for var in ("BENCH_EXTRAS", "BENCH_SCHEDULES", "BENCH_LAYOUT",
                    "BENCH_OFFLOAD", "BENCH_MEM", "BENCH_KERNELS",
                    "BENCH_SERVING"):
            os.environ[var] = "1"
        if not args.perf_ledger:
            args.perf_ledger = "perf.jsonl"
    return args


class _RowBudget:
    """Per-row wall budget over the extras families (--row-budget-s).
    `allow(name)` gates each family: permitted only while the extras wall
    is within budget x (rows completed so far + 1) — one overrunning row
    eats the later families' budget instead of the harness's patience.
    Skips are recorded for the ledger."""

    def __init__(self, per_row_s: float, count_rows=None):
        self.per_row = per_row_s
        self.t0 = None
        self._count = count_rows or (lambda: 0)
        self._initial = 0
        self.skipped: list[str] = []

    def start(self) -> None:
        self.t0 = time.perf_counter()
        self._initial = self._count()

    def allow(self, name: str) -> bool:
        if not self.per_row or self.t0 is None:
            return True
        rows_done = max(self._count() - self._initial, 0)
        elapsed = time.perf_counter() - self.t0
        if elapsed <= self.per_row * (rows_done + 1):
            return True
        print(f"bench row family {name} skipped: extras wall "
              f"{elapsed:.0f}s exceeds the --row-budget-s {self.per_row:.0f}"
              f"s x {rows_done + 1} rows", file=sys.stderr, flush=True)
        self.skipped.append(name)
        return False


def _write_ledger(path: str | None, summary: dict | None,
                  skipped: list[str], error: str | None = None) -> None:
    """Append this round's rows to the perf ledger (--perf-ledger): the
    model-vs-measured pairs of a healthy round, or ONE reason-tagged
    failure row for a probe-failed round — BENCH_r0*-style history stays
    summarizable by tools/perf_report.py either way. Never raises: the
    measurement JSON line is already out when this runs."""
    if not path:
        return
    try:
        from llama_pipeline_parallel_tpu.utils import perf

        label = os.environ.get("BENCH_RUN_LABEL") or \
            f"bench-{time.strftime('%Y%m%d-%H%M%S')}"
        if error is not None:
            rows = [perf.make_row("bench_round", source="bench", run=label,
                                  reason=error)]
        else:
            rows = perf.rows_from_bench_summary(summary or {}, run=label)
            # stamp the backend: a CPU smoke's mfu/host-bw are real numbers
            # about the WRONG hardware — derive_calibration must not feed
            # them into preflight's TPU model constants
            try:
                import jax

                backend = jax.default_backend()
                for row in rows:
                    row.setdefault("context", {})["backend"] = backend
            except Exception:
                pass
        rows += [perf.make_row("bench_row_family", source="bench", run=label,
                               reason=f"skipped: row budget exhausted "
                                      f"before {name}")
                 for name in skipped]
        n = perf.append_rows(path, rows)
        print(f"perf ledger: {n} row(s) appended to {path}",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"perf ledger write failed: {e!r}", file=sys.stderr, flush=True)


def _watchdog(seconds: int, report):
    """The TPU tunnel can wedge indefinitely (even trivial ops hang); emit a
    diagnostic JSON line instead of hanging the harness forever. Returns the
    timer; the caller cancels it the moment timing completes, BEFORE printing,
    so exactly one JSON line is ever emitted. If some sweep configs already
    finished when the timer fires, their best number is reported (tagged
    partial) rather than thrown away.

    A timer THREAD, not SIGALRM: the wedge sits in a blocking C call on the
    main thread, so a Python signal handler would never run — a thread still
    gets scheduled whenever the call releases the GIL."""
    import threading

    def fire():
        note = f"bench watchdog fired after {seconds}s (TPU unreachable?)"
        if report():  # best completed config, if any
            print(json.dumps({**report(), "partial": True, "error": note}),
                  flush=True)
            os._exit(0)
        print(json.dumps({
            "metric": "tokens_per_sec_per_chip", "value": 0.0,
            "unit": "tokens/s/chip", "vs_baseline": 0.0, "error": note,
        }), flush=True)
        os._exit(2)

    timer = threading.Timer(seconds, fire)
    timer.daemon = True
    timer.start()
    return timer


def _probe_devices(timeout_s: float) -> str | None:
    """Fail-fast accelerator probe: list devices and run one trivial op on a
    worker thread, bounded by `timeout_s`. Returns an error string when the
    backend is unreachable (probe wedged or raised), None when healthy.

    A thread for the same reason as the watchdog: an unreachable TPU wedges
    inside a blocking C call, where signal handlers never run. BENCH_r05
    burned the full 900 s watchdog before reporting rc=2 — with this probe
    the error JSON line is emitted within BENCH_PROBE_TIMEOUT_S instead."""
    import threading

    result: dict = {}

    def probe():
        try:
            import jax
            import jax.numpy as jnp

            devs = jax.devices()
            if not devs:
                result["error"] = "jax.devices() returned no devices"
                return
            # a real dispatch + value fetch: device enumeration can succeed
            # while the runtime tunnel is already wedged
            if float(jnp.asarray(1.0) + jnp.asarray(1.0)) != 2.0:
                result["error"] = "device arithmetic returned garbage"
        except Exception as e:
            result["error"] = f"device probe failed: {e!r}"

    t = threading.Thread(target=probe, daemon=True, name="bench-device-probe")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return (f"device probe did not respond within {timeout_s:.0f}s "
                f"(TPU unreachable/wedged?)")
    return result.get("error")


def main() -> None:
    cli = _parse_args()
    results: dict[str, dict] = {}  # name -> {"dt": s/step, "tokens_per_step": n}
    summary_ctx: dict = {}
    row_budget = _RowBudget(cli.row_budget_s, count_rows=lambda: len(results))

    def report():
        # extras (offload/packed/long-seq rows) are excluded from the
        # headline: their tokens/s are not shape-comparable with the sweep
        headliners = {k: r for k, r in results.items()
                      if r.get("headline", True)}
        if not headliners or not summary_ctx:
            return None
        tps_of = lambda r: r["tokens_per_step"] / r["dt"]
        best_name = max(headliners, key=lambda k: tps_of(headliners[k]))
        best = headliners[best_name]
        tps = tps_of(best)
        mfu = summary_ctx["flops_token"] * tps / summary_ctx["peak"]
        return {
            "metric": "tokens_per_sec_per_chip",
            "value": round(tps, 1),
            "unit": "tokens/s/chip",
            "vs_baseline": round(mfu / 0.45, 4),
            "mfu": round(mfu, 4),
            "step_time_ms": round(1000 * best["dt"], 1),
            "best_config": best_name,
            # untimed gauge rows (extra:mem-pagepool) carry dt=0: no tok_s
            "all_configs": {k: {"ms": round(1000 * r["dt"], 1),
                                "tok_s": round(tps_of(r), 1) if r["dt"]
                                else None,
                                **r.get("detail", {})}
                            for k, r in results.items()},
            # round-1 emitted a flat name->ms map under this key; keep it so
            # round-over-round consumers keep parsing (ADVICE round-3)
            "all_configs_ms": {k: round(1000 * r["dt"], 1)
                               for k, r in results.items()},
            "model": summary_ctx["model"],
        }

    # 900s is known to be within the driver's own patience (round-1 artifact
    # recorded a 900s watchdog fire); on a live chip the 9-config sweep takes
    # ~5-6 min, and a mid-sweep wedge reports the best completed config.
    watchdog = _watchdog(int(os.environ.get("BENCH_TIMEOUT_S", "900")), report)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from __graft_entry__ import _bench_config, _honor_cpu_request

    _honor_cpu_request()  # JAX_PLATFORMS=cpu smoke runs (sitecustomize pins TPU)

    # Up-front device probe: an unreachable TPU fails in seconds with the
    # same error-JSON contract, instead of wedging the first compile until
    # the 900 s watchdog fires (BENCH_r05).
    probe_err = _probe_devices(float(os.environ.get("BENCH_PROBE_TIMEOUT_S",
                                                    "60")))
    if probe_err:
        watchdog.cancel()
        print(json.dumps({
            "metric": "tokens_per_sec_per_chip", "value": 0.0,
            "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "error": f"no usable accelerator: {probe_err}",
        }), flush=True)
        # probe-failure rounds land in the ledger as reason-tagged rows so
        # perf_report can summarize "N rounds unreachable" from history
        _write_ledger(cli.perf_ledger, None, [],
                      error=f"no usable accelerator: {probe_err}")
        # the probe thread may still be wedged inside the runtime — a plain
        # sys.exit would hang interpreter shutdown on it
        os._exit(2)
    from llama_pipeline_parallel_tpu.models.llama import model as llama
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.ops.attention import attention
    from llama_pipeline_parallel_tpu.ops.flash_attention import flash_attention
    from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel import train_step as ts
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh
    from llama_pipeline_parallel_tpu.utils.metrics import (
        detect_chip_peak_flops,
        train_flops_per_token,
    )

    # BENCH_MODEL=tiny: CPU-runnable smoke of the full sweep machinery (the
    # headline model is the fixed ~550M shape; MFU on tiny is meaningless).
    if os.environ.get("BENCH_MODEL") == "tiny":
        from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig

        cfg, model_name = LlamaConfig.tiny(dtype=jnp.bfloat16), "tiny-smoke"
    else:
        cfg, model_name = _bench_config(), "llama-550m"
    # Batch sizes to sweep: 8 is the reference-comparable per-replica shape
    # (reference conf yaml:75); larger batches raise arithmetic intensity on
    # one chip, and the headline is the best measured config. Listed largest
    # (likely fastest per token) first.
    batches = [int(b) for b in
               os.environ.get("BENCH_BATCH", "32,16,8").split(",")]
    seq = int(os.environ.get("BENCH_SEQ", "512"))
    n_steps = int(os.environ.get("BENCH_STEPS", "10"))

    mesh = make_mesh(MeshConfig())  # single chip
    manifest = StageManifest.for_config(cfg, 1)
    canonical = llama.init_params(jax.random.PRNGKey(0), cfg)
    stacked = pl.stack_stages(canonical, manifest)
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-4, total_steps=1000,
                                               warmup_steps=10))

    def make_batch(batch_size: int, seq_len: int | None = None,
                   packed: bool = False) -> dict:
        L = seq_len or seq
        rs = np.random.RandomState(0)
        ids = rs.randint(3, cfg.vocab_size, (batch_size, L)).astype(np.int32)
        if not packed:
            return {
                "input_ids": jnp.asarray(ids),
                "attention_mask": jnp.ones((batch_size, L), jnp.int32),
                "position_ids": jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32),
                                                 (batch_size, L)),
                "labels": jnp.asarray(ids),
            }
        # FLAN-shaped packing: variable-length segments greedily packed per
        # row (the packed collator's contract — attention_mask carries
        # segment ids 1..k, 0 = pad; position_ids restart per segment;
        # segment-start labels ignored). Mean segment ~L/4 so rows carry
        # several segments plus a realistic pad tail.
        from llama_pipeline_parallel_tpu.models.llama.model import (
            IGNORE_INDEX as IGNORE,
        )

        mask = np.zeros((batch_size, L), np.int32)
        pos = np.zeros((batch_size, L), np.int32)
        labels = ids.astype(np.int32).copy()
        for b in range(batch_size):
            cursor, seg_id = 0, 1
            while L - cursor >= max(8, L // 16):
                length = min(int(rs.randint(L // 8, L // 2)), L - cursor)
                mask[b, cursor:cursor + length] = seg_id
                pos[b, cursor:cursor + length] = np.arange(length)
                labels[b, cursor] = IGNORE
                cursor += length
                seg_id += 1
            labels[b, cursor:] = IGNORE  # pad tail
        return {
            "input_ids": jnp.asarray(ids),
            "attention_mask": jnp.asarray(mask),
            "position_ids": jnp.asarray(pos),
            "labels": jnp.asarray(labels),
        }

    peak = detect_chip_peak_flops() or 197e12
    flops_token = train_flops_per_token(cfg, seq)
    summary_ctx.update(peak=peak, flops_token=flops_token,
                       model=f"{model_name} seq{seq} bf16 1f1b")

    offload_phases: dict = {}  # host.last_timings of the latest offload row

    def measure(remat: bool, attn_name: str, batch_size: int,
                loss_chunks: int = 1, trace_dir: str | None = None,
                seq_len: int | None = None, packed: bool = False,
                offload: bool = False, kernel_ce: bool = False,
                kernel_prologue: bool = False) -> float | None:
        """Mean steady-state step seconds for one config; None if it fails
        (e.g. flash unsupported shape / OOM with remat off) or its loss is
        not finite (a fast-but-broken config must never win the headline).
        `trace_dir` captures a profiler trace of the timed loop only (the
        warmup/compile step stays outside the trace). `offload` swaps the
        fused optax update for the host-offloaded AdamW (the 65B path's
        optimizer) and records its phase breakdown in `offload_phases`."""
        import math

        try:
            batch = make_batch(batch_size, seq_len, packed)
            attn_fn = flash_attention if attn_name == "flash" else attention
            pcfg = pl.PipelineConfig(num_stages=1, num_microbatches=1,
                                     remat=remat, loss_chunks=loss_chunks,
                                     kernel_ce=kernel_ce,
                                     kernel_prologue=kernel_prologue)
            if offload:
                from llama_pipeline_parallel_tpu.optim.offload import (
                    HostOffloadAdamW,
                )

                host = HostOffloadAdamW(OptimizerConfig(
                    learning_rate=1e-4, total_steps=1000, warmup_steps=0),
                    device_norm=True)  # the trainer's default streaming path
                host.init(stacked)
                grad_fn = jax.jit(pl.make_pipeline_loss_and_grad(
                    mesh, cfg, pcfg, host.abstract_tree(), attn_fn=attn_fn))
                dev_box = [host.device_params(cfg.dtype)]

                def step_once():
                    loss, grads = grad_fn(dev_box[0], batch)
                    dev_box[0] = host.update_and_refresh(grads, cfg.dtype)
                    return loss
            else:
                state_box = [ts.init_train_state(stacked, tx, mesh)]
                step = ts.make_train_step(mesh, cfg, pcfg, tx, sched, stacked,
                                          attn_fn=attn_fn)

                def step_once():
                    state_box[0], metrics = step(state_box[0], batch)
                    return metrics["loss"]

            # warmup (compile) + steady-state timing. The loss VALUE is
            # fetched every step: on the axon remote platform
            # block_until_ready alone does not wait for the donated-state
            # dependency chain, so value-fetch is the only reliable execution
            # barrier (cost: one scalar D2H per step).
            float(step_once())
            if trace_dir:
                jax.profiler.start_trace(trace_dir)
            try:
                t0 = time.perf_counter()
                last = 0.0
                for _ in range(n_steps):
                    last = float(step_once())
                dt = (time.perf_counter() - t0) / n_steps
            finally:
                if trace_dir:  # finalize whatever was captured, even on error
                    jax.profiler.stop_trace()
            if offload:
                offload_phases.clear()
                offload_phases.update({k: round(v, 2)
                                       for k, v in host.last_timings.items()})
            if not math.isfinite(last):
                print(f"bench config remat={remat} attn={attn_name} "
                      f"bs={batch_size} ce_chunks={loss_chunks} produced "
                      f"non-finite loss {last}; excluded",
                      file=sys.stderr, flush=True)
                return None
            return dt
        except Exception as e:
            print(f"bench config remat={remat} attn={attn_name} "
                  f"bs={batch_size} ce_chunks={loss_chunks} "
                  f"seq={seq_len or seq} packed={packed} "
                  f"offload={offload} failed: {e!r}", file=sys.stderr, flush=True)
            return None

    # Likely-fastest first, so a mid-sweep wedge still reports a strong
    # partial headline: remat off beats on (no recompute), and batches are
    # listed best-guess-first in `batches`. The flash rows run only at the
    # LARGEST batch (its best shot at seq 512 — BASELINE.md measured
    # exact/flash parity at seq <= 2048, so short-seq flash wins, if any,
    # come from batch-boosted occupancy): each extra config costs a full
    # XLA compile, and the sweep must finish inside the 900s watchdog.
    configs = {f"remat={int(remat)},attn={attn_name},bs={bs}":
               (remat, attn_name, bs, 1)
               for remat in (False, True) for attn_name in ("exact", "flash")
               for bs in batches
               if attn_name == "exact" or bs == max(batches)}
    # The vocab-chunked fused CE at the largest batch: the PP=1 step's
    # biggest single buffer is the fp32 [tokens, V] logits (2 GiB at bs32
    # seq512 V32k); the online-logsumexp head never materializes it, so this
    # row is the HBM-traffic winner candidate. One extra compile, placed
    # right after the likely-best plain row so a mid-sweep wedge still
    # compares the two.
    bs_top = max(batches)
    head = {f"remat=0,attn=exact,bs={bs_top}":
            configs.pop(f"remat=0,attn=exact,bs={bs_top}"),
            f"remat=0,attn=exact,bs={bs_top},ce=chunk8":
            (False, "exact", bs_top, 8)}
    configs = {**head, **configs}
    for name, (remat, attn_name, bs, chunks) in configs.items():
        dt = measure(remat, attn_name, bs, chunks)
        if dt is not None:
            results[name] = {"dt": dt, "tokens_per_step": bs * seq}

    # Non-headline rows: the paths the 65B run of record actually exercises
    # (offloaded optimizer, packed FLAN-shaped batches, long-context flash).
    # Run AFTER the sweep so a wedge here still reports the full headline;
    # BENCH_EXTRAS=0 skips them.
    if os.environ.get("BENCH_EXTRAS", "1") != "0":
        row_budget.start()  # the per-row wall budget covers the extras families
        bs_big = max(batches)
        long_seq = 2048 if os.environ.get("BENCH_MODEL") != "tiny" else seq * 2

        dt = measure(False, "exact", bs_big, offload=True)
        if dt is not None:
            fused = results.get(f"remat=0,attn=exact,bs={bs_big}")
            detail = {"phases_ms": dict(offload_phases)}
            if fused:  # measured offload stall vs the matching fused row
                detail["stall_vs_fused_ms"] = round(1000 * (dt - fused["dt"]), 1)
            results[f"extra:offload,bs={bs_big}"] = {
                "dt": dt, "tokens_per_step": bs_big * seq,
                "headline": False, "detail": detail}

        packed_batch = make_batch(bs_big, packed=True)
        real_tokens = int((np.asarray(packed_batch["attention_mask"]) != 0).sum())
        dt = measure(False, "exact", bs_big, packed=True)
        if dt is not None:
            # tokens/s counts REAL (non-pad) tokens: the packed-training
            # headline number (real_tokens_per_sec)
            results[f"extra:packed,bs={bs_big}"] = {
                "dt": dt, "tokens_per_step": real_tokens, "headline": False,
                "detail": {"real_tokens_per_step": real_tokens,
                           "padded_tokens_per_step": bs_big * seq}}

        dt = measure(False, "flash", 8, seq_len=long_seq)
        if dt is not None:
            results[f"extra:seq{long_seq}-flash,bs=8"] = {
                "dt": dt, "tokens_per_step": 8 * long_seq, "headline": False,
                "detail": {"seq": long_seq}}

        # Schedule ladder (BENCH_SCHEDULES=0 skips): flat vs interleaved vs
        # zb1 loss+grad step on a real pp ring over the chips this process
        # can see, each row carrying its analytic bubble_fraction NEXT to
        # the measured step time — so one live run lands a model-vs-
        # measured schedule trajectory point in one shot (the repo still
        # has no live perf number: every bench round recorded the TPU
        # unreachable, which is exactly why these rows sit behind the same
        # fail-fast probe as the headline). Non-headline: a pp-ring step at
        # these shapes is not tokens/s-comparable with the pp1 sweep.
        if os.environ.get("BENCH_SCHEDULES", "1") != "0" and row_budget.allow("sched"):
            n_dev = jax.device_count()
            pp_s = 4 if n_dev >= 4 else n_dev
            m_s = int(os.environ.get("BENCH_SCHED_MICROBATCHES", "8"))
            if pp_s < 2:
                print("bench schedule rows skipped: one visible device "
                      "(a pp ring needs >= 2 chips)", file=sys.stderr,
                      flush=True)
            else:
                sched_mesh = make_mesh(MeshConfig(pp=pp_s))
                sbatch = make_batch(m_s)  # one row per microbatch
                stacked_by_v: dict[int, tuple] = {}  # v -> (manifest, params)
            for sched_name, v_s in ((("1f1b", 1), ("interleaved_1f1b", 2),
                                ("zb1", 2), ("solver", 2))
                               if pp_s >= 2 else ()):
                if cfg.num_hidden_layers % (pp_s * v_s) or m_s % pp_s:
                    print(f"bench schedule row {sched_name} skipped: "
                          f"{cfg.num_hidden_layers} layers / m={m_s} do not "
                          f"fit pp={pp_s} v={v_s}", file=sys.stderr, flush=True)
                    continue
                try:
                    if v_s not in stacked_by_v:  # one ~550M re-stack per v
                        man_s = StageManifest.for_config(cfg, pp_s,
                                                         virtual_stages=v_s)
                        stacked_by_v[v_s] = (man_s,
                                             pl.stack_stages(canonical, man_s))
                    man_s, stacked_s = stacked_by_v[v_s]
                    seq_s = None
                    if sched_name == "solver":
                        # the list scheduler's drain-interleaved W variant:
                        # canonical zb1 bubble, compressed W queue — the
                        # measured point for the solver lane next to the
                        # three canonical rows (docs/SCHEDULES.md)
                        from llama_pipeline_parallel_tpu.parallel import (
                            schedule as usched,
                        )

                        seq_s = usched.list_schedule(m_s, pp_s, v_s,
                                                     w_placement="drain")
                    pcfg_s = pl.PipelineConfig(
                        num_stages=pp_s, num_microbatches=m_s,
                        schedule=sched_name, virtual_stages=v_s,
                        unit_schedule=seq_s)
                    fn = jax.jit(pl.make_pipeline_loss_and_grad(
                        sched_mesh, cfg, pcfg_s, stacked_s))
                    float(fn(stacked_s, sbatch)[0])  # compile off the clock
                    t0 = time.perf_counter()
                    for _ in range(n_steps):
                        last = float(fn(stacked_s, sbatch)[0])
                    dt = (time.perf_counter() - t0) / n_steps
                    if not np.isfinite(last):
                        raise ValueError(f"non-finite loss {last}")
                    detail = {
                        "schedule": sched_name, "pp": pp_s,
                        "virtual_stages": v_s, "microbatches": m_s,
                        "bubble_fraction_analytic":
                            round(pl.bubble_fraction(pcfg_s), 4)}
                    if pl.wgrad_queue_peak(pcfg_s):
                        detail["wgrad_queue_depth"] = pl.wgrad_queue_peak(pcfg_s)
                    if sched_name == "solver":
                        detail["sequence"] = seq_s.label
                    results[f"extra:sched-{sched_name},pp={pp_s}"] = {
                        "dt": dt, "tokens_per_step": m_s * seq,
                        "headline": False, "detail": detail}
                except Exception as e:
                    print(f"bench schedule row {sched_name} pp={pp_s} v={v_s} "
                          f"failed: {e!r}", file=sys.stderr, flush=True)

        # Cost-model auto-layout rows (BENCH_LAYOUT=0 skips): the generated
        # ladder's top rungs (tools/preflight.py layout_frontier — the
        # (pp, tp, dp, sp) frontier at the chips this process can see, the
        # same lane `--select --emit-ladder` walks), each measured over the
        # SAME global batch with the ANALYTIC step-time score emitted NEXT
        # to the measured step time — so one reachable-TPU run records the
        # whole model-vs-measured frontier in one pass (the standing
        # no-live-perf-number gap). Behind the same fail-fast probe as
        # everything else; on the CPU virtual mesh the absolute numbers are
        # meaningless but the rows prove the machinery end-to-end.
        if os.environ.get("BENCH_LAYOUT", "1") != "0" and row_budget.allow("layout"):
            try:
                sys.path.insert(0, os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "tools"))
                import preflight as _pf

                n_dev = jax.device_count()
                mb_l = 1
                m_l = int(os.environ.get("BENCH_SCHED_MICROBATCHES", "8"))
                g_l = mb_l * m_l * n_dev  # examples/step, rung-invariant
                # anchor the memory model on its own pp1 estimate (no
                # compile here — the budget only prunes absurd layouts; the
                # point of these rows is score-vs-measured, and
                # vocab_enabled=False keeps every rung on the as-written
                # loss head so the layout axis is the only variable)
                base_aw = _pf.layout_device_gib(cfg, 1, 1, 1)
                _, lrows = _pf.layout_frontier(
                    cfg, n_dev, mb_l, seq, g_l, base_aw, (1, 1, 1, 1),
                    float(os.environ.get("BENCH_LAYOUT_HBM_GB", "95")),
                    chip_flops=peak, vocab_enabled=False, solver_lane=False)
                top = [r for r in lrows if r["feasible"]][:3]
                if not top:
                    print("bench layout rows skipped: no feasible layout "
                          f"at {n_dev} device(s)", file=sys.stderr, flush=True)
                for r in top:
                    s = r["sched"]
                    try:
                        lay_mesh = make_mesh(MeshConfig(
                            pp=r["pp"], tp=r["tp"], dp=r["dp"], sp=r["sp"]))
                        man_l = StageManifest(
                            num_layers=cfg.num_hidden_layers,
                            num_stages=r["pp"],
                            layer_counts=(tuple(r["layer_counts"])
                                          if r["layer_counts"] else None),
                            virtual_stages=s["virtual_stages"])
                        stacked_l = pl.stack_stages(canonical, man_l)
                        pcfg_l = pl.PipelineConfig(
                            num_stages=r["pp"],
                            num_microbatches=r["microbatches"],
                            schedule=s["schedule"],
                            virtual_stages=s["virtual_stages"],
                            accum_chunks=s["accum_chunks"],
                            offload_wgrad=s["offload_wgrad"],
                            offload_activations=s["offload_activations"],
                            layer_counts=(None if man_l.is_even
                                          else man_l.stage_layer_counts))
                        fn = jax.jit(pl.make_pipeline_loss_and_grad(
                            lay_mesh, cfg, pcfg_l, stacked_l))
                        lbatch = make_batch(g_l)
                        float(fn(stacked_l, lbatch)[0])  # compile
                        t0 = time.perf_counter()
                        for _ in range(n_steps):
                            last = float(fn(stacked_l, lbatch)[0])
                        dt = (time.perf_counter() - t0) / n_steps
                        if not np.isfinite(last):
                            raise ValueError(f"non-finite loss {last}")
                        results[f"extra:layout-{r['layout']}"] = {
                            "dt": dt, "tokens_per_step": g_l * seq,
                            "headline": False, "detail": {
                                "layout": r["layout"],
                                "microbatches": r["microbatches"],
                                "layer_counts": r["layer_counts"],
                                "schedule": s["schedule"],
                                "virtual_stages": s["virtual_stages"],
                                "accum_chunks": s["accum_chunks"],
                                "bubble_fraction_analytic":
                                    r["bubble_fraction"],
                                "score_s_model": r["score_s"],
                                "est_peak_gib_model": r["est_peak_gib"]}}
                    except Exception as e:
                        print(f"bench layout row {r['layout']} failed: "
                              f"{e!r}", file=sys.stderr, flush=True)
            except Exception as e:
                print(f"bench layout rows failed: {e!r}", file=sys.stderr,
                      flush=True)

        # Host-stash offload rows (BENCH_OFFLOAD=0 skips): the measured
        # D2H/H2D host-link bandwidth (the number tools/preflight.py's
        # --host-bw-gibps feasibility assumption should be fed) and the
        # zb1 W-stash-offload step against its in-HBM twin, each row
        # carrying the MODELED transfer time and stash-hide ratio next to
        # the measured step time — one live TPU run = a model-vs-measured
        # offload point. Behind the same fail-fast probe as everything
        # else; on CPU the transfers are gated off (utils/host_stash.py),
        # so the rows exist but measure the restructured schedule only.
        if os.environ.get("BENCH_OFFLOAD", "1") != "0" and row_budget.allow("offload"):
            try:
                from llama_pipeline_parallel_tpu.utils import host_stash

                bw = host_stash.measure_transfer_bandwidth()
                probe_gib = bw["probe_mib"] / 1024
                results["extra:offload-bw"] = {
                    "dt": probe_gib / max(bw["d2h_gibps"], 1e-9),
                    "tokens_per_step": 0, "headline": False, "detail": bw}

                n_dev = jax.device_count()
                m_o = int(os.environ.get("BENCH_SCHED_MICROBATCHES", "8"))
                # largest ring (4 then 2) whose v=2 partition + microbatch
                # round-robin both divide — tiny's 4 layers land on pp=2
                pp_o = next((p for p in (4, 2)
                             if p <= n_dev and m_o % p == 0
                             and cfg.num_hidden_layers % (2 * p) == 0), 0)
                if pp_o:
                    off_mesh = make_mesh(MeshConfig(pp=pp_o))
                    man_o = StageManifest.for_config(cfg, pp_o,
                                                     virtual_stages=2)
                    stacked_o = pl.stack_stages(canonical, man_o)
                    obatch = make_batch(m_o)
                    dts = {}
                    for wgrad in (False, True):
                        pcfg_o = pl.PipelineConfig(
                            num_stages=pp_o, num_microbatches=m_o,
                            schedule="zb1", virtual_stages=2,
                            offload_wgrad=wgrad)
                        fn = jax.jit(pl.make_pipeline_loss_and_grad(
                            off_mesh, cfg, pcfg_o, stacked_o))
                        float(fn(stacked_o, obatch)[0])  # compile
                        t0 = time.perf_counter()
                        for _ in range(n_steps):
                            last = float(fn(stacked_o, obatch)[0])
                        dts[wgrad] = (time.perf_counter() - t0) / n_steps
                        if not np.isfinite(last):
                            raise ValueError(f"non-finite loss {last}")
                    pcfg_on = pl.PipelineConfig(
                        num_stages=pp_o, num_microbatches=m_o,
                        schedule="zb1", virtual_stages=2, offload_wgrad=True)
                    mb_o = obatch["input_ids"].shape[0] // m_o
                    stash = pl.wgrad_stash_bytes(pcfg_on, mb_o, seq,
                                                 cfg.hidden_size, 2)
                    # every residual pair moves D2H once + H2D once
                    transfer_s = 2 * stash / (
                        min(bw["d2h_gibps"], bw["h2d_gibps"]) * (1 << 30))
                    results[f"extra:offload-wgrad-stash,pp={pp_o}"] = {
                        "dt": dts[True], "tokens_per_step": m_o * seq,
                        "headline": False, "detail": {
                            "schedule": "zb1", "pp": pp_o,
                            "offload": "wgrad_stash",
                            "pinned_host": bw["pinned_host"],
                            "stash_mib": round(stash / (1 << 20), 1),
                            "in_hbm_step_ms": round(1000 * dts[False], 1),
                            "transfer_stall_ms":
                                round(1000 * (dts[True] - dts[False]), 1),
                            "transfer_ms_model": round(1000 * transfer_s, 2),
                            "stash_hide_ratio":
                                round(transfer_s / dts[False], 3)}}
            except Exception as e:
                print(f"bench offload rows failed: {e!r}", file=sys.stderr,
                      flush=True)

        # Memory observatory rows (BENCH_MEM=0 skips): the compiled
        # memory_analysis() peak (the byte model's measured counterpart —
        # utils/memwatch.py) next to the LIVE device peak after a real
        # step, plus a page-pool fragmentation point. The mem-peak pair is
        # what perf_report distills into the `mem_scale` calibration
        # constant preflight --select re-ranks with; on CPU the live half
        # is host RSS-ish and the row is tagged with its backend so
        # derive_calibration excludes it (cpu rows never calibrate).
        if os.environ.get("BENCH_MEM", "1") != "0" and row_budget.allow("mem"):
            try:
                from llama_pipeline_parallel_tpu.utils import memwatch

                n_dev = jax.device_count()
                m_m = int(os.environ.get("BENCH_SCHED_MICROBATCHES", "8"))
                pp_m = next((p for p in (4, 2, 1)
                             if p <= n_dev and m_m % p == 0
                             and cfg.num_hidden_layers % p == 0), 1)
                mem_mesh = make_mesh(MeshConfig(pp=pp_m))
                man_m = StageManifest.for_config(cfg, pp_m)
                stacked_m = pl.stack_stages(canonical, man_m)
                mbatch = make_batch(m_m)
                pcfg_m = pl.PipelineConfig(num_stages=pp_m,
                                           num_microbatches=m_m)
                fn = jax.jit(pl.make_pipeline_loss_and_grad(
                    mem_mesh, cfg, pcfg_m, stacked_m))
                info = memwatch.compiled_memory(
                    fn.lower(stacked_m, mbatch).compile(), top_buffers=4,
                    label="bench_step")
                t0 = time.perf_counter()
                last = float(fn(stacked_m, mbatch)[0])
                dt_m = time.perf_counter() - t0
                if not np.isfinite(last):
                    raise ValueError(f"non-finite loss {last}")
                live = memwatch.live_sample()
                live_peak = live.get("device_peak_bytes")
                gib = 1 << 30
                results["extra:mem-peak"] = {
                    "dt": dt_m, "tokens_per_step": m_m * seq,
                    "headline": False, "detail": {
                        "backend": jax.devices()[0].platform,
                        "pp": pp_m,
                        "compiled_peak_gib":
                            round(info["peak_bytes"] / gib, 3)
                            if info else None,
                        "temp_gib": round(info["temp_bytes"] / gib, 3)
                        if info else None,
                        "live_peak_gib": round(live_peak / gib, 3)
                        if live_peak else None,
                        "live_source": "device" if live_peak else "none",
                        "top_buffers": (info or {}).get("top_buffers",
                                                        [])[:4]}}

                # page-pool fragmentation point: reserve worst-case demand,
                # back only the prompt — the reserved-vs-allocated gap the
                # serving gauges publish per tick, measured here once
                from llama_pipeline_parallel_tpu.serve import pages as pages_mod

                kvp = pages_mod.PagedKVCache(cfg, max_slots=4, max_len=64,
                                             page_size=16, num_pages=32)
                demand = kvp.demand_pages(32, 16)
                kvp.reserve(demand)
                slot = kvp.acquire("bench-mem", demand)
                kvp.ensure_capacity(slot, 32)
                g = kvp.fragmentation_gauges()
                results["extra:mem-pagepool"] = {
                    "dt": 0.0, "tokens_per_step": 0, "headline": False,
                    "detail": {
                        "backend": jax.devices()[0].platform,
                        "pool_gib": round(pages_mod.paged_pool_bytes(
                            cfg, 32, 16) / gib, 4),
                        "reserved_gap_gib":
                            round(g["reserved_gap_bytes"] / gib, 6),
                        **{k: g[k] for k in ("pages_free", "pages_used",
                                             "pages_reserved",
                                             "reserved_unbacked",
                                             "fragmentation")}}}
            except Exception as e:
                print(f"bench memory rows failed: {e!r}", file=sys.stderr,
                      flush=True)

        # Pallas kernel rows (BENCH_KERNELS=0 skips): the fused CE head and
        # the fused rms_norm->RoPE->QKV prologue (`kernels.*`,
        # docs/KERNELS.md) against their XLA twins at the same shape, each
        # row carrying the MODELED bytes the kernel keeps in VMEM next to
        # the measured step-time delta and the implied bandwidth — so the
        # win is measured, not asserted (on CPU the kernels run in
        # interpret mode: the rows exist, the delta is meaningless and the
        # twin comparison is the parity smoke). Behind the same fail-fast
        # probe as everything else.
        if os.environ.get("BENCH_KERNELS", "1") != "0" and row_budget.allow("kernel"):
            try:
                from llama_pipeline_parallel_tpu.ops.pallas_ce import (
                    ce_head_traffic_bytes,
                )
                from llama_pipeline_parallel_tpu.ops.pallas_prologue import (
                    prologue_traffic_bytes,
                )

                gib = 1 << 30
                tokens = bs_big * seq
                # the kernel's own VMEM sizing (lane-exact 128-wide vocab
                # tiles — the XLA-scale 8 would blow VMEM on a real TPU and
                # the row would silently vanish from the one environment
                # that matters); twin measured at the SAME chunking
                ce_chunks = (cfg.vocab_size // 128
                             if cfg.vocab_size % 128 == 0 else 0)

                def kernel_row(name, dt_kernel, twin, bytes_model):
                    detail = {
                        "bytes_model_gib": round(bytes_model / gib, 3),
                        "interpret": jax.default_backend() != "tpu"}
                    if twin is not None:
                        delta = twin["dt"] - dt_kernel
                        detail["xla_step_ms"] = round(1000 * twin["dt"], 1)
                        detail["saved_ms"] = round(1000 * delta, 1)
                        if delta > 0:
                            # the bandwidth the deleted traffic effectively
                            # ran at — compare against the chip's HBM spec
                            detail["achieved_gibps"] = round(
                                bytes_model / gib / delta, 1)
                    results[name] = {"dt": dt_kernel,
                                     "tokens_per_step": tokens,
                                     "headline": False, "detail": detail}

                dt = (measure(False, "exact", bs_big, loss_chunks=ce_chunks,
                              kernel_ce=True) if ce_chunks else None)
                if not ce_chunks:
                    print(f"bench kernel-ce row skipped: vocab "
                          f"{cfg.vocab_size} has no 128-wide tiling",
                          file=sys.stderr, flush=True)
                if dt is not None:
                    twin = results.get(
                        f"remat=0,attn=exact,bs={bs_big},ce=chunk{ce_chunks}")
                    if twin is None:
                        twin_dt = measure(False, "exact", bs_big,
                                          loss_chunks=ce_chunks)
                        twin = ({"dt": twin_dt} if twin_dt is not None
                                else None)
                    kernel_row(f"extra:kernel-ce,bs={bs_big}", dt, twin,
                               ce_head_traffic_bytes(
                                   tokens, cfg.hidden_size, cfg.vocab_size,
                                   ce_chunks))

                dt = measure(False, "exact", bs_big, kernel_prologue=True)
                if dt is not None:
                    twin = results.get(f"remat=0,attn=exact,bs={bs_big}")
                    per_layer = prologue_traffic_bytes(
                        tokens, cfg.hidden_size,
                        cfg.num_attention_heads * cfg.head_dim,
                        cfg.kv_heads * cfg.head_dim,
                        jnp.dtype(cfg.dtype).itemsize)
                    kernel_row(f"extra:kernel-prologue,bs={bs_big}", dt, twin,
                               cfg.num_hidden_layers * per_layer)
            except Exception as e:
                print(f"bench kernel rows failed: {e!r}", file=sys.stderr,
                      flush=True)

        # Serving microbench (BENCH_SERVING=0 skips): prefill TTFT + steady-
        # state per-token decode latency at fixed batch through the REAL
        # continuous-batching engine (serve/engine.py), i.e. the numbers
        # docs/SERVING.md's SLOs are made of. Same fail-fast posture as the
        # other extras: a failure here reports, never wedges the headline
        # (the up-front device probe already ran).
        if os.environ.get("BENCH_SERVING", "1") != "0" and row_budget.allow("serve"):
            try:
                from llama_pipeline_parallel_tpu.models.llama.decode import (
                    GenerationConfig,
                )
                from llama_pipeline_parallel_tpu.serve import (
                    ServeConfig,
                    ServeEngine,
                    ServeRequest,
                )

                slots = int(os.environ.get("BENCH_SERVE_SLOTS", "8"))
                p_len = min(128, seq)
                decode_steps = int(os.environ.get("BENCH_SERVE_STEPS", "32"))
                budget = decode_steps + 8  # no row finishes mid-timing
                eng = ServeEngine(
                    pl.unstack_stages(stacked, manifest), cfg,
                    ServeConfig(max_slots=slots, max_len=p_len + budget + 1,
                                prompt_buckets=(p_len,),
                                max_queue=4 * slots))
                rs = np.random.RandomState(0)
                prompt = rs.randint(3, cfg.vocab_size, (p_len,)).tolist()

                def req(n):
                    return ServeRequest(input_ids=prompt,
                                        gen=GenerationConfig(max_new_tokens=n))

                # warmup: compile prefill + decode_step off the clock
                eng.submit(req(2))
                eng.drain(timeout_s=600)
                # TTFT: one cold request against a warm engine
                eng.submit(req(2))
                eng.drain(timeout_s=600)
                ttft = eng.stats.ttft[-1]
                results[f"extra:serve-ttft,p={p_len}"] = {
                    "dt": ttft, "tokens_per_step": p_len, "headline": False,
                    "detail": {"ttft_ms": round(1000 * ttft, 2)}}
                # steady-state decode: all slots occupied, timed ticks
                for _ in range(slots):
                    eng.submit(req(budget))
                eng.step()  # admissions + first tick
                t0 = time.perf_counter()
                for _ in range(decode_steps):
                    eng.step()
                dt = (time.perf_counter() - t0) / decode_steps
                results[f"extra:serve-decode,bs={slots}"] = {
                    "dt": dt, "tokens_per_step": slots, "headline": False,
                    "detail": {"per_token_ms": round(1000 * dt / slots, 3),
                               "step_ms": round(1000 * dt, 2),
                               "slots": slots}}
                eng.shutdown()
            except Exception as e:
                print(f"bench serving rows failed: {e!r}", file=sys.stderr,
                      flush=True)

            # Paged-KV rows (docs/SERVING.md "Paged KV cache"): the SAME
            # steady-state decode measurement through the paged engine (fp
            # and int8 pages), each row carrying the pool-vs-dense resident
            # byte model NEXT to the measured per-token time — the dense
            # `extra:serve-decode` row above is the twin, so one live TPU
            # run lands the paged-gather cost and the int8 capacity
            # doubling as measured deltas. Separate try: a paged failure
            # must not eat the dense rows already recorded.
            try:
                from llama_pipeline_parallel_tpu.serve.pages import (
                    dense_kv_cache_bytes,
                    paged_pool_bytes,
                )

                slots = int(os.environ.get("BENCH_SERVE_SLOTS", "8"))
                decode_steps = int(os.environ.get("BENCH_SERVE_STEPS", "32"))
                budget = decode_steps + 8
                page = 16
                # bucket rounded DOWN to a page multiple (paged buckets
                # must be page-aligned; a seq that isn't must not silently
                # drop these rows)
                p_len = max(page, min(128, seq) // page * page)
                max_len_p = -(-(p_len + budget + 1) // page) * page
                dense_twin = results.get(f"extra:serve-decode,bs={slots}")
                dense_mib = dense_kv_cache_bytes(cfg, slots,
                                                 max_len_p) / (1 << 20)
                rs = np.random.RandomState(0)
                prompt = rs.randint(3, cfg.vocab_size, (p_len,)).tolist()
                for quant in ("fp", "int8"):
                    scfg = ServeConfig(
                        max_slots=slots, max_len=max_len_p,
                        prompt_buckets=(p_len,), max_queue=4 * slots,
                        kv_cache="paged", page_size=page, kv_quant=quant)
                    eng = ServeEngine(pl.unstack_stages(stacked, manifest),
                                      cfg, scfg)
                    for _ in range(slots):
                        eng.submit(ServeRequest(
                            input_ids=prompt,
                            gen=GenerationConfig(max_new_tokens=budget)))
                    eng.step()  # admissions + first tick (compiles)
                    t0 = time.perf_counter()
                    for _ in range(decode_steps):
                        eng.step()
                    dt = (time.perf_counter() - t0) / decode_steps
                    detail = {
                        "per_token_ms": round(1000 * dt / slots, 3),
                        "step_ms": round(1000 * dt, 2), "slots": slots,
                        "page_size": page,
                        "pages_used": eng.slots.pages_used,
                        "pages_total": eng.slots.num_pages,
                        "pool_mib": round(paged_pool_bytes(
                            cfg, scfg.resolved_num_pages, page,
                            quant) / (1 << 20), 2),
                        "dense_cache_mib": round(dense_mib, 2),
                        "kv_quant": quant}
                    if dense_twin is not None:
                        detail["dense_step_ms"] = round(
                            1000 * dense_twin["dt"], 2)
                    tag = "-int8" if quant == "int8" else ""
                    results[f"extra:serve-paged{tag}-decode,bs={slots}"] = {
                        "dt": dt, "tokens_per_step": slots,
                        "headline": False, "detail": detail}
                    eng.shutdown()
            except Exception as e:
                print(f"bench paged serving rows failed: {e!r}",
                      file=sys.stderr, flush=True)

            # Chunked-prefill row: the synthetic traffic generator
            # (tools/serve_traffic.py — Poisson arrivals, prompt/output
            # length mixes) replayed against a paged engine with a bounded
            # per-tick prefill budget; the row's metadata records the mix
            # that generated the load, and the SLO percentiles are what
            # interleaved admissions cost in-flight decodes.
            try:
                sys.path.insert(0, os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "tools"))
                import serve_traffic as _tr

                p_small = max(16, min(64, seq) // 16 * 16)  # page-aligned
                chunk = p_small
                max_len_t = 4 * p_small
                prompt_mix = _tr.parse_mix(f"{p_small}:0.6,{2 * p_small}:0.4")
                output_mix = _tr.parse_mix("8:0.5,16:0.5")
                rate = float(os.environ.get("BENCH_TRAFFIC_RATE", "16"))
                n_req = int(os.environ.get("BENCH_TRAFFIC_REQUESTS", "12"))
                eng = ServeEngine(
                    pl.unstack_stages(stacked, manifest), cfg,
                    ServeConfig(
                        max_slots=4, max_len=max_len_t,
                        prompt_buckets=(p_small, 2 * p_small),
                        max_queue=4 * n_req, kv_cache="paged",
                        page_size=16, prefill_chunk_tokens=chunk))
                trace_reqs = _tr.poisson_trace(0, rate, n_req, prompt_mix,
                                               output_mix)
                summary = _tr.run_trace(eng, trace_reqs)
                eng.shutdown()
                results["extra:serve-prefill-chunked"] = {
                    "dt": summary["wall_s"],
                    "tokens_per_step": summary.get("tokens_generated", 0),
                    "headline": False, "detail": {
                        "mix": {"prompt": _tr.mix_label(prompt_mix),
                                "output": _tr.mix_label(output_mix),
                                "rate_rps": rate, "seed": 0,
                                "requests": n_req},
                        "chunk_tokens": chunk, **{
                            k: summary[k] for k in (
                                "requests_completed", "refused_pages",
                                "refused_overload", "tokens_per_sec",
                                "prefill_chunks_total",
                                "prefill_tokens_total")
                            if k in summary},
                        **{k: summary[k] for k in summary
                           if k.startswith(("ttft_", "tpot_"))}}}
            except Exception as e:
                print(f"bench prefill traffic row failed: {e!r}",
                      file=sys.stderr, flush=True)

            # Prefix-cache rows (docs/SERVING.md "Prefix caching"): the
            # SAME 90%-shared-prefix mix replayed twice — cache on
            # (`extra:serve-prefix-hot`) vs off (`-cold`) — so one run
            # lands the cache-hit TTFT win as a measured delta, plus the
            # capacity story: how many same-prefix requests a FIXED page
            # pool admits (queued, never stepped, until 429) under page
            # sharing vs without it. Separate try per the extras posture.
            try:
                from llama_pipeline_parallel_tpu.serve import ServeOverloaded

                page = 16
                tail = page
                bucket = max(2 * page, min(64, seq) // page * page)
                pre_len = bucket - tail
                prefix_mix = _tr.parse_prefix_mix(
                    f"sys{pre_len}:0.9,cold:0.1")
                prompt_mix_p = _tr.parse_mix(f"{tail}:1.0")
                output_mix_p = _tr.parse_mix("8:1.0")
                rate = float(os.environ.get("BENCH_TRAFFIC_RATE", "16"))
                n_req = int(os.environ.get("BENCH_TRAFFIC_REQUESTS", "12"))
                pool_pages = 4 * bucket // page  # fixed, deliberately tight
                shared = _tr.prefix_ids(f"sys{pre_len}", pre_len,
                                        cfg.vocab_size)

                def prefix_req(sd):
                    tail_ids = np.random.RandomState(sd).randint(
                        3, cfg.vocab_size, size=tail).tolist()
                    return ServeRequest(
                        input_ids=shared + tail_ids,
                        gen=GenerationConfig(max_new_tokens=8), seed=sd)

                for label, cache_on in (("hot", True), ("cold", False)):
                    eng = ServeEngine(
                        pl.unstack_stages(stacked, manifest), cfg,
                        ServeConfig(max_slots=4, max_len=bucket + page,
                                    prompt_buckets=(tail, bucket),
                                    max_queue=4 * n_req, kv_cache="paged",
                                    page_size=page, prefix_cache=cache_on))
                    # pay every compile off the clock (full prefill at
                    # both buckets, and — hot — the warm span path), and
                    # leave the shared chain registered so the trace's
                    # first hot request is already a hit; without this the
                    # hot row measures XLA compiles, not the cache
                    for wr in (prefix_req(0), prefix_req(10_000),
                               ServeRequest(
                                   input_ids=list(range(3, 3 + tail)),
                                   gen=GenerationConfig(max_new_tokens=8),
                                   seed=0)):
                        eng.submit(wr)
                        eng.drain(timeout_s=600)
                    trace_reqs = _tr.poisson_trace(
                        0, rate, n_req, prompt_mix_p, output_mix_p,
                        prefix_mix=prefix_mix)
                    s = _tr.run_trace(eng, trace_reqs)
                    eng.shutdown()
                    # admissions at a fixed pool: warm the cache with one
                    # drained request, then queue same-prefix requests
                    # without stepping until the pool refuses
                    eng = ServeEngine(
                        pl.unstack_stages(stacked, manifest), cfg,
                        ServeConfig(max_slots=4, max_len=bucket + page,
                                    prompt_buckets=(bucket,),
                                    max_queue=16 * pool_pages,
                                    kv_cache="paged", page_size=page,
                                    num_pages=pool_pages,
                                    prefix_cache=cache_on))
                    eng.submit(prefix_req(1))
                    eng.drain(timeout_s=600)
                    admitted = 0
                    try:
                        for sd in range(2, 2 + 16 * pool_pages):
                            eng.submit(prefix_req(sd))
                            admitted += 1
                    except ServeOverloaded:
                        pass
                    eng.shutdown()
                    ttft_p50 = s.get("ttft_p50_ms")
                    results[f"extra:serve-prefix-{label}"] = {
                        "dt": (ttft_p50 or 0) / 1000.0,
                        "tokens_per_step": s.get("tokens_generated", 0),
                        "headline": False, "detail": {
                            "mix": {"prompt": _tr.mix_label(prompt_mix_p),
                                    "output": _tr.mix_label(output_mix_p),
                                    "prefix": _tr.prefix_mix_label(
                                        prefix_mix),
                                    "rate_rps": rate, "seed": 0,
                                    "requests": n_req},
                            "prefix_cache": cache_on,
                            "admitted_at_fixed_pool": admitted,
                            "pool_pages": pool_pages, "page_size": page,
                            **{k: s[k] for k in (
                                "requests_completed", "refused_pages",
                                "prefix_hits", "prefix_misses",
                                "prefix_hit_rate", "prefix_cached_tokens",
                                "prefix_cow_forks") if k in s},
                            **{k: s[k] for k in s
                               if k.startswith(("ttft_", "tpot_"))}}}
            except Exception as e:
                print(f"bench prefix cache rows failed: {e!r}",
                      file=sys.stderr, flush=True)

    summary = report()
    watchdog.cancel()
    if summary is None:
        print(json.dumps({
            "metric": "tokens_per_sec_per_chip", "value": 0.0,
            "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "error": "every bench configuration failed"}), flush=True)
        _write_ledger(cli.perf_ledger, None, row_budget.skipped,
                      error="every bench configuration failed")
        sys.exit(1)
    print(json.dumps(summary), flush=True)
    _write_ledger(cli.perf_ledger, summary, row_budget.skipped)

    # BENCH_PROFILE=<dir>: afterwards (the result JSON is already out, so a
    # profiling failure or wedge can no longer cost the measurement), capture
    # a profiler trace of the winning config's steady state — the per-op
    # breakdown for the MFU hunt (SURVEY.md §5.1).
    profile_dir = os.environ.get("BENCH_PROFILE")
    if profile_dir:
        import threading

        threading.Timer(600, lambda: os._exit(0)).start()  # wedge guard
        best = summary["best_config"]
        try:
            ok = measure(*configs[best], trace_dir=profile_dir)
            print(f"profiler trace for {best} "
                  f"{'written to ' + profile_dir if ok is not None else 'FAILED'}",
                  file=sys.stderr, flush=True)
        except Exception as e:
            print(f"profiling failed: {e!r}", file=sys.stderr, flush=True)
        os._exit(0)  # the timer thread is non-daemon by design; don't join it


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Single-chip training-throughput benchmark.

Runs the real train-step path (pipeline machinery at PP=1, remat, bf16
compute, fp32 AdamW with ZeRO-1 layout) on a ~550M-param LLaMA-shaped model at
the reference workload shape (seq 512; reference conf yaml:32) and prints ONE
JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no throughput numbers (BASELINE.md), so vs_baseline
is measured MFU / 0.45 — the 45%-MFU north-star from BASELINE.json.
"""

from __future__ import annotations

import json
import os
import time


def _watchdog(seconds: int):
    """The TPU tunnel can wedge indefinitely (even trivial ops hang); emit a
    diagnostic JSON line instead of hanging the harness forever. Returns the
    timer; the caller cancels it the moment timing completes, BEFORE printing,
    so exactly one JSON line is ever emitted.

    A timer THREAD, not SIGALRM: the wedge sits in a blocking C call on the
    main thread, so a Python signal handler would never run — a thread still
    gets scheduled whenever the call releases the GIL."""
    import threading

    def fire():
        print(json.dumps({
            "metric": "tokens_per_sec_per_chip", "value": 0.0,
            "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "error": f"bench watchdog fired after {seconds}s (TPU unreachable?)",
        }), flush=True)
        os._exit(2)

    timer = threading.Timer(seconds, fire)
    timer.daemon = True
    timer.start()
    return timer


def main() -> None:
    watchdog = _watchdog(int(os.environ.get("BENCH_TIMEOUT_S", "900")))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from __graft_entry__ import _bench_config
    from llama_pipeline_parallel_tpu.models.llama import model as llama
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel import train_step as ts
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh
    from llama_pipeline_parallel_tpu.utils.metrics import (
        detect_chip_peak_flops,
        train_flops_per_token,
    )

    cfg = _bench_config()
    batch_size, seq = 8, 512

    mesh = make_mesh(MeshConfig())  # single chip
    manifest = StageManifest.for_config(cfg, 1)
    stacked = pl.stack_stages(llama.init_params(jax.random.PRNGKey(0), cfg), manifest)
    pcfg = pl.PipelineConfig(num_stages=1, num_microbatches=1, remat=True)
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-4, total_steps=1000,
                                               warmup_steps=10))
    state = ts.init_train_state(stacked, tx, mesh)
    step = ts.make_train_step(mesh, cfg, pcfg, tx, sched, stacked)

    ids = np.random.RandomState(0).randint(3, cfg.vocab_size,
                                           (batch_size, seq)).astype(np.int32)
    batch = {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.ones((batch_size, seq), jnp.int32),
        "position_ids": jnp.broadcast_to(jnp.arange(seq, dtype=jnp.int32),
                                         (batch_size, seq)),
        "labels": jnp.asarray(ids),
    }

    # warmup (compile) + steady-state timing. The loss VALUE is fetched every
    # step: on the axon remote platform block_until_ready alone does not wait
    # for the donated-state dependency chain, so value-fetch is the only
    # reliable execution barrier (cost: one scalar D2H per step).
    state, metrics = step(state, batch)
    float(metrics["loss"])
    n_steps = 10
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, batch)
        float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch_size * seq
    tps = tokens_per_step * n_steps / dt
    peak = detect_chip_peak_flops() or 197e12
    mfu = train_flops_per_token(cfg, seq) * tps / peak
    watchdog.cancel()
    print(json.dumps({
        "metric": "tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "step_time_ms": round(1000 * dt / n_steps, 1),
        "model": "llama-550m seq512 bs8 bf16 remat",
    }))


if __name__ == "__main__":
    main()

"""Data layer: collator label masking, datasets, dp-sharded sampling, repeat."""

import json

import numpy as np
import pytest

from llama_pipeline_parallel_tpu.data.collator import (
    IGNORE_INDEX,
    CausalLMCollator,
    PretokenizedCollator,
    get_lm_labels,
)
from llama_pipeline_parallel_tpu.data.datasets import (
    ConcatDataset,
    JsonSeq2SeqDataset,
    LazyJsonlDataset,
    MixtureDataset,
    SyntheticDataset,
)
from llama_pipeline_parallel_tpu.data.loader import DataLoader, RepeatingLoader, ShardedSampler
from llama_pipeline_parallel_tpu.data.tokenization import expand_special_tokenizer


class FakeTokenizer:
    """Whitespace tokenizer with an HF-ish callable interface."""

    eos_token = "</s>"
    pad_token = "</s>"

    def _encode(self, text):
        return [hash(w) % 1000 + 10 for w in text.split()]

    def __call__(self, texts, max_length, truncation, padding=None, return_tensors=None,
                 return_length=False):
        ids = [self._encode(t)[:max_length] for t in texts]
        if padding == "max_length":
            mask = [[1] * len(x) + [0] * (max_length - len(x)) for x in ids]
            ids = [x + [0] * (max_length - len(x)) for x in ids]
            out = {"input_ids": np.asarray(ids), "attention_mask": np.asarray(mask)}
            return out
        return {"input_ids": ids}


def test_get_lm_labels_masks_prompt_and_padding():
    ids = np.arange(1, 9).reshape(1, 8)
    mask = np.array([[1, 1, 1, 1, 1, 1, 0, 0]])
    labels = get_lm_labels(ids, mask, prompt_lens=np.array([3]))
    np.testing.assert_array_equal(
        labels[0], [IGNORE_INDEX] * 3 + [4, 5, 6] + [IGNORE_INDEX] * 2)


def test_causal_lm_collator_protocol():
    coll = CausalLMCollator(FakeTokenizer(), max_seq_length=16)
    batch = coll([{"inputs": "the quick brown", "targets": "fox jumps"},
                  {"inputs": "hello", "targets": "world"}])
    assert set(batch) == {"input_ids", "attention_mask", "position_ids", "labels"}
    for v in batch.values():
        assert v.shape == (2, 16)  # labels same length as inputs — no index column
    # prompt region masked
    assert (batch["labels"][0, :3] == IGNORE_INDEX).all()
    assert (batch["labels"][0, 3:5] != IGNORE_INDEX).all()
    # padding masked
    assert (batch["labels"][batch["attention_mask"] == 0] == IGNORE_INDEX).all()


def test_json_dataset_and_concat(tmp_path):
    p1 = tmp_path / "a.jsonl"
    with open(p1, "w") as f:
        f.write(json.dumps({"inputs": "i1", "targets": "t1"}) + "\n")
        f.write(json.dumps({"inputs": "i2", "targets": ""}) + "\n")  # filtered
    p2 = tmp_path / "b.json"
    with open(p2, "w") as f:
        json.dump([{"inputs": "i3", "targets": "t3"}], f)
    d1, d2 = JsonSeq2SeqDataset(str(p1)), JsonSeq2SeqDataset(str(p2))
    assert len(d1) == 1 and len(d2) == 1
    cat = ConcatDataset([d1, d2])
    assert len(cat) == 2 and cat[1]["inputs"] == "i3"
    with pytest.raises(IndexError):
        cat[2]


def test_lazy_jsonl_matches_eager(tmp_path):
    """LazyJsonlDataset is an access-for-access drop-in for the eager
    JsonSeq2SeqDataset: same filtering, same records, any access order,
    concurrent reads from multiple threads."""
    p = tmp_path / "corpus.jsonl"
    with open(p, "w") as f:
        for i in range(20):
            f.write(json.dumps({"inputs": f"in {i}",
                                "targets": "" if i % 5 == 0 else f"out {i}"}) + "\n")
        f.write("\n")  # blank line tolerated
    eager = JsonSeq2SeqDataset(str(p))
    lazy = LazyJsonlDataset(str(p))
    assert len(lazy) == len(eager) == 16
    for idx in [15, 0, 7, 7, 3]:  # arbitrary order, repeats
        assert lazy[idx] == eager[idx]

    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(4) as ex:
        rows = list(ex.map(lambda i: lazy[i], range(16)))
    assert rows == [eager[i] for i in range(16)]

    # custom field names: both datasets must filter on the SAME field
    q = tmp_path / "fields.jsonl"
    with open(q, "w") as f:
        f.write(json.dumps({"q": "a", "r": "keep"}) + "\n")
        f.write(json.dumps({"q": "b", "r": ""}) + "\n")
    for cls in (JsonSeq2SeqDataset, LazyJsonlDataset):
        d = cls(str(q), input_field="q", target_field="r")
        assert len(d) == 1 and d[0] == {"inputs": "a", "targets": "keep"}


def test_sharded_sampler_partition_and_epochs():
    samplers = [ShardedSampler(103, 4, rank=r, seed=1) for r in range(4)]
    all_idx = np.concatenate([s.indices() for s in samplers])
    assert len(all_idx) == 4 * (103 // 4)
    assert len(np.unique(all_idx)) == len(all_idx)  # disjoint shards
    e0 = samplers[0].indices().copy()
    for s in samplers:
        s.set_epoch(1)
    assert not np.array_equal(e0, samplers[0].indices())  # reshuffles
    samplers[0].set_epoch(0)
    np.testing.assert_array_equal(e0, samplers[0].indices())  # deterministic


def test_dataloader_global_layout_and_repeat():
    ds = SyntheticDataset(vocab_size=50, seq_length=8, pseudo_dataset_len=12, seed=3)
    dl = DataLoader(ds, PretokenizedCollator(), per_replica_batch=2, dp_size=2,
                    shuffle=False)
    assert len(dl) == 3  # 12 / 2 replicas / 2 per batch
    batches = list(dl)
    assert batches[0]["input_ids"].shape == (4, 8)
    # dp replica 0 rows come first, replica 1 rows second
    s0 = [ds[i]["input_ids"] for i in ShardedSampler(12, 2, 0, shuffle=False).indices()[:2]]
    np.testing.assert_array_equal(batches[0]["input_ids"][:2], np.stack(s0))

    rl = iter(RepeatingLoader(dl))
    seen = [next(rl) for _ in range(7)]  # crosses two epoch boundaries
    assert seen[3]["input_ids"].shape == (4, 8)


def test_mixture_dataset():
    a = [{"src": "a", "i": i} for i in range(30)]
    b = [{"src": "b", "i": i} for i in range(10)]
    mix = MixtureDataset([a, b], weights=[3.0, 1.0])
    items = [mix[i] for i in range(len(mix))]
    counts = {"a": sum(x["src"] == "a" for x in items),
              "b": sum(x["src"] == "b" for x in items)}
    assert counts["a"] == 3 * counts["b"]
    # every item from each source appears at most once and in order
    a_items = [x["i"] for x in items if x["src"] == "a"]
    assert a_items == sorted(set(a_items))
    assert mix[0] == mix[0]  # deterministic
    with pytest.raises(IndexError):
        mix[len(mix)]
    with pytest.raises(ValueError):
        MixtureDataset([a, b], weights=[1.0])


def test_prefetch_iterator():
    from llama_pipeline_parallel_tpu.data.loader import PrefetchIterator

    items = list(PrefetchIterator(iter(range(7)), depth=2))
    assert items == list(range(7))

    def boom():
        yield 1
        raise RuntimeError("producer failed")

    it = PrefetchIterator(boom())
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="producer failed"):
        next(it)


def test_synthetic_dataset_deterministic():
    ds = SyntheticDataset(vocab_size=100, seq_length=16, pseudo_dataset_len=4,
                          pad_fraction=0.25)
    a, b = ds[2], ds[2]
    np.testing.assert_array_equal(a["input_ids"], b["input_ids"])
    assert (a["attention_mask"][-4:] == 0).all()
    assert (a["labels"][-4:] == IGNORE_INDEX).all()
    with pytest.raises(IndexError):
        ds[4]


def test_expand_special_tokenizer_fills_missing_only():
    class Tok:
        bos_token = "<CUSTOM_BOS>"
        eos_token = None
        unk_token = "<unk>"
        pad_token = None

        def __init__(self):
            self.added = {}

        def add_special_tokens(self, d):
            self.added.update(d)
            for k, v in d.items():
                setattr(self, k, v)
            return len(d)

    t = Tok()
    n = expand_special_tokenizer(t)
    assert n == 1 and t.eos_token == "</s>"
    assert t.bos_token == "<CUSTOM_BOS>"  # untouched
    assert t.pad_token == "</s>"  # pad -> eos fallback


def test_expand_special_tokenizer_rejects_seq2seq():
    """The seq2seq branch is a recorded strike (docs/PARITY.md): an
    encoder-decoder tokenizer must fail loudly at normalization, not train
    a causal LM on encoder-only text."""

    class T5TokenizerFast:
        bos_token = eos_token = unk_token = pad_token = "<x>"

    with pytest.raises(ValueError, match="recorded strike"):
        expand_special_tokenizer(T5TokenizerFast())

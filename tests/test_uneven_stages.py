"""Uneven / cost-balanced stage partitions (SURVEY.md §7.3 item 2; the
reference's LayerSpec lists admit uneven cuts, models/llama_ds_mp_wrap.py:209).

The stacked runtime layout pads stages to max_layers_per_stage with all-zero
layers (exact identities with zero gradients); these tests pin that the
padding is invisible to the math — grads match single-device — and that the
checkpoint layout stays canonical across partition changes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

from tests.test_pipeline import (
    assert_tree_close,
    make_batch,
    reference_loss_and_grad,
)


def run_uneven(params, batch, cfg, pp, counts, microbatches=4, schedule="1f1b",
               dp=1, tp=1, unit_schedule=None):
    mesh = make_mesh(MeshConfig(pp=pp, dp=dp, tp=tp))
    manifest = StageManifest(num_layers=cfg.num_hidden_layers, num_stages=pp,
                             layer_counts=tuple(counts))
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=pp, num_microbatches=microbatches,
                             schedule=schedule,
                             layer_counts=manifest.stage_layer_counts,
                             unit_schedule=unit_schedule)
    fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked))
    loss, grads = fn(stacked, batch)
    return loss, pl.unstack_stages(grads, manifest), manifest


def assert_trees_bit_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
def test_13_layers_on_4_stages_matches_single_device(devices):
    """The VERDICT acceptance case: 13 layers, 4 stages, grad parity."""
    cfg = LlamaConfig.tiny(num_hidden_layers=13)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    batch = make_batch(cfg)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads, _ = run_uneven(params, batch, cfg, pp=4, counts=(4, 4, 4, 1))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    assert_tree_close(grads, ref_grads)


# ---------------------------------------------------------------------------
# Unequal stages through the unit-sequence interpreter (zb1 / solver):
# "unequal stages just change the unit sequence" — the split backward and a
# loaded sequence replay the SAME padded chunk function, so losses AND
# grads are bit-exact vs the flat uneven path (which already matches the
# single-device reference above). Grid extended, not forked.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def uneven_flat_ref(devices):
    """One flat-1f1b uneven run at the shared (pp2, (2,1), m=2) shape —
    the parity anchor both interpreter reps compare against (one compile,
    not one per test: tier-1 budget)."""
    cfg = LlamaConfig.tiny(num_hidden_layers=3)
    params = llama.init_params(jax.random.PRNGKey(5), cfg)
    batch = make_batch(cfg, batch_size=4)
    l_flat, g_flat, _ = run_uneven(params, batch, cfg, pp=2, counts=(2, 1),
                                   microbatches=2)
    return cfg, params, batch, l_flat, g_flat


def test_zb1_uneven_bit_exact_vs_flat_uneven(uneven_flat_ref):
    """The fast rep of the unequal-stage interpreter gate: zb1's B/W split
    on a (2,1) partition folds the identical gradients in the identical
    order as flat 1f1b — bit-exact, not allclose."""
    cfg, params, batch, l_flat, g_flat = uneven_flat_ref
    l_zb, g_zb, _ = run_uneven(params, batch, cfg, pp=2, counts=(2, 1),
                               microbatches=2, schedule="zb1")
    np.testing.assert_array_equal(np.asarray(l_flat), np.asarray(l_zb))
    assert_trees_bit_equal(g_flat, g_zb)


@pytest.mark.slow
def test_solver_uneven_sequence_roundtrips_and_replays_bit_exact(
        uneven_flat_ref, tmp_path):
    """A canonical zb1 sequence generated WITH stage costs, serialized to
    the JSON file a ladder rung would reference, loaded through the
    trainer's own loader, and replayed by the interpreter on the uneven
    partition — bit-exact vs flat uneven. Slow-marked (tier-1 budget):
    the fast interpreter rep is the zb1 test above, and the stage-costs
    JSON/validation wrinkles are covered fast in test_unit_schedule.py;
    the 13-on-4 acceptance pair replays the solver leg in the round
    gate."""
    from llama_pipeline_parallel_tpu.parallel import schedule as usched

    cfg, params, batch, l_flat, g_flat = uneven_flat_ref
    seq = usched.canonical_schedule("zb1", 2, 2, stage_costs=(2, 1))
    path = tmp_path / "uneven.schedule.json"
    path.write_text(usched.to_json(seq))
    loaded = usched.load(str(path))
    assert loaded.stage_costs == (2, 1)
    l_sv, g_sv, _ = run_uneven(params, batch, cfg, pp=2, counts=(2, 1),
                               microbatches=2, schedule="solver",
                               unit_schedule=loaded)
    np.testing.assert_array_equal(np.asarray(l_flat), np.asarray(l_sv))
    assert_trees_bit_equal(g_flat, g_sv)


@pytest.mark.parametrize("schedule", ["zb1", "solver"])
@pytest.mark.slow
def test_13_layers_on_4_stages_zb1_solver_acceptance(devices, schedule):
    """The acceptance criterion: an unequal-stage zb1/solver sequence (13
    layers on 4 stages) replays losses AND grads bit-exact vs the flat
    uneven path and matches the single-device reference."""
    from llama_pipeline_parallel_tpu.parallel import schedule as usched

    cfg = LlamaConfig.tiny(num_hidden_layers=13)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    batch = make_batch(cfg)
    counts = (4, 4, 4, 1)
    unit_schedule = None
    if schedule == "solver":
        unit_schedule = usched.from_json(usched.to_json(
            usched.canonical_schedule("zb1", 4, 4, stage_costs=counts)))
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    l_flat, g_flat, _ = run_uneven(params, batch, cfg, pp=4, counts=counts)
    loss, grads, _ = run_uneven(params, batch, cfg, pp=4, counts=counts,
                                schedule=schedule,
                                unit_schedule=unit_schedule)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    assert_tree_close(grads, ref_grads)
    np.testing.assert_array_equal(np.asarray(l_flat), np.asarray(loss))
    assert_trees_bit_equal(g_flat, grads)


def test_uneven_rejected_where_no_uneven_form_exists():
    """interleaved_1f1b (and any v>1) keeps the even-partition rejection:
    the round-robin chunk layout has no uneven form."""
    with pytest.raises(ValueError, match="no uneven form"):
        pl.PipelineConfig(num_stages=2, num_microbatches=4,
                          schedule="interleaved_1f1b",
                          layer_counts=(2, 1))
    with pytest.raises(ValueError, match="no uneven form"):
        pl.PipelineConfig(num_stages=2, num_microbatches=4,
                          schedule="zb1", virtual_stages=2,
                          layer_counts=(2, 1))
    # zb1 at v=1 is the lifted case
    pl.PipelineConfig(num_stages=2, num_microbatches=4, schedule="zb1",
                      layer_counts=(2, 1))


def test_solver_sequence_partition_mismatch_rejected():
    """A sequence generated for one partition cannot silently run another:
    the config validation names the mismatch."""
    from llama_pipeline_parallel_tpu.parallel import schedule as usched

    seq = usched.canonical_schedule("zb1", 4, 2, stage_costs=(2, 1))
    with pytest.raises(ValueError, match="stage layer counts"):
        pl.PipelineConfig(num_stages=2, num_microbatches=4,
                          schedule="solver", unit_schedule=seq,
                          layer_counts=(1, 2))
    with pytest.raises(ValueError, match="stage layer counts"):
        pl.PipelineConfig(num_stages=2, num_microbatches=4,
                          schedule="solver", unit_schedule=seq)


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
@pytest.mark.slow
def test_uneven_both_schedules(devices, schedule):
    cfg = LlamaConfig.tiny(num_hidden_layers=6)
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    batch = make_batch(cfg)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads, _ = run_uneven(params, batch, cfg, pp=4, counts=(2, 2, 1, 1),
                                schedule=schedule)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    assert_tree_close(grads, ref_grads)


@pytest.mark.slow
def test_uneven_with_tp_identity_padding(devices):
    """tp>1 forbids cond-skipping, so the padded slots COMPUTE — the all-zero
    layer must still behave as an exact identity under tp collectives."""
    cfg = LlamaConfig.tiny(num_hidden_layers=3)
    params = llama.init_params(jax.random.PRNGKey(2), cfg)
    batch = make_batch(cfg, batch_size=4)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads, _ = run_uneven(params, batch, cfg, pp=2, counts=(2, 1),
                                microbatches=2, tp=2)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    assert_tree_close(grads, ref_grads, rtol=5e-5, atol=2e-6)


def test_padded_slot_grads_are_zero(devices):
    """Padding slots must be AdamW fixed points: exactly zero gradient."""
    cfg = LlamaConfig.tiny(num_hidden_layers=3)
    params = llama.init_params(jax.random.PRNGKey(3), cfg)
    batch = make_batch(cfg, batch_size=4)
    mesh = make_mesh(MeshConfig(pp=2))
    manifest = StageManifest(num_layers=3, num_stages=2, layer_counts=(2, 1))
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=2,
                             layer_counts=(2, 1))
    fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked))
    _, grads = fn(stacked, batch)
    # stage 1's slot 1 is padding: stacked leaf [2, 2, ...] index [1, 1]
    for leaf in jax.tree.leaves(grads["layers"]):
        np.testing.assert_array_equal(np.asarray(leaf)[1, 1], 0.0)


@pytest.mark.parametrize("target", [
    StageManifest(num_layers=6, num_stages=2),
    StageManifest(num_layers=6, num_stages=3),
    StageManifest(num_layers=6, num_stages=4, layer_counts=(2, 2, 1, 1)),
    StageManifest(num_layers=6, num_stages=3, layer_counts=(3, 2, 1)),
], ids=["even2", "even3", "same-uneven", "other-uneven"])
@pytest.mark.slow
def test_ckpt_restore_across_partition_change(devices, tmp_path, target):
    """Save under an uneven PP=4 partition, restore into even AND uneven
    targets: the canonical checkpoint layout is partition-agnostic (the
    reference's filename arithmetic forbids exactly this, SURVEY.md §7.3
    item 5) — the grid a generated-ladder resize walks."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager

    cfg = LlamaConfig.tiny(num_hidden_layers=6)
    params = llama.init_params(jax.random.PRNGKey(4), cfg)
    uneven = StageManifest(num_layers=6, num_stages=4, layer_counts=(2, 2, 1, 1))
    stacked_uneven = pl.stack_stages(params, uneven)

    mgr = CheckpointManager(str(tmp_path))
    mgr.save(7, stacked_uneven, uneven, cfg)

    template = pl.stack_stages(
        jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params),
        target)
    restored = mgr.load_params(7, template, target)
    assert_tree_close(pl.unstack_stages(restored, target), params,
                      rtol=0, atol=0)


def test_balanced_factory_properties():
    """Cost balancing: valid cover, head stage never takes the remainder, and
    a genuinely heavy lm-head (vocab >> hidden) sheds decoder layers."""
    # 65B at PP=8: the lm-head is only ~0.3 layer-equivalents, so the even
    # 10x8 split IS the balanced one — balancing must not force unevenness.
    assert StageManifest.balanced(LlamaConfig.llama_65b(), 8).is_even
    # indivisible count: the remainder lands on the cheapest stages
    cfg = LlamaConfig.tiny(num_hidden_layers=13)
    man = StageManifest.balanced(cfg, 4)
    counts = man.stage_layer_counts
    assert sum(counts) == 13 and len(counts) == 4 and min(counts) >= 1
    assert counts[-1] == min(counts)  # head stage is the lightest
    # stage_of_layer / layers_of_stage stay mutually consistent
    for layer in range(13):
        assert layer in man.layers_of_stage(man.stage_of_layer(layer))
    # heavy head (vocab 4096 at hidden 64 ~= 7 layer-equivalents): the head
    # stage ends up strictly lighter than the middle stages
    heavy = LlamaConfig.tiny(num_hidden_layers=8, vocab_size=4096)
    c2 = StageManifest.balanced(heavy, 4).stage_layer_counts
    assert sum(c2) == 8 and c2[-1] < max(c2)


def test_manifest_validation():
    with pytest.raises(ValueError, match="sum to"):
        StageManifest(num_layers=8, num_stages=2, layer_counts=(3, 3))
    with pytest.raises(ValueError, match=">= 1 layer"):
        StageManifest(num_layers=4, num_stages=2, layer_counts=(4, 0))
    with pytest.raises(ValueError, match="not divisible"):
        StageManifest(num_layers=7, num_stages=2)
    # round-trips through JSON with counts intact
    man = StageManifest(num_layers=7, num_stages=2, layer_counts=(4, 3))
    assert StageManifest.from_json(man.to_json()) == man

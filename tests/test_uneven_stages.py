"""Uneven / cost-balanced stage partitions (SURVEY.md §7.3 item 2; the
reference's LayerSpec lists admit uneven cuts, models/llama_ds_mp_wrap.py:209).

The stacked runtime layout pads stages to max_layers_per_stage with all-zero
layers (exact identities with zero gradients); these tests pin that the
padding is invisible to the math — grads match single-device — and that the
checkpoint layout stays canonical across partition changes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

from tests.test_pipeline import (
    assert_tree_close,
    make_batch,
    reference_loss_and_grad,
)


def run_uneven(params, batch, cfg, pp, counts, microbatches=4, schedule="1f1b",
               dp=1, tp=1):
    mesh = make_mesh(MeshConfig(pp=pp, dp=dp, tp=tp))
    manifest = StageManifest(num_layers=cfg.num_hidden_layers, num_stages=pp,
                             layer_counts=tuple(counts))
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=pp, num_microbatches=microbatches,
                             schedule=schedule,
                             layer_counts=manifest.stage_layer_counts)
    fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked))
    loss, grads = fn(stacked, batch)
    return loss, pl.unstack_stages(grads, manifest), manifest


@pytest.mark.slow
def test_13_layers_on_4_stages_matches_single_device(devices):
    """The VERDICT acceptance case: 13 layers, 4 stages, grad parity."""
    cfg = LlamaConfig.tiny(num_hidden_layers=13)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    batch = make_batch(cfg)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads, _ = run_uneven(params, batch, cfg, pp=4, counts=(4, 4, 4, 1))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    assert_tree_close(grads, ref_grads)


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
@pytest.mark.slow
def test_uneven_both_schedules(devices, schedule):
    cfg = LlamaConfig.tiny(num_hidden_layers=6)
    params = llama.init_params(jax.random.PRNGKey(1), cfg)
    batch = make_batch(cfg)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads, _ = run_uneven(params, batch, cfg, pp=4, counts=(2, 2, 1, 1),
                                schedule=schedule)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    assert_tree_close(grads, ref_grads)


@pytest.mark.slow
def test_uneven_with_tp_identity_padding(devices):
    """tp>1 forbids cond-skipping, so the padded slots COMPUTE — the all-zero
    layer must still behave as an exact identity under tp collectives."""
    cfg = LlamaConfig.tiny(num_hidden_layers=3)
    params = llama.init_params(jax.random.PRNGKey(2), cfg)
    batch = make_batch(cfg, batch_size=4)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads, _ = run_uneven(params, batch, cfg, pp=2, counts=(2, 1),
                                microbatches=2, tp=2)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    assert_tree_close(grads, ref_grads, rtol=5e-5, atol=2e-6)


def test_padded_slot_grads_are_zero(devices):
    """Padding slots must be AdamW fixed points: exactly zero gradient."""
    cfg = LlamaConfig.tiny(num_hidden_layers=3)
    params = llama.init_params(jax.random.PRNGKey(3), cfg)
    batch = make_batch(cfg, batch_size=4)
    mesh = make_mesh(MeshConfig(pp=2))
    manifest = StageManifest(num_layers=3, num_stages=2, layer_counts=(2, 1))
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=2,
                             layer_counts=(2, 1))
    fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked))
    _, grads = fn(stacked, batch)
    # stage 1's slot 1 is padding: stacked leaf [2, 2, ...] index [1, 1]
    for leaf in jax.tree.leaves(grads["layers"]):
        np.testing.assert_array_equal(np.asarray(leaf)[1, 1], 0.0)


@pytest.mark.slow
def test_ckpt_restore_across_partition_change(devices, tmp_path):
    """Save under an uneven PP=4 partition, restore into an even PP=2 one:
    the canonical checkpoint layout is partition-agnostic (the reference's
    filename arithmetic forbids exactly this, SURVEY.md §7.3 item 5)."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager

    cfg = LlamaConfig.tiny(num_hidden_layers=6)
    params = llama.init_params(jax.random.PRNGKey(4), cfg)
    uneven = StageManifest(num_layers=6, num_stages=4, layer_counts=(2, 2, 1, 1))
    stacked_uneven = pl.stack_stages(params, uneven)

    mgr = CheckpointManager(str(tmp_path))
    mgr.save(7, stacked_uneven, uneven, cfg)

    even = StageManifest(num_layers=6, num_stages=2)
    template = pl.stack_stages(
        jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params),
        even)
    restored = mgr.load_params(7, template, even)
    assert_tree_close(pl.unstack_stages(restored, even), params, rtol=0, atol=0)


def test_balanced_factory_properties():
    """Cost balancing: valid cover, head stage never takes the remainder, and
    a genuinely heavy lm-head (vocab >> hidden) sheds decoder layers."""
    # 65B at PP=8: the lm-head is only ~0.3 layer-equivalents, so the even
    # 10x8 split IS the balanced one — balancing must not force unevenness.
    assert StageManifest.balanced(LlamaConfig.llama_65b(), 8).is_even
    # indivisible count: the remainder lands on the cheapest stages
    cfg = LlamaConfig.tiny(num_hidden_layers=13)
    man = StageManifest.balanced(cfg, 4)
    counts = man.stage_layer_counts
    assert sum(counts) == 13 and len(counts) == 4 and min(counts) >= 1
    assert counts[-1] == min(counts)  # head stage is the lightest
    # stage_of_layer / layers_of_stage stay mutually consistent
    for layer in range(13):
        assert layer in man.layers_of_stage(man.stage_of_layer(layer))
    # heavy head (vocab 4096 at hidden 64 ~= 7 layer-equivalents): the head
    # stage ends up strictly lighter than the middle stages
    heavy = LlamaConfig.tiny(num_hidden_layers=8, vocab_size=4096)
    c2 = StageManifest.balanced(heavy, 4).stage_layer_counts
    assert sum(c2) == 8 and c2[-1] < max(c2)


def test_manifest_validation():
    with pytest.raises(ValueError, match="sum to"):
        StageManifest(num_layers=8, num_stages=2, layer_counts=(3, 3))
    with pytest.raises(ValueError, match=">= 1 layer"):
        StageManifest(num_layers=4, num_stages=2, layer_counts=(4, 0))
    with pytest.raises(ValueError, match="not divisible"):
        StageManifest(num_layers=7, num_stages=2)
    # round-trips through JSON with counts intact
    man = StageManifest(num_layers=7, num_stages=2, layer_counts=(4, 3))
    assert StageManifest.from_json(man.to_json()) == man

"""Host-DRAM residual offload (utils/host_stash.py + the pipeline hooks).

The CI `Offload` gate: tiering the zb1 W-queue and the schedules' stage-input
ring buffer to host memory must change WHERE bytes live, never their values —
offload on/off is asserted bit-exact across the schedule parity grid (the
test_zero_bubble.py assertion style), the stash traffic must be structurally
ASYNC (device_put data movement in the jaxpr, no host-sync primitive in the
lowered step), the byte models preflight consumes are pinned, and the chaos
leg proves a SIGKILL with residuals resident on host resumes to bit parity.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh
from llama_pipeline_parallel_tpu.utils import host_stash

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny(num_hidden_layers=8)


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.PRNGKey(0), cfg)


def make_batch(cfg, batch_size=8, seqlen=16, seed=42):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, cfg.vocab_size, size=(batch_size, seqlen)).astype(np.int32)
    mask = np.ones((batch_size, seqlen), np.int32)
    mask[:, -3:] = 0
    labels = ids.copy()
    labels[mask == 0] = llama.IGNORE_INDEX
    labels[:, :2] = llama.IGNORE_INDEX
    pos = np.broadcast_to(np.arange(seqlen, dtype=np.int32),
                          (batch_size, seqlen)).copy()
    return {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.asarray(mask),
        "position_ids": jnp.asarray(pos),
        "labels": jnp.asarray(labels),
    }


def run_schedule(params, batch, cfg, pp, schedule, v=1, dp=1, tp=1,
                 microbatches=4, chunks=1, **offload):
    mesh = make_mesh(MeshConfig(pp=pp, dp=dp, tp=tp))
    manifest = StageManifest.for_config(cfg, pp, virtual_stages=v)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=pp, num_microbatches=microbatches,
                             schedule=schedule, virtual_stages=v,
                             accum_chunks=chunks, **offload)
    fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked))
    loss, grads = fn(stacked, batch)
    return float(loss), pl.unstack_stages(grads, manifest)


def assert_tree_bitexact(a, b):
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), a, b)


@pytest.fixture(scope="module")
def flat_reference(cfg, params):
    """One flat no-offload run shared by the fast-lane parity tests (every
    schedule below is already proven bit-equal to it in test_zero_bubble /
    test_interleaved, so it is the one baseline that covers them all)."""
    batch = make_batch(cfg)
    loss, grads = run_schedule(params, batch, cfg, 2, "1f1b")
    return batch, loss, grads


# ---------------------------------------------------------------------------
# Parity: offload on == offload off, bit for bit
# ---------------------------------------------------------------------------

def test_zb1_wgrad_and_acts_offload_bitexact(cfg, params, devices,
                                             flat_reference, monkeypatch):
    """Both tiers at once under zb1 (the offload conf's combination plus
    the ring): values must round-trip the host untouched — loss AND grads
    bit-equal to the flat no-offload schedule. FORCEd transfers: on CPU
    the gate would otherwise elide them, and this test exists to run the
    real device_put round trip (plain jit lowers it cleanly there)."""
    monkeypatch.setenv("LPT_HOST_STASH_FORCE", "1")
    batch, l_ref, g_ref = flat_reference
    l, g = run_schedule(params, batch, cfg, 2, "zb1", v=2,
                        offload_wgrad=True, offload_activations=True)
    assert l == l_ref
    assert_tree_bitexact(g, g_ref)


@pytest.mark.slow  # round gate: the zb1 both-tiers case above keeps the
# bit-exactness acceptance in the tier-1 lane; these two variants ride
# with the rest of the grid to respect the 870s budget
def test_1f1b_activation_offload_bitexact(cfg, params, devices,
                                          flat_reference, monkeypatch):
    """The flat schedule's ring buffer tiered to host: same stage inputs
    come back for every backward recompute."""
    monkeypatch.setenv("LPT_HOST_STASH_FORCE", "1")
    batch, l_ref, g_ref = flat_reference
    l, g = run_schedule(params, batch, cfg, 2, "1f1b",
                        offload_activations=True)
    assert l == l_ref
    assert_tree_bitexact(g, g_ref)


@pytest.mark.slow
def test_offload_parity_gated_off(cfg, params, devices, flat_reference,
                                  monkeypatch):
    """The gated-off mode (what a backend without pinned_host, or
    LPT_HOST_STASH_FORCE=0, runs): same schedule restructuring, stores
    device-resident, still bit-exact."""
    monkeypatch.setenv("LPT_HOST_STASH_FORCE", "0")
    batch, l_ref, g_ref = flat_reference
    l, g = run_schedule(params, batch, cfg, 2, "zb1", v=2,
                        offload_wgrad=True, offload_activations=True)
    assert l == l_ref
    assert_tree_bitexact(g, g_ref)


@pytest.mark.slow
@pytest.mark.parametrize("pp,schedule,v,kw", [
    (2, "interleaved_1f1b", 2, {"offload_activations": True}),
    (4, "zb1", 2, {"offload_wgrad": True}),
    (2, "zb1", 1, {"offload_wgrad": True, "offload_activations": True}),
    (4, "1f1b", 1, {"offload_activations": True}),
])
def test_offload_parity_grid(cfg, params, devices, flat_reference, pp,
                             schedule, v, kw, monkeypatch):
    """The rest of the pp x schedule x v grid (round gate) — each still
    pinned against the ONE flat reference (these shapes are all bit-equal
    to it, per test_zero_bubble/test_interleaved)."""
    monkeypatch.setenv("LPT_HOST_STASH_FORCE", "1")
    batch, l_ref, g_ref = flat_reference
    l, g = run_schedule(params, batch, cfg, pp, schedule, v=v, **kw)
    assert l == l_ref
    assert_tree_bitexact(g, g_ref)


@pytest.mark.slow
@pytest.mark.parametrize("tp,chunks", [(2, 1), (1, 2)])
def test_offload_parity_hybrids_on_vs_off(cfg, params, devices, tp, chunks,
                                          monkeypatch):
    """tp sharding and chunked accumulation change the numerics baseline
    itself (vocab-parallel CE / per-chunk fp32 fold order), so these
    hybrids pin offload ON against offload OFF at the SAME config — the
    knob's actual contract. The tp leg drives the split head's
    vocab-parallel grads through a host-tiered W queue, the hybrid most
    likely to break independently."""
    monkeypatch.setenv("LPT_HOST_STASH_FORCE", "1")
    batch = make_batch(cfg)
    l_off, g_off = run_schedule(params, batch, cfg, 2, "zb1", v=2, tp=tp,
                                chunks=chunks)
    l_on, g_on = run_schedule(params, batch, cfg, 2, "zb1", v=2, tp=tp,
                              chunks=chunks, offload_wgrad=True,
                              offload_activations=True)
    assert l_on == l_off
    assert_tree_bitexact(g_on, g_off)


# ---------------------------------------------------------------------------
# Structural: transfers are async data movement, not host syncs
# ---------------------------------------------------------------------------

def test_stash_transfers_async_no_host_sync(cfg, params, devices,
                                            monkeypatch):
    """The acceptance's structural assertion: with offload on, the scan
    phases' stash traffic appears in the jaxpr as `device_put` data
    movement targeting the pinned_host/device memory kinds (XLA lowers
    these to async copy-start/copy-done pairs), and the lowered program
    contains NO host-synchronizing primitive — no callback, no
    infeed/outfeed — anywhere a blocking sync could hide. Off, the jaxpr
    carries no memory-kind traffic at all (the knob adds nothing); gated
    off (a no-pinned_host backend), likewise."""
    batch = make_batch(cfg)
    mesh = make_mesh(MeshConfig(pp=2))
    manifest = StageManifest.for_config(cfg, 2, virtual_stages=2)
    stacked = pl.stack_stages(params, manifest)

    def build(**offload):
        pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=4,
                                 schedule="zb1", virtual_stages=2, **offload)
        return pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked)

    monkeypatch.setenv("LPT_HOST_STASH_FORCE", "1")
    on = build(offload_wgrad=True, offload_activations=True)
    jaxpr_on = str(jax.make_jaxpr(on)(stacked, batch))
    # pushes D2H: ring (warmup+steady) + W-queue pair (steady+drain);
    # pops H2D: ring read, W-drain prefetch pair + its initial fetch
    assert jaxpr_on.count("pinned_host") >= 6, \
        jaxpr_on.count("pinned_host")
    assert jaxpr_on.count("memory_kind='device'") >= 4
    assert "device_put" in jaxpr_on

    off = build()
    jaxpr_off = str(jax.make_jaxpr(off)(stacked, batch))
    assert "pinned_host" not in jaxpr_off

    # the lowered step: transfers must not smuggle in a host round-trip
    text = jax.jit(on).lower(stacked, batch).as_text()
    for marker in ("callback", "infeed", "outfeed", "SendToHost",
                   "RecvFromHost"):
        assert marker not in text, f"host-sync marker {marker!r} in HLO"

    # the capability gate: on a backend with no distinct host memory space
    # (CPU) the default mode emits no transfer at all — the program the
    # sharded-jit partitioner sees is annotation-free
    monkeypatch.delenv("LPT_HOST_STASH_FORCE")
    gated = str(jax.make_jaxpr(build(offload_wgrad=True,
                                     offload_activations=True))(
                                         stacked, batch))
    assert "pinned_host" not in gated


def test_wdrain_prefetches_one_unit_ahead(cfg, params, devices):
    """Pin the double-buffered drain's structure: the offloaded W-drain
    scan carries the NEXT unit's residual pair (two extra hidden-shaped
    carries vs the in-HBM drain), so the H2D fetch of unit g+1 is in
    flight while unit g replays."""
    batch = make_batch(cfg)
    mesh = make_mesh(MeshConfig(pp=2))
    manifest = StageManifest.for_config(cfg, 2, virtual_stages=2)
    stacked = pl.stack_stages(params, manifest)

    def sub_jaxprs(v):
        if hasattr(v, "eqns"):       # open Jaxpr (shard_map's param)
            return [v]
        if hasattr(v, "jaxpr"):      # ClosedJaxpr (scan/pjit's param)
            return [v.jaxpr]
        if isinstance(v, (tuple, list)):  # cond branches
            return [j for x in v for j in sub_jaxprs(x)]
        return []

    def scan_carry_counts(jaxpr, acc=None):
        acc = [] if acc is None else acc
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "scan":
                acc.append(eqn.params["num_carry"])
            for v in eqn.params.values():
                for j in sub_jaxprs(v):
                    scan_carry_counts(j, acc)
        return acc

    def counts(offload):
        pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=4,
                                 schedule="zb1", virtual_stages=2,
                                 offload_wgrad=offload)
        fn = pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked)
        return sorted(scan_carry_counts(
            jax.make_jaxpr(fn)(stacked, batch).jaxpr))

    counts_off, counts_on = counts(False), counts(True)
    # offload adds exactly TWO carries (the prefetched x/dy residual pair)
    # to exactly ONE scan — the W-drain (the grad-accumulator-only scan;
    # the phase scans and the within-chunk layer scans are untouched)
    assert len(counts_on) == len(counts_off)
    deltas = sorted(a - b for a, b in zip(counts_on, counts_off))
    assert deltas == [0] * (len(deltas) - 1) + [2], (counts_off, counts_on)


# ---------------------------------------------------------------------------
# The staging-layer primitives + byte models
# ---------------------------------------------------------------------------

def test_stash_push_pop_roundtrip_and_garbage_slot(monkeypatch):
    monkeypatch.setenv("LPT_HOST_STASH_FORCE", "1")  # real transfers on CPU
    v = jnp.arange(4.0)

    @jax.jit
    def drill():
        # memory-kind transfers only exist inside jit (the schedules'
        # usage); stash_init is called there too
        buf = host_stash.stash_init(3, (4,), jnp.float32)
        buf = host_stash.stash_push(buf, v, jnp.int32(1), jnp.bool_(True))
        # invalid write must land in the garbage slot, not slot 2
        buf = host_stash.stash_push(buf, v * 9, jnp.int32(2), jnp.bool_(False))
        return (host_stash.stash_pop(buf, jnp.int32(1)),
                host_stash.stash_pop(buf, jnp.int32(2)), buf)

    got1, got2, buf = drill()
    assert buf.shape == (4, 4)  # 3 slots + 1 garbage
    np.testing.assert_array_equal(np.asarray(got1), np.asarray(v))
    np.testing.assert_array_equal(np.asarray(got2), np.zeros(4))
    np.testing.assert_array_equal(np.asarray(buf)[3], 9 * np.asarray(v))


def test_supports_host_memory_reports_backend():
    # CPU exposes no distinct pinned_host space; the call must not raise
    # and the staging layer must still run (every parity test above)
    assert host_stash.supports_host_memory() is False


def test_measure_transfer_bandwidth_smoke():
    bw = host_stash.measure_transfer_bandwidth(nbytes=1 << 16, reps=1)
    assert bw["h2d_gibps"] > 0 and bw["d2h_gibps"] > 0
    assert bw["pinned_host"] is False  # CPU


def _pcfg(schedule, s, m, c=1, v=1, **kw):
    return pl.PipelineConfig(num_stages=s, num_microbatches=m,
                             accum_chunks=c, schedule=schedule,
                             virtual_stages=v, **kw)


def test_activation_ring_model():
    # flat: min(2S-1, m) per flush; chunked: min(2vS-1, mv)
    assert pl.activation_ring_slots(_pcfg("1f1b", 4, 16)) == 7
    assert pl.activation_ring_slots(_pcfg("1f1b", 4, 2)) == 2
    assert pl.activation_ring_slots(_pcfg("1f1b", 1, 8)) == 0
    assert pl.activation_ring_slots(_pcfg("gpipe", 4, 8)) == 0
    assert pl.activation_ring_slots(_pcfg("interleaved_1f1b", 4, 16, v=2)) == 15
    assert pl.activation_ring_slots(_pcfg("zb1", 8, 256, v=2)) == 31
    assert pl.activation_ring_slots(_pcfg("zb1", 2, 8, c=4, v=2)) == 4
    # bytes: slots x [mb, L, d] x dtype (the 65B pp8 v2 shape: 31 x 64 MiB)
    assert pl.activation_ring_bytes(_pcfg("zb1", 8, 256, v=2), 8, 512,
                                    8192, 2) == 31 * 8 * 512 * 8192 * 2


def test_host_stash_bytes_model():
    dims = (8, 512, 8192, 2)
    slot = 8 * 512 * 8192 * 2
    off = _pcfg("zb1", 8, 256, v=2)
    assert pl.host_stash_bytes(off, *dims) == 0  # nothing tiered
    wg = _pcfg("zb1", 8, 256, v=2, offload_wgrad=True)
    assert pl.host_stash_bytes(wg, *dims) == (
        pl.wgrad_stash_bytes(wg, *dims) + 2 * slot)  # + garbage slots
    both = _pcfg("zb1", 8, 256, v=2, offload_wgrad=True,
                 offload_activations=True)
    assert pl.host_stash_bytes(both, *dims) == (
        pl.wgrad_stash_bytes(both, *dims) + 2 * slot
        + pl.activation_ring_bytes(both, *dims) + slot)
    # ~64 GiB of W stash at the reference micro-batch shape — the number
    # the offload conf's header and docs/PREFLIGHT.md quote
    assert round(pl.wgrad_stash_bytes(wg, *dims) / (1 << 30)) == 64


# ---------------------------------------------------------------------------
# Validation + config plumbing
# ---------------------------------------------------------------------------

def test_offload_wgrad_requires_zb1():
    with pytest.raises(ValueError, match="zb1"):
        _pcfg("1f1b", 2, 4, offload_wgrad=True)
    with pytest.raises(ValueError, match="zb1"):
        _pcfg("interleaved_1f1b", 2, 4, v=2, offload_wgrad=True)


def test_offload_activations_rejects_gpipe():
    with pytest.raises(ValueError, match="gpipe"):
        _pcfg("gpipe", 2, 4, offload_activations=True)


def test_offload_config_block_parses():
    from llama_pipeline_parallel_tpu.train import (
        _offload_flags,
        build_manifest,
        build_pipeline_config,
    )

    assert _offload_flags({}) == (False, False)
    assert _offload_flags({"offload": {"wgrad_stash": True}}) == (True, False)
    assert _offload_flags({"offload": {"activations": True}}) == (False, True)
    with pytest.raises(ValueError, match="unknown offload"):
        _offload_flags({"offload": {"wgrad": True}})

    cfg = LlamaConfig.tiny(num_hidden_layers=8)
    raw = {"pipeline_schedule": "zb1", "virtual_stages": 2,
           "gradient_accumulation_steps": 2,
           "offload": {"wgrad_stash": True, "activations": True}}
    pcfg = build_pipeline_config(raw, MeshConfig(pp=2),
                                 build_manifest(raw, cfg, 2))
    assert pcfg.offload_wgrad and pcfg.offload_activations


def test_offload_static_metrics_keys():
    from llama_pipeline_parallel_tpu.train import _offload_static

    off = _pcfg("zb1", 2, 4, v=2)
    assert _offload_static(off, 2, 16, 64, 4) == {}
    on = _pcfg("zb1", 2, 4, v=2, offload_wgrad=True,
               offload_activations=True)
    static = _offload_static(on, 2, 16, 64, 4)
    assert static["offload_stash"] == "wgrad_stash+activations"
    assert static["offload_stash_resident_gib"] == round(
        pl.host_stash_bytes(on, 2, 16, 64, 4) / (1 << 30), 6)
    assert static["offload_stash_resident_gib"] > 0  # KiB resolution: the
    # tiny shapes the trainer e2e logs must not flatten to an all-zero key


# ---------------------------------------------------------------------------
# Trainer e2e + chaos (round gate)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_trainer_offload_end_to_end(tmp_path, devices):
    """run_training with the host stash on: final loss bit-matches the
    no-offload zb1 run, the metrics line + health.json carry the
    offload_stash keys, and a plain run carries neither (no always-zero
    columns)."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.train import run_training

    model_cfg = LlamaConfig.tiny(dtype=jnp.float32)
    man = StageManifest.for_config(model_cfg, 2)
    warm_dir = str(tmp_path / "warm")
    CheckpointManager(warm_dir).save(
        0, pl.stack_stages(llama.init_params(jax.random.PRNGKey(7), model_cfg),
                           man), man, model_cfg)

    def cfg_for(out, **kw):
        base = {
            "output_dir": str(tmp_path / out),
            "mesh": {"pp": 2, "dp": 2},
            "model": {"preset": "tiny", "dtype": "float32"},
            "model_name_or_path": warm_dir,
            "dataset": {"synthetic": True, "seq_length": 16,
                        "pseudo_dataset_len": 128},
            "seed": 7,
            "per_device_train_batch_size": 2,
            "gradient_accumulation_steps": 2,
            "pipeline_schedule": "zb1",
            "virtual_stages": 2,
            "max_steps": 3,
            "learning_rate": 1e-3,
            "warmup_steps": 1,
            "logging_steps": 1,
            "save_steps": 0,
            "save_final": False,
        }
        base.update(kw)
        return base

    plain = run_training(cfg_for("plain"))
    off = run_training(cfg_for("off", offload={"wgrad_stash": True,
                                               "activations": True}))
    assert off["final_loss"] == plain["final_loss"]

    lines = [json.loads(l) for l in
             open(os.path.join(str(tmp_path / "off"), "metrics.jsonl"))]
    assert lines[0]["offload_stash"] == "wgrad_stash+activations"
    assert lines[0]["offload_stash_resident_gib"] > 0
    plain_lines = [json.loads(l) for l in
                   open(os.path.join(str(tmp_path / "plain"), "metrics.jsonl"))]
    assert "offload_stash" not in plain_lines[0]
    health = json.load(open(os.path.join(str(tmp_path / "off"), "health.json")))
    assert health["offload_stash"] == "wgrad_stash+activations"


@pytest.mark.slow
def test_chaos_sigkill_with_host_residuals_resumes_bitexact(tmp_path):
    """The chaos leg: the fault plan SIGKILLs the trainer AT THE STEP SITE
    while the host stash is live (zb1 + offload.wgrad_stash — W residuals
    tier through host DRAM every step), the supervisor restarts it, and the
    resumed run — whose in-flight host residuals died with the process —
    restores the last verified checkpoint and finishes with the final loss
    bit-matching an unfaulted offload run."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.utils import faults

    out = str(tmp_path / "chaos")
    ref = str(tmp_path / "straight")
    env_base = {**os.environ,
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "LPT_RETRY_BASE_DELAY_S": "0.01"}

    def train_cmd(output_dir):
        return [sys.executable, "train.py", "--config", "conf/tiny_smoke.yaml",
                "--platform", "cpu", f"output_dir={output_dir}",
                "pipeline_schedule=zb1", "virtual_stages=2",
                "offload.wgrad_stash=true", "offload.activations=true",
                "max_steps=6", "total_steps=6", "save_steps=2",
                "logging_steps=1", "save_final=true", "attention=exact"]

    plan = {"faults": [{"site": "step", "op": "die", "at_step": 4,
                        "marker": os.path.join(out, "fault.fired")}]}
    sup = subprocess.run(
        [sys.executable, "tools/supervisor.py", "--output-dir", out,
         "--max-restarts", "2", "--hang-timeout-s", "600",
         "--poll-s", "0.2", "--"] + train_cmd(out),
        cwd=_REPO, env={**env_base, faults.ENV_PLAN: json.dumps(plan)},
        capture_output=True, text=True, timeout=540)
    assert sup.returncode == 0, (
        f"supervisor failed:\n{sup.stdout[-3000:]}\n{sup.stderr[-3000:]}")
    assert os.path.exists(os.path.join(out, "fault.fired"))
    ledger = [json.loads(l)
              for l in open(os.path.join(out, "incarnations.jsonl"))]
    assert [r["outcome"] for r in ledger] == ["crash", "clean"]
    mgr = CheckpointManager(out)
    assert mgr.latest_step() == 6
    mgr.verify(6)

    straight = subprocess.run(train_cmd(ref), cwd=_REPO, env=env_base,
                              capture_output=True, text=True, timeout=360)
    assert straight.returncode == 0, straight.stdout[-3000:]

    def losses(d):
        lines = [json.loads(l) for l in open(os.path.join(d, "metrics.jsonl"))]
        return {l["step"]: l["loss"] for l in lines if "loss" in l}

    # bit parity at the final step: resume from checkpoint-2 replayed the
    # exact batch stream, host residuals reconstructed from scratch
    assert losses(out)[6] == losses(ref)[6]

"""Span layer, RunClock goodput accounting, heartbeat/health.json, and the
trainer's end-to-end telemetry contract (docs/OBSERVABILITY.md)."""

import json
import os
import threading
import time

import pytest

from llama_pipeline_parallel_tpu.parallel.pipeline import (
    PipelineConfig,
    bubble_fraction,
)
from llama_pipeline_parallel_tpu.utils import trace


@pytest.fixture
def recorder(tmp_path):
    rec = trace.configure(str(tmp_path))
    yield rec
    trace.configure(None)


def read_spans(tmp_path):
    with open(tmp_path / "spans.jsonl") as f:
        return [json.loads(l) for l in f if l.strip()]


# ---- spans -----------------------------------------------------------------

def test_span_nesting_ordering_and_roundtrip(tmp_path, recorder):
    with trace.span("outer", step=3):
        time.sleep(0.01)
        with trace.span("inner"):
            time.sleep(0.005)
    recs = read_spans(tmp_path)
    # inner finishes first (jsonl is completion-ordered), nesting is explicit
    assert [r["name"] for r in recs] == ["inner", "outer"]
    inner, outer = recs
    assert inner["depth"] == 1 and inner["parent"] == "outer"
    assert outer["depth"] == 0 and outer["parent"] is None
    assert outer["step"] == 3
    assert outer["dur"] >= inner["dur"] > 0
    assert outer["ts"] <= inner["ts"] and inner["end"] <= outer["end"] + 1e-6
    assert outer["main_thread"] is True


def test_span_records_on_exception(tmp_path, recorder):
    with pytest.raises(RuntimeError):
        with trace.span("doomed"):
            raise RuntimeError("boom")
    (rec,) = read_spans(tmp_path)
    assert rec["name"] == "doomed" and rec["dur"] >= 0


def test_retroactive_emit_and_unconfigured_noop(tmp_path):
    trace.configure(None)
    with trace.span("nobody-listening"):  # must not raise, nothing persisted
        pass
    rec = trace.configure(str(tmp_path))
    rec.emit("init", ts=123.0, dur=4.5)
    (r,) = read_spans(tmp_path)
    assert (r["name"], r["ts"], r["dur"], r["end"]) == ("init", 123.0, 4.5, 127.5)
    trace.configure(None)


def test_spans_threadsafe_and_thread_tagged(tmp_path, recorder):
    def worker():
        with trace.span("bg"):
            time.sleep(0.002)

    t = threading.Thread(target=worker)
    with trace.span("fg"):
        t.start()
        t.join()
    recs = {r["name"]: r for r in read_spans(tmp_path)}
    assert recs["bg"]["main_thread"] is False
    # the worker's span must not see the main thread's stack as its parent
    assert recs["bg"]["depth"] == 0 and recs["bg"]["parent"] is None
    assert recs["fg"]["main_thread"] is True


# ---- RunClock --------------------------------------------------------------

def test_runclock_buckets_goodput_and_untracked(recorder):
    clock = trace.RunClock()
    recorder.add_listener(clock.on_span)
    with trace.span("step_dispatch"):
        time.sleep(0.02)
    with trace.span("data_wait"):
        time.sleep(0.01)
        with trace.span("prefetch_stall"):  # nested: must NOT double-count
            time.sleep(0.005)
    time.sleep(0.01)  # untracked gap
    snap = clock.snapshot()
    b = snap["buckets"]
    assert b["train"] >= 0.02
    assert 0.015 <= b["data_stall"] <= snap["elapsed"]  # outer span only
    assert b["untracked"] >= 0.005
    # snapshot is internally consistent: goodput vs its own elapsed sample
    assert snap["goodput"] == b["train"] / snap["elapsed"]
    # buckets partition elapsed wall time
    assert sum(b.values()) == pytest.approx(snap["elapsed"], rel=0.05)


def test_runclock_ignores_background_thread_spans(recorder):
    clock = trace.RunClock()
    recorder.add_listener(clock.on_span)

    def worker():
        with trace.span("ckpt_save"):  # async commit analogue
            time.sleep(0.01)

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert clock.snapshot()["buckets"]["ckpt"] == 0.0


def test_runclock_resume_accumulates_prior():
    prior = {"elapsed": 100.0,
             "buckets": {"train": 60.0, "init": 10.0, "untracked": 30.0}}
    clock = trace.RunClock(prior=prior, already_elapsed=5.0)
    clock.add("init", 5.0)
    clock.add("train", 20.0)
    snap = clock.snapshot()
    # elapsed: prior 100 + pre-clock 5 + (own ticking, ~0)
    assert snap["elapsed"] == pytest.approx(105.0, abs=1.0)
    assert snap["buckets"]["train"] == pytest.approx(80.0)
    assert snap["buckets"]["init"] == pytest.approx(15.0)
    # prior `untracked` is recomputed against the new elapsed, never summed
    assert snap["buckets"]["untracked"] == pytest.approx(
        snap["elapsed"] - 95.0, abs=1.0)
    assert snap["goodput"] == pytest.approx(80.0 / snap["elapsed"])


def test_runclock_prior_badput_depresses_goodput():
    """Wall time a preemption threw away (elapsed without train seconds)
    must keep depressing the cumulative goodput after resume."""
    # prior incarnation: 100s elapsed, only 50s of it training (50s lost)
    lossy = {"elapsed": 100.0, "buckets": {"train": 50.0}}
    clock = trace.RunClock(prior=lossy)
    assert clock.goodput() == pytest.approx(0.5, abs=0.01)
    # vs a clean prior of the same train seconds in half the wall
    clean = trace.RunClock(prior={"elapsed": 50.0, "buckets": {"train": 50.0}})
    assert clean.goodput() > clock.goodput()


# ---- device memory ---------------------------------------------------------

def test_device_peak_bytes_always_reports(devices):
    val, src = trace.device_peak_bytes()
    # CPU backend has no memory_stats -> host RSS stands in; either way the
    # metrics field exists and is a sane positive byte count
    assert src in ("device", "host_rss")
    assert val > 1 << 20


# ---- bubble fraction -------------------------------------------------------

def test_bubble_fraction_hand_computed():
    mk = lambda **kw: PipelineConfig(**{"num_stages": 4, "num_microbatches": 8,
                                        **kw})
    # 1f1b: 2c(S-1) / (M + 2c(S-1)) = 6 / 14
    assert bubble_fraction(mk()) == pytest.approx(6 / 14)
    # gpipe: c(S-1) / (M + c(S-1)) = 3 / 11
    assert bubble_fraction(mk(schedule="gpipe")) == pytest.approx(3 / 11)
    # chunks multiply the flush bubble: c=2 -> 12 / 20 and 6 / 14
    assert bubble_fraction(mk(accum_chunks=2)) == pytest.approx(12 / 20)
    assert bubble_fraction(mk(schedule="gpipe", accum_chunks=2)) \
        == pytest.approx(6 / 14)
    # no pipeline, no bubble; more microbatches amortize it monotonically
    assert bubble_fraction(mk(num_stages=1)) == 0.0
    assert bubble_fraction(mk(num_microbatches=64)) < bubble_fraction(mk())


# ---- heartbeat / health.json ----------------------------------------------

def test_heartbeat_atomic_rewrite_and_fields(tmp_path):
    clock = trace.RunClock()
    clock.add("train", 1.0)
    hb = trace.Heartbeat(str(tmp_path), clock, interval=30.0,
                         min_write_interval=0.0)
    path = tmp_path / "health.json"
    assert path.exists()  # file exists from construction
    first = json.load(open(path))
    assert first["last_step"] is None and first["pid"] == os.getpid()

    hb.beat(7, step_dur=0.25)
    mid = json.load(open(path))
    assert mid["last_step"] == 7 and mid["last_step_dur"] == 0.25
    # top-level goodput mirrors the embedded clock snapshot exactly
    assert mid["goodput"] == mid["clock"]["goodput"]
    assert mid["clock"]["buckets"]["train"] == pytest.approx(1.0)

    hb.stop()
    final = json.load(open(path))
    assert final["time"] >= mid["time"]
    # atomic contract: no torn temp files left behind
    assert [p.name for p in tmp_path.iterdir()] == ["health.json"]


def test_heartbeat_thread_refreshes_time(tmp_path):
    hb = trace.Heartbeat(str(tmp_path), clock=None, interval=0.05)
    t0 = json.load(open(tmp_path / "health.json"))["time"]
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if json.load(open(tmp_path / "health.json"))["time"] > t0:
            break
        time.sleep(0.02)
    else:
        pytest.fail("heartbeat thread never rewrote health.json")
    hb.stop()


def test_load_health_roundtrip_and_missing(tmp_path):
    assert trace.load_health(str(tmp_path)) is None
    hb = trace.Heartbeat(str(tmp_path), trace.RunClock(), interval=30.0)
    hb.beat(3, 0.1)
    hb.stop()
    health = trace.load_health(str(tmp_path))
    assert health["last_step"] == 3
    assert "clock" in health  # the RunClock resume seed


# ---- trainer end-to-end ----------------------------------------------------

@pytest.mark.slow
def test_trainer_emits_observability_surface(tmp_path, devices):
    """The acceptance contract: a toy run writes nested spans, goodput +
    device_peak_bytes on every metrics line, and a live health.json whose
    bucket sum matches wall-clock (tools/goodput_report.py checks the 5%)."""
    from llama_pipeline_parallel_tpu.train import run_training

    out = tmp_path / "run"
    run_training({
        "output_dir": str(out),
        "mesh": {"pp": 2, "dp": 2},
        "model": {"preset": "tiny", "dtype": "float32"},
        "dataset": {"synthetic": True, "seq_length": 16,
                    "pseudo_dataset_len": 128},
        "seed": 7, "per_device_train_batch_size": 2,
        "gradient_accumulation_steps": 2, "max_steps": 4,
        "learning_rate": 1e-3, "warmup_steps": 1, "logging_steps": 2,
        "save_steps": 0, "save_final": True,
    })

    spans = [json.loads(l) for l in open(out / "spans.jsonl")]
    names = {s["name"] for s in spans}
    assert {"init", "compile_block", "data_wait", "step_dispatch",
            "device_step", "ckpt_save"} <= names

    for line in [json.loads(l) for l in open(out / "metrics.jsonl")]:
        assert 0.0 <= line["goodput"] <= 1.0
        assert line["device_peak_bytes"] > 0
        assert line["bubble_fraction"] == pytest.approx(2 / 4)  # S=2, M=2

    health = json.load(open(out / "health.json"))
    assert health["last_step"] == 4
    buckets = health["clock"]["buckets"]
    assert sum(buckets.values()) == pytest.approx(health["clock"]["elapsed"],
                                                  rel=0.05)

    import goodput_report  # tools/ on sys.path via conftest

    rep = goodput_report.build_report(str(out))
    assert sum(rep["buckets"].values()) == pytest.approx(rep["wall_seconds"],
                                                         rel=0.05)

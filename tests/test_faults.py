"""Chaos suite: deterministic fault injection exercising every recovery path.

Fast lane (tier-1, CI): plan/rule semantics, retried storage + dataset-read
faults, barrier failure reporting, checkpoint integrity (flipped byte ->
quarantine -> fallback), and the trainer's resume-past-corruption path.
Slow lane (round gate): the full kill-mid-async-save chaos run under
tools/supervisor.py, resumed to loss parity with an unfaulted run.
"""

import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.ckpt.checkpoint import (
    CheckpointCorruptError,
    CheckpointManager,
    find_resume_checkpoint,
)
from llama_pipeline_parallel_tpu.data.loader import DataLoader
from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.parallel import distributed as dist
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.utils import faults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fast_retries_then_clean_plan(monkeypatch):
    """Millisecond backoffs for every retried path, and no fault plan can
    leak into the next test (the injector is process-global)."""
    monkeypatch.setenv("LPT_RETRY_BASE_DELAY_S", "0.001")
    monkeypatch.setenv("LPT_RETRY_MAX_DELAY_S", "0.01")
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    yield
    faults.configure(None)


# ---------------------------------------------------------------------------
# plan + rule semantics
# ---------------------------------------------------------------------------

def test_plan_validation_rejects_typos():
    with pytest.raises(faults.FaultPlanError, match="unknown site"):
        faults.FaultInjector({"faults": [{"site": "nope", "op": "error"}]})
    with pytest.raises(faults.FaultPlanError, match="unknown op"):
        faults.FaultInjector({"faults": [{"site": "step", "op": "explode"}]})
    with pytest.raises(faults.FaultPlanError, match="unknown keys"):
        faults.FaultInjector({"faults": [{"site": "step", "op": "die",
                                          "atstep": 3}]})
    with pytest.raises(faults.FaultPlanError, match="missing"):
        faults.FaultInjector({"faults": [{"op": "error"}]})


def test_match_after_times_every_semantics():
    inj = faults.FaultInjector({"faults": [
        {"site": "data_read", "op": "error", "match": "idx-1", "after": 1,
         "times": 2}]})
    fired = []
    for i in range(8):
        try:
            inj.fire("data_read", tag="idx-1")
        except faults.InjectedFault:
            fired.append(i)
    assert fired == [1, 2]  # skip 1, then fire at most 2 times
    assert inj.fire("data_read", tag="idx-2") is None  # no match, no count
    assert inj.stats()[0]["fired"] == 2

    inj = faults.FaultInjector({"faults": [
        {"site": "step", "op": "corrupt", "every": 3}]})
    got = [inj.fire("step", step=s) for s in range(7)]
    assert [g == "corrupt" for g in got] == [True, False, False] * 2 + [True]


def test_at_step_gates_on_step():
    inj = faults.FaultInjector({"faults": [
        {"site": "step", "op": "error", "at_step": 5}]})
    for s in (3, 4, 6):
        inj.fire("step", step=s)
    with pytest.raises(faults.InjectedFault):
        inj.fire("step", step=5)


def test_marker_fires_once_across_injector_rebuilds(tmp_path):
    """The cross-restart latch: a rebuilt injector (new process after a
    supervisor restart) must NOT re-fire a marker-latched rule."""
    marker = str(tmp_path / "fired.marker")
    plan = {"faults": [{"site": "step", "op": "error", "marker": marker}]}
    inj = faults.FaultInjector(plan)
    with pytest.raises(faults.InjectedFault):
        inj.fire("step", step=0)
    assert os.path.exists(marker)
    inj.fire("step", step=1)  # same injector: latched
    assert faults.FaultInjector(plan).fire("step", step=0) is None  # "restart"


def test_env_plan_inline_and_file(tmp_path, monkeypatch):
    plan = {"faults": [{"site": "step", "op": "corrupt"}]}
    monkeypatch.setenv(faults.ENV_PLAN, json.dumps(plan))
    assert faults.configure_from_env().fire("step") == "corrupt"

    path = tmp_path / "plan.json"
    path.write_text(json.dumps(plan))
    monkeypatch.setenv(faults.ENV_PLAN, f"@{path}")
    assert faults.configure_from_env().fire("step") == "corrupt"

    monkeypatch.setenv(faults.ENV_PLAN, "{not json")
    with pytest.raises(faults.FaultPlanError):
        faults.configure_from_env()

    monkeypatch.delenv(faults.ENV_PLAN)
    assert faults.configure_from_env() is None


def test_no_plan_is_free():
    faults.configure(None)
    assert faults.fire("step", step=3) is None


# ---------------------------------------------------------------------------
# dataset-read faults: the loader retries before killing training
# ---------------------------------------------------------------------------

def _int_loader(n=32, batch=4):
    return DataLoader(dataset=list(range(n)),
                      collate_fn=lambda rows: {"x": np.asarray(rows)},
                      per_replica_batch=batch, dp_size=1, seed=3)


def test_data_read_error_retries_no_lost_or_duplicated_rows():
    baseline = np.sort(np.concatenate(
        [b["x"] for b in _int_loader()]))
    faults.configure({"faults": [
        {"site": "data_read", "op": "error", "times": 3}]})
    got = np.sort(np.concatenate([b["x"] for b in _int_loader()]))
    np.testing.assert_array_equal(got, baseline)
    assert faults.active().stats()[0]["fired"] == 3


def test_corrupt_record_retries_to_a_clean_read():
    faults.configure({"faults": [
        {"site": "data_read", "op": "corrupt", "times": 1}]})
    batches = list(_int_loader(n=8, batch=4))
    assert sorted(np.concatenate([b["x"] for b in batches]).tolist()) == list(range(8))


def test_slow_record_only_delays():
    faults.configure({"faults": [
        {"site": "data_read", "op": "slow", "seconds": 0.02, "times": 1}]})
    t0 = time.perf_counter()
    batches = list(_int_loader(n=8, batch=4))
    assert len(batches) == 2 and time.perf_counter() - t0 >= 0.02


def test_read_failure_past_retry_budget_is_fatal(monkeypatch):
    monkeypatch.setenv("LPT_RETRY_MAX_ATTEMPTS", "2")
    faults.configure({"faults": [{"site": "data_read", "op": "error"}]})
    with pytest.raises(faults.InjectedFault):
        list(_int_loader(n=8, batch=4))


# ---------------------------------------------------------------------------
# barrier failures: tag + elapsed reporting, transient retry
# ---------------------------------------------------------------------------

def test_barrier_stall_fault_delays_single_process():
    faults.configure({"faults": [
        {"site": "barrier", "op": "stall", "seconds": 0.03, "match": "ckpt"}]})
    t0 = time.perf_counter()
    dist.host_barrier("ckpt-arrays-test")
    assert time.perf_counter() - t0 >= 0.03


def test_barrier_error_fault_retried_via_plan():
    """An op=error barrier rule is classified as a TRANSIENT barrier failure
    and retried — the plan mechanism exercises the same recovery path a real
    coordination-service blip takes, even single-process."""
    faults.configure({"faults": [
        {"site": "barrier", "op": "error", "times": 1}]})
    dist.host_barrier("sync-z")  # injected blip on attempt 1, clean retry
    assert faults.active().stats()[0]["fired"] == 1


def test_barrier_timeout_reports_tag_and_elapsed(monkeypatch):
    calls = []

    def sync(key, timeout_ms):
        calls.append(key)
        raise RuntimeError("deadline exceeded waiting for peers")

    monkeypatch.setattr(dist, "_barrier_sync_fn", lambda: sync)
    monkeypatch.setattr(dist.jax, "process_count", lambda: 2)
    monkeypatch.setenv("LPT_BARRIER_TIMEOUT_S", "123")
    with pytest.raises(dist.BarrierTimeoutError) as ei:
        dist.host_barrier("ckpt-commit-abc")
    msg = str(ei.value)
    assert "ckpt-commit-abc" in msg and "timeout_s=123" in msg and "after" in msg
    assert calls == ["ckpt-commit-abc"]  # timeouts are never retried


def test_barrier_transient_error_retries_with_fresh_keys(monkeypatch):
    calls = []

    def sync(key, timeout_ms):
        calls.append(key)
        if len(calls) < 3:
            raise RuntimeError("connection reset by peer")

    monkeypatch.setattr(dist, "_barrier_sync_fn", lambda: sync)
    monkeypatch.setattr(dist.jax, "process_count", lambda: 2)
    monkeypatch.setenv("LPT_BARRIER_RETRIES", "2")
    dist.host_barrier("sync-x")
    assert calls == ["sync-x", "sync-x~retry1", "sync-x~retry2"]


def test_barrier_retry_budget_is_bounded_by_default(monkeypatch):
    """An asymmetric one-process blip must not spin through the full shared
    retry budget: the default is ONE retry, then the error surfaces for the
    supervisor to handle."""
    calls = []

    def sync(key, timeout_ms):
        calls.append(key)
        raise RuntimeError("connection reset by peer")

    monkeypatch.setattr(dist, "_barrier_sync_fn", lambda: sync)
    monkeypatch.setattr(dist.jax, "process_count", lambda: 2)
    with pytest.raises(dist.TransientBarrierError):
        dist.host_barrier("sync-y")
    assert calls == ["sync-y", "sync-y~retry1"]


def test_barrier_timeout_resolution_order(monkeypatch):
    assert dist.barrier_timeout_s() == 1800.0
    dist.set_barrier_timeout(900)
    try:
        assert dist.barrier_timeout_s() == 900.0
        monkeypatch.setenv("LPT_BARRIER_TIMEOUT_S", "60")
        assert dist.barrier_timeout_s() == 60.0
    finally:
        dist.set_barrier_timeout(None)


# ---------------------------------------------------------------------------
# checkpoint integrity: digests, flipped bytes, quarantine, fallback
# ---------------------------------------------------------------------------

@pytest.fixture()
def ckpt_env(tmp_path):
    cfg = LlamaConfig.tiny()
    manifest = StageManifest.for_config(cfg, 1)
    stacked = pl.stack_stages(llama.init_params(jax.random.PRNGKey(0), cfg),
                              manifest)
    return CheckpointManager(str(tmp_path)), stacked, manifest, cfg


def _largest_array_file(root):
    """The biggest file under an item dir — array payload, not metadata."""
    best, best_size = None, -1
    for dirpath, _, files in os.walk(root):
        for name in files:
            full = os.path.join(dirpath, name)
            if os.path.getsize(full) > best_size:
                best, best_size = full, os.path.getsize(full)
    return best


def test_save_records_digests_and_verify_passes(ckpt_env):
    mgr, stacked, manifest, cfg = ckpt_env
    mgr.save(1, stacked, manifest, cfg)
    meta = mgr.load_meta(1)
    integ = meta["integrity"]
    assert integ["algo"] == "sha256" and integ["files"]
    assert "meta.json" not in integ["files"]
    mgr.verify(1)  # no raise


def test_storage_write_faults_are_retried(ckpt_env):
    mgr, stacked, manifest, cfg = ckpt_env
    faults.configure({"faults": [
        {"site": "storage_write", "op": "error", "match": "meta.json",
         "times": 2}]})
    mgr.save(1, stacked, manifest, cfg)
    assert mgr.latest_step() == 1
    mgr.verify(1)
    assert faults.active().stats()[0]["fired"] == 2


def test_flipped_byte_detected_quarantined_and_skipped(ckpt_env):
    """The acceptance criterion: one flipped byte in any array item is
    detected on restore, the checkpoint is quarantined, and latest_step()
    falls back to the previous complete checkpoint."""
    mgr, stacked, manifest, cfg = ckpt_env
    mgr.save(1, stacked, manifest, cfg)
    mgr.save(2, stacked, manifest, cfg)
    victim = _largest_array_file(os.path.join(mgr.step_dir(2), "params"))
    with open(victim, "r+b") as f:
        f.seek(os.path.getsize(victim) // 2)
        byte = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([byte[0] ^ 0xFF]))

    with pytest.raises(CheckpointCorruptError, match="sha256"):
        mgr.load_params(2, stacked, manifest)
    assert not os.path.isdir(mgr.step_dir(2))
    assert os.path.isdir(mgr.step_dir(2) + ".corrupt")
    assert mgr.latest_step() == 1
    assert find_resume_checkpoint(mgr.root)[0] == 1
    # the survivor still restores
    restored = mgr.load_params(1, stacked, manifest)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), restored, stacked)


def test_missing_item_file_is_corrupt(ckpt_env):
    mgr, stacked, manifest, cfg = ckpt_env
    mgr.save(1, stacked, manifest, cfg)
    mgr.save(3, stacked, manifest, cfg)
    os.remove(_largest_array_file(os.path.join(mgr.step_dir(3), "params")))
    with pytest.raises(CheckpointCorruptError, match="missing"):
        mgr.load_params(3, stacked, manifest)
    assert mgr.latest_step() == 1


def test_truncated_meta_quarantined_and_fallback(ckpt_env):
    mgr, stacked, manifest, cfg = ckpt_env
    mgr.save(1, stacked, manifest, cfg)
    mgr.save(2, stacked, manifest, cfg)
    meta = os.path.join(mgr.step_dir(2), "meta.json")
    with open(meta) as f:
        content = f.read()
    with open(meta, "w") as f:
        f.write(content[: len(content) // 2])  # torn write
    assert mgr.latest_step() == 1
    assert os.path.isdir(mgr.step_dir(2) + ".corrupt")


def test_digests_can_be_disabled(ckpt_env, monkeypatch):
    monkeypatch.setenv("LPT_CKPT_DIGESTS", "0")
    mgr, stacked, manifest, cfg = ckpt_env
    mgr.save(1, stacked, manifest, cfg)
    assert "integrity" not in mgr.load_meta(1)
    mgr.verify(1)  # pre-integrity format: verification is a no-op
    mgr.load_params(1, stacked, manifest)


def test_atomic_writes_leave_no_tmp_droppings(ckpt_env, monkeypatch):
    monkeypatch.setenv("LPT_RETRY_MAX_ATTEMPTS", "1")
    mgr, stacked, manifest, cfg = ckpt_env
    faults.configure({"faults": [
        {"site": "storage_write", "op": "error", "match": "meta.json"}]})
    with pytest.raises(faults.InjectedFault):
        mgr.save(1, stacked, manifest, cfg)
    faults.configure(None)
    assert not mgr.is_complete(1)  # arrays landed, no completeness marker
    droppings = [f for f in os.listdir(mgr.step_dir(1)) if ".tmp." in f]
    assert droppings == []
    assert mgr.latest_step() is None


# ---------------------------------------------------------------------------
# trainer resume falls back past a corrupt checkpoint (in-process, fast lane)
# ---------------------------------------------------------------------------

def _trainer_cfg(out, **kw):
    cfg = {
        "output_dir": str(out),
        "mesh": {"pp": 2, "dp": 2},
        "model": {"preset": "tiny", "dtype": "float32"},
        "dataset": {"synthetic": True, "seq_length": 16, "pseudo_dataset_len": 128},
        "seed": 7,
        "per_device_train_batch_size": 2,
        "gradient_accumulation_steps": 2,
        "max_steps": 2,
        "learning_rate": 1e-3,
        "warmup_steps": 1,
        "logging_steps": 1,
        "save_steps": 0,
        "save_final": True,
        "attention": "exact",
    }
    cfg.update(kw)
    return cfg


def test_run_training_resumes_past_corrupt_checkpoint(tmp_path, devices):
    """End-to-end fallback: the newest checkpoint has a flipped byte; the
    trainer quarantines it, resumes from the previous complete one, and
    still reaches end_step."""
    from llama_pipeline_parallel_tpu.train import run_training

    out = tmp_path / "out"
    run_training(_trainer_cfg(out, max_steps=2))         # writes checkpoint-2
    run_training(_trainer_cfg(out, max_steps=3))         # writes checkpoint-3
    victim = _largest_array_file(os.path.join(str(out), "checkpoint-3", "params"))
    with open(victim, "r+b") as f:
        f.seek(os.path.getsize(victim) // 2)
        byte = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([byte[0] ^ 0xFF]))

    summary = run_training(_trainer_cfg(out, max_steps=4))
    assert summary["final_step"] == 4
    assert os.path.isdir(os.path.join(str(out), "checkpoint-3.corrupt"))
    # the re-trained checkpoint-4 is complete and verifiable
    mgr = CheckpointManager(str(out))
    assert mgr.latest_step() == 4
    mgr.verify(4)


# ---------------------------------------------------------------------------
# the full chaos run: kill mid-async-save, supervised restart, clean resume
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_kill_mid_async_save_supervised_resume_loss_parity(tmp_path):
    """The acceptance chaos test: a fault plan SIGKILLs the trainer on the
    async commit thread AFTER the checkpoint-4 arrays land but BEFORE its
    meta/tag commit; tools/supervisor.py restarts it; the new incarnation
    quarantine-proofs its resume point (checkpoint-2, the previous VERIFIED
    checkpoint), fast-forwards the loader, and finishes — with the final
    loss bit-matching an unfaulted run (no duplicated or lost batches)."""
    out = str(tmp_path / "chaos")
    ref = str(tmp_path / "straight")
    env_base = {**os.environ,
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "LPT_RETRY_BASE_DELAY_S": "0.01"}

    def train_cmd(output_dir):
        return [sys.executable, "train.py", "--config", "conf/tiny_smoke.yaml",
                "--platform", "cpu", f"output_dir={output_dir}",
                "max_steps=6", "total_steps=6", "save_steps=2",
                "async_save=true", "logging_steps=1", "save_final=true",
                "attention=exact"]

    plan = {"faults": [{"site": "ckpt_commit", "op": "die", "after": 1,
                        "marker": os.path.join(out, "fault.fired")}]}
    sup = subprocess.run(
        [sys.executable, "tools/supervisor.py", "--output-dir", out,
         "--max-restarts", "2", "--hang-timeout-s", "600",
         "--poll-s", "0.2", "--"] + train_cmd(out),
        cwd=_REPO, env={**env_base, faults.ENV_PLAN: json.dumps(plan)},
        capture_output=True, text=True, timeout=540)
    assert sup.returncode == 0, f"supervisor failed:\n{sup.stdout[-3000:]}\n{sup.stderr[-3000:]}"

    ledger = [json.loads(l) for l in open(os.path.join(out, "incarnations.jsonl"))]
    assert [r["outcome"] for r in ledger] == ["crash", "clean"]
    assert os.path.exists(os.path.join(out, "fault.fired"))
    # the killed incarnation left checkpoint-4 incomplete; the resumed one
    # rewrote it and finished at checkpoint-6, all verified
    mgr = CheckpointManager(out)
    assert mgr.latest_step() == 6
    mgr.verify(6)
    meta = mgr.load_meta(6)
    assert meta["step"] == 6 and meta["has_optimizer_state"]

    straight = subprocess.run(train_cmd(ref), cwd=_REPO, env=env_base,
                              capture_output=True, text=True, timeout=360)
    assert straight.returncode == 0, straight.stdout[-3000:]

    def last_loss(d):
        lines = [json.loads(l) for l in open(os.path.join(d, "metrics.jsonl"))]
        return [l["loss"] for l in lines if "loss" in l][-1]

    # loss parity at the final step proves the resumed incarnation saw the
    # exact batch stream an uninterrupted run sees (no dup/lost batches)
    np.testing.assert_allclose(last_loss(out), last_loss(ref), rtol=1e-6)

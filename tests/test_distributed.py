"""Single-process paths of the multi-host helpers."""

import numpy as np

from llama_pipeline_parallel_tpu.parallel import distributed as dist
from llama_pipeline_parallel_tpu.parallel.distributed import (
    barrier,
    form_global_batch,
    host_dp_shard,
    initialize_distributed,
)
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh


def test_initialize_and_barrier_noops_single_process(devices, monkeypatch):
    for env in dist._COORDINATOR_ENVS:
        monkeypatch.delenv(env, raising=False)
    monkeypatch.setattr(dist, "_initialized", False)
    initialize_distributed()  # no coordinator configured -> no-op
    initialize_distributed()  # idempotent
    barrier("test")  # single-process -> immediate


def test_host_dp_shard_single_process(devices):
    mesh = make_mesh(MeshConfig(pp=2, dp=4))
    assert host_dp_shard(mesh) == (0, 4)


def test_form_global_batch_places_dp_sharded(devices):
    mesh = make_mesh(MeshConfig(dp=4, pp=2))
    batch = {"input_ids": np.arange(32).reshape(8, 4).astype(np.int32)}
    out = form_global_batch(mesh, batch)
    arr = out["input_ids"]
    assert arr.shape == (8, 4)
    spec = arr.sharding.spec
    assert tuple(spec)[0] == "dp"
    np.testing.assert_array_equal(np.asarray(arr), batch["input_ids"])


def test_form_global_batch_shards_sequence_over_sp(devices):
    """With an sp axis the sequence dim is sharded too: each device holds a
    [rows/dp, seq/sp] slab of the right slice, and values round-trip."""
    mesh = make_mesh(MeshConfig(dp=2, sp=4))
    batch = {"input_ids": np.arange(64).reshape(4, 16).astype(np.int32)}
    arr = form_global_batch(mesh, batch)["input_ids"]
    assert tuple(arr.sharding.spec) == ("dp", "sp")
    np.testing.assert_array_equal(np.asarray(arr), batch["input_ids"])
    for shard in arr.addressable_shards:
        assert shard.data.shape == (2, 4)  # 4/dp rows x 16/sp columns
        np.testing.assert_array_equal(np.asarray(shard.data),
                                      batch["input_ids"][shard.index])

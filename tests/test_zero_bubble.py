"""Zero-bubble (zb1) schedule correctness: the split B/W backward.

The CI `schedule-parity` gate's zb1 lane: the decomposed backward — B
(input-grad only) units on the critical-path tick clock, W (weight-grad
only) units replayed from stashed residuals in the collective-free fourth
phase — must match the flat 1f1b schedule BIT-exactly on the parity grid
(the decomposition changes when weight grads materialize, never what is
summed; docs/SCHEDULES.md "Zero-bubble 1F1B"). Plus: the analytic
`bubble_fraction` derivation at the 65B shape and the
zb1 <= interleaved <= flat ordering across the degenerate grid, the
W-queue/stash accounting preflight consumes, checkpoint restores across
schedules in both directions, [S, v] activation stats, the eval path, the
trainer/offload plumbing with the new metrics/health keys, and every new
validation error."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny(num_hidden_layers=8)  # 8 layers: pp*v up to 8


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.PRNGKey(0), cfg)


def make_batch(cfg, batch_size=8, seqlen=16, seed=42):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, cfg.vocab_size, size=(batch_size, seqlen)).astype(np.int32)
    mask = np.ones((batch_size, seqlen), np.int32)
    mask[:, -3:] = 0
    labels = ids.copy()
    labels[mask == 0] = llama.IGNORE_INDEX
    labels[:, :2] = llama.IGNORE_INDEX
    pos = np.broadcast_to(np.arange(seqlen, dtype=np.int32), (batch_size, seqlen)).copy()
    return {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.asarray(mask),
        "position_ids": jnp.asarray(pos),
        "labels": jnp.asarray(labels),
    }


def run_schedule(params, batch, cfg, pp, schedule, v=1, dp=1, tp=1, sp=1,
                 microbatches=4, chunks=1, collect_stats=False):
    mesh = make_mesh(MeshConfig(pp=pp, dp=dp, tp=tp, sp=sp))
    manifest = StageManifest.for_config(cfg, pp, virtual_stages=v)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=pp, num_microbatches=microbatches,
                             schedule=schedule, virtual_stages=v,
                             accum_chunks=chunks)
    fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked,
                                                collect_stats=collect_stats))
    out = fn(stacked, batch)
    loss, grads = out[0], pl.unstack_stages(out[1], manifest)
    return (loss, grads, out[2]) if collect_stats else (loss, grads, None)


def assert_tree_bitexact(a, b):
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), a, b)


# ---------------------------------------------------------------------------
# Schedule parity: zb1 == flat == interleaved, bit for bit
# ---------------------------------------------------------------------------

# The fast lane keeps one case per structural regime (flat form, chunked
# form, M < S masking) to fit the tier-1 time budget; the rest of the grid
# is slow-marked and runs in the round gate.
@pytest.mark.parametrize("pp,v,microbatches", [
    # flat zero-bubble (v=1): slow since PR 11 — the v1 split form shares
    # the interpreter's segment machinery with the fast (4,1,2) M<S row,
    # and the solver lane (test_unit_schedule.py) took its fast-lane slot
    pytest.param(2, 1, 4, marks=pytest.mark.slow),
    (2, 2, 4),                  # the dryrun_multichip acceptance grid
    pytest.param(4, 2, 4, marks=pytest.mark.slow),
    pytest.param(2, 4, 4, marks=pytest.mark.slow),   # deeper interleaving
    # M < S masking: slow since PR 17 (actuation rebalance) — the regime
    # keeps a fast rep in test_interleaved_v1_degenerates_to_flat[4-2]
    # through the same unit interpreter; the zb1-specific B/W split stays
    # gated fast by the (2, 2, 4) row above
    pytest.param(4, 1, 2, marks=pytest.mark.slow),
    pytest.param(4, 1, 1, marks=pytest.mark.slow),   # M == 1
    pytest.param(4, 2, 8, marks=pytest.mark.slow),
])
def test_zb1_matches_flat_bitexact(cfg, params, devices, pp, v, microbatches):
    """Loss AND unstacked gradients identical to the flat fused-backward
    schedule: every B unit's dx and every W unit's dparams replay the same
    chunk recompute + cotangent chain the fused vjp ran, and the W drain
    folds in the fused backward's unit order — the only difference is WHEN
    dparams materialize."""
    batch = make_batch(cfg, batch_size=max(microbatches * 2, 2))
    l_flat, g_flat, _ = run_schedule(params, batch, cfg, pp, "1f1b",
                                     microbatches=microbatches)
    l_zb, g_zb, _ = run_schedule(params, batch, cfg, pp, "zb1", v=v,
                                 microbatches=microbatches)
    assert float(l_zb) == float(l_flat)
    assert_tree_bitexact(g_zb, g_flat)


@pytest.mark.slow
def test_zb1_matches_interleaved_bitexact(cfg, params, devices):
    """zb1 is the interleaved tick clock with the backward split — at the
    same (pp, v, m) the two must agree bit-for-bit, not just via flat."""
    batch = make_batch(cfg)
    l_int, g_int, _ = run_schedule(params, batch, cfg, 2, "interleaved_1f1b",
                                   v=2)
    l_zb, g_zb, _ = run_schedule(params, batch, cfg, 2, "zb1", v=2)
    assert float(l_zb) == float(l_int)
    assert_tree_bitexact(g_zb, g_int)


@pytest.mark.parametrize("dp,tp,sp,chunks", [
    pytest.param(2, 1, 1, 1, marks=pytest.mark.slow),
    (1, 2, 1, 1),   # tp fast: the split head's vocab-parallel grads are
                    # the hybrid most likely to break independently
    pytest.param(1, 1, 2, 1, marks=pytest.mark.slow),
    pytest.param(1, 1, 1, 2, marks=pytest.mark.slow),
])
def test_zb1_hybrid_grids_bitexact(cfg, params, devices, dp, tp, sp, chunks):
    """The split backward composes with dp/tp/sp sharding and chunked
    accumulation without losing the bit-exact flat equivalence — the W
    replay re-runs the SAME stage-uniform tp/sp collectives the fused
    backward ran (they sit inside chunk_fwd, shared by both paths)."""
    m = 4
    batch = make_batch(cfg, batch_size=dp * m * 2)
    l_flat, g_flat, _ = run_schedule(params, batch, cfg, 2, "1f1b", dp=dp,
                                     tp=tp, sp=sp, microbatches=m, chunks=chunks)
    l_zb, g_zb, _ = run_schedule(params, batch, cfg, 2, "zb1", v=2, dp=dp,
                                 tp=tp, sp=sp, microbatches=m, chunks=chunks)
    assert float(l_zb) == float(l_flat)
    assert_tree_bitexact(g_zb, g_flat)


@pytest.mark.slow
def test_zb1_matches_single_device_reference(cfg, params, devices):
    """And pinned to the plain unpipelined forward, so the zb1 grads are
    the true ones, not merely self-consistent."""
    batch = make_batch(cfg)

    def loss(p):
        logits = llama.forward(p, batch["input_ids"], batch["attention_mask"],
                               batch["position_ids"], cfg=cfg)
        return llama.loss_fn(logits, batch["labels"])

    ref_loss, ref_grads = jax.value_and_grad(loss)(params)
    l_zb, g_zb, _ = run_schedule(params, batch, cfg, 4, "zb1", v=2,
                                 microbatches=4)
    np.testing.assert_allclose(float(l_zb), float(ref_loss), rtol=1e-5)
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), rtol=2e-5, atol=1e-6), g_zb, ref_grads)


@pytest.mark.slow  # PR 11: eval is the untouched forward-only loop (not
# the unit interpreter); the interleaved eval rep stays fast
def test_zb1_eval_matches(cfg, params, devices):
    """make_pipeline_eval_fn under a zb1 pcfg (the forward-only loop walks
    the same v*S virtual ring; B/W only exist in training)."""
    batch = make_batch(cfg)
    mesh = make_mesh(MeshConfig(pp=2))
    manifest = StageManifest.for_config(cfg, 2, virtual_stages=2)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=4,
                             schedule="zb1", virtual_stages=2)
    loss_sum, count = jax.jit(pl.make_pipeline_eval_fn(
        mesh, cfg, pcfg, stacked))(stacked, batch)
    l_flat, _, _ = run_schedule(params, batch, cfg, 2, "1f1b")
    np.testing.assert_allclose(float(loss_sum) / float(count), float(l_flat),
                               rtol=1e-6)


@pytest.mark.slow  # round gate; the trainer e2e below keeps the flat->zb1
# restore direction in the fast lane, and test_interleaved.py keeps the
# manager-level v2<->flat mechanics there too
def test_zb1_checkpoint_roundtrips_across_schedules(cfg, params, tmp_path,
                                                    devices):
    """A checkpoint written under the zb1 (chunked) layout restores into the
    flat layout and vice versa, unchanged: the canonical [num_layers, ...]
    on-disk layout is the interchange — PR-2/PR-5 checkpoints restore into
    the new schedule with no migration, in both directions."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager

    man_zb = StageManifest.for_config(cfg, 2, virtual_stages=2)  # zb1 v=2
    man_f = StageManifest.for_config(cfg, 4)                     # flat pp=4
    stacked_zb = pl.stack_stages(params, man_zb)
    stacked_f = pl.stack_stages(params, man_f)

    # zb1 -> flat
    mgr = CheckpointManager(str(tmp_path / "from_zb1"))
    mgr.save(3, stacked_zb, man_zb, cfg)
    restored_f = mgr.load_params(3, stacked_f, man_f)
    assert_tree_bitexact(pl.unstack_stages(restored_f, man_f), params)
    # flat -> zb1
    mgr2 = CheckpointManager(str(tmp_path / "from_flat"))
    mgr2.save(5, stacked_f, man_f, cfg)
    restored_zb = mgr2.load_params(5, stacked_zb, man_zb)
    assert_tree_bitexact(restored_zb, stacked_zb)


# ---------------------------------------------------------------------------
# Stats: [S, v] activation reductions under the split backward
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_zb1_collect_stats_shapes(cfg, params, devices):
    """Per-stage numerics telemetry resolves under zb1: the B ticks fold
    the same chunk-boundary activation stats the fused backward folded, so
    [S, v] and [S] keys exist, finite and positive — and match the
    interleaved schedule's EXACTLY (same primals, same fold order).
    Slow-marked (PR 10 rebalance): the interleaved stats test is the fast
    [S, v]-stats gate, and zb1 rides the identical fold path it pins."""
    batch = make_batch(cfg)
    _, _, stats = run_schedule(params, batch, cfg, 2, "zb1", v=2,
                               collect_stats=True)
    assert np.asarray(stats["act_absmax_per_chunk"]).shape == (2, 2)
    assert np.asarray(stats["act_rms_per_chunk"]).shape == (2, 2)
    assert np.asarray(stats["act_absmax_per_stage"]).shape == (2,)
    assert np.asarray(stats["act_rms_per_stage"]).shape == (2,)
    for val in stats.values():
        assert np.all(np.isfinite(np.asarray(val)))
        assert np.all(np.asarray(val) > 0)
    _, _, stats_int = run_schedule(params, batch, cfg, 2, "interleaved_1f1b",
                                   v=2, collect_stats=True)
    assert_tree_bitexact(stats, stats_int)


@pytest.mark.slow
def test_zb1_collect_stats_v1(cfg, params, devices):
    """The v=1 (flat zero-bubble) degenerate still emits the chunked stat
    keys, with the chunk axis of size 1 agreeing with the per-stage view."""
    _, _, stats = run_schedule(params, make_batch(cfg), cfg, 2, "zb1", v=1,
                               collect_stats=True)
    assert np.asarray(stats["act_absmax_per_chunk"]).shape == (2, 1)
    np.testing.assert_allclose(
        np.asarray(stats["act_absmax_per_stage"]),
        np.asarray(stats["act_absmax_per_chunk"])[:, 0], rtol=1e-6)


# ---------------------------------------------------------------------------
# bubble_fraction: the zb1 derivation + the full schedule ordering
# ---------------------------------------------------------------------------

def _pcfg(schedule, s, m, c=1, v=1):
    return pl.PipelineConfig(num_stages=s, num_microbatches=m, accum_chunks=c,
                             schedule=schedule, virtual_stages=v)


def test_bubble_fraction_zb1_derivation_at_65b_shape():
    """Pin the derivation at the config-of-record shape (S=8, M=256, v=2,
    c=1), in unit terms with F = B = W = 1 (docs/SCHEDULES.md):

        warmup   vS-1 = 15 ticks x {F}      =   15 units
        steady   Mv+S-vS = 504 ticks x {F,B} = 1008 units
        drain    vS-1 = 15 ticks x {B}      =   15 units
        w-drain  Mv = 512 ticks x {W}       =  512 units
        total 1550 units, useful 3*Mv = 1536
        -> bubble = 2(S-1) / (3Mv + 2(S-1)) = 14/1550

    strictly below interleaved's 7/519 (~1.35%) and flat's 14/270 (5.19%)
    — the acceptance number of this PR."""
    zb = pl.bubble_fraction(_pcfg("zb1", 8, 256, v=2))
    inter = pl.bubble_fraction(_pcfg("interleaved_1f1b", 8, 256, v=2))
    flat = pl.bubble_fraction(_pcfg("1f1b", 8, 256))
    assert zb == pytest.approx(14 / 1550)
    assert inter == pytest.approx(7 / 519)
    assert flat == pytest.approx(14 / 270)
    assert zb < inter < flat


@pytest.mark.parametrize("s,m,c,v,expected", [
    # zb1: 2c(S-1) / (3Mv + 2c(S-1))
    (4, 8, 1, 2, 6 / 54),
    (8, 256, 1, 2, 14 / 1550),
    (4, 8, 2, 2, 12 / 60),
    (4, 8, 1, 1, 6 / 30),          # flat zero-bubble form
    (2, 4, 2, 2, 4 / 28),          # m per flush == accum chunks degenerate
    (4, 2, 1, 1, 6 / 12),          # M < S: fill dominates
    (1, 8, 1, 4, 0.0),             # S=1: no pipeline, no bubble
    (1, 8, 8, 1, 0.0),
])
def test_bubble_fraction_zb1_grid(s, m, c, v, expected):
    assert pl.bubble_fraction(_pcfg("zb1", s, m, c, v)) == pytest.approx(expected)


def test_bubble_fraction_ordering_zb1_interleaved_flat():
    """zb1 <= interleaved <= flat at EVERY grid point — including S=1,
    M < S, and m == accum_chunks degenerates (strict once S > 1)."""
    grid = [(s, m, c, v)
            for s in (1, 2, 4, 8)
            for m in (1, 2, 4, 8, 256)
            for c in (1, 2, m)
            for v in (1, 2, 4)
            # valid PipelineConfigs only: c | m, and v > 1 needs the
            # round-robin constraint (m per flush divisible by S)
            if m % c == 0 and (v == 1 or (m // c) % s == 0)]
    assert len(grid) > 60        # S=1, M<S, m==c degenerates all present
    assert any(m < s for s, m, c, v in grid)
    assert any(m == c and m > 1 for s, m, c, v in grid)
    for s, m, c, v in grid:
        zb = pl.bubble_fraction(_pcfg("zb1", s, m, c, v))
        inter = pl.bubble_fraction(_pcfg("interleaved_1f1b", s, m, c, v))
        flat = pl.bubble_fraction(_pcfg("1f1b", s, m, c))
        if s == 1:
            assert zb == inter == flat == 0.0
        else:
            assert zb < inter, (s, m, c, v, zb, inter)
            assert inter <= flat, (s, m, c, v, inter, flat)
            # interleaved < flat needs v > 1 OR the warmup/drain pairing;
            # both formulas agree only in the no-pipeline limit
            assert 0.0 < zb < 1.0


# ---------------------------------------------------------------------------
# W-queue / stash accounting (the preflight memory-model term)
# ---------------------------------------------------------------------------

def test_wgrad_queue_peak_and_stash_bytes():
    # fused-backward schedules queue nothing
    assert pl.wgrad_queue_peak(_pcfg("1f1b", 8, 256)) == 0
    assert pl.wgrad_queue_peak(_pcfg("interleaved_1f1b", 8, 256, v=2)) == 0
    # zb1: Mv / accum_chunks per-flush units
    assert pl.wgrad_queue_peak(_pcfg("zb1", 8, 256, v=2)) == 512
    assert pl.wgrad_queue_peak(_pcfg("zb1", 8, 256, c=4, v=2)) == 128
    assert pl.wgrad_queue_peak(_pcfg("zb1", 2, 4, v=1)) == 4
    # stash = 2 residuals x queue x [mb, L, d] x dtype: the 65B shape's
    # 64 GiB (mb=8, seq 512, d 8192, bf16) — the number the config's
    # header and docs/SCHEDULES.md quote
    stash = pl.wgrad_stash_bytes(_pcfg("zb1", 8, 256, v=2), mb_rows=8,
                                 local_seqlen=512, hidden_size=8192,
                                 dtype_bytes=2)
    assert stash == 2 * 512 * 8 * 512 * 8192 * 2
    assert round(stash / (1 << 30)) == 64
    assert pl.wgrad_stash_bytes(_pcfg("1f1b", 8, 256), 8, 512, 8192) == 0


def test_preflight_resume_block_names_schedule_change(tmp_path):
    """The elastic-resume preflight names a schedule change like it names
    topology changes: restoring a flat-schedule checkpoint into a zb1
    config reports `schedule_changed` with both names."""
    import preflight  # tools/ on sys.path via conftest

    ckpt = tmp_path / "out" / "checkpoint-7"
    ckpt.mkdir(parents=True)
    (ckpt / "meta.json").write_text(json.dumps({
        "topology": {"pp": 2, "dp": 2, "tp": 1, "sp": 1, "layout": "pp2xdp2",
                     "schedule": "1f1b", "virtual_stages": 1,
                     "process_count": 1}}))
    report = preflight.resume_compat({
        "output_dir": str(tmp_path / "out"),
        "mesh": {"pp": 2, "dp": 2},
        "pipeline_schedule": "zb1", "virtual_stages": 2})
    assert report["resume_step"] == 7
    assert "schedule" in report["topology_changed"]
    assert "1f1b -> zb1" in report["schedule_changed"]


@pytest.mark.slow
def test_preflight_reports_wgrad_stash_for_zb1():
    """tools/preflight.py compiles a zb1 config (the conf-sweep contract for
    conf/llama_65b_pp8_zb1_tp2_dp2.yaml at tiny scale) and reports the
    W-stash term; on a blown budget the FAIL message names the
    accum_chunks dial — the actionable rejection the acceptance requires."""
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run(*args):
        return subprocess.run(
            [_sys.executable, os.path.join(repo, "tools", "preflight.py"),
             *args], capture_output=True, text=True, cwd=repo, timeout=600,
            env={**os.environ, "PYTHONPATH": repo})

    ok = run("--config", "conf/tiny_smoke.yaml", "pipeline_schedule=zb1",
             "virtual_stages=2")
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "schedule: zb1" in ok.stdout
    assert "wgrad_queue_depth: 4" in ok.stdout  # M=2 microbatches x v=2
    assert "wgrad_stash_gib" in ok.stdout

    fail = run("--config", "conf/tiny_smoke.yaml", "pipeline_schedule=zb1",
               "virtual_stages=2", "--hbm-gb", "0.000001")
    assert fail.returncode == 1
    assert "preflight FAIL" in fail.stdout
    assert "gradient_accumulation_chunks" in fail.stdout
    assert "interleaved_1f1b" in fail.stdout


# ---------------------------------------------------------------------------
# Validation
# ---------------------------------------------------------------------------

def test_zb1_accepts_virtual_stages():
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=4,
                             schedule="zb1", virtual_stages=2)
    assert pcfg.virtual_stages == 2


def test_zb1_requires_divisible_microbatches():
    with pytest.raises(ValueError, match="divisible by num_stages"):
        pl.PipelineConfig(num_stages=4, num_microbatches=6,
                          schedule="zb1", virtual_stages=2)
    with pytest.raises(ValueError, match="divisible by num_stages"):
        pl.PipelineConfig(num_stages=4, num_microbatches=8, accum_chunks=4,
                          schedule="zb1", virtual_stages=2)


def test_zb1_uneven_partition_needs_v1():
    """Since the auto-layout PR, zb1 at v=1 RUNS unequal partitions
    through the unit interpreter (tests/test_uneven_stages.py has the
    parity grid); the round-robin chunk layout (any v>1) still has no
    uneven form and keeps the rejection."""
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=4,
                             schedule="zb1", layer_counts=(5, 3))
    assert pcfg.layer_counts == (5, 3)
    with pytest.raises(ValueError, match="no uneven form"):
        pl.PipelineConfig(num_stages=2, num_microbatches=4,
                          schedule="zb1", virtual_stages=2,
                          layer_counts=(5, 3))


def test_zb1_layout_schedule_mismatch_fails_at_build(cfg, params, devices):
    mesh = make_mesh(MeshConfig(pp=2))
    flat = pl.stack_stages(params, StageManifest.for_config(cfg, 2))
    pcfg_zb = pl.PipelineConfig(num_stages=2, num_microbatches=4,
                                schedule="zb1", virtual_stages=2)
    with pytest.raises(ValueError, match="stack_stages"):
        pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg_zb, flat)


def test_trainer_accepts_zb1_virtual_stages(cfg):
    from llama_pipeline_parallel_tpu.train import build_manifest

    man = build_manifest({"virtual_stages": 2, "pipeline_schedule": "zb1"},
                         cfg, 2)
    assert man.virtual_stages == 2
    with pytest.raises(ValueError, match="interleaved_1f1b, zb1, or solver"):
        build_manifest({"virtual_stages": 2, "pipeline_schedule": "1f1b"},
                       cfg, 2)


# ---------------------------------------------------------------------------
# Full-trainer plumbing (the CI schedule-parity gate's artifact producer)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # PR 14 rebalance: the Observatory suite's timeline e2e
# drives run_training over the SAME zb1-v2 interpreter path every fast run
# (tests/test_timeline.py::test_trainer_timeline_e2e, with metrics/health
# assertions on top); the zb1 parity reps above stay fast
def test_trainer_zb1_end_to_end(tmp_path, devices):
    """run_training with schedule: zb1 + virtual_stages: 2 — the metrics
    line carries schedule/bubble_fraction/wgrad_queue_depth, health.json
    carries the queue depth + the zb1 topology, numerics.jsonl resolves
    activations per [S, v] chunk, and the final loss matches the flat
    schedule bit-for-bit.

    Both runs warm-start from ONE canonical-layout checkpoint (the PR-2
    format, written with a flat manifest and restored into both layouts —
    the flat->zb1 restore direction through the trainer), because fresh
    `init_params_sharded` RNG draws are sharding-layout-dependent (the
    pre-existing partitioned-threefry quirk, see test_interleaved.py)."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.train import run_training

    model_cfg = LlamaConfig.tiny(dtype=jnp.float32)
    man = StageManifest.for_config(model_cfg, 2)
    warm_dir = str(tmp_path / "warm")
    CheckpointManager(warm_dir).save(
        0, pl.stack_stages(llama.init_params(jax.random.PRNGKey(7), model_cfg),
                           man), man, model_cfg)

    def cfg_for(out, **kw):
        base = {
            "output_dir": str(tmp_path / out),
            "mesh": {"pp": 2, "dp": 2},
            "model": {"preset": "tiny", "dtype": "float32"},
            "model_name_or_path": warm_dir,
            "dataset": {"synthetic": True, "seq_length": 16,
                        "pseudo_dataset_len": 128},
            "seed": 7,
            "per_device_train_batch_size": 2,
            "gradient_accumulation_steps": 2,
            "max_steps": 3,
            "learning_rate": 1e-3,
            "warmup_steps": 1,
            "logging_steps": 1,
            "save_steps": 0,
            "save_final": False,
        }
        base.update(kw)
        return base

    flat = run_training(cfg_for("flat"))
    zb = run_training(cfg_for("zb", pipeline_schedule="zb1",
                              virtual_stages=2))
    assert zb["final_loss"] == flat["final_loss"]

    lines = [json.loads(l) for l in
             open(os.path.join(str(tmp_path / "zb"), "metrics.jsonl"))]
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=2,
                             schedule="zb1", virtual_stages=2)
    assert lines[0]["schedule"] == "zb1"
    assert lines[0]["wgrad_queue_depth"] == pl.wgrad_queue_peak(pcfg) == 4
    assert lines[0]["bubble_fraction"] == round(pl.bubble_fraction(pcfg), 4)
    flat_lines = [json.loads(l) for l in
                  open(os.path.join(str(tmp_path / "flat"), "metrics.jsonl"))]
    assert flat_lines[0]["schedule"] == "1f1b"
    assert "wgrad_queue_depth" not in flat_lines[0]  # no always-zero column
    assert lines[0]["bubble_fraction"] < flat_lines[0]["bubble_fraction"]

    health = json.load(open(os.path.join(str(tmp_path / "zb"), "health.json")))
    assert health["topology"]["schedule"] == "zb1"
    assert health["wgrad_queue_depth"] == 4

    nrec = [json.loads(l) for l in
            open(os.path.join(str(tmp_path / "zb"), "numerics.jsonl"))]
    per_chunk = np.asarray(nrec[0]["act_rms_per_chunk"])
    assert per_chunk.shape == (2, 2) and np.all(per_chunk > 0)


@pytest.mark.slow
def test_trainer_zb1_offload_zero2(tmp_path, devices):
    """The zb1 run-of-record combination (conf/llama_65b_pp8_zb1_tp2_dp2
    .yaml at tiny scale): the split backward under the ZeRO-2
    host-offloaded optimizer — the W-drain's incremental grad folds must
    stream through dp-sharded grad outputs and host masters unchanged."""
    from llama_pipeline_parallel_tpu.train import run_training

    summary = run_training({
        "output_dir": str(tmp_path / "out"),
        "mesh": {"pp": 2, "dp": 2},
        "model": {"preset": "tiny", "dtype": "float32"},
        "dataset": {"synthetic": True, "seq_length": 16,
                    "pseudo_dataset_len": 128},
        "seed": 7,
        "per_device_train_batch_size": 2,
        "gradient_accumulation_steps": 2,
        "pipeline_schedule": "zb1",
        "virtual_stages": 2,
        "optimizer_offload": True,
        "optimizer_offload_zero2": True,
        "max_steps": 2,
        "learning_rate": 1e-3,
        "warmup_steps": 1,
        "logging_steps": 1,
        "save_steps": 0,
        "save_final": True,
    })
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_loss"])
    meta = json.load(open(os.path.join(str(tmp_path / "out"),
                                       "checkpoint-2", "meta.json")))
    assert meta["manifest"]["virtual_stages"] == 2
    assert meta["topology"]["schedule"] == "zb1"

"""The perf ledger + calibration loop (utils/perf.py,
tools/perf_report.py, preflight --calibration —
docs/OBSERVABILITY.md "Perf ledger & calibration").

Pins: the bench-summary -> rows conversion (model-vs-measured pairs,
probe-failure rounds as reason-tagged rows, the repo's own BENCH_r0*
history summarizing as "N rounds unreachable"); the reader's
degrade-don't-traceback contract; the report CLI (table + failure
summary + --emit-calibration); and the acceptance pin — a calibration
file distilled from a measured starved host link makes
`preflight --select --calibration` re-rank the schedule frontier away
from the offload winner the uncalibrated CLI defaults pick."""

import argparse
import json

import pytest

import perf_report  # tools/ on sys.path via conftest
import preflight

from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.utils import perf

BENCH_SUMMARY = {
    "metric": "tokens_per_sec_per_chip", "value": 1234.5, "mfu": 0.31,
    "best_config": "remat=0,attn=exact,bs=32",
    "all_configs": {
        "remat=0,attn=exact,bs=32": {"ms": 100.0, "tok_s": 1234.5},
        "extra:sched-zb1,pp=4": {
            "ms": 250.0, "tok_s": 90.0,
            "detail": {"schedule": "zb1", "bubble_fraction_analytic": 0.009}},
        "extra:layout-pp4tp2dp1sp1": {
            "ms": 300.0, "tok_s": 80.0,
            "detail": {"layout": "pp4tp2dp1sp1", "score_s_model": 0.28}},
        "extra:offload-bw": {
            "ms": 50.0, "tok_s": 0.0,
            "detail": {"d2h_gibps": 21.0, "h2d_gibps": 24.0,
                       "probe_mib": 256, "pinned_host": True}},
        "extra:offload-wgrad-stash,pp=4": {
            "ms": 260.0, "tok_s": 88.0,
            "detail": {"transfer_ms_model": 12.0,
                       "transfer_stall_ms": 15.5}},
        "extra:kernel-ce,bs=32": {
            "ms": 90.0, "tok_s": 1300.0,
            "detail": {"bytes_model_gib": 2.0, "saved_ms": 10.0,
                       "achieved_gibps": 200.0}},
    },
}


# ---------------------------------------------------------------------------
# rows + readers
# ---------------------------------------------------------------------------

def test_rows_from_bench_summary_pairs():
    rows = perf.rows_from_bench_summary(BENCH_SUMMARY, run="r1")
    by_metric = {r["metric"]: r for r in rows}
    assert by_metric["mfu"]["measured"] == 0.31
    # headline sweep rows contribute nothing; extras all do
    assert "step_s:remat=0,attn=exact,bs=32" not in by_metric
    lay = by_metric["step_s:extra:layout-pp4tp2dp1sp1"]
    assert lay["model"] == 0.28 and lay["measured"] == pytest.approx(0.3)
    assert by_metric["host_bw_gibps"]["measured"] == 21.0  # min(d2h, h2d)
    tr = by_metric["transfer_ms:extra:offload-wgrad-stash,pp=4"]
    assert tr["model"] == 12.0 and tr["measured"] == 15.5
    assert by_metric["bubble_fraction:extra:sched-zb1,pp=4"]["model"] == 0.009
    assert by_metric["kernel_bw_gibps:extra:kernel-ce,bs=32"][
        "measured"] == 200.0


def test_error_round_becomes_failure_row():
    rows = perf.rows_from_bench_summary(
        {"metric": "tokens_per_sec_per_chip", "value": 0.0,
         "error": "no usable accelerator: device probe did not respond"},
        run="BENCH_r05")
    assert len(rows) == 1 and rows[0]["reason"].startswith("no usable")


def test_repo_bench_history_summarizes_unreachable(capsys):
    """The five archived rounds (BENCH_r01-r05) are all TPU-unreachable;
    the report must say so instead of printing an empty table."""
    perf_report.main(["--bench-glob", "BENCH_r0*.json"])
    out = capsys.readouterr().out
    assert "round(s) produced no live number" in out
    assert "BENCH_r0" in out


def test_read_ledger_degrades(tmp_path):
    assert perf.read_ledger(str(tmp_path / "absent.jsonl")) == []
    p = tmp_path / "perf.jsonl"
    p.write_text("")
    assert perf.read_ledger(str(p)) == []
    p.write_text('garbage\n{"metric": "mfu", "measured": 0.3}\n'
                 '{"not_a_row": 1}\n{"metric": "x", "mea')
    rows = perf.read_ledger(str(p))
    assert len(rows) == 1 and rows[0]["metric"] == "mfu"


def test_append_and_report_roundtrip(tmp_path, capsys):
    path = tmp_path / "perf.jsonl"
    n = perf.append_rows(str(path), perf.rows_from_bench_summary(
        BENCH_SUMMARY, run="r1"))
    assert n > 0
    calib_path = tmp_path / "calib.json"
    perf_report.main([str(path), "--emit-calibration", str(calib_path)])
    out = capsys.readouterr().out
    assert "host_bw_gibps" in out and "mfu" in out
    calib = json.loads(calib_path.read_text())
    assert calib["host_bw_gibps"] == 21.0 and calib["mfu"] == 0.31
    # run-dir spelling reads <dir>/perf.jsonl
    perf_report.main([str(tmp_path)])
    assert "host_bw_gibps" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# preflight --calibration
# ---------------------------------------------------------------------------

def test_load_calibration_degrades(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{torn")
    with pytest.raises(SystemExit, match="not readable JSON"):
        preflight.load_calibration(str(bad))
    not_obj = tmp_path / "list.json"
    not_obj.write_text("[1, 2]")
    with pytest.raises(SystemExit, match="not a JSON object"):
        preflight.load_calibration(str(not_obj))
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"generated_at": 1.0, "rows_used": 0,
                                 "mfu": None, "host_bw_gibps": "n/a"}))
    assert preflight.load_calibration(str(empty)) == {}


def test_calibration_rerank_pinned(tmp_path):
    """THE acceptance pin: at the 65B pp8 shape with the CLI defaults
    (30 GiB/s host link) --select picks zb1 + wgrad offload; a ledger
    whose measured host bandwidth is a starved 0.5 GiB/s distills into a
    calibration file that re-ranks the SAME frontier to interleaved —
    offload refused analytically from the MEASUREMENT, not the guess."""
    dims = pl.stash_dims(8, 512, 1, 8192, "bfloat16")
    cands = preflight.enumerate_candidates(8, 256, 80)
    compute = lambda pcfg: 60.0

    def pick(bw):
        winner, _ = preflight.select_schedule(cands, 70.0, dims, 95.0, bw,
                                              compute)
        return winner

    # a measured starved link lands in the ledger...
    ledger = tmp_path / "perf.jsonl"
    perf.append_rows(str(ledger), [
        perf.make_row("host_bw_gibps", measured=0.5, unit="GiB/s",
                      source="bench", run="r1")])
    calib = perf.derive_calibration(perf.read_ledger(str(ledger)))
    calib_path = tmp_path / "calib.json"
    calib_path.write_text(json.dumps(calib))

    # ...and flows through the --calibration arg surface
    args = argparse.Namespace(mfu=0.45, host_bw_gibps=30.0,
                              ici_bw_gibps=90.0)
    applied = preflight.apply_calibration(args, str(calib_path))
    assert applied == {"host_bw_gibps": 0.5}
    assert args.host_bw_gibps == 0.5 and args.mfu == 0.45  # absent key kept

    uncalibrated = pick(30.0)
    calibrated = pick(args.host_bw_gibps)
    assert uncalibrated["schedule"] == "zb1" and uncalibrated["offload_wgrad"]
    assert calibrated["schedule"] == "interleaved_1f1b"
    assert not calibrated["offload_wgrad"]


def test_bench_ledger_writer(tmp_path, monkeypatch):
    """bench.py's _write_ledger: healthy summary -> rows; probe failure ->
    one reason-tagged row; budget skips -> reason rows. (The full
    --full-trajectory run is the slow-marked e2e.)"""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(os.path.dirname(preflight.__file__),
                                  os.pardir, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    path = tmp_path / "perf.jsonl"
    monkeypatch.setenv("BENCH_RUN_LABEL", "round-x")
    bench._write_ledger(str(path), BENCH_SUMMARY, ["serve"])
    rows = perf.read_ledger(str(path))
    assert any(r["metric"] == "mfu" for r in rows)
    skip = [r for r in rows if r["metric"] == "bench_row_family"]
    assert len(skip) == 1 and "serve" in skip[0]["reason"]
    assert all(r["run"] == "round-x" for r in rows)
    # the rows are stamped with THIS process's backend (cpu under the test
    # mesh) — and cpu-stamped measurements must never calibrate preflight's
    # TPU model constants (a CPU smoke's mfu/host-bw are about the wrong
    # hardware)
    mfu_row = next(r for r in rows if r["metric"] == "mfu")
    assert mfu_row["context"]["backend"] == "cpu"
    calib = perf.derive_calibration(rows)
    assert "mfu" not in calib and "host_bw_gibps" not in calib
    # an unstamped mfu below the 0.01 sanity floor is dropped too
    assert "mfu" not in perf.derive_calibration(
        [perf.make_row("mfu", measured=1e-4)])

    path2 = tmp_path / "fail.jsonl"
    bench._write_ledger(str(path2), None, [], error="no usable accelerator")
    rows2 = perf.read_ledger(str(path2))
    assert len(rows2) == 1 and rows2[0]["reason"] == "no usable accelerator"
    # a None path is a no-op, never an error
    bench._write_ledger(None, BENCH_SUMMARY, [])


@pytest.mark.slow
def test_bench_full_trajectory_cpu_runbook(tmp_path):
    """The one-shot runbook end-to-end on CPU (several minutes — round
    gate): `bench.py --full-trajectory` runs every extra:* row family in
    one invocation under a per-row budget and writes the ledger; the
    report then renders model-vs-measured pairs from it."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(
        preflight.__file__)))
    ledger = tmp_path / "perf.jsonl"
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
           "BENCH_MODEL": "tiny", "BENCH_BATCH": "2", "BENCH_STEPS": "1",
           "BENCH_SEQ": "64", "BENCH_TIMEOUT_S": "1500",
           "BENCH_RUN_LABEL": "runbook-smoke"}
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--full-trajectory", "--perf-ledger", str(ledger),
         "--row-budget-s", "240"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=1500)
    assert out.returncode == 0, out.stderr[-2000:]
    summary = json.loads(out.stdout.strip().splitlines()[-1])
    extras = [k for k in summary["all_configs"] if k.startswith("extra:")]
    # one pass covers every family (offload/sched/layout/kernel/serve)
    for fam in ("offload", "sched-", "layout-", "kernel-", "serve-"):
        assert any(fam in k for k in extras), (fam, extras)
    rows = perf.read_ledger(str(ledger))
    assert any(r["metric"] == "host_bw_gibps" and r["measured"]
               for r in rows)
    assert any(r["metric"].startswith("transfer_ms") and r["model"]
               for r in rows)

"""Alert-driven actuation (utils/actions.py + tools/fleetctl.py —
docs/RESILIENCE.md "Actuation").

Fast lanes: the action journal's intent/outcome pairing and id recovery,
the actions.* config surface (unknown keys rejected), the autoscaler's
borrow/handback hysteresis and cooldown, the deployer's tail / hold /
rollback / lag-force gate, crash recovery (reconcile completes evidenced
intents, safely voids unevidenced ones), the honest Retry-After formula's
pinned values, reader degradation on torn/garbage actions.jsonl, and the
inert-by-default pins. The whole-pod chaos e2e lives in
test_actuation_e2e.py."""

import json
import os
import time

import pytest

import fleetctl
from llama_pipeline_parallel_tpu.serve.telemetry import retry_after_s
from llama_pipeline_parallel_tpu.utils import actions, fleet
from llama_pipeline_parallel_tpu.utils.actions import (
    ActionJournal,
    ActionsConfig,
    Autoscaler,
    AutoscaleConfig,
    Deployer,
    DeployConfig,
    TrainActions,
    read_actions,
    reconcile_open_intents,
    write_action_request,
)


def firing_status(rules, since, now=None):
    """A minimal fleet_status.json payload with the given alerts firing."""
    now = time.time() if now is None else now
    return {"time": now, "members": {}, "pod": {},
            "alerts": {f"{rule}:serve:r0": {"state": "firing",
                                            "since": since,
                                            "value": 1, "threshold": 0}
                       for rule in rules}}


def autoscaler(tmp_path, **kw):
    root = str(tmp_path / "fleet")
    trainer = str(tmp_path / "train")
    cfg = AutoscaleConfig.from_cfg({"trainer_dir": trainer,
                                    "borrow_rung": "half",
                                    "restore_rung": "full", **kw})
    return Autoscaler(cfg, ActionJournal(root), root), trainer


def deployer(tmp_path, n_replicas=1, **kw):
    root = str(tmp_path / "fleet")
    trainer = str(tmp_path / "train")
    replicas = [str(tmp_path / f"serve{i}") for i in range(n_replicas)]
    for d in (trainer, *replicas):
        os.makedirs(d, exist_ok=True)
    cfg = DeployConfig.from_cfg({"trainer_dir": trainer,
                                 "replica_dirs": replicas, **kw})
    return Deployer(cfg, ActionJournal(root)), trainer, replicas


def write_ckpt(trainer, step, eval_loss=None, complete=True):
    d = os.path.join(trainer, f"checkpoint-{step}")
    os.makedirs(d, exist_ok=True)
    if complete:
        meta = {"step": step}
        if eval_loss is not None:
            meta["eval_loss"] = eval_loss
        with open(os.path.join(d, "meta.json"), "w") as f:
            json.dump(meta, f)


def set_serving(replica_dir, step):
    fleet.write_json_atomic(os.path.join(replica_dir, "serve.json"),
                            {"pid": 1, "checkpoint_step": step})


# ---------------------------------------------------------------------------
# the journal
# ---------------------------------------------------------------------------

def test_journal_pairs_and_recovers_ids(tmp_path):
    j = ActionJournal(str(tmp_path))
    a = j.begin("borrow", params={"rung": "half"}, alert="ttft_p95:serve:r0")
    assert a == "action-000000"
    assert [r["id"] for r in j.open_intents()] == [a]
    j.finish(a, "done", rung="half")
    assert j.open_intents() == []
    hist = j.history()
    assert hist[0]["result"]["outcome"] == "done"
    assert hist[0]["result"]["kind"] == "borrow"  # outcome carries the kind
    assert hist[0]["alert"] == "ttft_p95:serve:r0"
    # ids are recovered from the file, not memory: a NEW journal object
    # (an actuator restart) continues the sequence
    j2 = ActionJournal(str(tmp_path))
    assert j2.begin("handback") == "action-000001"


def test_journal_last_done_ts_ignores_voids(tmp_path):
    j = ActionJournal(str(tmp_path))
    a = j.begin("borrow")
    j.finish(a, "done")
    done_ts = j.history()[0]["result"]["ts"]
    b = j.begin("borrow")
    j.finish(b, "voided")
    assert j.last_done_ts(("borrow", "handback")) == done_ts


def test_journal_reader_degrades_on_torn_and_garbage(tmp_path):
    j = ActionJournal(str(tmp_path))
    a = j.begin("deploy", params={"step": 10})
    j.finish(a, "done")
    with open(j.path, "a") as f:
        f.write("not json at all\n")
        f.write('["a", "list", "row"]\n')
        f.write('{"id": "action-000009", "phase": "intent"')  # torn tail
    rows = read_actions(os.path.dirname(j.path))
    assert [r["phase"] for r in rows] == ["intent", "outcome"]
    assert ActionJournal(str(tmp_path)).next_id() == "action-000001"
    assert read_actions(str(tmp_path / "nowhere")) == []


# ---------------------------------------------------------------------------
# the actions.* config surface
# ---------------------------------------------------------------------------

def test_actions_config_rejects_unknown_keys(tmp_path):
    with pytest.raises(ValueError, match="unknown actions"):
        ActionsConfig.from_cfg({"autoscaler": {}})  # typo'd block name
    with pytest.raises(ValueError, match="unknown actions.autoscale"):
        AutoscaleConfig.from_cfg({"trainer_dir": "t", "borrow_rung": "a",
                                  "restore_rung": "b", "for_secs": 5})
    with pytest.raises(ValueError, match="unknown actions.deploy"):
        DeployConfig.from_cfg({"trainer_dir": "t", "replica_dirs": ["r"],
                               "rollback": True})
    with pytest.raises(ValueError, match="unknown actions"):
        TrainActions.from_cfg({"resize": True})
    with pytest.raises(ValueError, match="required"):
        AutoscaleConfig.from_cfg({"trainer_dir": "t", "borrow_rung": "a"})
    with pytest.raises(ValueError, match="must be >= 0"):
        AutoscaleConfig.from_cfg({"trainer_dir": "t", "borrow_rung": "a",
                                  "restore_rung": "b", "cooldown_s": -1})
    with pytest.raises(ValueError, match="non-empty list"):
        DeployConfig.from_cfg({"trainer_dir": "t", "replica_dirs": []})
    # empty/None block -> inert config, no actuators
    assert ActionsConfig.from_cfg(None) == ActionsConfig()
    assert TrainActions.from_cfg(None).resize_on_request is False


def test_fleetctl_parse_actions_inline_and_file(tmp_path):
    spec = {"deploy": {"trainer_dir": str(tmp_path),
                       "replica_dirs": [str(tmp_path / "r")]}}
    cfg = fleetctl.parse_actions(json.dumps(spec))
    assert cfg.deploy.trainer_dir == str(tmp_path)
    assert cfg.autoscale is None
    path = tmp_path / "actions.json"
    path.write_text(json.dumps(spec))
    assert fleetctl.parse_actions(f"@{path}") == cfg
    assert fleetctl.parse_actions(None) == ActionsConfig()
    with pytest.raises(ValueError):
        fleetctl.parse_actions('{"bogus": 1}')


# ---------------------------------------------------------------------------
# the honest Retry-After
# ---------------------------------------------------------------------------

def test_retry_after_pinned_values():
    """The formula is deterministic — same backlog, rate, and request key
    give the SAME hint across processes and retries (crc32 jitter, not a
    salted hash). Pinned so the contract cannot drift silently."""
    # 4 ahead + self = 5 requests at 2/s -> 2.5s base; crc32("req-1") %
    # 1000 = 545 -> jitter = 0.545 * 0.25 * 2.5
    assert retry_after_s(4, 2.0, key="req-1") == 2.841
    assert retry_after_s(4, 2.0, key="req-1") == 2.841  # deterministic
    assert retry_after_s(4, 2.0, key="req-2") == 2.502  # key-dependent
    # no measured rate yet -> the static fallback (plus jitter), never 0
    assert retry_after_s(100, None, key="x", fallback=1.0) < 1.5
    assert retry_after_s(0, 0.0, key="x", fallback=1.0) >= 0.1
    # clamped: a dead-slow drain cannot tell a client to wait an hour
    assert retry_after_s(10_000, 0.001, key="x", max_s=60.0) == 60.0


# ---------------------------------------------------------------------------
# the autoscaler state machine
# ---------------------------------------------------------------------------

def test_autoscaler_borrows_on_sustained_breach_only(tmp_path):
    scaler, trainer = autoscaler(tmp_path, for_s=10)
    now = time.time()
    # firing, but not for long enough -> hysteresis holds
    assert scaler.tick(firing_status(["ttft_p95"], since=now - 3), now) == []
    assert scaler.mode() == "normal"
    # sustained past for_s -> borrow: intent row, request file, done row
    taken = scaler.tick(firing_status(["ttft_p95"], since=now - 11), now)
    assert len(taken) == 1
    req = actions.read_json_file(
        os.path.join(trainer, actions.ACTION_REQUEST_NAME))
    assert req == {"ts": req["ts"], "action": "resize", "rung": "half",
                   "id": taken[0]}
    assert scaler.mode() == "borrowed"
    hist = scaler.journal.history()
    assert hist[0]["alert"] == "ttft_p95:serve:r0"
    assert hist[0]["result"]["outcome"] == "done"
    # borrowed + still breaching -> nothing further to take
    assert scaler.tick(firing_status(["ttft_p95"], since=now - 20),
                       now + 1) == []


def test_autoscaler_handback_after_sustained_quiet(tmp_path):
    scaler, trainer = autoscaler(tmp_path, idle_for_s=5)
    now = time.time()
    scaler.tick(firing_status(["queue_wait_p95"], since=now - 1), now)
    assert scaler.mode() == "borrowed"
    os.remove(os.path.join(trainer, actions.ACTION_REQUEST_NAME))
    quiet = {"time": now, "alerts": {}}
    assert scaler.tick(quiet, now + 10) == []      # quiet clock starts
    assert scaler.tick(quiet, now + 12) == []      # 2s quiet < idle_for_s
    # a breach mid-quiet resets the clock
    scaler.tick(firing_status(["queue_wait_p95"], since=now + 13), now + 13)
    assert scaler.tick(quiet, now + 14) == []
    taken = scaler.tick(quiet, now + 20)           # 6s quiet -> handback
    assert len(taken) == 1
    req = actions.read_json_file(
        os.path.join(trainer, actions.ACTION_REQUEST_NAME))
    assert req["rung"] == "full"
    assert scaler.mode() == "normal"


def test_autoscaler_cooldown_rate_limits_transitions(tmp_path):
    scaler, trainer = autoscaler(tmp_path, cooldown_s=30)
    now = time.time()
    scaler.tick(firing_status(["ttft_p95"], since=now - 1), now)
    os.remove(os.path.join(trainer, actions.ACTION_REQUEST_NAME))
    quiet = {"time": now, "alerts": {}}
    assert scaler.tick(quiet, now + 5) == []    # quiet, but cooling down
    assert scaler.tick(quiet, now + 29) == []
    assert len(scaler.tick(quiet, now + 31)) == 1  # cooled -> handback


def test_autoscaler_ignores_unconfigured_alerts(tmp_path):
    scaler, _ = autoscaler(tmp_path, breach_alerts=["queue_wait_p95"])
    now = time.time()
    assert scaler.tick(firing_status(["ttft_p95", "checkpoint_lag"],
                                     since=now - 100), now) == []
    assert scaler.tick(None, now) == []  # no status snapshot yet


# ---------------------------------------------------------------------------
# the deployer gate
# ---------------------------------------------------------------------------

def test_deployer_tails_latest_verified_checkpoint(tmp_path):
    dep, trainer, (replica,) = deployer(tmp_path)
    assert dep.tick(None, time.time()) == []        # no checkpoints yet
    write_ckpt(trainer, 10, eval_loss=2.0)
    write_ckpt(trainer, 20, eval_loss=1.5)
    write_ckpt(trainer, 30, complete=False)         # no meta -> not verified
    taken = dep.tick(None, time.time())
    assert len(taken) == 1
    req = actions.read_json_file(
        os.path.join(replica, actions.ACTION_REQUEST_NAME))
    assert req["action"] == "deploy" and req["step"] == 20
    # the request is still unconsumed -> no stacking
    assert dep.tick(None, time.time()) == []
    # consumed and serving 20 -> converged, nothing to do
    os.remove(os.path.join(replica, actions.ACTION_REQUEST_NAME))
    set_serving(replica, 20)
    assert dep.tick(None, time.time()) == []


def test_deployer_holds_regressed_candidate_once(tmp_path):
    dep, trainer, (replica,) = deployer(tmp_path)
    write_ckpt(trainer, 10, eval_loss=1.5)
    write_ckpt(trainer, 20, eval_loss=1.9)          # regressed vs deployed
    set_serving(replica, 10)
    assert dep.tick(None, time.time()) == []
    assert dep.tick(None, time.time()) == []
    holds = [h for h in dep.journal.history() if h["kind"] == "hold"]
    assert len(holds) == 1                          # journaled ONCE
    assert holds[0]["params"]["step"] == 20
    assert holds[0]["params"]["candidate_eval"] == 1.9
    assert not os.path.exists(
        os.path.join(replica, actions.ACTION_REQUEST_NAME))


def test_deployer_rolls_back_deployed_regression(tmp_path):
    dep, trainer, (replica,) = deployer(tmp_path)
    write_ckpt(trainer, 10, eval_loss=1.5)
    write_ckpt(trainer, 20, eval_loss=1.9)
    set_serving(replica, 20)                        # the regression is LIVE
    taken = dep.tick(None, time.time())
    assert len(taken) == 1
    req = actions.read_json_file(
        os.path.join(replica, actions.ACTION_REQUEST_NAME))
    assert req["step"] == 10                        # previous verified step
    hist = dep.journal.history()
    assert hist[-1]["kind"] == "rollback"
    assert hist[-1]["params"]["reason"] == "eval_regression"


def test_deployer_eval_regression_tolerance(tmp_path):
    dep, trainer, (replica,) = deployer(tmp_path, eval_regression=0.5)
    write_ckpt(trainer, 10, eval_loss=1.5)
    write_ckpt(trainer, 20, eval_loss=1.9)          # within the 0.5 band
    set_serving(replica, 10)
    taken = dep.tick(None, time.time())             # tolerated -> deploys
    assert len(taken) == 1
    assert dep.journal.history()[-1]["kind"] == "deploy"


def test_deployer_lag_alert_forces_handoff(tmp_path):
    dep, trainer, (replica,) = deployer(tmp_path, cooldown_s=3600)
    write_ckpt(trainer, 10, eval_loss=1.5)
    write_ckpt(trainer, 20, eval_loss=1.9)          # regressed AND cooling
    set_serving(replica, 10)
    now = time.time()
    lag = firing_status(["checkpoint_lag"], since=now - 1, now=now)
    taken = dep.tick(lag, now)                      # forced past both gates
    assert len(taken) == 1
    hist = dep.journal.history()
    assert hist[-1]["params"]["reason"] == "lag_alert"
    assert hist[-1]["alert"] == "checkpoint_lag:serve:r0"
    req = actions.read_json_file(
        os.path.join(replica, actions.ACTION_REQUEST_NAME))
    assert req["step"] == 20


def test_deployer_on_lag_alert_false_keeps_the_gate(tmp_path):
    dep, trainer, (replica,) = deployer(tmp_path, on_lag_alert=False)
    write_ckpt(trainer, 10, eval_loss=1.5)
    write_ckpt(trainer, 20, eval_loss=1.9)
    set_serving(replica, 10)
    now = time.time()
    assert dep.tick(firing_status(["checkpoint_lag"], since=now - 1,
                                  now=now), now) == []


# ---------------------------------------------------------------------------
# crash recovery: reconcile the open intents
# ---------------------------------------------------------------------------

def test_reconcile_voids_unevidenced_borrow(tmp_path):
    """Killed between the intent row and the request write: the world is
    unchanged, so the intent is safely VOIDED — the still-firing alert
    re-triggers a fresh action (and the void does not consume cooldown)."""
    scaler, trainer = autoscaler(tmp_path, cooldown_s=3600)
    a = scaler.journal.begin("borrow", params={"rung": "half"})
    resolved = reconcile_open_intents(scaler.journal, scaler, None)
    assert resolved == [(a, "borrow", "voided")]
    assert scaler.journal.open_intents() == []
    now = time.time()
    # the void consumed no cooldown: the breach re-triggers immediately
    taken = scaler.tick(firing_status(["ttft_p95"], since=now - 1), now)
    assert len(taken) == 1


def test_reconcile_completes_evidenced_borrow(tmp_path):
    """Killed between the request write and the outcome row: the request
    (or the supervisor's ack of it) is the delivery evidence — the intent
    COMPLETES as done instead of double-firing."""
    scaler, trainer = autoscaler(tmp_path)
    a = scaler.journal.begin("borrow", params={"rung": "half"})
    write_action_request(trainer, {"action": "resize", "rung": "half",
                                   "id": a})
    assert reconcile_open_intents(scaler.journal, scaler,
                                  None) == [(a, "borrow", "done")]
    row = scaler.journal.history()[0]["result"]
    assert row["evidence"] == "request_pending" and row["reconciled"]
    assert scaler.mode() == "borrowed"
    # same, with the request already consumed into the supervisor's ack
    b = scaler.journal.begin("handback", params={"rung": "full"})
    os.replace(os.path.join(trainer, actions.ACTION_REQUEST_NAME),
               os.path.join(trainer, actions.ACTION_ACK_NAME))
    fleet.write_json_atomic(
        os.path.join(trainer, actions.ACTION_ACK_NAME),
        {"id": b, "action": "resize"})
    assert scaler.reconcile(scaler.journal.open_intents()[0]) == "done"
    assert scaler.mode() == "normal"


def test_reconcile_redelivers_open_deploy(tmp_path):
    """Deploy is idempotent (the request names an absolute step), so an
    unevidenced open deploy intent RE-DELIVERS and completes."""
    dep, trainer, (replica,) = deployer(tmp_path)
    a = dep.journal.begin("deploy", params={"replica_dir": replica,
                                            "step": 20})
    assert reconcile_open_intents(dep.journal, None,
                                  dep) == [(a, "deploy", "done")]
    req = actions.read_json_file(
        os.path.join(replica, actions.ACTION_REQUEST_NAME))
    assert req["step"] == 20 and req["id"] == a
    # already serving the target -> evidence enough, no re-delivery
    os.remove(os.path.join(replica, actions.ACTION_REQUEST_NAME))
    b = dep.journal.begin("deploy", params={"replica_dir": replica,
                                            "step": 30})
    set_serving(replica, 30)
    assert dep.reconcile(dep.journal.open_intents()[0]) == "done"
    assert not os.path.exists(
        os.path.join(replica, actions.ACTION_REQUEST_NAME))
    # malformed params can only void
    c = dep.journal.begin("rollback", params={"replica_dir": None})
    assert dep.reconcile(dep.journal.open_intents()[0]) == "voided"


def test_reconcile_voids_unowned_kinds(tmp_path):
    j = ActionJournal(str(tmp_path))
    a = j.begin("deploy", params={"replica_dir": "/x", "step": 1})
    assert reconcile_open_intents(j, None, None) == [(a, "deploy", "voided")]


# ---------------------------------------------------------------------------
# tools/fleetctl.py end to end (in-process and CLI)
# ---------------------------------------------------------------------------

def test_fleetctl_actuator_reads_status_and_acts(tmp_path):
    root = str(tmp_path / "fleet")
    trainer = str(tmp_path / "train")
    os.makedirs(root, exist_ok=True)
    now = time.time()
    fleet.write_json_atomic(os.path.join(root, fleet.STATUS_NAME),
                            firing_status(["ttft_p95"], since=now - 60))
    act = fleetctl.FleetActuator(root, fleetctl.parse_actions(json.dumps({
        "autoscale": {"trainer_dir": trainer, "borrow_rung": "half",
                      "restore_rung": "full", "for_s": 5}})))
    assert act.reconcile() == []
    taken = act.tick()
    assert len(taken) == 1
    assert actions.read_json_file(
        os.path.join(trainer, actions.ACTION_REQUEST_NAME))["rung"] == "half"


def test_fleetctl_once_cli(tmp_path, capsys):
    root = str(tmp_path / "fleet")
    os.makedirs(root, exist_ok=True)
    # an open intent from a "killed" predecessor reconciles at startup
    ActionJournal(root).begin("borrow", params={"rung": "half"})
    spec = json.dumps({"autoscale": {"trainer_dir": str(tmp_path / "t"),
                                     "borrow_rung": "half",
                                     "restore_rung": "full"}})
    assert fleetctl.main(["--fleet-root", root, "--actions", spec,
                          "--once"]) == 0
    out = capsys.readouterr().out
    assert "reconciled action-000000 (borrow): voided" in out
    assert json.loads(out.strip().splitlines()[-1]) == {"actions": []}
    with pytest.raises(SystemExit, match="bad --actions"):
        fleetctl.main(["--fleet-root", root, "--actions", '{"bogus": 1}',
                       "--once"])


def test_fleetctl_inert_without_actions(tmp_path, capsys):
    """No --actions -> no actuators, no journal writes, no request files:
    the inert-by-default pin."""
    root = str(tmp_path / "fleet")
    now = time.time()
    os.makedirs(root, exist_ok=True)
    fleet.write_json_atomic(os.path.join(root, fleet.STATUS_NAME),
                            firing_status(["ttft_p95"], since=now - 3600))
    assert fleetctl.main(["--fleet-root", root, "--once"]) == 0
    assert json.loads(
        capsys.readouterr().out.strip().splitlines()[-1]) == {"actions": []}
    assert not os.path.exists(os.path.join(root, actions.ACTIONS_NAME))


# ---------------------------------------------------------------------------
# the report timeline
# ---------------------------------------------------------------------------

def test_fleet_report_interleaves_actions_with_alert_edges(tmp_path, capsys):
    import fleet_report

    root = str(tmp_path / "fleet")
    os.makedirs(root, exist_ok=True)
    t0 = 1000.0
    with open(os.path.join(root, fleet.ALERTS_NAME), "a") as f:
        f.write(json.dumps({"ts": t0, "alert": "ttft_p95",
                            "member": "serve:r0", "state": "firing",
                            "value": 900, "threshold": 500}) + "\n")
        f.write(json.dumps({"ts": t0 + 30, "alert": "ttft_p95",
                            "member": "serve:r0", "state": "resolved",
                            "value": 100, "threshold": 500}) + "\n")
    with open(os.path.join(root, actions.ACTIONS_NAME), "a") as f:
        f.write(json.dumps({"ts": t0 + 10, "id": "action-000000",
                            "kind": "borrow", "phase": "intent",
                            "params": {"rung": "half"},
                            "alert": "ttft_p95:serve:r0"}) + "\n")
        f.write(json.dumps({"ts": t0 + 11, "id": "action-000000",
                            "kind": "borrow", "phase": "outcome",
                            "outcome": "done"}) + "\n")
        f.write("garbage line\n")                      # reader degrades
    rep = fleet_report.build_report(root)
    assert [r["id"] for r in rep["action_timeline"]] == ["action-000000"] * 2
    fleet_report.print_report(rep)
    out = capsys.readouterr().out
    assert "actions timeline (interleaved with alert edges)" in out
    section = out[out.index("actions timeline"):].splitlines()
    lines = [ln for ln in section if ln.strip().startswith("t+")]
    # merged clock: firing edge, then the intent it caused, its outcome,
    # then the resolve
    assert "FIRING" in lines[0]
    assert "INTENT" in lines[1] and "<- ttft_p95:serve:r0" in lines[1]
    assert "DONE" in lines[2]
    assert "RESOLVED" in lines[3]


def test_fleet_report_without_actions_is_unchanged(tmp_path, capsys):
    """No actions.jsonl -> no actions section at all (inertness: the
    report reads byte-identically to a pre-actuation pod)."""
    import fleet_report

    root = str(tmp_path / "fleet")
    os.makedirs(root, exist_ok=True)
    rep = fleet_report.build_report(root)
    assert rep["action_timeline"] == []
    fleet_report.print_report(rep)
    assert "actions timeline" not in capsys.readouterr().out

"""Sequence packing: segment-masked attention equivalence, packed-collator
layout, trainer gating, and a packed end-to-end training run.

The reference pads every example to the full 512-token row (reference conf
yaml:32, data/flan.py:264-268) — packing is the capability it left on the
table. The invariant everything here pins: a packed row must behave exactly
like its examples run separately.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.data.collator import (
    IGNORE_INDEX,
    PackedCausalLMCollator,
)
from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig


class FakeTokenizer:
    eos_token = "</s>"
    pad_token = "</s>"

    def _encode(self, text):
        return [hash(w) % 200 + 10 for w in text.split()]

    def __call__(self, texts, max_length, truncation, padding=None,
                 return_tensors=None, return_length=False):
        return {"input_ids": [self._encode(t)[:max_length] for t in texts]}


def test_packed_forward_matches_separate_sequences():
    """Logits of two sequences packed into one row (segment ids 1/2,
    positions reset) equal each sequence's standalone logits."""
    cfg = LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    r = np.random.RandomState(0)
    a = r.randint(3, cfg.vocab_size, (5,)).astype(np.int32)
    b = r.randint(3, cfg.vocab_size, (7,)).astype(np.int32)

    L = 16
    ids = np.zeros((1, L), np.int32)
    seg = np.zeros((1, L), np.int32)
    pos = np.zeros((1, L), np.int32)
    ids[0, :5], ids[0, 5:12] = a, b
    seg[0, :5], seg[0, 5:12] = 1, 2
    pos[0, :5], pos[0, 5:12] = np.arange(5), np.arange(7)

    packed = np.asarray(llama.forward(
        params, jnp.asarray(ids), jnp.asarray(seg), jnp.asarray(pos), cfg=cfg))
    alone_a = np.asarray(llama.forward(params, jnp.asarray(a[None]), cfg=cfg))
    alone_b = np.asarray(llama.forward(params, jnp.asarray(b[None]), cfg=cfg))

    np.testing.assert_allclose(packed[0, :5], alone_a[0], rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(packed[0, 5:12], alone_b[0], rtol=2e-5, atol=2e-5)


def test_packed_collator_layout():
    tok = FakeTokenizer()
    # lengths (whitespace tokens incl. eos glued to the last target word):
    # 5, 3, 4, 5 -> first-fit-DECREASING at L=10 places 5, 5, 4, 3:
    # row0 = [5, 5], row1 = [4, 3]
    coll = PackedCausalLMCollator(tok, max_seq_length=10, pack_factor=2)
    examples = [{"inputs": "a b c", "targets": "d e"},
                {"inputs": "f g", "targets": "h"},
                {"inputs": "i", "targets": "j k l"},
                {"inputs": "m n o p", "targets": "q"}]
    batch = coll(examples)
    assert batch["input_ids"].shape == (2, 10)

    for row in range(2):
        seg = batch["attention_mask"][row]
        pos = batch["position_ids"][row]
        lab = batch["labels"][row]
        ids = batch["input_ids"][row]
        used = seg != 0
        # segments are 1..k, contiguous, ascending
        packed_segs = seg[used]
        assert packed_segs.min() == 1 and packed_segs.max() >= 2
        assert (np.diff(packed_segs) >= 0).all()
        # positions restart at each segment start
        starts = np.flatnonzero(np.diff(np.concatenate([[0], seg])) > 0)
        assert (pos[starts] == 0).all()
        # prompt (first tokens of each segment) masked; pads masked
        assert (lab[starts] == IGNORE_INDEX).all()
        assert (lab[~used] == IGNORE_INDEX).all()
        # unmasked labels equal the input ids there (targets span)
        tgt = (lab != IGNORE_INDEX)
        np.testing.assert_array_equal(lab[tgt], ids[tgt])
    assert coll.dropped_total == 0


def test_packed_empty_prompt_still_masks_segment_start():
    """Even a zero-token prompt must leave the segment's FIRST token
    IGNORE — the previous segment's last position takes its shifted target
    from that slot."""
    coll = PackedCausalLMCollator(FakeTokenizer(), max_seq_length=16,
                                  pack_factor=2)
    batch = coll([{"inputs": "", "targets": "x y"},
                  {"inputs": "", "targets": "z w"}])
    seg, lab = batch["attention_mask"][0], batch["labels"][0]
    starts = np.flatnonzero(np.diff(np.concatenate([[0], seg])) > 0)
    assert len(starts) == 2
    assert (lab[starts] == IGNORE_INDEX).all()
    assert (lab[seg != 0] != IGNORE_INDEX).any()  # targets still train


def test_packed_collator_drops_overflow():
    tok = FakeTokenizer()
    coll = PackedCausalLMCollator(tok, max_seq_length=8, pack_factor=4)
    examples = [{"inputs": "a b c d", "targets": "e f g"} for _ in range(4)]
    batch = coll(examples)  # 1 row of 8; only one 8-token example fits
    assert batch["input_ids"].shape == (1, 8)
    assert coll.dropped_total == 3
    assert coll.packed_total == 1
    assert coll.drop_rate() == pytest.approx(0.75)


def test_ffd_beats_arrival_order_first_fit():
    """First-fit-decreasing packs batches that arrival-order first-fit
    drops from: lengths [3, 3, 7, 7] at L=10 — arrival order fills row0
    with the two short examples and can only place one 7; FFD pairs each
    long with a short. (The round-3 advisor's bias note: arrival order
    dropped exactly the LONG examples.)"""
    tok = FakeTokenizer()
    coll = PackedCausalLMCollator(tok, max_seq_length=10, pack_factor=2)
    # FakeTokenizer: token count == word count (targets glue eos to last word)
    examples = [{"inputs": "a b", "targets": "c"},           # 3 tokens
                {"inputs": "d e", "targets": "f"},           # 3 tokens
                {"inputs": "g h i j k", "targets": "l m"},   # 7 tokens
                {"inputs": "n o p q r", "targets": "s t"}]   # 7 tokens
    batch = coll(examples)
    assert coll.dropped_total == 0, "FFD must fit 7+3 per row"
    assert batch["input_ids"].shape == (2, 10)
    # arrival-order first-fit simulation on the same lengths drops one
    lens, L, rows = [3, 3, 7, 7], 10, 2
    cursors, dropped = [0] * rows, 0
    for n in lens:
        row = next((r for r in range(rows) if cursors[r] + n <= L), None)
        if row is None:
            dropped += 1
        else:
            cursors[row] += n
    assert dropped == 1  # what the pre-FFD collator would have lost


def test_ffd_fuzz_retains_more_tokens_than_arrival_order():
    """Property fuzz: aggregated over random batches, FFD placement retains
    MORE training tokens than arrival-order first-fit on the same lengths.
    (Not per-trial — first-fit heuristics trade wins; and not example
    counts — FFD deliberately keeps long examples and sheds short ones,
    reversing the arrival-order bias the round-3 advisor flagged. Measured
    over this seeded distribution FFD places ~5% more tokens.)"""
    tok = FakeTokenizer()
    r = np.random.RandomState(17)
    words = [f"w{i}" for i in range(30)]
    ffd_tokens = arrival_tokens = 0
    for trial in range(30):
        L = int(r.choice([8, 16, 24]))
        factor = int(r.choice([2, 4]))
        coll = PackedCausalLMCollator(tok, max_seq_length=L, pack_factor=factor)
        n_ex = factor * int(r.randint(1, 5))
        examples = [{"inputs": " ".join(r.choice(words, r.randint(1, 9))),
                     "targets": " ".join(r.choice(words, r.randint(1, 9)))}
                    for _ in range(n_ex)]
        texts = [f"{e['inputs']} {e['targets']}</s>" for e in examples]
        lens = [min(len(t.split()), L) for t in texts]
        batch = coll(examples)
        ffd_tokens += int((batch["attention_mask"] != 0).sum())
        rows = max(n_ex // factor, 1)
        cursors = [0] * rows
        for n in lens:
            row = next((q for q in range(rows) if cursors[q] + n <= L), None)
            if row is not None:
                cursors[row] += n
        arrival_tokens += sum(cursors)
    assert ffd_tokens > arrival_tokens, (
        f"FFD retained {ffd_tokens} tokens vs arrival-order "
        f"{arrival_tokens} — the decreasing sort stopped paying for itself")


def test_packed_collator_fuzz_invariants():
    """Property fuzz over random batches: every emitted row satisfies the
    packing invariants regardless of example lengths/truncation/drops."""
    tok = FakeTokenizer()
    r = np.random.RandomState(7)
    words = [f"w{i}" for i in range(30)]
    for trial in range(20):
        L = int(r.choice([8, 16, 24]))
        factor = int(r.choice([2, 4]))
        coll = PackedCausalLMCollator(tok, max_seq_length=L, pack_factor=factor)
        n_ex = factor * int(r.randint(1, 4))
        examples = [{"inputs": " ".join(r.choice(words, r.randint(1, 9))),
                     "targets": " ".join(r.choice(words, r.randint(1, 9)))}
                    for _ in range(n_ex)]
        batch = coll(examples)
        rows = n_ex // factor
        assert batch["input_ids"].shape == (rows, L)
        for row in range(rows):
            seg = batch["attention_mask"][row]
            pos = batch["position_ids"][row]
            lab = batch["labels"][row]
            pad = seg == 0
            # pads carry no ids, no labels, and sit after all segments
            assert (lab[pad] == IGNORE_INDEX).all()
            k = seg.max()
            for s in range(1, k + 1):
                span = np.flatnonzero(seg == s)
                # segments are contiguous runs with positions 0..len-1
                assert (np.diff(span) == 1).all()
                np.testing.assert_array_equal(pos[span], np.arange(len(span)))
                assert lab[span[0]] == IGNORE_INDEX  # first token never trains
            # trained labels always equal their input id
            t = lab != IGNORE_INDEX
            np.testing.assert_array_equal(lab[t], batch["input_ids"][row][t])


def test_packing_gating(devices):
    from llama_pipeline_parallel_tpu.train import build_dataset_and_collator

    with pytest.raises(ValueError, match="tokenizer-backed"):
        build_dataset_and_collator(
            {"packing_factor": 2, "dataset": {"synthetic": True}},
            LlamaConfig.tiny())


def test_packed_flash_matches_exact():
    """The flash kernel's in-tile segment mask (interpret mode) agrees with
    the exact op on a packed batch — forward AND input gradients."""
    from llama_pipeline_parallel_tpu.ops.attention import attention
    from llama_pipeline_parallel_tpu.ops.flash_attention import flash_attention

    r = np.random.RandomState(3)
    b, s, h, hd = 2, 32, 4, 8
    q = jnp.asarray(r.randn(b, s, h, hd), jnp.float32)
    k = jnp.asarray(r.randn(b, s, h, hd), jnp.float32)
    v = jnp.asarray(r.randn(b, s, h, hd), jnp.float32)
    seg = np.zeros((b, s), np.int32)
    seg[0, :10], seg[0, 10:25] = 1, 2          # packed row + trailing pad
    seg[1, :s] = 1                             # plain full row
    seg = jnp.asarray(seg)

    def loss(fn, q_, k_, v_):
        out = fn(q_, k_, v_, seg, causal=True)
        real = (seg != 0)[:, :, None, None]
        return (jnp.where(real, out, 0.0) ** 2).sum()

    exact_val, exact_grads = jax.value_and_grad(
        lambda *a: loss(attention, *a), argnums=(0, 1, 2))(q, k, v)
    flash_val, flash_grads = jax.value_and_grad(
        lambda *a: loss(flash_attention, *a), argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(flash_val, exact_val, rtol=1e-5)
    for fg, eg, name in zip(flash_grads, exact_grads, "qkv"):
        np.testing.assert_allclose(fg, eg, rtol=1e-4, atol=1e-5,
                                   err_msg=f"d{name} mismatch")

    # empty-row contract: fully seg-masked (pad) rows emit exactly 0
    out = np.asarray(flash_attention(q, k, v, seg, causal=True))
    assert (out[np.asarray(seg) == 0] == 0).all()


@pytest.fixture(scope="module")
def tokenizer_dir(tmp_path_factory):
    from tokenizers import SentencePieceUnigramTokenizer
    from transformers import PreTrainedTokenizerFast

    spm = SentencePieceUnigramTokenizer()
    spm.train_from_iterator(
        ["the quick brown fox jumps over the lazy dog",
         "pipeline parallelism cuts a model into stages",
         "what is the capital of france paris is the capital"] * 8,
        vocab_size=120, unk_token="<unk>",
        special_tokens=["<unk>", "<s>", "</s>"])
    tok = PreTrainedTokenizerFast(tokenizer_object=spm._tokenizer,
                                  bos_token="<s>", eos_token="</s>",
                                  unk_token="<unk>")
    d = tmp_path_factory.mktemp("tok")
    tok.save_pretrained(str(d))
    return str(d)


def _packed_cfg(tmp_path, tokenizer_dir, out: str, **kw) -> dict:
    rows = [{"inputs": f"what is item {i}", "targets": f"item {i} is thing {i}"}
            for i in range(64)]
    data = tmp_path / "train.jsonl"
    if not data.exists():
        data.write_text("\n".join(json.dumps(r) for r in rows))
    cfg = {
        "output_dir": str(tmp_path / out),
        "mesh": {"pp": 2, "dp": 2},
        "model": {"preset": "tiny", "dtype": "float32",
                  "vocab_size": 128},
        "dataset": {"_target_":
                    "llama_pipeline_parallel_tpu.data.datasets.JsonSeq2SeqDataset",
                    "path": str(data)},
        "tokenizer_path": tokenizer_dir,
        "packing_factor": 2,
        "max_seq_length": 32,
        "per_device_train_batch_size": 2,
        "gradient_accumulation_steps": 2,
        "max_steps": 2,
        "learning_rate": 1e-3,
        "warmup_steps": 1,
        "logging_steps": 1,
        "save_final": False,
    }
    cfg.update(kw)
    return cfg


@pytest.mark.slow
def test_packed_training_end_to_end(devices, tmp_path, tokenizer_dir):
    """run_training with packing_factor=2 over a real jsonl dataset and
    tokenizer: packed rows flow through the PP=2 pipeline, loss is finite,
    and the metrics stream carries the cumulative packing drop counters
    (round-3 weak #4: drops were near-invisible)."""
    from llama_pipeline_parallel_tpu.train import run_training

    summary = run_training(_packed_cfg(tmp_path, tokenizer_dir, "out"))
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_loss"])
    lines = [json.loads(l)
             for l in open(tmp_path / "out" / "metrics.jsonl")]
    assert lines, "no metrics written"
    for line in lines:
        assert "packing_dropped_total" in line
        assert 0.0 <= line["packing_drop_rate"] <= 1.0


@pytest.mark.slow
def test_packed_ulysses_sp_matches_sp1(devices, tmp_path, tokenizer_dir):
    """Packing composes with Ulysses sequence parallelism (the mask is
    all-gathered to full length, so segment pairing stays exact): the sp=2
    loss equals the sp=1 loss on the identical run."""
    from llama_pipeline_parallel_tpu.train import run_training

    base = run_training(_packed_cfg(tmp_path, tokenizer_dir, "sp1",
                                    mesh={"pp": 2, "dp": 1}))
    sp2 = run_training(_packed_cfg(tmp_path, tokenizer_dir, "sp2",
                                   mesh={"pp": 2, "dp": 1, "sp": 2},
                                   sequence_parallel="ulysses"))
    np.testing.assert_allclose(sp2["final_loss"], base["final_loss"],
                               rtol=2e-5)


@pytest.mark.slow
def test_packed_ring_sp_matches_sp1(devices, tmp_path, tokenizer_dir):
    """Packing composes with RING sequence parallelism: pcfg.packed switches
    on the rotating kv segment slab (parallel/ring_attention.py), so the
    sp=2 ring loss equals the sp=1 loss on the identical packed run — the
    round-3 gap where the segment rotation machinery existed but was
    unreachable from the trainer."""
    from llama_pipeline_parallel_tpu.train import run_training

    base = run_training(_packed_cfg(tmp_path, tokenizer_dir, "ring_sp1",
                                    mesh={"pp": 2, "dp": 1}))
    sp2 = run_training(_packed_cfg(tmp_path, tokenizer_dir, "ring_sp2",
                                   mesh={"pp": 2, "dp": 1, "sp": 2},
                                   sequence_parallel="ring"))
    np.testing.assert_allclose(sp2["final_loss"], base["final_loss"],
                               rtol=2e-5)

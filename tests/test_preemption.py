"""Preemption-aware checkpointing: SIGTERM mid-run saves and exits cleanly."""

import os
import signal
import subprocess
import sys
import time

import pytest

# multi-process spawns: the expensive lane (round gate); `-m 'not slow'` skips
pytestmark = pytest.mark.slow


def test_sigterm_saves_checkpoint(tmp_path):
    out_dir = tmp_path / "out"
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.Popen(
        [sys.executable, "train.py", "--config", "conf/tiny_smoke.yaml",
         "--platform", "cpu", "max_steps=500", "total_steps=500",
         "logging_steps=1", f"output_dir={out_dir}"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    # wait until training has made at least one step (first metrics line);
    # read via a thread so a silently-wedged trainer can't block readline
    # past the deadline
    import queue
    import threading

    line_q: "queue.Queue[str]" = queue.Queue()
    threading.Thread(target=lambda: [line_q.put(l) for l in proc.stdout],
                     daemon=True).start()
    deadline = time.time() + 240
    progressed = False
    lines = []
    while time.time() < deadline:
        try:
            line = line_q.get(timeout=5)
        except queue.Empty:
            if proc.poll() is not None:
                break
            continue
        lines.append(line)
        if "loss=" in line:
            progressed = True
            break
    assert progressed, "trainer never made a step:\n" + "".join(lines[-20:])

    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0

    ckpts = [d for d in os.listdir(out_dir) if d.startswith("checkpoint-")]
    assert ckpts, f"no checkpoint written on SIGTERM; dir: {os.listdir(out_dir)}"
    assert os.path.exists(out_dir / "latest")

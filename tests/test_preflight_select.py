"""The preflight memory model's SELECTION pass, unit-tested as pure
arithmetic (tools/preflight.py enumerate_candidates / select_schedule —
no compile, no subprocess: the fast lane the CI Offload gate runs).

Pins which candidate wins at degenerate shapes: plenty of HBM -> zb1 with
nothing tiered; a stash-blown budget with a healthy host link -> zb1 +
offload.wgrad_stash (the offload conf's story); the same budget with a
starved link -> offload is REFUSED analytically and selection falls back
to interleaved; an impossible base -> no winner at all."""

import pytest

import preflight  # tools/ on sys.path via conftest

from llama_pipeline_parallel_tpu.parallel import pipeline as pl

# the 65B pp8 shape the configs of record run: 8 rows x 512 seq x 8192
# hidden x bf16 -> one stash slot = 64 MiB (the ONE dims spelling every
# consumer shares — parallel/pipeline.py:stash_dims)
DIMS = pl.stash_dims(8, 512, 1, 8192, "bfloat16")
S, M, LAYERS = 8, 256, 80
COMPUTE = lambda pcfg: 60.0  # modeled step-compute seconds, accum-invariant


def pick(base_gib, hbm, bw):
    return preflight.select_schedule(
        preflight.enumerate_candidates(S, M, LAYERS), base_gib, DIMS,
        hbm, bw, COMPUTE)


def test_grid_shape():
    cands = preflight.enumerate_candidates(S, M, LAYERS)
    # v=4 is layer-indivisible (80 % 32 != 0): only v in {1, 2} appears
    assert {c.virtual_stages for c in cands} == {1, 2}
    assert {c.schedule for c in cands} == {"1f1b", "interleaved_1f1b", "zb1"}
    assert any(c.offload_wgrad for c in cands)
    assert all(not c.offload_wgrad or c.schedule == "zb1" for c in cands)


def test_plenty_of_hbm_picks_zb1_untiered():
    """With room for the 64 GiB stash in HBM, zb1 v2 c1 wins on bubble and
    the tie-break keeps every store on device (never move bytes for
    nothing)."""
    winner, rows = pick(base_gib=70.0, hbm=1000.0, bw=30.0)
    assert winner["schedule"] == "zb1"
    assert winner["virtual_stages"] == 2 and winner["accum_chunks"] == 1
    assert not winner["offload_wgrad"] and not winner["offload_activations"]
    assert winner["bubble_fraction"] == round(14 / 1550, 4)


def test_stash_blown_budget_picks_wgrad_offload():
    """The offload conf's exact story: base ~70 GiB + 64 GiB stash refuses
    a 95 GiB part, but tiering the W queue to host (128 GiB of traffic
    hiding inside a 60 s step at 30 GiB/s) keeps micro=8 AND the 0.90%
    bubble."""
    winner, rows = pick(base_gib=70.0, hbm=95.0, bw=30.0)
    assert winner["schedule"] == "zb1" and winner["offload_wgrad"]
    assert not winner["offload_activations"]  # ring fits; don't tier it
    assert winner["bubble_fraction"] == round(14 / 1550, 4)
    assert winner["est_peak_gib"] < 95.0
    # the in-HBM zb1 candidate at the same shape was scored and refused
    in_hbm = next(r for r in rows if r["schedule"] == "zb1"
                  and r["virtual_stages"] == 2 and r["accum_chunks"] == 1
                  and not r["offload_wgrad"] and not r["offload_activations"])
    assert not in_hbm["feasible"] and in_hbm["why_not"] == "exceeds HBM budget"


def test_starved_host_link_refuses_offload_falls_back_to_interleaved():
    """At 0.5 GiB/s the 128 GiB stash can never hide inside the step:
    every offload candidate is rejected ANALYTICALLY (hide_ratio, not a
    live-run stall) and selection falls back to interleaved v2 — the
    lowest-bubble schedule whose memory fits without the host."""
    winner, rows = pick(base_gib=70.0, hbm=95.0, bw=0.5)
    assert winner["schedule"] == "interleaved_1f1b"
    assert winner["virtual_stages"] == 2 and winner["accum_chunks"] == 1
    assert not winner["offload_wgrad"] and not winner["offload_activations"]
    refused = [r for r in rows if r["offload_wgrad"]]
    assert refused and all(not r["feasible"] for r in refused)
    assert any(r["why_not"] == "offload traffic cannot hide behind compute"
               for r in refused)


def test_nothing_fits_returns_no_winner():
    winner, rows = pick(base_gib=120.0, hbm=95.0, bw=30.0)
    assert winner is None
    assert all(not r["feasible"] for r in rows)


def test_offload_traffic_arithmetic():
    slot = 8 * 512 * 8192 * 2
    wg = pl.PipelineConfig(num_stages=S, num_microbatches=M, schedule="zb1",
                           virtual_stages=2, offload_wgrad=True)
    # 2 buffers per unit x Mv units x 2 directions = 4 * 512 slots
    assert preflight.offload_traffic_bytes(wg, DIMS) == 4 * 512 * slot
    both = pl.PipelineConfig(num_stages=S, num_microbatches=M,
                             schedule="zb1", virtual_stages=2,
                             offload_wgrad=True, offload_activations=True)
    assert preflight.offload_traffic_bytes(both, DIMS) == 6 * 512 * slot
    # accum_chunks shifts WHEN bytes move, not how much
    chunked = pl.PipelineConfig(num_stages=S, num_microbatches=M,
                                schedule="zb1", virtual_stages=2,
                                accum_chunks=4, offload_wgrad=True)
    assert preflight.offload_traffic_bytes(chunked, DIMS) == 4 * 512 * slot
    none = pl.PipelineConfig(num_stages=S, num_microbatches=M,
                             schedule="zb1", virtual_stages=2)
    assert preflight.offload_traffic_bytes(none, DIMS) == 0


def test_feasibility_report_keys():
    wg = pl.PipelineConfig(num_stages=S, num_microbatches=M, schedule="zb1",
                           virtual_stages=2, offload_wgrad=True)
    feas = preflight.offload_feasibility(wg, DIMS, step_compute_s=60.0,
                                         host_bw_gibps=30.0)
    assert feas["offload_traffic_gib_per_step"] == 128.0
    assert feas["offload_hide_ratio"] == pytest.approx(128 / 30 / 60,
                                                       abs=1e-3)


def test_select_overrides_roundtrip():
    winner, _ = pick(base_gib=70.0, hbm=95.0, bw=30.0)
    line = preflight.select_overrides(winner)
    assert "pipeline_schedule=zb1" in line
    assert "virtual_stages=2" in line
    assert "offload.wgrad_stash=true" in line
    assert "offload.activations" not in line
    # the legacy grid carries no ce axis: no kernel overrides appear
    assert "kernels.ce" not in line and "loss_vocab_chunks" not in line


# ---------------------------------------------------------------------------
# The ce axis (PR 10): loss_chunks / kernels.ce as selection candidates
# ---------------------------------------------------------------------------

VOCAB = 32000
# the real constructor's output at this shape: as-written dense, 8-way
# chunked XLA, and the ONE VMEM-sized pallas option (128-wide tiles)
CE_AXIS = ((1, False), (8, False), (250, True))


def pick_ce(base_gib, hbm, bw):
    return preflight.select_schedule(
        preflight.enumerate_candidates(S, M, LAYERS, ce_options=CE_AXIS),
        base_gib, DIMS, hbm, bw, COMPUTE, vocab=VOCAB)


def test_ce_axis_expands_grid_and_scores_loss_head():
    """Each (loss_chunks, kernel_ce) option appears per schedule point, and
    the scored rows carry the loss-head term: dense XLA = the fp32
    [tokens, V] block (4096 tokens x 32000 x 4B = 0.49 GiB at the 65B pp8
    shape), chunked-8 = block/8 + the fp32 dh accumulator, pallas = 0."""
    rows = pick_ce(base_gib=70.0, hbm=1000.0, bw=30.0)[1]
    combos = {(r["loss_chunks"], r["kernel_ce"]) for r in rows}
    assert combos == set(CE_AXIS)
    zb1_v2 = [r for r in rows if r["schedule"] == "zb1"
              and r["virtual_stages"] == 2 and r["accum_chunks"] == 1
              and not r["offload_wgrad"] and not r["offload_activations"]]
    by_ce = {(r["loss_chunks"], r["kernel_ce"]): r for r in zb1_v2}
    tokens = 8 * 512
    assert by_ce[(1, False)]["loss_head_gib"] == pytest.approx(
        tokens * VOCAB * 4 / (1 << 30), abs=0.01)
    assert by_ce[(8, False)]["loss_head_gib"] == pytest.approx(
        (tokens * VOCAB // 8 * 4 + tokens * 8192 * 4) / (1 << 30), abs=0.01)
    assert by_ce[(250, True)]["loss_head_gib"] == 0.0
    # est_peak orders pallas < chunked-xla < dense-xla at fixed schedule
    assert by_ce[(250, True)]["est_peak_gib"] \
        < by_ce[(8, False)]["est_peak_gib"] \
        < by_ce[(1, False)]["est_peak_gib"]


def test_ce_axis_winner_takes_the_zero_byte_head():
    """At the same bubble/host point the tie-break resolves through
    est_peak, so the Pallas head (the only option with a zero loss-head
    term) wins the axis; the overrides line names both knobs."""
    winner, _ = pick_ce(base_gib=70.0, hbm=1000.0, bw=30.0)
    assert winner["kernel_ce"] and winner["loss_chunks"] == 250
    assert winner["schedule"] == "zb1"  # the schedule choice is unchanged
    line = preflight.select_overrides(winner)
    assert "kernels.ce=pallas" in line and "loss_vocab_chunks=250" in line


def test_ce_axis_options_shape():
    """The axis constructor _print_selection uses: tp>1 suppresses the axis
    entirely (the trainer rejects loss_chunks/kernels.ce there — selection
    must never emit overrides the launch line refuses), and the Pallas
    head is offered CHUNKED only (loss_chunks=1 would hold the whole
    [d, V] weight as one VMEM block)."""
    assert preflight.ce_axis_options(1, VOCAB, tp=2) is None
    axis = preflight.ce_axis_options(1, VOCAB, tp=1)
    assert axis == CE_AXIS
    # the pallas option exists ONLY at the kernel's VMEM sizing — never at
    # the XLA-scale chunk counts, never unchunked
    assert all(chunks == 250 for chunks, k in axis if k)
    # as-written chunking is kept as its own option alongside the 8-way
    assert preflight.ce_axis_options(16, VOCAB, tp=1) == (
        (8, False), (16, False), (250, True))
    # vocab with no 128-wide tiling: no pallas option at all
    assert preflight.ce_axis_options(1, VOCAB + 8, tp=1) == (
        (1, False), (8, False))


def test_ce_axis_rescues_a_budget_the_xla_head_blows():
    """A budget sized between the pallas and XLA loss-head terms: only the
    kernels.ce=pallas rows fit, selection says so analytically."""
    # flat S=8 ring = 15 slots x 64 MiB = 0.94 GiB: base 94.0 leaves room
    # for ring + the zero-byte pallas head but not ring + 0.49 GiB dense
    rows = pick_ce(base_gib=94.0, hbm=95.0, bw=30.0)[1]
    flat = [r for r in rows if r["schedule"] == "1f1b"
            and r["accum_chunks"] == 1 and not r["offload_activations"]]
    verdict = {(r["loss_chunks"], r["kernel_ce"]): r["feasible"]
               for r in flat}
    assert verdict[(250, True)] and not verdict[(1, False)]


# ---------------------------------------------------------------------------
# The solver lane (PR 11): list-scheduled sequences with per-unit offload
# ---------------------------------------------------------------------------

import numpy as np


def test_solver_candidates_beat_canonicals_at_65b_shape():
    """The acceptance case: at the 65B pp8 shape under the PR 8 budget +
    hide-ratio constraints, the solver emits a sequence preflight scores
    STRICTLY better than all three canonical schedules — zb1's 0.90%
    bubble with only the budget-required fraction of residuals tiered, so
    it wins the (bubble, tiered-bytes, peak) tie-break on tiered bytes."""
    cands = preflight.enumerate_candidates(S, M, LAYERS)
    cands += preflight.solver_candidates(S, M, LAYERS, 70.0, DIMS, 95.0)
    winner, rows = preflight.select_schedule(cands, 70.0, DIMS, 95.0, 30.0,
                                             COMPUTE)
    assert winner["schedule"] == "solver"
    best_canon = min((r for r in rows if r["schedule"] != "solver"
                      and r["feasible"]),
                     key=lambda r: (r["bubble_fraction"],
                                    r["host_stash_gib"],
                                    r["est_peak_gib"]))
    assert winner["bubble_fraction"] == best_canon["bubble_fraction"] \
        == round(14 / 1550, 4)
    assert winner["host_stash_gib"] < best_canon["host_stash_gib"]
    assert winner["est_peak_gib"] <= 95.0
    # selective offload: strictly between the boolean's extremes
    assert 0 < winner["wgrad_offload_units"] < winner["wgrad_units_total"]


def test_solver_offload_boundary_points():
    """The per-unit decision space contains both `offload.wgrad_stash`
    extremes: a roomy budget sizes the vector all-False (== off), a budget
    with no room for any HBM slot sizes it all-True (== on)."""
    roomy = preflight.solver_candidates(S, M, LAYERS, 70.0, DIMS, 10000.0)
    assert roomy and all(c.unit_schedule.offloaded_units == 0 for c in roomy)
    # base 70 + ring ~0.94 + 4 transfer slots 0.25 GiB ~= 71.2: everything
    # must tier for the 72 GiB budget to hold at the v2 c1 point
    tight = preflight.solver_candidates(S, M, LAYERS, 70.0, DIMS, 72.0)
    v2c1 = [c for c in tight if c.virtual_stages == 2 and c.accum_chunks == 1
            and c.unit_schedule.label.endswith("trailing-w")]
    assert v2c1 and all(
        c.unit_schedule.offloaded_units == c.unit_schedule.n_units
        for c in v2c1)
    from llama_pipeline_parallel_tpu.parallel import pipeline as _pl

    # and the boundary candidate's byte models equal the boolean's
    zb = _pl.PipelineConfig(num_stages=S, num_microbatches=M,
                            schedule="zb1", virtual_stages=2,
                            offload_wgrad=True)
    assert _pl.host_stash_bytes(v2c1[0], *DIMS) == \
        _pl.host_stash_bytes(zb, *DIMS)
    assert preflight.offload_traffic_bytes(v2c1[0], DIMS) == \
        preflight.offload_traffic_bytes(zb, DIMS)


def test_solver_rows_respect_hide_ratio():
    """The hide-ratio bound refuses tiered solver rows with the SAME
    analytic verdict as the boolean candidates — and a MIXED vector is
    charged the FULL unit traffic, not just its tiered subset: the
    interpreter's tick-uniform body pushes the host buffer every B tick
    (non-tiered units land in the garbage slot, but the D2H still moves)
    and where-selects every W pop from both buffers, so selective offload
    buys host RESIDENCY, never link bytes — on a starved 0.5 GiB/s link
    every tiered row is refused, exactly like the boolean
    (test_starved_host_link_refuses_offload_falls_back_to_interleaved)."""
    cands = preflight.solver_candidates(S, M, LAYERS, 70.0, DIMS, 95.0)
    _, rows = preflight.select_schedule(cands, 70.0, DIMS, 95.0, 0.5,
                                        COMPUTE)
    tiered = [r for r in rows if r.get("wgrad_offload_units")]
    assert tiered and all(not r["feasible"] for r in tiered)
    assert all(r["why_not"] == "offload traffic cannot hide behind compute"
               for r in tiered)
    # the mixed rows' traffic equals the boolean's at the same (v, c)
    from llama_pipeline_parallel_tpu.parallel import pipeline as _pl

    mixed = next(c for c in cands
                 if 0 < c.unit_schedule.offloaded_units
                 < c.unit_schedule.n_units)
    zb = _pl.PipelineConfig(num_stages=S, num_microbatches=M,
                            schedule="zb1",
                            virtual_stages=mixed.virtual_stages,
                            accum_chunks=mixed.accum_chunks,
                            offload_wgrad=True)
    assert preflight.offload_traffic_bytes(mixed, DIMS) == \
        preflight.offload_traffic_bytes(zb, DIMS)


def test_select_overrides_solver_row():
    cands = preflight.enumerate_candidates(S, M, LAYERS)
    cands += preflight.solver_candidates(S, M, LAYERS, 70.0, DIMS, 95.0)
    winner, _ = preflight.select_schedule(cands, 70.0, DIMS, 95.0, 30.0,
                                          COMPUTE)
    line = preflight.select_overrides(winner)
    assert "pipeline_schedule=solver" in line
    assert "schedule_file=<path from --emit-schedule>" in line
    assert "offload.wgrad_stash" not in line  # the vector, not the boolean
    line2 = preflight.select_overrides(winner, schedule_file="/tmp/s.json")
    assert "schedule_file=/tmp/s.json" in line2


def test_stash_remedies_derive_from_sequences():
    """The refusal text's numbers come from counting the emitted sequences'
    idle ticks, not hard-coded formulas: the named fallback's bubble must
    equal bubble_fraction of that schedule at this shape."""
    from llama_pipeline_parallel_tpu.parallel import pipeline as _pl

    zb = _pl.PipelineConfig(num_stages=S, num_microbatches=M,
                            schedule="zb1", virtual_stages=2)
    text = preflight.stash_remedies(zb)
    assert f"{zb.num_microbatches * 2} residual units" in text
    alt = _pl.PipelineConfig(num_stages=S, num_microbatches=M,
                             schedule="interleaved_1f1b", virtual_stages=2)
    assert f"bubble {100 * _pl.bubble_fraction(alt):.2f}%" in text
    assert f"vs {100 * _pl.bubble_fraction(zb):.2f}%" in text
    assert "solver" in text

"""Pipeline schedule correctness: PP=N must match PP=1 must match plain forward.

This is the numerical-equivalence suite SURVEY.md §4(c) calls for — the
verification the reference never had (it validated its schedule with print
statements, reference README.md:161)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()  # 4 layers


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.PRNGKey(0), cfg)


def make_batch(cfg, batch_size=8, seqlen=16, seed=42):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, cfg.vocab_size, size=(batch_size, seqlen)).astype(np.int32)
    mask = np.ones((batch_size, seqlen), np.int32)
    mask[:, -3:] = 0  # trailing padding
    labels = ids.copy()
    labels[mask == 0] = llama.IGNORE_INDEX
    labels[:, :2] = llama.IGNORE_INDEX  # prompt masking, reference get_lm_labels
    pos = np.broadcast_to(np.arange(seqlen, dtype=np.int32), (batch_size, seqlen)).copy()
    return {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.asarray(mask),
        "position_ids": jnp.asarray(pos),
        "labels": jnp.asarray(labels),
    }


def reference_loss_and_grad(params, batch, cfg):
    """Plain single-device forward+loss, the ground truth."""

    def loss(p):
        logits = llama.forward(p, batch["input_ids"], batch["attention_mask"],
                               batch["position_ids"], cfg=cfg)
        return llama.loss_fn(logits, batch["labels"])

    return jax.value_and_grad(loss)(params)


def run_pipeline(params, batch, cfg, pp, dp, microbatches, remat=True, chunks=1,
                 schedule="1f1b"):
    mesh = make_mesh(MeshConfig(pp=pp, dp=dp))
    manifest = StageManifest.for_config(cfg, pp)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=pp, num_microbatches=microbatches,
                             remat=remat, accum_chunks=chunks, schedule=schedule)
    fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked))
    loss, grads = fn(stacked, batch)
    return loss, pl.unstack_stages(grads, manifest)


def assert_tree_close(a, b, rtol=2e-5, atol=1e-6):
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


def test_pp1_matches_plain_forward(cfg, params, devices):
    batch = make_batch(cfg)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads = run_pipeline(params, batch, cfg, pp=1, dp=1, microbatches=4)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    assert_tree_close(grads, ref_grads)


@pytest.mark.parametrize("pp,dp,microbatches", [
    # pp4 pure rep slow-marked (PR 14 rebalance): (2,2,3) composes dp +
    # odd M over the same interpreter, and the zb1/interleaved grids keep
    # their own pp reps fast
    pytest.param(4, 1, 4, marks=pytest.mark.slow),
    (2, 2, 3),
    pytest.param(4, 1, 6, marks=pytest.mark.slow),
    pytest.param(4, 2, 4, marks=pytest.mark.slow)])
def test_pp_matches_reference(cfg, params, devices, pp, dp, microbatches):
    """PP=N hybrid grids reproduce the single-device loss AND gradients."""
    batch = make_batch(cfg, batch_size=dp * microbatches * 2)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads = run_pipeline(params, batch, cfg, pp=pp, dp=dp, microbatches=microbatches)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    assert_tree_close(grads, ref_grads)


@pytest.mark.parametrize("chunks,schedule", [
    (2, "1f1b"), (2, "gpipe"),
    # chunks=4 adds no new fold structure over chunks=2 (PR 10 rebalance)
    pytest.param(4, "1f1b", marks=pytest.mark.slow)])
def test_chunked_accumulation_matches(cfg, params, devices, chunks, schedule):
    """accum_chunks splits the flush without changing loss or gradients —
    under both schedules (chunks are the gpipe path's only memory bound)."""
    batch = make_batch(cfg, batch_size=8)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads = run_pipeline(params, batch, cfg, pp=4, dp=1, microbatches=4,
                               chunks=chunks, schedule=schedule)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    assert_tree_close(grads, ref_grads)


def test_gpipe_schedule_matches(cfg, params, devices):
    """The legacy AD-differentiated GPipe schedule stays available and exact."""
    batch = make_batch(cfg)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads = run_pipeline(params, batch, cfg, pp=4, dp=1, microbatches=4,
                               schedule="gpipe")
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    assert_tree_close(grads, ref_grads)


@pytest.mark.parametrize("pp,microbatches", [
    (2, 1),
    pytest.param(4, 2, marks=pytest.mark.slow),
    pytest.param(4, 4, marks=pytest.mark.slow)])
def test_1f1b_fewer_microbatches_than_stages(cfg, params, devices, pp, microbatches):
    """1F1B edge cases M < S, M == S, M == 1: the warmup/drain masking and the
    min(2S-1, M) input ring buffer must stay exact when the pipe never fills."""
    batch = make_batch(cfg, batch_size=microbatches * 2)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads = run_pipeline(params, batch, cfg, pp=pp, dp=1,
                               microbatches=microbatches)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    assert_tree_close(grads, ref_grads)


def test_bad_schedule():
    with pytest.raises(ValueError, match="schedule"):
        pl.PipelineConfig(num_stages=2, num_microbatches=4, schedule="pipedream")


def test_bad_chunks():
    with pytest.raises(ValueError, match="accum_chunks"):
        pl.PipelineConfig(num_stages=2, num_microbatches=4, accum_chunks=3)


def test_remat_off_matches(cfg, params, devices):
    batch = make_batch(cfg)
    l1, g1 = run_pipeline(params, batch, cfg, pp=4, dp=1, microbatches=4, remat=True)
    l2, g2 = run_pipeline(params, batch, cfg, pp=4, dp=1, microbatches=4, remat=False)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    assert_tree_close(g1, g2)


@pytest.mark.slow
def test_pp8_headline_topology(devices):
    """The 65B config-of-record topology (PP=8, chunked accumulation) at tiny
    scale on the full 8-device mesh — every stage boundary exercised."""
    cfg8 = LlamaConfig.tiny(num_hidden_layers=8)
    params = llama.init_params(jax.random.PRNGKey(0), cfg8)
    batch = make_batch(cfg8, batch_size=8)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg8)
    loss, grads = run_pipeline(params, batch, cfg8, pp=8, dp=1, microbatches=4,
                               chunks=2)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    assert_tree_close(grads, ref_grads)


@pytest.mark.slow
def test_1f1b_memory_bounded_in_microbatches(cfg, params, devices):
    """THE point of 1F1B (VERDICT round-1 item 3's acceptance criterion):
    in-flight activation memory must not grow with the grad-accumulation
    depth M. XLA's compile-time memory analysis makes the claim checkable
    without hardware: the 1f1b program's temp allocation stays ~flat from
    M=8 to M=64 while the AD-differentiated gpipe program's grows ~linearly
    (it stores one boundary activation per tick)."""
    mesh = make_mesh(MeshConfig(pp=4))
    manifest = StageManifest.for_config(cfg, 4)
    stacked = pl.stack_stages(params, manifest)

    def temp_bytes(schedule, m):
        batch = make_batch(cfg, batch_size=m, seqlen=16)
        pcfg = pl.PipelineConfig(num_stages=4, num_microbatches=m,
                                 schedule=schedule)
        fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked))
        analysis = fn.lower(stacked, batch).compile().memory_analysis()
        if analysis is None or not getattr(analysis, "temp_size_in_bytes", 0):
            pytest.skip("backend exposes no compile-time memory analysis")
        return analysis.temp_size_in_bytes

    growth_1f1b = temp_bytes("1f1b", 64) / temp_bytes("1f1b", 8)
    growth_gpipe = temp_bytes("gpipe", 64) / temp_bytes("gpipe", 8)
    assert growth_1f1b < 1.3, f"1f1b temp memory grew {growth_1f1b:.2f}x in M"
    assert growth_gpipe > 1.8, (
        f"gpipe grew only {growth_gpipe:.2f}x — if XLA learned to bound it, "
        f"revisit whether the 1f1b schedule is still the memory win")


def test_stack_unstack_roundtrip(cfg, params):
    man = StageManifest.for_config(cfg, 4)
    rt = pl.unstack_stages(pl.stack_stages(params, man), man)
    assert_tree_close(rt, params, rtol=0, atol=0)


def test_bad_microbatch_split(cfg, params, devices):
    batch = make_batch(cfg, batch_size=6)
    with pytest.raises(ValueError, match="not divisible"):
        run_pipeline(params, batch, cfg, pp=2, dp=1, microbatches=4)

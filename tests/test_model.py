"""Model numerics: ops sanity, HF logits parity, loss masking."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as M
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.hf import (
    hf_state_dict_from_params,
    params_from_hf_state_dict,
)
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest


@pytest.fixture(scope="module")
def tiny_cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return M.init_params(jax.random.PRNGKey(0), tiny_cfg)


def test_forward_shapes(tiny_cfg, tiny_params):
    ids = jnp.zeros((2, 16), jnp.int32)
    logits = M.forward(tiny_params, ids, cfg=tiny_cfg)
    assert logits.shape == (2, 16, tiny_cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_remat_equivalence(tiny_cfg, tiny_params):
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, tiny_cfg.vocab_size)
    labels = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, tiny_cfg.vocab_size)

    def loss(p, remat):
        return M.loss_fn(M.forward(p, ids, cfg=tiny_cfg, remat=remat), labels)

    l0, g0 = jax.value_and_grad(loss)(tiny_params, False)
    l1, g1 = jax.value_and_grad(loss)(tiny_params, True)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6), g0, g1)


def test_loss_ignore_index(tiny_cfg):
    logits = jnp.zeros((1, 4, 8), jnp.float32)
    labels_all_ignored = jnp.full((1, 4), M.IGNORE_INDEX, jnp.int32)
    assert float(M.loss_fn(logits, labels_all_ignored)) == 0.0
    labels = jnp.array([[M.IGNORE_INDEX, 1, M.IGNORE_INDEX, 2]], jnp.int32)
    # uniform logits over 8 classes -> loss = log(8) per valid token
    np.testing.assert_allclose(float(M.loss_fn(logits, labels)), np.log(8.0), rtol=1e-6)


def test_padding_mask_affects_only_padded_context(tiny_cfg, tiny_params):
    """Changing a padded-out token's id must not change logits of real tokens."""
    ids = jax.random.randint(jax.random.PRNGKey(3), (1, 8), 0, tiny_cfg.vocab_size)
    mask = jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.int32)
    ids2 = ids.at[0, 5].set((ids[0, 5] + 1) % tiny_cfg.vocab_size)
    out1 = M.forward(tiny_params, ids, attention_mask=mask, cfg=tiny_cfg)
    out2 = M.forward(tiny_params, ids2, attention_mask=mask, cfg=tiny_cfg)
    np.testing.assert_allclose(out1[0, :4], out2[0, :4], atol=1e-5)


def test_manifest():
    man = StageManifest(num_layers=8, num_stages=4)
    assert man.layers_per_stage == 2
    assert man.stage_of_layer(5) == 2
    assert list(man.layers_of_stage(3)) == [6, 7]
    assert man.head_stage == 3
    rt = StageManifest.from_json(man.to_json())
    assert rt == man
    with pytest.raises(ValueError):
        StageManifest(num_layers=7, num_stages=4)


@pytest.mark.parametrize("kv_heads", [4, 2])
def test_logits_match_hf(kv_heads):
    """Bit-level parity with transformers' LlamaForCausalLM (eager, fp32).

    The reference delegates all block math to HF's LlamaDecoderLayer
    (models/llama_ds_mp_wrap.py:8-13); this pins our re-implementation to the
    same numerics, including GQA and rotary embedding conventions."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM

    hf_cfg = HFLlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=kv_heads,
        max_position_embeddings=128, rms_norm_eps=1e-6, rope_theta=10000.0,
        attn_implementation="eager", tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf_model = LlamaForCausalLM(hf_cfg).eval()

    cfg = LlamaConfig.from_hf_config(hf_cfg, dtype=jnp.float32)
    params = params_from_hf_state_dict(hf_model.state_dict(), cfg)

    ids_np = np.random.RandomState(0).randint(0, 256, size=(2, 12))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids_np)).logits.numpy()
    ours = np.asarray(M.forward(params, jnp.asarray(ids_np), cfg=cfg))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)

    # round-trip the export path too
    sd2 = hf_state_dict_from_params(params, cfg)
    for k, v in sd2.items():
        np.testing.assert_allclose(v, hf_model.state_dict()[k].float().numpy(),
                                   rtol=1e-6, atol=1e-6)

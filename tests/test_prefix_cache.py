"""Prefix caching over the paged KV pool (serve/pages.py block-hash chains
+ copy-on-write forks, the warm-admission span path in serve/engine.py and
models/llama/decode.py — docs/SERVING.md "Prefix caching").

The acceptance contracts live here:
- a cache-hit request's tokens are BIT-EQUAL (fp32 and bf16) to the same
  request served cold on a cache-off engine AND to an independent
  `generate()` call — full-row re-serve, mid-page divergence (CoW fork),
  and page-boundary divergence (no fork) all land on the same stream;
- sharing is cache-aware admission: at a fixed pool the shared-prefix
  workload admits >= 2x what the cache-off reservation math admits, the
  admissions are REAL (every one reaches a slot), and the refusal is
  still ServePagesExhausted with a positive Retry-After;
- refcount-0 cached pages evict (LRU, whole-subtree cascade) BEFORE the
  pool refuses, and an evicted-then-refilled prompt reproduces its
  original tokens exactly;
- nothing leaks: after draining, non-cached pages are back on the free
  list, every cached page sits at refcount zero on the idle list, and a
  cancelled (abandoned) request frees its slot + unshared pages at the
  next tick while shared pages just drop a refcount;
- cache OFF is the exact PR-13 engine: no prefix keys in the snapshot,
  identical exhaustion math; `prefix_cache` on the dense cache is a
  config error;
- int8 pages keep the tolerance-gated contract (greedy warm stream
  matches the greedy cold int8 stream token-for-token on this grid);
- the telemetry shows up end-to-end: engine snapshot counters, the
  `prefix_cache_hit` span + record fields in request_trace.jsonl, and
  the serving_report / request_report render lines.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import request_report
import serve_traffic as traffic
import serving_report
from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.decode import (
    GenerationConfig,
    generate,
)
from llama_pipeline_parallel_tpu.serve import (
    PagedKVCache,
    ServeConfig,
    ServeEngine,
    ServePagesExhausted,
    ServeRequest,
)
from llama_pipeline_parallel_tpu.serve.pages import chain_hashes, page_demand
from llama_pipeline_parallel_tpu.serve.reqtrace import (
    REQUEST_TRACE_NAME,
    RequestTraceRecorder,
)
from llama_pipeline_parallel_tpu.utils.perf import read_jsonl

BUCKET = 8
PAGE = 4


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def make_engine(cfg, params, **kw):
    """The standard paged test shape (test_paged_serving.py) with the
    prefix cache ON — shared so the warm-span programs compile once."""
    reqtrace = kw.pop("reqtrace", None)
    defaults = dict(max_slots=2, max_len=BUCKET + 8, prompt_buckets=(BUCKET,),
                    max_queue=8, metrics_every=1, decode_span_every=1,
                    kv_cache="paged", page_size=PAGE, num_pages=16,
                    prefix_cache=True)
    defaults.update(kw)
    return ServeEngine(params, cfg, ServeConfig(**defaults),
                       reqtrace=reqtrace)


def reference_tokens(params, cfg, prompt, gen, seed, bucket=BUCKET):
    pad = bucket - len(prompt)
    ids = np.concatenate([np.zeros(pad, np.int32),
                          np.asarray(prompt, np.int32)])[None]
    mask = np.asarray([[0] * pad + [1] * len(prompt)], np.int32)
    out = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, gen,
                   rng=jax.random.PRNGKey(seed))
    return np.asarray(out["tokens"])[0].tolist()


def serve_one(engine, prompt, gen, seed):
    h = engine.submit(ServeRequest(input_ids=list(prompt), gen=gen,
                                   seed=seed))
    engine.drain(timeout_s=120)
    return h.result(timeout=1), h


def assert_no_leaks(engine):
    """The drained-pool invariant: every non-cached page is free, every
    cached page is idle at refcount zero, nothing is reserved."""
    s = engine.slots
    assert s.pages_reserved == 0
    assert s._held == 0 and not s._ref
    assert set(s._idle) == set(s._page_node)
    assert s.pages_free == s.num_pages - s.pages_cached


# -- block-hash chains (host-side, no engine) ---------------------------------


def test_chain_hashes_depend_on_content_chain_and_mask():
    ids = np.arange(8, dtype=np.int32) + 3
    mask = np.ones(8, np.int32)
    base = chain_hashes(ids, mask, PAGE)
    assert len(base) == 2
    assert chain_hashes(ids.copy(), mask.copy(), PAGE) == base

    # a late-block edit leaves earlier hashes intact (prefix reuse)...
    late = ids.copy()
    late[6] += 1
    h = chain_hashes(late, mask, PAGE)
    assert h[0] == base[0] and h[1] != base[1]

    # ...an early edit poisons the whole chain (KV at j depends on [0, j])
    early = ids.copy()
    early[2] += 1
    h = chain_hashes(early, mask, PAGE)
    assert h[0] != base[0] and h[1] != base[1]

    # pad layout participates: same ids, different mask must NOT share
    shifted = mask.copy()
    shifted[0] = 0
    h = chain_hashes(ids, shifted, PAGE)
    assert h[0] != base[0] and h[1] != base[1]


def _register_chain(cache, ids, mask, demand, rid="seed"):
    """Drive one prompt through the miss -> prefill -> register -> release
    lifecycle so its pages sit cached at refcount zero."""
    m = cache.match_and_reserve(rid, ids, mask, demand)
    assert m is not None and m.tokens == 0 and m.pages == []
    slot = cache.acquire(rid, m.new_demand, match=m)
    cache.ensure_capacity(slot, len(ids))
    assert cache.register_prefix(slot, m.hashes, ids, mask) == \
        len(ids) // cache.page_size
    cache.release(slot)
    return m.hashes


def test_match_geometry_full_midpage_and_boundary(setup):
    cfg, _ = setup
    cache = PagedKVCache(cfg, max_slots=2, max_len=16, page_size=PAGE,
                         num_pages=8, prefix_cache=True)
    ids = np.arange(8, dtype=np.int32) + 3
    mask = np.ones(8, np.int32)
    hashes = _register_chain(cache, ids, mask, page_demand(8, 8, PAGE))
    assert cache.pages_cached == 2 and cache._held == 0
    assert cache.pages_free == 6
    p0 = cache._index[hashes[0]].page
    p1 = cache._index[hashes[1]].page

    # full-row match: one position must recompute for the first-token
    # sample, so the verdict caps at bucket-1 and forks the last page
    m = cache.match_and_reserve("full", ids, mask, 4)
    assert (m.tokens, m.pages, m.fork_src, m.new_demand) == (7, [p0], p1, 3)
    cache.cancel_match(m)

    # page-boundary divergence: whole pages share, nothing forks
    bnd = ids.copy()
    bnd[4] += 1
    m = cache.match_and_reserve("bnd", bnd, mask, 4)
    assert (m.tokens, m.pages, m.fork_src, m.new_demand) == (4, [p0], None, 3)
    cache.cancel_match(m)

    # mid-page divergence: the longest common block prefix forks its page
    mid = ids.copy()
    mid[6] += 1
    m = cache.match_and_reserve("mid", mid, mask, 4)
    assert (m.tokens, m.pages, m.fork_src, m.new_demand) == (6, [p0], p1, 3)
    cache.cancel_match(m)

    # every pin undone: cached pages idle again, nothing reserved or held
    assert cache._held == 0 and cache.pages_reserved == 0
    assert len(cache._idle) == 2


def test_refcount_zero_pages_evict_before_refusal(setup):
    cfg, _ = setup
    cache = PagedKVCache(cfg, max_slots=2, max_len=16, page_size=PAGE,
                         num_pages=4, prefix_cache=True)
    ids = np.arange(8, dtype=np.int32) + 3
    mask = np.ones(8, np.int32)
    _register_chain(cache, ids, mask, page_demand(8, 1, PAGE))
    assert (cache.pages_cached, cache.pages_free) == (2, 2)

    # idle cached pages do NOT count against admission: the whole pool is
    # still reservable even though only two pages sit on the free list
    assert cache.reserve(4)
    slot = cache.acquire("r2", 4)
    cache.ensure_capacity(slot, 16)    # needs 4 pages: evicts the chain
    assert cache.prefix_evictions == 2 and cache.pages_cached == 0
    cache.release(slot)
    assert cache.pages_free == 4


# -- traffic-shape purity ------------------------------------------------------


def test_prefix_mix_draws_do_not_perturb_the_trace():
    kw = dict(prompt_mix=traffic.parse_mix("8:0.5,16:0.5"),
              output_mix=traffic.parse_mix("4:1.0"))
    base = traffic.poisson_trace(3, 8.0, 20, **kw)
    mixed = traffic.poisson_trace(
        3, 8.0, 20, prefix_mix=traffic.parse_prefix_mix("sys16:0.5,cold:0.5"),
        **kw)
    # prefix draws come AFTER the arrival/length/seed streams: the trace
    # is identical in every pre-existing dimension
    key = lambda r: (r.arrival_s, r.prompt_len, r.max_new_tokens, r.seed,
                     r.tenant)
    assert [key(r) for r in base] == [key(r) for r in mixed]
    assert all(r.prefix is None for r in base)
    assert {(r.prefix, r.prefix_len) for r in mixed} <= \
        {("sys16", 16), ("cold", 0)}
    assert any(r.prefix == "sys16" for r in mixed)
    # the class prefix is a pure function of the class name
    assert traffic.prefix_ids("sys16", 16, 256) == \
        traffic.prefix_ids("sys16", 16, 256)
    assert traffic.prefix_ids("sys16", 16, 256) != \
        traffic.prefix_ids("other16", 16, 256)


# -- the parity gate (fp32 grid, bf16, int8) -----------------------------------


def test_warm_hits_bit_equal_cold_engine_and_generate(setup):
    cfg, params = setup
    gen = GenerationConfig(max_new_tokens=6, temperature=0.8, top_k=5)
    rng = np.random.RandomState(11)
    base = rng.randint(3, cfg.vocab_size, size=BUCKET).tolist()
    mid = list(base)
    mid[6] = 3 + (mid[6] - 2) % (cfg.vocab_size - 3)      # diverge mid-page
    bnd = list(base)
    bnd[4] = 3 + (bnd[4] - 2) % (cfg.vocab_size - 3)      # diverge at page 1
    plan = [(base, 1), (base, 2), (mid, 3), (bnd, 4)]

    warm = make_engine(cfg, params)
    cold = make_engine(cfg, params, prefix_cache=False)
    got = {}
    for prompt, seed in plan:
        tokens, h = serve_one(warm, prompt, gen, seed)
        got[seed] = (tokens, h.prefix_cached_tokens)
    # two CONCURRENT hits map the same physical pages read-only
    h5 = warm.submit(ServeRequest(input_ids=list(base), gen=gen, seed=5))
    h6 = warm.submit(ServeRequest(input_ids=list(base), gen=gen, seed=6))
    warm.drain(timeout_s=120)
    got[5] = (h5.result(timeout=1), h5.prefix_cached_tokens)
    got[6] = (h6.result(timeout=1), h6.prefix_cached_tokens)

    # the hit geometry: miss, full-row (bucket-1), mid-page, page-boundary
    assert [got[s][1] for s in (1, 2, 3, 4, 5, 6)] == [0, 7, 6, 4, 7, 7]
    for prompt, seed in plan + [(base, 5), (base, 6)]:
        cold_tokens, ch = serve_one(cold, prompt, gen, seed)
        assert ch.prefix_cached_tokens == 0
        ref = reference_tokens(params, cfg, prompt, gen, seed)
        assert got[seed][0] == cold_tokens == ref, f"seed {seed} diverged"

    snap = warm.metrics_snapshot()
    assert snap["prefix_cache"] == 1
    assert (snap["prefix_hits"], snap["prefix_misses"]) == (5, 1)
    assert snap["prefix_hit_rate"] == round(5 / 6, 4)
    assert snap["prefix_cached_tokens"] == 7 + 6 + 4 + 7 + 7
    assert snap["prefix_cow_forks"] == 4          # full x3 + mid; bnd doesn't
    assert snap["pages_cached"] == 4              # base chain + 2 tail forks
    assert snap["prefix_evictions"] == 0
    assert_no_leaks(warm)
    off = cold.metrics_snapshot()
    assert "prefix_cache" not in off and "prefix_hits" not in off
    warm.shutdown()
    cold.shutdown()


@pytest.mark.slow  # funds the Gateway tier-1 rows: the fp32 warm-hit
# bit-equality test covers the cold-vs-warm contract every tier-1 run;
# this row re-proves it under bf16 (a second full compile) nightly
def test_warm_hit_bit_equal_bf16(setup):
    cfg_b = LlamaConfig.tiny(dtype=jnp.bfloat16)
    params_b = llama.init_params(jax.random.PRNGKey(0), cfg_b)
    gen = GenerationConfig(max_new_tokens=4)
    prompt = list(range(5, 5 + BUCKET))
    warm = make_engine(cfg_b, params_b)
    cold_tokens, _ = serve_one(make_engine(cfg_b, params_b,
                                           prefix_cache=False),
                               prompt, gen, 7)
    first, _ = serve_one(warm, prompt, gen, 7)
    hit, h = serve_one(warm, prompt, gen, 7)
    assert h.prefix_cached_tokens == BUCKET - 1
    ref = reference_tokens(params_b, cfg_b, prompt, gen, 7)
    assert first == hit == cold_tokens == ref
    assert_no_leaks(warm)
    warm.shutdown()


def test_int8_warm_greedy_matches_cold_int8(setup):
    cfg, params = setup
    gen = GenerationConfig(max_new_tokens=5)                # greedy
    prompt = [9, 4, 11, 6, 13, 8, 15, 10]
    cold_tokens, _ = serve_one(
        make_engine(cfg, params, kv_quant="int8", prefix_cache=False),
        prompt, gen, 0)
    warm = make_engine(cfg, params, kv_quant="int8")
    first, _ = serve_one(warm, prompt, gen, 0)
    assert first == cold_tokens                   # cold path is unchanged
    hit, h = serve_one(warm, prompt, gen, 0)
    assert h.prefix_cached_tokens == BUCKET - 1
    # the PR-13 spirit of the int8 contract, token-level: the warm stream
    # (span recompute + decode over dequantized shared pages) greedily
    # agrees with the cold int8 stream
    assert hit == cold_tokens
    assert_no_leaks(warm)
    warm.shutdown()


# -- eviction under pressure, then refill --------------------------------------


def test_eviction_then_refill_reproduces_tokens(setup):
    cfg, params = setup
    gen = GenerationConfig(max_new_tokens=8)      # demand: the full 4 pages
    engine = make_engine(cfg, params, max_slots=1, num_pages=8)
    prompts = {}
    rng = np.random.RandomState(23)
    for name in "ABCD":
        prompts[name] = rng.randint(3, cfg.vocab_size, size=BUCKET).tolist()

    tokens_a, _ = serve_one(engine, prompts["A"], gen, 1)
    assert tokens_a == reference_tokens(params, cfg, prompts["A"], gen, 1)
    for name in "BC":
        serve_one(engine, prompts[name], gen, 1)
    assert engine.slots.pages_cached == 6 and engine.slots.prefix_evictions == 0

    # D's allocation outruns the free list: the LRU chain (A, released
    # first) evicts as a subtree instead of the pool refusing
    serve_one(engine, prompts["D"], gen, 1)
    assert engine.slots.prefix_evictions == 2

    # refill: A is a miss again, but its tokens reproduce exactly...
    again, h = serve_one(engine, prompts["A"], gen, 1)
    assert h.prefix_cached_tokens == 0
    assert again == tokens_a
    # ...and the refilled chain serves the next request as a hit
    third, h = serve_one(engine, prompts["A"], gen, 1)
    assert h.prefix_cached_tokens == BUCKET - 1
    assert third == tokens_a
    assert_no_leaks(engine)
    engine.shutdown()


# -- cache-aware admission at a fixed pool -------------------------------------


def test_sharing_doubles_admissions_at_fixed_pool(setup):
    cfg, params = setup
    bucket, pool = 16, 20
    gen = GenerationConfig(max_new_tokens=4)
    assert page_demand(bucket, 4, PAGE) == 5      # worst-case, cache off
    shared = list(range(30, 30 + 12))             # three full shared pages
    prompts = [shared + [3 + i, 7, 8, 9] for i in range(10)]

    def fixed_pool_engine(**kw):
        return make_engine(cfg, params, max_slots=12, max_len=bucket + 4,
                           prompt_buckets=(bucket,), max_queue=64,
                           num_pages=pool, **kw)

    def admit_until_refused(engine):
        admitted = 0
        for prompt in prompts:
            try:
                engine.submit(ServeRequest(input_ids=list(prompt), gen=gen,
                                           seed=admitted))
            except ServePagesExhausted as exc:
                assert exc.retry_after_s > 0
                return admitted
            admitted += 1
        raise AssertionError("pool never refused")

    cold = fixed_pool_engine(prefix_cache=False)
    cold_admitted = admit_until_refused(cold)
    assert cold_admitted == pool // 5             # the PR-13 reservation math
    cold.shutdown()

    warm = fixed_pool_engine()
    serve_one(warm, shared + [200, 7, 8, 9], gen, 99)     # prime the chain
    warm_admitted = admit_until_refused(warm)
    assert warm_admitted >= 2 * cold_admitted
    assert warm_admitted == 8                     # 3 held + 8 * 2 <= 20 < +2

    # the admissions are REAL: every one reaches a slot and prefills
    for _ in range(4):
        warm._advance_prefill()
    assert warm.slots.active_count == warm_admitted
    assert warm.queue_depth() == 0

    # refcount-aware gauges: a page shared by 8 slots is counted ONCE —
    # the logical mapping count exceeds the physical pages_used
    table = warm.slots.page_table
    live = table[table != warm.slots.garbage_page]
    assert len(live) == warm_admitted * 4
    assert warm.slots.pages_used == len(np.unique(live)) + 1  # + idle tail
    assert warm.slots.pages_used < len(live)
    assert warm.slots.reserved_unbacked >= 0
    assert "pages_cached" in warm.slots.fragmentation_gauges()
    warm.shutdown()


def test_cache_off_is_the_baseline_engine(setup):
    cfg, params = setup
    with pytest.raises(ValueError, match="prefix_cache"):
        ServeConfig(kv_cache="dense", prefix_cache=True)
    engine = make_engine(cfg, params, prefix_cache=False, max_slots=8)
    gen = GenerationConfig(max_new_tokens=8)
    for i in range(4):                            # 16 pages / demand 4
        engine.submit(ServeRequest(input_ids=[3 + i] * BUCKET, gen=gen,
                                   seed=i))
    with pytest.raises(ServePagesExhausted):
        engine.submit(ServeRequest(input_ids=[50] * BUCKET, gen=gen, seed=9))
    s = engine.slots
    assert s._held == 0 and s.pages_cached == 0 and s.pages_reserved == 16
    assert "pages_cached" not in s.fragmentation_gauges()
    engine.shutdown()


# -- cancellation frees slots, pages, and queued pins --------------------------


def test_abandoned_requests_release_pages_at_next_tick(setup):
    cfg, params = setup
    gen = GenerationConfig(max_new_tokens=6)
    engine = make_engine(cfg, params, max_slots=1)
    prompt = [7, 12, 9, 14, 11, 16, 13, 18]
    serve_one(engine, prompt, gen, 1)             # prime: 2 cached pages

    h1 = engine.submit(ServeRequest(input_ids=list(prompt), gen=gen, seed=2))
    engine.step()                                 # h1 admits + streams
    engine.step()
    h2 = engine.submit(ServeRequest(input_ids=list(prompt), gen=gen, seed=3))
    assert engine.queue_depth() == 1              # queued with its pins live
    assert 0 < len(h1.tokens_out) < 6

    engine.note_abandoned(h1.request)
    engine.note_abandoned(h2.request)
    engine.step()                                 # cancels at the boundary
    # the decoding slot freed (unshared pages released, shared refcounts
    # dropped); the queued entry's pins + reservation unwound
    assert engine.slots.free_count == 1
    assert engine.queue_depth() == 0
    assert_no_leaks(engine)
    # both handles complete with what they had — no error, partial stream
    assert h1.result(timeout=1) == h1.tokens_out and len(h1.tokens_out) < 6
    assert h2.result(timeout=1) == []
    assert engine.metrics_snapshot()["requests_abandoned"] == 2
    engine.shutdown()


# -- the measured win ----------------------------------------------------------


@pytest.mark.slow  # funds the Gateway tier-1 rows: the hit-rate win is
# already pinned by the unit-level reuse tests; this 12-request Poisson
# grid row is the nightly end-to-end re-proof
def test_shared_mix_trace_hits_every_hot_request(setup):
    cfg_big = LlamaConfig.tiny(max_position_embeddings=256)
    _, params = setup
    pre, tail, bucket = 112, 16, 128
    shared = traffic.prefix_ids(f"sys{pre}", pre, cfg_big.vocab_size)
    trace = traffic.poisson_trace(
        5, 100.0, 12, prompt_mix=traffic.parse_mix(f"{tail}:1.0"),
        output_mix=traffic.parse_mix("4:1.0"),
        prefix_mix=traffic.parse_prefix_mix(f"sys{pre}:0.9,cold:0.1"))
    gen = GenerationConfig(max_new_tokens=4)

    engine = make_engine(cfg_big, params, max_slots=4, max_len=144,
                         prompt_buckets=(tail, bucket), max_queue=32,
                         num_pages=16 * 144 // PAGE)
    serve_one(engine, shared + [3] * tail, gen, 0)        # prime the chain
    summary = traffic.run_trace(engine, trace, result_timeout_s=120)
    engine.shutdown()

    assert summary["submitted"] == 12 and summary["requests_failed"] == 0
    classes = summary["prefix_classes"]
    hot = classes[f"sys{pre}"]
    assert hot["hit_rate"] == 1.0
    # every hot-class request skipped AT LEAST the shared prefix's prefill
    assert hot["cached_tokens"] >= pre * hot["hits"]
    assert hot["submitted"] + classes.get("cold", {}).get("submitted", 0) \
        == 12


def test_cache_hit_ttft_beats_cold_prefill(setup):
    """The measured CPU win: a closed-loop (one request in flight, compiles
    paid off the clock) TTFT median over a 496-token shared prefix — the
    hit prefills a 16-token span instead of the 512-token bucket."""
    cfg_big = LlamaConfig.tiny(max_position_embeddings=768)
    _, params = setup
    pre, tail, bucket = 496, 16, 512
    shared = traffic.prefix_ids(f"sys{pre}", pre, cfg_big.vocab_size)
    gen = GenerationConfig(max_new_tokens=4)

    def ttft_median(cache_on):
        engine = make_engine(cfg_big, params, max_len=bucket + 16,
                             prompt_buckets=(bucket,), max_queue=16,
                             num_pages=8 * (bucket + 16) // PAGE,
                             prefix_cache=cache_on)

        def serve_timed(prompt):
            t0 = time.perf_counter()
            h = engine.submit(ServeRequest(input_ids=list(prompt), gen=gen,
                                           seed=0))
            while not h.tokens_out:
                engine.step()
            ttft = time.perf_counter() - t0
            engine.drain(timeout_s=300)
            return ttft, h.prefix_cached_tokens

        serve_timed(shared + [3] * tail)    # compile prefill / prime chain
        serve_timed(shared + [4] * tail)    # compile the warm span path
        timed = [serve_timed(shared + [5 + i] * tail) for i in range(5)]
        engine.shutdown()
        assert [c for _, c in timed] == [pre if cache_on else 0] * 5
        return float(np.median([t for t, _ in timed]))

    hot, cold = ttft_median(True), ttft_median(False)
    print(f"closed-loop TTFT median, {pre}-token shared prefix at bucket "
          f"{bucket}: hit {1000 * hot:.2f} ms vs cold {1000 * cold:.2f} ms")
    assert hot < cold


# -- telemetry renders end-to-end ----------------------------------------------


def test_reports_render_prefix_cache_lines(setup, tmp_path, capsys):
    cfg, params = setup
    rec = RequestTraceRecorder(str(tmp_path))
    engine = make_engine(cfg, params, reqtrace=rec)
    gen = GenerationConfig(max_new_tokens=4)
    prompt = [21, 8, 23, 10, 25, 12, 27, 14]
    serve_one(engine, prompt, gen, 1)
    serve_one(engine, prompt, gen, 2)             # the hit
    with open(tmp_path / "metrics.jsonl", "w") as f:
        f.write(json.dumps(engine.metrics_snapshot()) + "\n")
    rec.close()
    engine.shutdown()

    rows = read_jsonl(str(tmp_path / REQUEST_TRACE_NAME))
    hit = [r for r in rows if r.get("prefix_cached_tokens")]
    assert len(hit) == 1
    assert hit[0]["prefix_cached_tokens"] == BUCKET - 1
    assert hit[0]["prefix_shared_pages"] == 1
    assert hit[0]["prefix_cow_fork"] is True
    assert any(s.get("name") == "prefix_cache_hit"
               for s in hit[0]["spans"])
    bd = request_report.ttft_breakdown(hit[0])
    assert bd["prefix_cached_tokens"] == BUCKET - 1

    assert request_report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "prefix cache: 1 hit(s), 7 cached tokens" in out
    assert serving_report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "prefix:" in out and "prefix_hit_rate=0.5" in out

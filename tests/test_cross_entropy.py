"""Fused vocab-chunked cross-entropy (ops/cross_entropy.py): exactness of
value and gradients against the materialized-logits reference, and parity
inside both pipeline schedules via the `loss_chunks` knob."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.ops.cross_entropy import fused_ce_sum_count


def _inputs(n=6, s=10, d=16, v=32, seed=0):
    r = np.random.RandomState(seed)
    h = jnp.asarray(r.randn(n, s, d).astype(np.float32))
    w = jnp.asarray(r.randn(d, v).astype(np.float32) * 0.1)
    t = r.randint(0, v, (n, s))
    t[:, -2:] = llama.IGNORE_INDEX  # some untargeted positions
    t[0, 0] = llama.IGNORE_INDEX
    return h, w, jnp.asarray(t, jnp.int32)


def _reference(h, w, t):
    logits = (h @ w).astype(jnp.float32)
    return llama.token_loss_sum_and_count_preshifted(logits, t)


@pytest.mark.parametrize("chunks", [1, 2, 8])
def test_value_and_count_match_reference(chunks):
    h, w, t = _inputs()
    want_sum, want_count = _reference(h, w, t)
    got_sum, got_count = fused_ce_sum_count(h, w, t, chunks)
    np.testing.assert_allclose(got_sum, want_sum, rtol=1e-6)
    assert int(got_count) == int(want_count)


def test_gradients_match_reference():
    h, w, t = _inputs()

    def ref(h_, w_):
        return _reference(h_, w_, t)[0]

    def fused(h_, w_):
        return fused_ce_sum_count(h_, w_, t, 4)[0]

    dref = jax.grad(ref, argnums=(0, 1))(h, w)
    dfused = jax.grad(fused, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(dfused[0], dref[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(dfused[1], dref[1], rtol=1e-5, atol=1e-6)


def test_all_tokens_ignored_is_finite():
    h, w, _ = _inputs()
    t = jnp.full(h.shape[:2], llama.IGNORE_INDEX, jnp.int32)
    s, c = fused_ce_sum_count(h, w, t, 4)
    assert float(s) == 0.0 and int(c) == 0
    g = jax.grad(lambda h_: fused_ce_sum_count(h_, w, t, 4)[0])(h)
    assert np.isfinite(np.asarray(g)).all() and float(jnp.abs(g).sum()) == 0.0
    # the head-weight grad must vanish too: every token's (softmax - onehot)
    # row is masked by the zero valid scale, not just dh's
    gw = jax.grad(lambda w_: fused_ce_sum_count(h, w_, t, 4)[0])(w)
    assert np.isfinite(np.asarray(gw)).all() and float(jnp.abs(gw).sum()) == 0.0


def test_vocab_equals_num_chunks_degenerate():
    """V == num_chunks: every scan iteration owns a single-logit chunk —
    the smallest legal chunking must still match the dense reference."""
    h, w, t = _inputs(v=8)
    want_sum, want_count = _reference(h, w, t)
    got_sum, got_count = fused_ce_sum_count(h, w, t, 8)
    np.testing.assert_allclose(got_sum, want_sum, rtol=1e-6)
    assert int(got_count) == int(want_count)
    dref = jax.grad(lambda a, b: _reference(a, b, t)[0], argnums=(0, 1))(h, w)
    dgot = jax.grad(lambda a, b: fused_ce_sum_count(a, b, t, 8)[0],
                    argnums=(0, 1))(h, w)
    np.testing.assert_allclose(dgot[0], dref[0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(dgot[1], dref[1], rtol=1e-5, atol=1e-6)


# Fast lane keeps the pinned boundary points (2, and the measured
# last-mantissa-bit cases 8/32 the docstring cites); the interior rows
# exercise no new reassociation order and ride the round gate.
@pytest.mark.parametrize("chunks", [
    2,
    pytest.param(4, marks=pytest.mark.slow),
    8,
    pytest.param(16, marks=pytest.mark.slow),
    32,
])
def test_num_chunks_invariance_grid(chunks):
    """Chunk-count invariance of loss AND grads against the chunks=1
    anchor. The online-logsumexp rescaling reassociates exp sums, so the
    pinned contract is ~1-ulp tight tolerance, NOT bit-equality (measured:
    chunks 8/32 differ from the anchor in the last mantissa bit)."""
    h, w, t = _inputs()
    base_sum, base_count = fused_ce_sum_count(h, w, t, 1)
    got_sum, got_count = fused_ce_sum_count(h, w, t, chunks)
    np.testing.assert_allclose(got_sum, base_sum, rtol=1e-7)
    assert int(got_count) == int(base_count)
    dbase = jax.grad(lambda a, b: fused_ce_sum_count(a, b, t, 1)[0],
                     argnums=(0, 1))(h, w)
    dgot = jax.grad(lambda a, b: fused_ce_sum_count(a, b, t, chunks)[0],
                    argnums=(0, 1))(h, w)
    np.testing.assert_allclose(dgot[0], dbase[0], rtol=1e-5, atol=5e-7)
    np.testing.assert_allclose(dgot[1], dbase[1], rtol=1e-5, atol=5e-7)


def test_indivisible_vocab_rejected():
    h, w, t = _inputs(v=30)
    with pytest.raises(ValueError, match="not divisible"):
        fused_ce_sum_count(h, w, t, 4)


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
@pytest.mark.slow
def test_pipeline_loss_chunks_parity(devices, schedule):
    """loss AND grads identical with/without the fused loss head at PP=2."""
    from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg = LlamaConfig.tiny()
    mesh = make_mesh(MeshConfig(pp=2))
    manifest = StageManifest.for_config(cfg, 2)
    stacked = pl.stack_stages(llama.init_params(jax.random.PRNGKey(0), cfg), manifest)

    r = np.random.RandomState(1)
    bsz, seq = 4, 16
    ids = r.randint(3, cfg.vocab_size, (bsz, seq)).astype(np.int32)
    batch = {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.ones((bsz, seq), jnp.int32),
        "position_ids": jnp.broadcast_to(jnp.arange(seq, dtype=jnp.int32), (bsz, seq)),
        "labels": jnp.asarray(ids),
    }

    losses, grads = [], []
    for chunks in (1, 4):
        pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=2,
                                 schedule=schedule, loss_chunks=chunks)
        fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked))
        l, g = fn(stacked, batch)
        losses.append(float(l))
        grads.append(g)
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
    for a, b in zip(jax.tree.leaves(grads[0]), jax.tree.leaves(grads[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_loss_chunks_with_tp_rejected(devices):
    from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg = LlamaConfig.tiny()
    mesh = make_mesh(MeshConfig(pp=2, tp=2))
    manifest = StageManifest.for_config(cfg, 2)
    stacked = pl.stack_stages(llama.init_params(jax.random.PRNGKey(0), cfg), manifest)
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=2, loss_chunks=2)
    with pytest.raises(ValueError, match="redundant under tp"):
        pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked)

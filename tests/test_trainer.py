"""Trainer e2e: smoke run, checkpoint/resume continuity, warm start."""

import json
import os

import jax

import numpy as np
import pytest

from llama_pipeline_parallel_tpu.train import run_training
from llama_pipeline_parallel_tpu.utils.config import load_config


def base_cfg(tmp_path, **kw):
    cfg = {
        "output_dir": str(tmp_path / "out"),
        "mesh": {"pp": 2, "dp": 2},
        "model": {"preset": "tiny", "dtype": "float32"},
        "dataset": {"synthetic": True, "seq_length": 16, "pseudo_dataset_len": 128},
        "seed": 7,
        "per_device_train_batch_size": 2,
        "gradient_accumulation_steps": 2,
        "max_steps": 4,
        "learning_rate": 1e-3,
        "warmup_steps": 1,
        "logging_steps": 2,
        "save_steps": 0,
        "save_final": True,
    }
    cfg.update(kw)
    return cfg


def test_smoke_run_writes_metrics_and_ckpt(tmp_path, devices):
    summary = run_training(base_cfg(tmp_path))
    assert summary["final_step"] == 4
    out = summary["output_dir"]
    lines = [json.loads(l) for l in open(os.path.join(out, "metrics.jsonl"))]
    assert lines and {"loss", "lr", "tokens_per_sec"} <= set(lines[0])
    assert os.path.isdir(os.path.join(out, "checkpoint-4"))
    assert os.path.exists(os.path.join(out, "training_config.json"))


def test_compilation_cache_dir_knob(tmp_path, devices):
    """`compilation_cache_dir` populates a persistent XLA compile cache —
    restarts of a big run skip the minutes-long compiles."""
    cache = tmp_path / "xla_cache"
    prev = jax.config.jax_compilation_cache_dir
    # the tiny program compiles in well under the default 1s persistence
    # threshold — drop it so the toy run actually writes entries
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        # unique seq length: an identical program compiled by an earlier test
        # would hit XLA's in-memory cache and never write the persistent one
        run_training(base_cfg(
            tmp_path, compilation_cache_dir=str(cache),
            dataset={"synthetic": True, "seq_length": 24,
                     "pseudo_dataset_len": 128}))
    finally:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)
    assert cache.is_dir() and any(cache.iterdir())
    # run_training save/restores the process-global jax setting itself
    assert jax.config.jax_compilation_cache_dir == prev


@pytest.mark.slow
def test_schedule_knob_equivalence(tmp_path, devices):
    """pipeline_schedule: gpipe (+ chunks) through the FULL trainer produces
    the same losses as the default 1f1b — the knob is plumbed end to end and
    the schedules are numerically interchangeable."""
    ref = run_training(base_cfg(tmp_path, output_dir=str(tmp_path / "s1")))
    gp = run_training(base_cfg(tmp_path, output_dir=str(tmp_path / "s2"),
                               pipeline_schedule="gpipe",
                               gradient_accumulation_chunks=2))
    np.testing.assert_allclose(gp["final_loss"], ref["final_loss"], rtol=1e-5)


@pytest.mark.slow
def test_resume_continues_identically(tmp_path, devices):
    """Interrupted-at-4 + resume-to-8 must equal straight-through-to-8
    (the reference's resume fast-forward contract, trainer_base_ds_mp:345-351)."""
    cfg_a = base_cfg(tmp_path, output_dir=str(tmp_path / "a"), max_steps=8)
    straight = run_training(cfg_a)

    cfg_b = base_cfg(tmp_path, output_dir=str(tmp_path / "b"), max_steps=4,
                     total_steps=8)  # schedule horizon stays 8 across the interruption
    run_training(cfg_b)
    cfg_b2 = base_cfg(tmp_path, output_dir=str(tmp_path / "b"), max_steps=8)
    resumed = run_training(cfg_b2)

    np.testing.assert_allclose(resumed["final_loss"], straight["final_loss"], rtol=1e-6)


@pytest.mark.slow
def test_async_save_loop_durable_and_resumable(tmp_path, devices):
    """async_save: periodic checkpoints commit in the background but are
    durable by loop exit, and a resumed run picks the latest one up."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager

    cfg = base_cfg(tmp_path, save_steps=2, async_save=True, max_steps=4,
                   total_steps=8)
    out = run_training(cfg)["output_dir"]
    mgr = CheckpointManager(out)
    assert mgr.list_steps(complete_only=True) == [2, 4]
    assert mgr.latest_step() == 4

    resumed = run_training(base_cfg(tmp_path, save_steps=2, async_save=True,
                                    max_steps=8))
    assert resumed["final_step"] == 8
    assert CheckpointManager(out).latest_step() == 8


def test_warm_start_requires_checkpoint(tmp_path, devices):
    cfg = base_cfg(tmp_path, model_name_or_path=str(tmp_path / "missing"), resume=False)
    with pytest.raises(FileNotFoundError, match="convert_hf"):
        run_training(cfg)


@pytest.mark.slow
def test_offload_loop_runs_and_resumes(tmp_path, devices):
    """Host-offloaded optimizer path: loss decreases on a fixed-seed synthetic
    set; interrupted + resumed equals straight-through."""
    base = dict(base_cfg(tmp_path, output_dir=str(tmp_path / "o"), max_steps=8,
                         total_steps=8, optimizer_offload=True, learning_rate=1e-2))
    straight = run_training(dict(base, output_dir=str(tmp_path / "oa")))
    run_training(dict(base, output_dir=str(tmp_path / "ob"), max_steps=4))
    resumed = run_training(dict(base, output_dir=str(tmp_path / "ob"), max_steps=8))
    np.testing.assert_allclose(resumed["final_loss"], straight["final_loss"], rtol=1e-5)


@pytest.mark.slow
def test_offload_zero2_matches_plain_offload(tmp_path, devices):
    """optimizer_offload_zero2 (dp-sharded masters/moments + reduce-scattered
    grads + per-step dp re-gather of the bf16 working copy) is numerically
    identical to the plain offload layout — and each host stores only 1/dp
    of the dp-shardable leaves."""
    base = dict(base_cfg(tmp_path, optimizer_offload=True, learning_rate=1e-2,
                         max_steps=4, total_steps=4))
    plain = run_training(dict(base, output_dir=str(tmp_path / "p")))
    z2 = run_training(dict(base, output_dir=str(tmp_path / "z"),
                           optimizer_offload_zero2=True))
    np.testing.assert_allclose(z2["final_loss"], plain["final_loss"],
                               rtol=1e-6)


@pytest.mark.slow
def test_offload_zero2_resumes_identically(tmp_path, devices):
    """z2 interrupted-at-2 + resume-to-4 equals straight z2: the dp-sharded
    master/moment templates round-trip through the checkpoint (the canonical
    reshape preserves trailing-dim dp shardings)."""
    base = dict(base_cfg(tmp_path, optimizer_offload=True,
                         optimizer_offload_zero2=True, learning_rate=1e-2,
                         max_steps=4, total_steps=4))
    straight = run_training(dict(base, output_dir=str(tmp_path / "s")))
    run_training(dict(base, output_dir=str(tmp_path / "r"), max_steps=2))
    resumed = run_training(dict(base, output_dir=str(tmp_path / "r")))
    assert resumed["final_step"] == 4
    np.testing.assert_allclose(resumed["final_loss"], straight["final_loss"],
                               rtol=1e-6)


@pytest.mark.slow
def test_offload_zero2_uneven_partition_resumes(tmp_path, devices):
    """z2 composed with an uneven stage partition (5 layers on pp=2): the
    abstract unstack now carries trailing-dim (dp) shardings through the
    uneven gather, so the resume templates stay dp-sharded and the
    interrupted run continues identically."""
    model = {"preset": "tiny", "dtype": "float32", "num_hidden_layers": 5}
    base = dict(base_cfg(tmp_path, optimizer_offload=True,
                         optimizer_offload_zero2=True, learning_rate=1e-2,
                         model=model, max_steps=4, total_steps=4))
    straight = run_training(dict(base, output_dir=str(tmp_path / "us")))
    run_training(dict(base, output_dir=str(tmp_path / "ur"), max_steps=2))
    resumed = run_training(dict(base, output_dir=str(tmp_path / "ur")))
    np.testing.assert_allclose(resumed["final_loss"], straight["final_loss"],
                               rtol=1e-6)


def test_offload_zero2_requires_offload(tmp_path, devices):
    with pytest.raises(ValueError, match="requires optimizer_offload"):
        run_training(base_cfg(tmp_path, optimizer_offload_zero2=True))


def test_zero2_param_specs_shard_over_dp(devices):
    """The z2 spec rule: every dp-shardable leaf gains AXIS_DP on its
    rightmost free dim; indivisible leaves keep their plain spec."""
    import jax
    from jax.sharding import PartitionSpec as P

    from llama_pipeline_parallel_tpu.models.llama import model as llama
    from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.parallel import pipeline as pl
    from llama_pipeline_parallel_tpu.parallel import train_step as ts
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(pp=2, dp=2))
    cfg = LlamaConfig.tiny()
    stacked = pl.stack_stages(
        jax.eval_shape(lambda: llama.init_params(jax.random.PRNGKey(0), cfg)),
        StageManifest.for_config(cfg, 2))
    specs = ts.zero2_param_specs(stacked, mesh)
    # stacked layer matmul leaf [pp, k, d, d]: dp lands on the last dim
    assert specs["layers"]["attn"]["wq"] == P("pp", None, None, "dp")
    flat = jax.tree_util.tree_leaves(specs, is_leaf=lambda x: isinstance(x, P))
    assert all(isinstance(s, P) for s in flat)
    # every leaf of this model is dp-shardable (all dims are multiples of 2)
    assert all("dp" in s for s in flat), flat


def test_offload_save_total_limit(tmp_path, devices):
    """The retention knob covers the offload save path too: only the newest
    checkpoint survives at save_total_limit=1."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager

    cfg = base_cfg(tmp_path, optimizer_offload=True, save_steps=2,
                   save_total_limit=1, max_steps=4, total_steps=4)
    out = run_training(cfg)["output_dir"]
    mgr = CheckpointManager(out)
    assert mgr.list_steps(complete_only=True) == [4]
    assert mgr.latest_step() == 4


@pytest.mark.slow
def test_offload_with_uneven_stages(tmp_path, devices):
    """Host-offloaded optimizer composed with an auto-balanced uneven
    partition (5 layers on pp=2): the padded stacked layout must survive the
    host round-trip (shard-keyed masters, f32 working copy) unchanged —
    pinned by matching the fused-optimizer path's losses on the identical
    run (the offload kernel mirrors optax numerics)."""
    model = {"preset": "tiny", "dtype": "float32", "num_hidden_layers": 5}
    fused = run_training(base_cfg(tmp_path, output_dir=str(tmp_path / "f"),
                                  learning_rate=1e-2, model=model))
    off = run_training(base_cfg(tmp_path, output_dir=str(tmp_path / "o"),
                                optimizer_offload=True, learning_rate=1e-2,
                                model=model))
    assert off["final_step"] == 4
    np.testing.assert_allclose(off["final_loss"], fused["final_loss"], rtol=2e-5)


@pytest.mark.slow
def test_eval_loop(tmp_path, devices):
    cfg = base_cfg(tmp_path, eval_steps=2,
                   eval_dataset={"synthetic": True, "seq_length": 16,
                                 "pseudo_dataset_len": 16})
    summary = run_training(cfg)
    lines = [json.loads(l) for l in
             open(os.path.join(summary["output_dir"], "metrics.jsonl"))]
    evals = [l for l in lines if "eval_loss" in l]
    assert len(evals) == 2 and all(np.isfinite(l["eval_loss"]) for l in evals)
    # the LAST eval lands in the final checkpoint's meta.json — the quality
    # signal the continuous-deployment gate (utils/actions.Deployer) reads
    from llama_pipeline_parallel_tpu.utils.actions import checkpoint_eval_loss

    meta = json.load(open(os.path.join(summary["output_dir"],
                                       "checkpoint-4", "meta.json")))
    assert meta["eval_loss"] == evals[-1]["eval_loss"]
    assert meta["eval_step"] == 4
    assert checkpoint_eval_loss(summary["output_dir"], 4) == meta["eval_loss"]


def test_shipped_configs_parse():
    """EVERY shipped config must parse, build its model config, and satisfy
    the mesh divisibility rules the runtime enforces (tp over heads/kv/ffn/
    vocab, sp over the sequence) — a new yaml cannot ship broken."""
    import glob

    from llama_pipeline_parallel_tpu.train import build_model_config

    conf_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "conf")
    paths = sorted(glob.glob(os.path.join(conf_dir, "*.yaml")))
    assert len(paths) >= 5
    for path in paths:
        cfg = load_config(path)
        assert isinstance(cfg["learning_rate"], float), path
        mesh = cfg.get("mesh", {})
        assert mesh.get("pp", 1) >= 1, path
        mc = build_model_config(cfg["model"])
        tp, sp = mesh.get("tp", 1), mesh.get("sp", 1)
        assert mc.num_attention_heads % tp == 0, path
        assert mc.kv_heads % tp == 0, path
        assert mc.intermediate_size % tp == 0, path
        assert mc.vocab_size % tp == 0, path
        assert cfg.get("max_seq_length", 512) % sp == 0, path
        assert mc.num_hidden_layers >= mesh.get("pp", 1), path


def test_resize_request_checkpoints_acks_and_exits(tmp_path, devices):
    """actions.resize_on_request: a `resize.request` dropped into
    output_dir (the supervisor's actuation RPC) stops the loop at the next
    step boundary — checkpoint saved, THEN the request renamed to
    `resize.request.ack` (ack-after-save: a crash mid-save leaves the
    request for the next incarnation), clean exit."""
    import threading
    import time as _time

    from llama_pipeline_parallel_tpu.utils.actions import (
        RESIZE_ACK_NAME,
        RESIZE_REQUEST_NAME,
    )

    out = str(tmp_path / "out")
    req = os.path.join(out, RESIZE_REQUEST_NAME)

    def drop_once_running():
        deadline = _time.time() + 120
        metrics = os.path.join(out, "metrics.jsonl")
        while _time.time() < deadline and not os.path.exists(metrics):
            _time.sleep(0.05)
        with open(req + ".tmp", "w") as f:
            json.dump({"rung": "half", "id": "action-000000"}, f)
        os.replace(req + ".tmp", req)

    t = threading.Thread(target=drop_once_running)
    t.start()
    try:
        summary = run_training(base_cfg(
            tmp_path, max_steps=60, logging_steps=1,
            actions={"resize_on_request": True}))
    finally:
        t.join()
    assert summary["preempted_at"] is not None
    assert summary["preempted_at"] < 60
    step = summary["final_step"]
    assert os.path.isdir(os.path.join(out, f"checkpoint-{step}"))
    assert not os.path.exists(req)
    ack = json.load(open(os.path.join(out, RESIZE_ACK_NAME)))
    assert ack["rung"] == "half"


def test_resize_request_inert_without_actions_block(tmp_path, devices):
    """No `actions` config -> the trainer never reads resize.request: the
    run completes untouched and the file survives (actuation is opt-in at
    every layer)."""
    from llama_pipeline_parallel_tpu.utils.actions import RESIZE_REQUEST_NAME

    out = tmp_path / "out"
    out.mkdir()
    req = os.path.join(str(out), RESIZE_REQUEST_NAME)
    with open(req, "w") as f:
        json.dump({"rung": "half"}, f)
    summary = run_training(base_cfg(tmp_path))
    assert summary["preempted_at"] is None and summary["final_step"] == 4
    assert os.path.exists(req)  # nobody consumed it

"""Pallas fused CE head (ops/pallas_ce.py): the parity gate.

The kernel promotion contract (ISSUE 10 / ROADMAP item 5): interpret-mode
parity vs the XLA vocab-chunked scan `fused_ce_sum_count` — loss BIT-equal
fp32 at the same chunking (the kernel runs the identical online-logsumexp
update at the same vocab-block width), dh bit-equal, dW within the pinned
tolerance (token-block fold order) — across dtype x chunking x IGNORE_INDEX
grids; a jaxpr assertion proving the kernel is in-graph and no
logits-shaped intermediate exists in HBM at ANY chunk granularity (the
style of test_tensor_parallel's head-gating pin); and pipeline-level parity
across the schedule grid (flat/interleaved/zb1, offload on/off — the zb1
W-replay differentiates the kernel w.r.t. params only)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.ops.cross_entropy import fused_ce_sum_count
from llama_pipeline_parallel_tpu.ops.pallas_ce import (
    ce_head_traffic_bytes,
    pallas_ce_sum_count,
)
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

# dW folds token blocks sequentially where the XLA path does one einsum per
# vocab chunk over all tokens — everything else in the contract is bit-equal
DW_ATOL = 2e-6


def _inputs(n=6, s=10, d=16, v=32, seed=0, dtype=jnp.float32,
            ignore="some"):
    r = np.random.RandomState(seed)
    h = jnp.asarray(r.randn(n, s, d).astype(np.float32), dtype)
    w = jnp.asarray((r.randn(d, v) * 0.1).astype(np.float32), dtype)
    t = r.randint(0, v, (n, s))
    if ignore == "some":
        t[:, -2:] = llama.IGNORE_INDEX
        t[0, 0] = llama.IGNORE_INDEX
    elif ignore == "all":
        t[:] = llama.IGNORE_INDEX
    return h, w, jnp.asarray(t, jnp.int32)


# ---------------------------------------------------------------------------
# Op-level parity vs fused_ce_sum_count
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("chunks", [1, 2, 8])
@pytest.mark.parametrize("ignore", ["some", "none", "all"])
def test_loss_bit_equal_vs_xla_op(dtype, chunks, ignore):
    h, w, t = _inputs(dtype=dtype, ignore=ignore)
    want_sum, want_count = fused_ce_sum_count(h, w, t, chunks)
    got_sum, got_count = pallas_ce_sum_count(h, w, t, chunks)
    assert np.asarray(got_sum).tobytes() == np.asarray(want_sum).tobytes(), \
        (float(got_sum), float(want_sum))
    assert int(got_count) == int(want_count)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("chunks", [1, 4])
def test_grads_match_xla_op(dtype, chunks):
    """dh bit-equal (same per-row fold order over vocab tiles); dW within
    the pinned token-block-fold tolerance."""
    h, w, t = _inputs(dtype=dtype)
    dref = jax.grad(lambda a, b: fused_ce_sum_count(a, b, t, chunks)[0],
                    argnums=(0, 1))(h, w)
    dgot = jax.grad(lambda a, b: pallas_ce_sum_count(a, b, t, chunks)[0],
                    argnums=(0, 1))(h, w)
    np.testing.assert_array_equal(np.asarray(dgot[0]), np.asarray(dref[0]))
    np.testing.assert_allclose(np.asarray(dgot[1], np.float32),
                               np.asarray(dref[1], np.float32), atol=DW_ATOL)


def test_all_ignored_zero_loss_zero_grads():
    h, w, t = _inputs(ignore="all")
    s, c = pallas_ce_sum_count(h, w, t, 4)
    assert float(s) == 0.0 and int(c) == 0
    g = jax.grad(lambda a, b: pallas_ce_sum_count(a, b, t, 4)[0],
                 argnums=(0, 1))(h, w)
    assert float(jnp.abs(g[0]).sum()) == 0.0
    assert float(jnp.abs(g[1]).sum()) == 0.0


def test_nonuniform_cotangent_scales_grads():
    """The custom VJP must honor an arbitrary upstream cotangent (the
    pipeline divides loss_sum by the global token count)."""
    h, w, t = _inputs()
    ct = 0.37
    g1 = jax.grad(lambda a: pallas_ce_sum_count(a, w, t, 4)[0])(h)
    g2 = jax.grad(lambda a: ct * pallas_ce_sum_count(a, w, t, 4)[0])(h)
    np.testing.assert_allclose(np.asarray(g2), ct * np.asarray(g1),
                               rtol=1e-6, atol=1e-8)


def test_validation_errors():
    h, w, t = _inputs(v=30)
    with pytest.raises(ValueError, match="not divisible"):
        pallas_ce_sum_count(h, w, t, 4)
    h, w, t = _inputs()
    with pytest.raises(ValueError, match="block_tokens"):
        pallas_ce_sum_count(h, w, t, 4, 7)  # 7 does not divide 60 tokens


def test_traffic_model_arithmetic():
    # 8 chunks x (4 x [tokens, V/8] fp32 logits + 2 x [tokens, d] fp32 dh)
    assert ce_head_traffic_bytes(1024, 64, 256, 8) == \
        8 * (4 * 1024 * 32 * 4 + 2 * 1024 * 64 * 4)
    # chunks=1: the XLA twin is the DENSE head — no scan, no dh accumulator
    assert ce_head_traffic_bytes(1024, 64, 256, 1) == 4 * 1024 * 256 * 4


# ---------------------------------------------------------------------------
# Lowering: kernel in-graph, logits never HBM-resident at any granularity
# ---------------------------------------------------------------------------

def _walk_eqns(jxp, skip_pallas=True):
    """Yield (eqn, inside_pallas) over a jaxpr and its sub-jaxprs; by
    default the kernel bodies (pallas_call params) are NOT descended into —
    their [block, block] tiles are VMEM-resident by construction."""
    from jax.extend.core import ClosedJaxpr, Jaxpr

    def subs(v):
        if isinstance(v, ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                yield from subs(x)

    for eqn in jxp.eqns:
        yield eqn
        if skip_pallas and eqn.primitive.name == "pallas_call":
            continue
        for val in eqn.params.values():
            for sub in subs(val):
                yield from _walk_eqns(sub, skip_pallas)


def test_lowering_no_logits_shaped_intermediates():
    """The head-gating-test-style structural pin: with the kernel on, the
    fwd+bwd jaxpr contains pallas_call equations and NO [tokens, V]- or
    [tokens, V/chunks]-shaped aval outside them; the XLA op at the same
    chunking materializes the [tokens, V/chunks] block (the traffic the
    kernel deletes)."""
    # vc (20) and v (80) collide with no other width in the graph (d=16) —
    # the test_tp_head_matmul_is_cond_gated disambiguation trick
    n, s, d, v, chunks = 4, 8, 16, 80, 4
    h, w, t = _inputs(n=n, s=s, d=d, v=v)
    tokens, vc = n * s, v // chunks

    def logits_avals(fn):
        jaxpr = jax.make_jaxpr(fn)(h, w)
        pallas, hits = 0, []
        for eqn in _walk_eqns(jaxpr.jaxpr):
            if eqn.primitive.name == "pallas_call":
                pallas += 1
            for var in eqn.outvars:
                shape = getattr(var.aval, "shape", ())
                if len(shape) == 2 and shape[0] == tokens and \
                        shape[1] in (v, vc):
                    hits.append(shape)
        return pallas, hits

    grad_pallas = jax.grad(
        lambda a, b: pallas_ce_sum_count(a, b, t, chunks)[0], argnums=(0, 1))
    n_pallas, hits = logits_avals(grad_pallas)
    assert n_pallas >= 3, "expected fwd + dh + dW pallas_call equations"
    assert not hits, f"logits-shaped HBM intermediates escaped: {hits}"

    grad_xla = jax.grad(
        lambda a, b: fused_ce_sum_count(a, b, t, chunks)[0], argnums=(0, 1))
    _, xla_hits = logits_avals(grad_xla)
    assert xla_hits, "sanity: the XLA scan materializes the chunk block"


# ---------------------------------------------------------------------------
# Pipeline integration: kernels.ce across the schedule grid
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny(num_hidden_layers=8)


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.PRNGKey(0), cfg)


def make_batch(cfg, batch_size=8, seqlen=16, seed=42):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, cfg.vocab_size, size=(batch_size, seqlen)).astype(np.int32)
    mask = np.ones((batch_size, seqlen), np.int32)
    mask[:, -3:] = 0
    labels = ids.copy()
    labels[mask == 0] = llama.IGNORE_INDEX
    return {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.asarray(mask),
        "position_ids": jnp.asarray(np.broadcast_to(
            np.arange(seqlen, dtype=np.int32), (batch_size, seqlen)).copy()),
        "labels": jnp.asarray(labels),
    }


def run_pipeline(params, batch, cfg, pp=2, schedule="1f1b", v=1, tp=1,
                 microbatches=4, **pkw):
    mesh = make_mesh(MeshConfig(pp=pp, tp=tp))
    manifest = StageManifest.for_config(cfg, pp, virtual_stages=v)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=pp, num_microbatches=microbatches,
                             schedule=schedule, virtual_stages=v, **pkw)
    fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked))
    loss, grads = fn(stacked, batch)
    return float(loss), pl.unstack_stages(grads, manifest)


def assert_grads_close(a, b, atol=5e-7):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=atol)


# Fast lane: one flat row + the zb1 W-replay row (params-only
# differentiation of the kernel); the rest of the schedule x offload grid
# is slow-marked for the round gate.
@pytest.mark.parametrize("schedule,v,offload", [
    ("1f1b", 1, {}),
    ("zb1", 2, {}),
    pytest.param("interleaved_1f1b", 2, {}, marks=pytest.mark.slow),
    pytest.param("zb1", 2, {"offload_wgrad": True}, marks=pytest.mark.slow),
    pytest.param("1f1b", 1, {"offload_activations": True},
                 marks=pytest.mark.slow),
    pytest.param("zb1", 1, {"offload_wgrad": True,
                            "offload_activations": True},
                 marks=pytest.mark.slow),
])
def test_pipeline_kernel_ce_matches_xla_head(cfg, params, devices, schedule,
                                             v, offload):
    """kernels.ce on-vs-off at the same loss_chunks: loss BIT-equal (the
    op-level contract survives the cond-gated head, remat, and the zb1
    B/W split), grads within the dW fold tolerance."""
    batch = make_batch(cfg)
    l_xla, g_xla = run_pipeline(params, batch, cfg, schedule=schedule, v=v,
                                loss_chunks=4, **offload)
    l_ker, g_ker = run_pipeline(params, batch, cfg, schedule=schedule, v=v,
                                loss_chunks=4, kernel_ce=True, **offload)
    assert l_ker == l_xla
    assert_grads_close(g_ker, g_xla)


@pytest.mark.slow
def test_pipeline_kernel_ce_dense_head_parity(cfg, params, devices):
    """kernels.ce at loss_chunks=1 vs the dense [tokens, V] head: same
    quantity, different lse association — tolerance, not bits."""
    batch = make_batch(cfg)
    l_xla, g_xla = run_pipeline(params, batch, cfg)
    l_ker, g_ker = run_pipeline(params, batch, cfg, kernel_ce=True)
    np.testing.assert_allclose(l_ker, l_xla, rtol=1e-6)
    assert_grads_close(g_ker, g_xla, atol=1e-6)


def test_kernel_ce_with_tp_rejected(cfg, params, devices):
    mesh = make_mesh(MeshConfig(pp=2, tp=2))
    manifest = StageManifest.for_config(cfg, 2)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=2, kernel_ce=True)
    with pytest.raises(ValueError, match="redundant under tp"):
        pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked)


def test_kernel_ce_vmem_tile_check_on_tpu_backend(cfg, params, devices,
                                                  monkeypatch):
    """On a TPU backend the build refuses a [hidden, V/loss_chunks] weight
    tile over VMEM with the actionable loss_vocab_chunks message, instead
    of dying inside Mosaic; a VMEM-sized chunking at the same shape builds.
    (Backend faked — interpret mode has no such limit, so the check must
    key on the real backend.)"""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    big = LlamaConfig.tiny(vocab_size=4096, hidden_size=4096,
                           num_attention_heads=64, num_key_value_heads=64,
                           intermediate_size=64)
    mesh = make_mesh(MeshConfig(pp=2))
    manifest = StageManifest.for_config(big, 2)
    stacked = jax.eval_shape(
        lambda r: pl.stack_stages(llama.init_params(r, big), manifest),
        jax.random.PRNGKey(0))
    dense = pl.PipelineConfig(num_stages=2, num_microbatches=2,
                              kernel_ce=True)
    with pytest.raises(ValueError, match="loss_vocab_chunks"):
        pl.make_pipeline_loss_and_grad(mesh, big, dense, stacked)
    chunked = pl.PipelineConfig(num_stages=2, num_microbatches=2,
                                kernel_ce=True, loss_chunks=32)
    pl.make_pipeline_loss_and_grad(mesh, big, chunked, stacked)  # builds


def test_loss_head_bytes_model():
    """The preflight memory-model term: XLA dense = one fp32 [tokens, V]
    block; XLA chunked adds the fp32 dh accumulator; Pallas = 0."""
    mk = lambda **kw: pl.PipelineConfig(num_stages=2, num_microbatches=2, **kw)
    tokens = 8 * 16
    assert pl.loss_head_bytes(mk(), 8, 16, 64, 256) == tokens * 256 * 4
    assert pl.loss_head_bytes(mk(loss_chunks=8), 8, 16, 64, 256) == \
        tokens * 32 * 4 + tokens * 64 * 4
    assert pl.loss_head_bytes(mk(loss_chunks=8, kernel_ce=True),
                              8, 16, 64, 256) == 0
    assert pl.loss_head_bytes(mk(kernel_ce=True), 8, 16, 64, 256) == 0


def test_kernel_flags_config_block():
    """train.py's `kernels.*` parse: xla/pallas values, unknown-key and
    bad-value rejection (the offload.* pattern)."""
    from llama_pipeline_parallel_tpu.train import _kernel_flags

    assert _kernel_flags({}) == (False, False)
    assert _kernel_flags({"kernels": {"ce": "pallas"}}) == (True, False)
    assert _kernel_flags({"kernels": {"ce": "xla", "prologue": "pallas"}}) \
        == (False, True)
    with pytest.raises(ValueError, match="unknown kernels"):
        _kernel_flags({"kernels": {"attention": "pallas"}})
    with pytest.raises(ValueError, match="must be 'xla' or 'pallas'"):
        _kernel_flags({"kernels": {"ce": True}})
    with pytest.raises(ValueError, match="mapping"):
        _kernel_flags({"kernels": "pallas"})

"""bench.py's fail-fast device probe (BENCH_r05: an unreachable TPU used to
burn the full 900 s watchdog before the error JSON appeared; the probe
bounds that to BENCH_PROBE_TIMEOUT_S)."""

import os
import sys
import time

import jax
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from bench import _probe_devices  # noqa: E402


def test_probe_passes_on_live_backend(devices):
    assert _probe_devices(timeout_s=60.0) is None


def test_probe_reports_wedged_backend(monkeypatch):
    """A backend that never answers (the blocking-C-call wedge) turns into
    an error string within the timeout instead of hanging forever."""
    monkeypatch.setattr(jax, "devices", lambda *a, **k: time.sleep(3600))
    err = _probe_devices(timeout_s=0.2)
    assert err is not None and "did not respond" in err


def test_probe_reports_broken_backend(monkeypatch):
    monkeypatch.setattr(jax, "devices",
                        lambda *a, **k: (_ for _ in ()).throw(
                            RuntimeError("no TPU visible")))
    err = _probe_devices(timeout_s=5.0)
    assert err is not None and "no TPU visible" in err


def test_probe_reports_empty_device_list(monkeypatch):
    monkeypatch.setattr(jax, "devices", lambda *a, **k: [])
    err = _probe_devices(timeout_s=5.0)
    assert err is not None and "no devices" in err

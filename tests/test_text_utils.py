from llama_pipeline_parallel_tpu.data.text_utils import (
    char_to_token_spans,
    chunk_by_spans,
    find_spans,
    get_unused_tokens,
    resolve_spans,
    word_tokenize,
)


def test_find_spans_word_boundaries():
    text = "the cat scattered the cats"
    assert find_spans(text, "cat") == [(4, 7)]  # not inside "scattered"/"cats"
    assert find_spans(text, "cats") == [(22, 26)]
    assert find_spans(text, "") == []


def test_resolve_spans_nested_and_overlap():
    # nested span dropped, partial overlap clipped
    assert resolve_spans([(0, 10), (2, 5)]) == [(0, 10)]
    assert resolve_spans([(0, 6), (4, 9)]) == [(0, 6), (6, 9)]


def test_chunk_by_spans_indicator():
    text = "Johann Wolfgang Goethe studied in Leipzig"
    pieces, mask = chunk_by_spans(text, ["Johann Wolfgang Goethe", "Leipzig"])
    assert pieces == ["Johann Wolfgang Goethe", "studied in", "Leipzig"]
    assert mask == [1, 0, 1]
    pieces2, mask2 = chunk_by_spans(text, ["Leipzig"], word_split=True)
    assert pieces2[-1] == "Leipzig" and mask2[-1] == 1
    assert mask2[:-1] == [0] * (len(pieces2) - 1)


def test_word_tokenize_contractions():
    assert word_tokenize("don't stop, now!") == ["don't", "stop", ",", "now", "!"]


def test_get_unused_tokens():
    class Tok:
        def get_vocab(self):
            return {"[unused0]": 1}

    toks = get_unused_tokens(Tok(), num=2)
    assert toks == ["[unused1]", "[unused2]"]


def test_char_to_token_spans():
    # "hello world" -> tokens [hello][ world] with offsets
    offsets = [(0, 0), (0, 5), (5, 11)]  # leading special token
    assert char_to_token_spans(offsets, [(0, 5)]) == [(1, 2)]
    assert char_to_token_spans(offsets, [(6, 11)]) == [(2, 3)]
    assert char_to_token_spans(offsets, [(100, 105)]) == [(0, 0)]

"""Sequence parallelism wired into the training path: PP x SP x DP grids must
reproduce single-device loss AND gradients exactly.

The capability the reference lacks entirely (SURVEY.md §5.7: sequence length
fixed at 512) and VERDICT round-1 missing item #2: ring/Ulysses existed as
tested islands; these tests pin their integration into the pipeline schedule,
including the cross-shard causal label shift and the sp gradient reductions.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

from tests.test_pipeline import (
    assert_tree_close,
    make_batch,
    reference_loss_and_grad,
)


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()  # 4 layers, 4 heads, 2 kv heads


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.PRNGKey(0), cfg)


def run_sp_pipeline(params, batch, cfg, pp, dp, sp, microbatches,
                    sequence_parallel="ring", schedule="1f1b", tp=1):
    mesh = make_mesh(MeshConfig(pp=pp, dp=dp, sp=sp, tp=tp))
    manifest = StageManifest.for_config(cfg, pp)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=pp, num_microbatches=microbatches,
                             schedule=schedule,
                             sequence_parallel=sequence_parallel)
    fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked))
    loss, grads = fn(stacked, batch)
    return loss, pl.unstack_stages(grads, manifest)


@pytest.mark.parametrize("pp,dp,sp,strategy", [
    # sp=4 slow-marked (PR 10 rebalance): the pp2xdp2xsp2 hybrid is the
    # fast ring rep (more composition per second than the deeper ring)
    pytest.param(1, 1, 4, "ring", marks=pytest.mark.slow),
    (2, 2, 2, "ring"),
    (1, 1, 2, "ulysses"),
    pytest.param(2, 1, 2, "ring", marks=pytest.mark.slow),
    pytest.param(2, 1, 2, "ulysses", marks=pytest.mark.slow),
])
def test_sp_in_pipeline_matches_reference(cfg, params, devices, pp, dp, sp, strategy):
    """PP x SP x DP grids, both strategies: exact loss and gradient parity.

    The batch has trailing padding and prompt masking, so the cross-shard
    label shift (the target of the slab boundary token lives on the next sp
    rank) and the IGNORE_INDEX bookkeeping are both exercised."""
    batch = make_batch(cfg, batch_size=2 * dp, seqlen=16)  # 2 microbatch rows per dp shard
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads = run_sp_pipeline(params, batch, cfg, pp=pp, dp=dp, sp=sp,
                                  microbatches=2, sequence_parallel=strategy)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    assert_tree_close(grads, ref_grads, rtol=5e-5, atol=2e-6)


def test_sp_gpipe_schedule(cfg, params, devices):
    """SP composes with the legacy gpipe schedule too."""
    batch = make_batch(cfg, batch_size=4, seqlen=16)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads = run_sp_pipeline(params, batch, cfg, pp=2, dp=1, sp=2,
                                  microbatches=2, schedule="gpipe")
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    assert_tree_close(grads, ref_grads, rtol=5e-5, atol=2e-6)


def test_sp_with_tp(cfg, params, devices):
    """sp x tp: sequence sharding over head-sharded attention plus the
    vocab-parallel loss taking the preshifted-target path."""
    batch = make_batch(cfg, batch_size=2, seqlen=16)
    ref_loss, ref_grads = reference_loss_and_grad(params, batch, cfg)
    loss, grads = run_sp_pipeline(params, batch, cfg, pp=1, dp=1, sp=2, tp=2,
                                  microbatches=2)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    assert_tree_close(grads, ref_grads, rtol=5e-5, atol=2e-6)


def test_ulysses_head_divisibility_guard(cfg, params, devices):
    mesh = make_mesh(MeshConfig(pp=1, dp=1, sp=8))
    manifest = StageManifest.for_config(cfg, 1)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=1, num_microbatches=1,
                             sequence_parallel="ulysses")
    with pytest.raises(ValueError, match="divisible by sp"):
        pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked)


def test_trainer_rejects_indivisible_sp_sequence(devices, tmp_path):
    """The trainer validates seq % sp up front with a clear message instead
    of a cryptic GSPMD sharding error at first jit."""
    from llama_pipeline_parallel_tpu.train import run_training

    cfg = {"output_dir": str(tmp_path), "mesh": {"sp": 4},
           "model": {"preset": "tiny", "dtype": "float32"},
           "dataset": {"synthetic": True, "seq_length": 30,
                       "pseudo_dataset_len": 8},
           "per_device_train_batch_size": 2, "max_steps": 2, "warmup_steps": 1}
    with pytest.raises(ValueError, match="equal slabs"):
        run_training(cfg)


def test_16k_ladder_config_runs_tiny(devices, tmp_path):
    """The shipped 16k stress config (BASELINE.md ladder #5) drives the real
    trainer end-to-end at tiny scale: every mesh axis the config uses stays
    >1 (pp x tp x sp), same sequence_parallel=ring and offloaded optimizer,
    tiny model/sequence via overrides. The config's full 16-device topology
    is halved to the test mesh's 8 (pp 4 -> 2) — its real shape is backed by
    tools/preflight.py (docs/PREFLIGHT.md) and tests/test_preflight.py."""
    from llama_pipeline_parallel_tpu.train import run_training
    from llama_pipeline_parallel_tpu.utils.config import load_config

    cfg = load_config(os.path.join(os.path.dirname(__file__), "..",
                                   "conf", "codellama_34b_16k.yaml"),
                      overrides=[
                          f"output_dir={tmp_path}",
                          "mesh.pp=2",
                          "model.preset=tiny",
                          "model.dtype=float32",
                          "dataset.seq_length=32",
                          "dataset.pseudo_dataset_len=64",
                          "max_seq_length=32",
                          "gradient_accumulation_steps=2",
                          "per_device_train_batch_size=1",
                          "attention=exact",
                          "max_steps=4",
                          "warmup_steps=1",
                          "save_steps=0",
                          "save_final=false",
                      ])
    summary = run_training(cfg)
    assert summary["final_step"] == 4
    assert np.isfinite(summary["final_loss"])

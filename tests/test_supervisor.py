"""tools/supervisor.py: restart-on-crash, hang detection, crash-loop abort,
and the incarnations ledger — driven with fake millisecond-scale children."""

import json
import os
import sys

import pytest

import supervisor  # tools/ on sys.path via conftest
from supervisor import Supervisor, SupervisorConfig


def fast_cfg(out, **kw):
    defaults = dict(output_dir=str(out), max_restarts=2, hang_timeout_s=2.0,
                    grace_s=1.0, crash_loop_threshold=3,
                    crash_loop_window_s=0.0, poll_s=0.05)
    defaults.update(kw)
    return SupervisorConfig(**defaults)


def py(script):
    return [sys.executable, "-c", script]


def ledger(out):
    with open(os.path.join(str(out), supervisor.LEDGER_NAME)) as f:
        return [json.loads(l) for l in f]


def test_clean_child_single_incarnation(tmp_path):
    rc = Supervisor(py("pass"), fast_cfg(tmp_path)).run()
    assert rc == 0
    rows = ledger(tmp_path)
    assert len(rows) == 1
    assert rows[0]["incarnation"] == 0 and rows[0]["outcome"] == "clean"
    assert rows[0]["exit_code"] == 0


def test_crash_restarts_until_clean(tmp_path):
    """First incarnation crashes, second completes: the supervised-restart
    happy path. The marker file stands in for 'a checkpoint now exists'."""
    marker = tmp_path / "crashed.once"
    script = (f"import os, sys\n"
              f"m = {str(marker)!r}\n"
              f"if not os.path.exists(m):\n"
              f"    open(m, 'w').close(); sys.exit(17)\n")
    rc = Supervisor(py(script), fast_cfg(tmp_path)).run()
    assert rc == 0
    outcomes = [(r["incarnation"], r["outcome"], r["exit_code"])
                for r in ledger(tmp_path)]
    assert outcomes == [(0, "crash", 17), (1, "clean", 0)]


def test_restart_budget_exhausted(tmp_path):
    rc = Supervisor(py("import sys; sys.exit(1)"),
                    fast_cfg(tmp_path, max_restarts=1,
                             crash_loop_threshold=99)).run()
    assert rc == 2
    assert [r["outcome"] for r in ledger(tmp_path)] == ["crash", "crash"]


def test_crash_loop_aborts_before_budget(tmp_path):
    rc = Supervisor(py("import sys; sys.exit(1)"),
                    fast_cfg(tmp_path, max_restarts=50,
                             crash_loop_threshold=2,
                             crash_loop_window_s=100.0)).run()
    assert rc == 3
    assert len(ledger(tmp_path)) == 2  # gave up after 2 fast failures


def test_hang_detection_kills_and_restarts(tmp_path):
    """A child whose heartbeat goes stale is SIGTERMed (grace) and counted
    as a hang; with every incarnation hanging, the budget drains to rc 2."""
    health = os.path.join(str(tmp_path), "health.json")
    script = (f"import json, time\n"
              f"json.dump({{'time': time.time(), 'last_step': 3}}, "
              f"open({health!r}, 'w'))\n"
              f"time.sleep(60)\n")
    rc = Supervisor(py(script),
                    fast_cfg(tmp_path, max_restarts=1, hang_timeout_s=1.0,
                             grace_s=1.0, crash_loop_threshold=99)).run()
    assert rc == 2
    rows = ledger(tmp_path)
    assert [r["outcome"] for r in rows] == ["hang", "hang"]
    assert all(r["exit_code"] != 0 for r in rows)  # died by signal
    assert rows[0]["last_step"] == 3  # health context lands in the ledger


def test_stale_health_from_previous_incarnation_ignored(tmp_path):
    """A health.json left by a DEAD incarnation must not vouch for a new
    child that never wrote one — but liveness falls back to the launch time,
    so a fast clean child is still fine."""
    with open(os.path.join(str(tmp_path), "health.json"), "w") as f:
        json.dump({"time": 1.0}, f)  # ancient
    rc = Supervisor(py("pass"), fast_cfg(tmp_path)).run()
    assert rc == 0


def test_read_health_degrades_on_garbage(tmp_path):
    assert supervisor.read_health(str(tmp_path)) is None  # missing
    p = os.path.join(str(tmp_path), "health.json")
    with open(p, "w") as f:
        f.write('{"time": 12')  # torn
    assert supervisor.read_health(str(tmp_path)) is None
    with open(p, "w") as f:
        f.write("[1, 2]")  # valid JSON, wrong shape
    assert supervisor.read_health(str(tmp_path)) is None
    with open(p, "w") as f:
        json.dump({"time": 5.0}, f)
    assert supervisor.read_health(str(tmp_path)) == {"time": 5.0}


def test_cli_requires_command(tmp_path, capsys):
    with pytest.raises(SystemExit):
        supervisor.main(["--output-dir", str(tmp_path)])


def test_cli_runs_command_after_separator(tmp_path):
    rc = supervisor.main(["--output-dir", str(tmp_path), "--poll-s", "0.05",
                          "--"] + py("pass"))
    assert rc == 0
    assert ledger(tmp_path)[0]["outcome"] == "clean"

"""tools/supervisor.py: restart-on-crash, hang detection, crash-loop abort,
and the incarnations ledger — driven with fake millisecond-scale children."""

import json
import os
import sys

import pytest

import supervisor  # tools/ on sys.path via conftest
from supervisor import Supervisor, SupervisorConfig


def fast_cfg(out, **kw):
    defaults = dict(output_dir=str(out), max_restarts=2, hang_timeout_s=2.0,
                    grace_s=1.0, crash_loop_threshold=3,
                    crash_loop_window_s=0.0, poll_s=0.05)
    defaults.update(kw)
    return SupervisorConfig(**defaults)


def py(script):
    return [sys.executable, "-c", script]


def ledger(out):
    with open(os.path.join(str(out), supervisor.LEDGER_NAME)) as f:
        return [json.loads(l) for l in f]


def test_clean_child_single_incarnation(tmp_path):
    rc = Supervisor(py("pass"), fast_cfg(tmp_path)).run()
    assert rc == 0
    rows = ledger(tmp_path)
    assert len(rows) == 1
    assert rows[0]["incarnation"] == 0 and rows[0]["outcome"] == "clean"
    assert rows[0]["exit_code"] == 0


def test_crash_restarts_until_clean(tmp_path):
    """First incarnation crashes, second completes: the supervised-restart
    happy path. The marker file stands in for 'a checkpoint now exists'."""
    marker = tmp_path / "crashed.once"
    script = (f"import os, sys\n"
              f"m = {str(marker)!r}\n"
              f"if not os.path.exists(m):\n"
              f"    open(m, 'w').close(); sys.exit(17)\n")
    rc = Supervisor(py(script), fast_cfg(tmp_path)).run()
    assert rc == 0
    outcomes = [(r["incarnation"], r["outcome"], r["exit_code"])
                for r in ledger(tmp_path)]
    assert outcomes == [(0, "crash", 17), (1, "clean", 0)]


def test_restart_budget_exhausted(tmp_path):
    rc = Supervisor(py("import sys; sys.exit(1)"),
                    fast_cfg(tmp_path, max_restarts=1,
                             crash_loop_threshold=99)).run()
    assert rc == 2
    assert [r["outcome"] for r in ledger(tmp_path)] == ["crash", "crash"]


def test_crash_loop_aborts_before_budget(tmp_path):
    rc = Supervisor(py("import sys; sys.exit(1)"),
                    fast_cfg(tmp_path, max_restarts=50,
                             crash_loop_threshold=2,
                             crash_loop_window_s=100.0)).run()
    assert rc == 3
    assert len(ledger(tmp_path)) == 2  # gave up after 2 fast failures


def test_hang_detection_kills_and_restarts(tmp_path):
    """A child whose heartbeat goes stale is SIGTERMed (grace) and counted
    as a hang; with every incarnation hanging, the budget drains to rc 2."""
    health = os.path.join(str(tmp_path), "health.json")
    script = (f"import json, time\n"
              f"json.dump({{'time': time.time(), 'last_step': 3}}, "
              f"open({health!r}, 'w'))\n"
              f"time.sleep(60)\n")
    rc = Supervisor(py(script),
                    fast_cfg(tmp_path, max_restarts=1, hang_timeout_s=1.0,
                             grace_s=1.0, crash_loop_threshold=99)).run()
    assert rc == 2
    rows = ledger(tmp_path)
    assert [r["outcome"] for r in rows] == ["hang", "hang"]
    assert all(r["exit_code"] != 0 for r in rows)  # died by signal
    assert rows[0]["last_step"] == 3  # health context lands in the ledger


def test_stale_health_from_previous_incarnation_ignored(tmp_path):
    """A health.json left by a DEAD incarnation must not vouch for a new
    child that never wrote one — but liveness falls back to the launch time,
    so a fast clean child is still fine."""
    with open(os.path.join(str(tmp_path), "health.json"), "w") as f:
        json.dump({"time": 1.0}, f)  # ancient
    rc = Supervisor(py("pass"), fast_cfg(tmp_path)).run()
    assert rc == 0


def test_read_health_degrades_on_garbage(tmp_path):
    assert supervisor.read_health(str(tmp_path)) is None  # missing
    p = os.path.join(str(tmp_path), "health.json")
    with open(p, "w") as f:
        f.write('{"time": 12')  # torn
    assert supervisor.read_health(str(tmp_path)) is None
    with open(p, "w") as f:
        f.write("[1, 2]")  # valid JSON, wrong shape
    assert supervisor.read_health(str(tmp_path)) is None
    with open(p, "w") as f:
        json.dump({"time": 5.0}, f)
    assert supervisor.read_health(str(tmp_path)) == {"time": 5.0}


def test_cli_requires_command(tmp_path, capsys):
    with pytest.raises(SystemExit):
        supervisor.main(["--output-dir", str(tmp_path)])


def test_cli_runs_command_after_separator(tmp_path):
    rc = supervisor.main(["--output-dir", str(tmp_path), "--poll-s", "0.05",
                          "--"] + py("pass"))
    assert rc == 0
    assert ledger(tmp_path)[0]["outcome"] == "clean"


# ---------------------------------------------------------------------------
# actuation (--actuate): the action.request RPC from tools/fleetctl.py
# ---------------------------------------------------------------------------

def _actions():
    from llama_pipeline_parallel_tpu.utils import actions

    return actions


def _write_request(out, payload):
    with open(os.path.join(str(out), _actions().ACTION_REQUEST_NAME),
              "w") as f:
        json.dump(payload, f)


def test_actuate_resize_pins_rung_and_persists(tmp_path, monkeypatch):
    """A pre-launch resize request pins the named ladder rung (overriding
    best-fit), drops the trainer-visible resize.request, writes the ack +
    action_state.json, removes the request — and a FRESH Supervisor over
    the same output_dir reloads the pin."""
    actions = _actions()
    monkeypatch.setenv("LPT_DEVICE_COUNT", "8")
    argv_log = str(tmp_path / "argv.jsonl")
    ladder = supervisor.parse_ladder(json.dumps([
        {"name": "full", "devices": 8, "overrides": ["mesh.dp=2"]},
        {"name": "half", "devices": 4, "overrides": ["mesh.dp=1"]}]))
    _write_request(tmp_path, {"action": "resize", "rung": "half",
                              "id": "action-000004"})
    child = (f"import json, sys\n"
             f"open({argv_log!r}, 'a').write(json.dumps(sys.argv[1:]))\n")
    rc = Supervisor(py(child),
                    fast_cfg(tmp_path, ladder=ladder, actuate=True)).run()
    assert rc == 0
    # the pin beat best-fit: 8 devices available, half rung launched
    assert json.loads(open(argv_log).read()) == ["mesh.dp=1"]
    rows = ledger(tmp_path)
    assert [r["layout"] for r in rows] == ["half"]
    # every on-disk artifact of the RPC, in its final state
    assert not os.path.exists(
        os.path.join(str(tmp_path), actions.ACTION_REQUEST_NAME))
    resize = json.load(open(
        os.path.join(str(tmp_path), actions.RESIZE_REQUEST_NAME)))
    assert resize["rung"] == "half" and resize["id"] == "action-000004"
    ack = json.load(open(
        os.path.join(str(tmp_path), actions.ACTION_ACK_NAME)))
    assert ack["id"] == "action-000004" and ack["action"] == "resize"
    state = json.load(open(
        os.path.join(str(tmp_path), supervisor.ACTION_STATE_NAME)))
    assert state["rung"] == "half" and state["last_id"] == "action-000004"
    # a supervisor RESTART (fresh object, same dir) keeps honoring the pin
    sup2 = Supervisor(py("pass"),
                      fast_cfg(tmp_path, ladder=ladder, actuate=True))
    assert sup2._pinned_rung == "half"
    # ... but only under --actuate: the pin never leaks into a plain run
    sup3 = Supervisor(py("pass"), fast_cfg(tmp_path, ladder=ladder))
    assert sup3._pinned_rung is None


def test_actuate_deploy_restarts_child_with_step_override(tmp_path):
    """A deploy request that lands while the child is RUNNING: the child is
    gracefully stopped, its clean exit continues supervision (restart
    boundary, not the end), and the next incarnation gets `--step N`
    spliced in — replacing any existing --step."""
    actions = _actions()
    argv_log = str(tmp_path / "argv.jsonl")
    marker = str(tmp_path / "first.marker")
    req = json.dumps({"action": "deploy", "step": 7, "id": "action-000002"})
    req_path = os.path.join(str(tmp_path), actions.ACTION_REQUEST_NAME)
    child = (
        f"import json, os, signal, sys\n"
        f"open({argv_log!r}, 'a').write(json.dumps(sys.argv[1:]) + '\\n')\n"
        f"if os.path.exists({marker!r}):\n"
        f"    sys.exit(0)\n"
        f"open({marker!r}, 'w').close()\n"
        f"signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))\n"
        f"os.replace({marker!r} + '.tmp', {req_path!r})\n"
        f"signal.pause()\n")
    with open(marker + ".tmp", "w") as f:
        f.write(req)
    rc = Supervisor(py(child) + ["--step", "1"],
                    fast_cfg(tmp_path, actuate=True, max_restarts=3)).run()
    assert rc == 0
    argvs = [json.loads(l) for l in open(argv_log)]
    assert argvs[0] == ["--step", "1"]
    assert argvs[1] == ["--step", "7"]          # replaced, not appended
    rows = ledger(tmp_path)
    assert [r["outcome"] for r in rows] == ["clean", "clean"]
    # the ledger says WHY incarnation 0 ended: the applied action
    assert rows[0]["action"] == {"id": "action-000002", "action": "deploy"}
    assert "action" not in rows[1]
    assert json.load(open(os.path.join(
        str(tmp_path), supervisor.ACTION_STATE_NAME)))["step"] == 7


def test_actuate_off_leaves_requests_untouched(tmp_path):
    """Inert by default: without --actuate an action.request is never read,
    never removed, and no actuation artifact appears."""
    actions = _actions()
    _write_request(tmp_path, {"action": "resize", "rung": "half",
                              "id": "action-000000"})
    rc = Supervisor(py("pass"), fast_cfg(tmp_path)).run()
    assert rc == 0
    assert os.path.exists(
        os.path.join(str(tmp_path), actions.ACTION_REQUEST_NAME))
    for leftover in (actions.ACTION_ACK_NAME, actions.RESIZE_REQUEST_NAME,
                     supervisor.ACTION_STATE_NAME):
        assert not os.path.exists(os.path.join(str(tmp_path), leftover))
    assert "action" not in ledger(tmp_path)[0]


def test_actuate_degrades_on_bad_requests(tmp_path):
    """Torn, unknown-action, and step-less deploy requests are removed and
    ignored (never a traceback, never a wedged skip-if-present writer)."""
    actions = _actions()
    req_path = os.path.join(str(tmp_path), actions.ACTION_REQUEST_NAME)
    for bad in ('{"torn',
                json.dumps({"action": "defrag", "id": "action-000001"}),
                json.dumps({"action": "deploy", "step": "latest"})):
        with open(req_path, "w") as f:
            f.write(bad)
        rc = Supervisor(py("pass"), fast_cfg(tmp_path, actuate=True)).run()
        assert rc == 0
        assert not os.path.exists(req_path)
        assert not os.path.exists(
            os.path.join(str(tmp_path), actions.ACTION_ACK_NAME))


def test_abort_writes_terminal_registry_rows(tmp_path):
    """Crash-loop / budget / no-rung give-ups write outcome=aborted registry
    rows for BOTH member keys (child + supervisor), so the aggregator stops
    counting a pod nothing will restart as merely quiet."""
    from llama_pipeline_parallel_tpu.utils import fleet

    fleet_root = str(tmp_path / "fleet")
    rc = Supervisor(py("import sys; sys.exit(1)"),
                    fast_cfg(tmp_path / "run", max_restarts=50,
                             crash_loop_threshold=2,
                             crash_loop_window_s=100.0,
                             fleet_root=fleet_root, role="trainer")).run()
    assert rc == 3
    rows = [r for r in fleet.load_registry(fleet_root)
            if r.get("outcome") == "aborted"]
    assert {r.get("role") for r in rows} == {"trainer", "supervisor"}
    assert all(r["reason"] == "crash_loop" for r in rows)
    # budget exhaustion aborts too, with its own reason
    fleet_root2 = str(tmp_path / "fleet2")
    rc = Supervisor(py("import sys; sys.exit(1)"),
                    fast_cfg(tmp_path / "run2", max_restarts=0,
                             crash_loop_threshold=9,
                             fleet_root=fleet_root2, role="trainer")).run()
    assert rc == 2
    reasons = {r["reason"] for r in fleet.load_registry(fleet_root2)
               if r.get("outcome") == "aborted"}
    assert reasons == {"budget_exhausted"}

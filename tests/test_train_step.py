"""Train-step integration: optimization works under PP x DP with ZeRO-1."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer, warmup_decay_schedule
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel import train_step as ts
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh


def test_warmup_decay_schedule():
    sched = warmup_decay_schedule(1.0, total_steps=100, warmup_steps=10)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(10)), 1.0)
    np.testing.assert_allclose(float(sched(55)), 0.5)
    np.testing.assert_allclose(float(sched(100)), 0.0)
    with pytest.raises(ValueError):
        warmup_decay_schedule(1.0, total_steps=10, warmup_steps=10)


def _setup(pp, dp, microbatches=2, lr=5e-3):
    cfg = LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshConfig(pp=pp, dp=dp))
    manifest = StageManifest.for_config(cfg, pp)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=pp, num_microbatches=microbatches)
    ocfg = OptimizerConfig(learning_rate=lr, total_steps=50, warmup_steps=5)
    tx, sched = make_optimizer(ocfg)
    state = ts.init_train_state(stacked, tx, mesh)
    step = ts.make_train_step(mesh, cfg, pcfg, tx, sched, stacked)
    return cfg, mesh, state, step


def _batch(cfg, batch_size, seqlen=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, cfg.vocab_size, size=(batch_size, seqlen)).astype(np.int32)
    return {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.ones((batch_size, seqlen), jnp.int32),
        "position_ids": jnp.broadcast_to(jnp.arange(seqlen, dtype=jnp.int32),
                                         (batch_size, seqlen)),
        "labels": jnp.asarray(ids),
    }


def test_loss_decreases_pp4_dp2(devices):
    """The §7.2 end-to-end slice: loss goes down on a fixed batch, PP=4 DP=2."""
    cfg, mesh, state, step = _setup(pp=4, dp=2, lr=1e-2)
    batch = _batch(cfg, batch_size=2 * 2 * 2)
    losses = []
    for _ in range(8):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.3, losses
    assert int(state.step) == 8
    assert np.isfinite(losses).all()


def test_zero1_opt_state_is_dp_sharded(devices):
    cfg, mesh, state, _ = _setup(pp=2, dp=2)
    # find a moment leaf for a matmul weight and check its sharding spec
    mu = state.opt_state[1][0].mu  # chain(clip, adamw) -> adamw scale_by_adam
    spec = mu["layers"]["attn"]["wq"].sharding.spec
    assert "dp" in jax.tree.leaves(tuple(spec)), spec
    assert spec[0] == "pp"
    # params stay dp-replicated
    pspec = state.params["layers"]["attn"]["wq"].sharding.spec
    assert "dp" not in [s for s in jax.tree.leaves(tuple(pspec))]


@pytest.mark.slow
def test_train_step_matches_across_topologies(devices):
    """Same data, same init: PP=4xDP=2 and PP=1xDP=1 produce the same params
    after a step (the hybrid-grid determinism the reference could never test)."""
    cfg1, _, state1, step1 = _setup(pp=1, dp=1, microbatches=4, lr=1e-3)
    cfg4, _, state4, step4 = _setup(pp=4, dp=2, microbatches=2, lr=1e-3)
    batch = _batch(cfg1, batch_size=4)
    state1, m1 = step1(state1, batch)
    state4, m4 = step4(state4, batch)
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]), rtol=1e-5)
    p1 = state1.params["layers"]["attn"]["wq"].reshape(4, -1)
    p4 = np.asarray(state4.params["layers"]["attn"]["wq"]).reshape(4, -1)
    np.testing.assert_allclose(p1, p4, rtol=1e-4, atol=1e-7)

"""Pallas fused RMSNorm->RoPE->QKV prologue (ops/pallas_prologue.py).

Parity gate vs the composed ops/rmsnorm.py -> matmul -> ops/rope.py
reference (the exact sequence models/llama/model.py's decoder_layer runs):
bf16 forward bit-equal, fp32 within the pinned ~1-ulp tolerance (one
blocked-vs-unblocked matmul rounding); grads within pinned tolerances,
including GQA head layouts and tp-sharded weights (the tp_copy psum moves
inside the op's custom VJP); a jaxpr assertion pinning the kernel in-graph
under `kernels.prologue: pallas`; and pipeline-level on-vs-off parity
across the schedule grid (the zb1 W-replay differentiates the kernel
w.r.t. params only)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.ops.pallas_prologue import (
    fused_prologue,
    prologue_traffic_bytes,
)
from llama_pipeline_parallel_tpu.ops.rmsnorm import rms_norm
from llama_pipeline_parallel_tpu.ops.rope import apply_rope, rope_cos_sin
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

EPS = 1e-6
# fp32: a single blocked-vs-unblocked matmul rounding (~1 ulp of the
# activations); bf16 forward is bit-equal, its grads differ only where the
# custom VJP's fp32 dhidden rounds once vs the reference's bf16 chain
FP32_ATOL = 1e-5
BF16_GRAD_RTOL = 0.05


def _shapes(d=32, hd=8, h=4, kvh=2):
    return d, hd, h, kvh


def _inputs(b=2, s=8, d=32, hd=8, h=4, kvh=2, dtype=jnp.float32, seed=0):
    r = np.random.RandomState(seed)
    x = jnp.asarray(r.randn(b, s, d).astype(np.float32), dtype)
    nw = jnp.asarray(1.0 + 0.1 * r.randn(d).astype(np.float32), dtype)
    wq = jnp.asarray((r.randn(d, h * hd) * 0.05).astype(np.float32), dtype)
    wk = jnp.asarray((r.randn(d, kvh * hd) * 0.05).astype(np.float32), dtype)
    wv = jnp.asarray((r.randn(d, kvh * hd) * 0.05).astype(np.float32), dtype)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    cos, sin = rope_cos_sin(pos, hd, dtype=dtype)
    return x, nw, wq, wk, wv, cos, sin


def reference(x, nw, wq, wk, wv, cos, sin, hd):
    """The exact decoder_layer prologue sequence."""
    b, s, _ = x.shape
    hidden = rms_norm(x, nw, EPS)
    q = (hidden @ wq).reshape(b, s, wq.shape[-1] // hd, hd)
    k = (hidden @ wk).reshape(b, s, wk.shape[-1] // hd, hd)
    v = (hidden @ wv).reshape(b, s, wv.shape[-1] // hd, hd)
    q, k = apply_rope(q, k, cos, sin)
    return q, k, v


# ---------------------------------------------------------------------------
# Op-level parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("h,kvh", [(4, 4), (4, 2), (8, 1)])  # MHA, GQA, MQA
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_forward_parity(dtype, h, kvh):
    x, nw, wq, wk, wv, cos, sin = _inputs(h=h, kvh=kvh, dtype=dtype)
    want = reference(x, nw, wq, wk, wv, cos, sin, 8)
    got = fused_prologue(x, nw, wq, wk, wv, cos, sin, eps=EPS, head_dim=8)
    for name, a, b in zip("qkv", got, want):
        assert a.shape == b.shape and a.dtype == b.dtype
        if dtype == jnp.bfloat16:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=name)
        else:
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=FP32_ATOL, rtol=1e-6,
                                       err_msg=name)


@pytest.mark.parametrize("h,kvh", [(4, 2)])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_grad_parity(dtype, h, kvh):
    x, nw, wq, wk, wv, cos, sin = _inputs(h=h, kvh=kvh, dtype=dtype)

    def scalar(fn):
        def run(x_, nw_, wq_, wk_, wv_):
            q, k, v = fn(x_, nw_, wq_, wk_, wv_)
            return (jnp.sum(q.astype(jnp.float32) ** 2)
                    + jnp.sum((k.astype(jnp.float32) * 1.3) ** 2)
                    + jnp.sum(v.astype(jnp.float32) ** 3))
        return run

    ref_fn = scalar(lambda *a: reference(*a, cos, sin, 8))
    got_fn = scalar(lambda *a: fused_prologue(*a, cos, sin, eps=EPS,
                                              head_dim=8))
    dref = jax.grad(ref_fn, argnums=(0, 1, 2, 3, 4))(x, nw, wq, wk, wv)
    dgot = jax.grad(got_fn, argnums=(0, 1, 2, 3, 4))(x, nw, wq, wk, wv)
    for name, a, b in zip(("dx", "dnorm", "dwq", "dwk", "dwv"), dgot, dref):
        a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
        if dtype == jnp.bfloat16:
            scale = max(1e-6, float(np.abs(b32).max()))
            assert np.abs(a32 - b32).max() / scale < BF16_GRAD_RTOL, name
        else:
            np.testing.assert_allclose(a32, b32, atol=2e-5, rtol=1e-5,
                                       err_msg=name)


def test_cos_sin_cotangents_are_zero():
    """cos/sin are positional data: the op pins their cotangents to zero
    (nothing in the pipeline differentiates them — a nonzero value would
    only ever feed dead code)."""
    x, nw, wq, wk, wv, cos, sin = _inputs()
    g = jax.grad(lambda c: jnp.sum(fused_prologue(
        x, nw, wq, wk, wv, c, sin, eps=EPS, head_dim=8)[0]
        .astype(jnp.float32) ** 2))(cos)
    assert float(jnp.abs(g).sum()) == 0.0


def test_validation_errors():
    x, nw, wq, wk, wv, cos, sin = _inputs()
    with pytest.raises(ValueError, match="multiples of head_dim"):
        fused_prologue(x, nw, wq[:, :-1], wk, wv, cos, sin, eps=EPS,
                       head_dim=8)
    with pytest.raises(ValueError, match="must be even"):
        fused_prologue(x, nw, wq, wk, wv, cos, sin, eps=EPS, head_dim=1)
    with pytest.raises(ValueError, match="must match"):
        fused_prologue(x, nw, wq, wk, wv[:, :8], cos, sin, eps=EPS,
                       head_dim=8)


def test_traffic_model_arithmetic():
    # fwd+bwd: 2 x (hidden write + 3 reads) + 2 x (pre-rope q/k round trip)
    assert prologue_traffic_bytes(64, 32, 32, 16, 2) == \
        2 * 4 * 64 * 32 * 2 + 2 * 2 * 64 * (32 + 16) * 2


def test_lowering_kernel_in_graph():
    """Structural pin: the fwd+bwd jaxpr holds the forward kernel plus the
    flash-style split backward (dhidden + dW) as pallas_call equations, so
    the zb1 B unit can DCE the dW kernel and the W replay the dhidden one."""
    x, nw, wq, wk, wv, cos, sin = _inputs()

    def loss(x_, nw_, wq_):
        q, k, v = fused_prologue(x_, nw_, wq_, wk, wv, cos, sin, eps=EPS,
                                 head_dim=8)
        return jnp.sum(q.astype(jnp.float32) ** 2) + \
            jnp.sum(k.astype(jnp.float32) ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(x, nw, wq)
    text = str(jaxpr)
    assert text.count("pallas_call") >= 3


# ---------------------------------------------------------------------------
# Pipeline integration: kernels.prologue across schedules, tp, eval
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny(num_hidden_layers=8)


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.PRNGKey(0), cfg)


def make_batch(cfg, batch_size=8, seqlen=16, seed=42):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, cfg.vocab_size, size=(batch_size, seqlen)).astype(np.int32)
    mask = np.ones((batch_size, seqlen), np.int32)
    mask[:, -3:] = 0
    labels = ids.copy()
    labels[mask == 0] = llama.IGNORE_INDEX
    return {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.asarray(mask),
        "position_ids": jnp.asarray(np.broadcast_to(
            np.arange(seqlen, dtype=np.int32), (batch_size, seqlen)).copy()),
        "labels": jnp.asarray(labels),
    }


def run_pipeline(params, batch, cfg, pp=2, schedule="1f1b", v=1, tp=1,
                 microbatches=4, **pkw):
    mesh = make_mesh(MeshConfig(pp=pp, tp=tp))
    manifest = StageManifest.for_config(cfg, pp, virtual_stages=v)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=pp, num_microbatches=microbatches,
                             schedule=schedule, virtual_stages=v, **pkw)
    fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked))
    loss, grads = fn(stacked, batch)
    return float(loss), pl.unstack_stages(grads, manifest)


def assert_grads_close(a, b, atol=5e-7):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=2e-5, atol=atol)


# Fast lane: flat + the zb1 split backward (W replay differentiates the
# kernel w.r.t. params only); interleaved + offload rows slow-marked.
@pytest.mark.parametrize("schedule,v,offload", [
    ("1f1b", 1, {}),
    ("zb1", 2, {}),
    pytest.param("interleaved_1f1b", 2, {}, marks=pytest.mark.slow),
    pytest.param("zb1", 2, {"offload_wgrad": True,
                            "offload_activations": True},
                 marks=pytest.mark.slow),
])
def test_pipeline_prologue_on_vs_off(cfg, params, devices, schedule, v,
                                     offload):
    batch = make_batch(cfg)
    l_off, g_off = run_pipeline(params, batch, cfg, schedule=schedule, v=v,
                                **offload)
    l_on, g_on = run_pipeline(params, batch, cfg, schedule=schedule, v=v,
                              kernel_prologue=True, **offload)
    np.testing.assert_allclose(l_on, l_off, rtol=1e-6)
    assert_grads_close(g_on, g_off)


def test_pipeline_prologue_under_tp(cfg, params, devices):
    """tp=2: the fused op's in-VJP psum must reproduce the tp_copy
    backward — norm/embedding grads are full tp sums, not 1/tp of them."""
    batch = make_batch(cfg)
    l_off, g_off = run_pipeline(params, batch, cfg, tp=2)
    l_on, g_on = run_pipeline(params, batch, cfg, tp=2, kernel_prologue=True)
    np.testing.assert_allclose(l_on, l_off, rtol=1e-6)
    assert_grads_close(g_on, g_off, atol=1e-6)


@pytest.mark.slow
def test_pipeline_both_kernels_zb1(cfg, params, devices):
    """The full `kernels: {ce: pallas, prologue: pallas}` config under the
    zb1 split backward — the PR's two tentpole kernels composed."""
    batch = make_batch(cfg)
    l_off, g_off = run_pipeline(params, batch, cfg, schedule="zb1", v=2,
                                loss_chunks=4)
    l_on, g_on = run_pipeline(params, batch, cfg, schedule="zb1", v=2,
                              loss_chunks=4, kernel_ce=True,
                              kernel_prologue=True)
    assert l_on == l_off  # the CE contract holds with the prologue fused too
    assert_grads_close(g_on, g_off)


def test_single_device_forward_parity(cfg, params):
    """model.forward's pallas_prologue flag: logits parity on the PP=1
    degenerate path (the decode/serve stack shares decoder_layer)."""
    batch = make_batch(cfg, batch_size=2)
    base = llama.forward(params, batch["input_ids"], batch["attention_mask"],
                         cfg=cfg)
    fused = llama.forward(params, batch["input_ids"], batch["attention_mask"],
                          cfg=cfg, pallas_prologue=True)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(base),
                               rtol=1e-5, atol=1e-5)


def test_kernel_prologue_vmem_scratch_check_on_tpu_backend(cfg, params,
                                                           devices,
                                                           monkeypatch):
    """On a TPU backend the build refuses an unsharded layer whose fp32
    q+k+v dW scratches exceed VMEM, naming the tp/xla remedies; tp-sharding
    the same shape under the guard's arithmetic builds. (Backend faked —
    interpret mode has no such limit.)"""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    big = LlamaConfig.tiny(hidden_size=2048, num_attention_heads=32,
                           num_key_value_heads=32, intermediate_size=64)
    mesh = make_mesh(MeshConfig(pp=2))
    manifest = StageManifest.for_config(big, 2)
    stacked = jax.eval_shape(
        lambda r: pl.stack_stages(llama.init_params(r, big), manifest),
        jax.random.PRNGKey(0))
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=2,
                             kernel_prologue=True)
    # 2048 rows x 3*2048 local columns x 4 B = 48 MiB of dW scratch
    with pytest.raises(ValueError, match="kernels.prologue=xla"):
        pl.make_pipeline_loss_and_grad(mesh, big, pcfg, stacked)
    mesh_tp = make_mesh(MeshConfig(pp=2, tp=4))
    stacked_tp = stacked  # spec construction only; shapes unchanged
    pl.make_pipeline_loss_and_grad(mesh_tp, big, pcfg, stacked_tp)  # builds


def test_pipeline_jaxpr_has_kernel_only_when_on(cfg, params, devices):
    mesh = make_mesh(MeshConfig(pp=2))
    manifest = StageManifest.for_config(cfg, 2)
    stacked = pl.stack_stages(params, manifest)
    batch = make_batch(cfg, batch_size=2)
    texts = {}
    for on in (False, True):
        pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=2,
                                 kernel_prologue=on)
        fn = pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked)
        texts[on] = str(jax.make_jaxpr(fn)(stacked, batch))
    assert "pallas_call" in texts[True]
    assert "pallas_call" not in texts[False]

"""Config system: YAML load, interpolation, overrides, factories."""

import pytest

from llama_pipeline_parallel_tpu.utils.config import instantiate, load_config


def _write(tmp_path, text):
    p = tmp_path / "c.yaml"
    p.write_text(text)
    return str(p)


def test_interpolation_and_types(tmp_path):
    cfg = load_config(_write(tmp_path, """
model_name: /models/llama
lr: 1e-4
paths:
  out: ${model_name}/out
  lr_copy: ${lr}
nested: ${paths.out}
"""))
    assert cfg["paths"]["out"] == "/models/llama/out"
    assert cfg["nested"] == "/models/llama/out"
    assert cfg["lr"] == 1e-4  # sci-notation coerced to float
    assert cfg["paths"]["lr_copy"] == 1e-4  # whole-string interp keeps type


def test_overrides(tmp_path):
    path = _write(tmp_path, "a:\n  b: 1\nc: x\n")
    cfg = load_config(path, ["a.b=2", "--c=hello", "d.e=[1,2]"])
    assert cfg["a"]["b"] == 2
    assert cfg["c"] == "hello"
    assert cfg["d"]["e"] == [1, 2]
    with pytest.raises(ValueError, match="key=value"):
        load_config(path, ["oops"])


def test_interpolation_cycle(tmp_path):
    with pytest.raises(ValueError, match="cycle"):
        load_config(_write(tmp_path, "a: ${b}\nb: ${a}\n"))


def test_instantiate_target(tmp_path):
    node = {"_target_": "llama_pipeline_parallel_tpu.models.llama.config.LlamaConfig.tiny",
            "vocab_size": 128}
    cfg = instantiate(node)
    assert cfg.vocab_size == 128 and cfg.num_hidden_layers == 4
    with pytest.raises(ValueError, match="dotted"):
        instantiate({"_target_": "nodots"})

"""Request observatory (serve/reqtrace.py + tools/request_report.py —
docs/SERVING.md "Request tracing").

The acceptance contracts live here:
- W3C traceparent handling: valid headers join the caller's trace,
  malformed ones mint a fresh context instead of rejecting.
- the span tree is INTERNALLY CONSISTENT: queue-wait span == the recorded
  queue_wait_s, a request's own prefill chunks sum to prefill_s <= TTFT,
  child spans never exceed the request wall, decode ticks are contiguous.
- tracing OFF is structurally free (no builder dict entries, no page-pool
  listener, no stream) and tracing ON changes NO tokens (the OFF-twin
  parity run is bit-identical).
- the tail-exemplar ring keeps the slowest-K in eviction order, and the
  offline report degrades on torn/garbage/missing trace files.
- THE e2e acceptance: a deliberately slow long-prompt chunked-prefill
  request is named the p99-TTFT exemplar, its waterfall attributes TTFT
  to prefill chunks, and the SLO-breach capture's meta names the same
  trace id.
"""

import json
import os
import time

import jax
import numpy as np
import pytest

import request_report  # tools/ on sys.path via conftest
import serve_traffic
from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.decode import (
    GenerationConfig,
    generate,
)
from llama_pipeline_parallel_tpu.serve import (
    RequestTraceRecorder,
    ServeConfig,
    ServeEngine,
    ServeRequest,
    TraceContext,
)
from llama_pipeline_parallel_tpu.serve.reqtrace import (
    EXEMPLARS_NAME,
    REQUEST_TRACE_NAME,
    ExemplarRing,
)
from llama_pipeline_parallel_tpu.utils.trace import (
    format_traceparent,
    mint_span_id,
    mint_trace_id,
    parse_traceparent,
)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def chunked_engine(cfg, params, **kw):
    """The chunked-prefill shape of tests/test_paged_serving.py: buckets 8
    and 32, 8-token chunk budget — a bucket-32 prompt takes 4 interleaved
    chunks, the slow-request shape the waterfall must attribute."""
    engine_kw = {k: kw.pop(k) for k in ("reqtrace", "profiler", "slo")
                 if k in kw}
    defaults = dict(max_slots=2, max_len=48, prompt_buckets=(8, 32),
                    page_size=4, kv_cache="paged", num_pages=24,
                    prefill_chunk_tokens=8, max_queue=32, metrics_every=1,
                    decode_span_every=1)
    defaults.update(kw)
    return ServeEngine(params, cfg, ServeConfig(**defaults), **engine_kw)


def reference_tokens(params, cfg, prompt, gen, seed, bucket):
    import jax.numpy as jnp

    pad = bucket - len(prompt)
    ids = np.concatenate([np.zeros(pad, np.int32),
                          np.asarray(prompt, np.int32)])[None]
    mask = np.asarray([[0] * pad + [1] * len(prompt)], np.int32)
    out = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, gen,
                   rng=jax.random.PRNGKey(seed))
    return np.asarray(out["tokens"])[0].tolist()


def load_records(d: str) -> list[dict]:
    with open(os.path.join(d, REQUEST_TRACE_NAME)) as f:
        return [json.loads(line) for line in f]


# -- W3C trace context --------------------------------------------------------


def test_traceparent_parse_format_grid():
    tid, sid = "ab" * 16, "cd" * 8
    assert parse_traceparent(f"00-{tid}-{sid}-01") == (tid, sid)
    assert parse_traceparent(f"00-{tid}-{sid}-00") == (tid, sid)
    # a future version is parseable as long as the fields are sound
    assert parse_traceparent(f"01-{tid}-{sid}-01") == (tid, sid)
    for bad in (None, "", "garbage", f"ff-{tid}-{sid}-01",
                f"00-{tid[:-2]}-{sid}-01", f"00-{tid}-{sid[:-2]}-01",
                f"00-{'zz' * 16}-{sid}-01", f"00-{'00' * 16}-{sid}-01",
                f"00-{tid}-{'00' * 8}-01", f"00-{tid}-{sid}",
                f"00-{tid.upper()}-{sid}-01"):
        assert parse_traceparent(bad) is None, bad
    assert format_traceparent(tid, sid) == f"00-{tid}-{sid}-01"
    assert parse_traceparent(format_traceparent(tid, sid)) == (tid, sid)

    minted = {mint_trace_id() for _ in range(32)}
    assert len(minted) == 32 and all(len(t) == 32 for t in minted)
    assert all(len(mint_span_id()) == 16 for _ in range(4))


def test_trace_context_adopts_or_mints():
    ctx = TraceContext.from_traceparent("00-" + "ab" * 16 + "-"
                                        + "cd" * 8 + "-01")
    assert ctx.trace_id == "ab" * 16
    assert ctx.parent_span == "cd" * 8
    assert ctx.span_id not in ("cd" * 8, "00" * 8)  # OUR span, fresh
    # the outgoing header continues OUR span, not the caller's
    assert ctx.traceparent() == format_traceparent(ctx.trace_id, ctx.span_id)

    fresh = TraceContext.from_traceparent("not-a-header")
    assert fresh.parent_span is None and len(fresh.trace_id) == 32
    assert TraceContext.mint().trace_id != TraceContext.mint().trace_id


def test_submit_mints_trace_when_absent(setup):
    cfg, params = setup
    engine = chunked_engine(cfg, params)
    try:
        r = ServeRequest(input_ids=[5, 6],
                         gen=GenerationConfig(max_new_tokens=1))
        assert r.trace is None
        engine.submit(r)
        assert r.trace is not None and len(r.trace.trace_id) == 32
        ctx = TraceContext.mint()
        r2 = ServeRequest(input_ids=[5, 6],
                          gen=GenerationConfig(max_new_tokens=1), trace=ctx)
        engine.submit(r2)
        assert r2.trace is ctx            # a provided context is kept
    finally:
        engine.shutdown()


# -- exemplar ring ------------------------------------------------------------


def test_exemplar_ring_keeps_slowest_k_in_order():
    ring = ExemplarRing(3)
    for v in (0.3, 0.1, 0.9, 0.2):
        assert ring.offer(v, {"v": v})
    # full ring: 0.1 was evicted (always the LEAST slow), order slowest-first
    assert [r["v"] for r in ring.records()] == [0.9, 0.3, 0.2]
    assert not ring.offer(0.15, {"v": 0.15})      # below the floor: rejected
    assert ring.offer(0.5, {"v": 0.5})
    assert [r["v"] for r in ring.records()] == [0.9, 0.5, 0.3]
    with pytest.raises(ValueError):
        ExemplarRing(0)


def test_recorder_writes_shed_and_exemplars(tmp_path):
    rec = RequestTraceRecorder(str(tmp_path), exemplar_k=2)
    shed = ServeRequest(input_ids=[1], tenant="free",
                        trace=TraceContext.mint())
    rec.record_shed(shed, "queue_full", retry_after_s=1.5)
    for i, ttft in enumerate((0.2, 0.9, 0.5)):
        rec.write({"request_id": f"r{i}", "outcome": "completed",
                   "ttft_s": ttft, "tpot_s": 0.01 * (i + 1)})
    rec.close()
    rec.close()                                    # idempotent

    rows = load_records(str(tmp_path))
    assert rows[0]["outcome"] == "shed"
    assert rows[0]["reason"] == "queue_full"
    assert rows[0]["retry_after_s"] == 1.5
    assert rows[0]["trace_id"] == shed.trace.trace_id
    assert len(rows) == 4
    with open(tmp_path / EXEMPLARS_NAME) as f:
        snap = json.load(f)
    assert [r["request_id"] for r in snap["ttft"]] == ["r1", "r2"]
    assert [r["request_id"] for r in snap["tpot"]] == ["r2", "r1"]


# -- offline report: math + degrade grid --------------------------------------


def test_ttft_breakdown_and_tail_attribution():
    rec = {"ttft_s": 1.0, "queue_wait_s": 0.12, "prefill_s": 0.71,
           "wall_s": 1.5}
    bd = request_report.ttft_breakdown(rec)
    assert bd["queue_pct"] == 12.0 and bd["prefill_pct"] == 71.0
    assert bd["interleave_pct"] == pytest.approx(17.0)
    assert bd["decode_s"] == pytest.approx(0.5)
    assert request_report.ttft_breakdown({"outcome": "shed"}) is None

    tail = request_report.tail_attribution([rec] * 4, quantile=99.0)
    assert tail["requests"] >= 1 and tail["queue_pct"] == 12.0
    assert request_report.tail_attribution([]) == {}


@pytest.mark.parametrize("damage", ["missing", "torn", "garbage"])
def test_report_degrades_on_damaged_trace(tmp_path, damage, capsys):
    good = {"schema": 1, "request_id": "r0", "trace_id": "t" * 32,
            "tenant": "paid", "outcome": "completed", "arrival": 100.0,
            "wall_s": 1.0, "tokens": 4, "ttft_s": 0.5, "tpot_s": 0.01,
            "queue_wait_s": 0.1, "prefill_s": 0.2, "spans": []}
    if damage != "missing":
        with open(tmp_path / REQUEST_TRACE_NAME, "w") as f:
            f.write(json.dumps(good) + "\n")
            if damage == "garbage":
                f.write("not json at all\n")
                f.write(json.dumps(good | {"request_id": "r1"}) + "\n")
            else:
                f.write('{"torn tail')
        with open(tmp_path / EXEMPLARS_NAME, "w") as f:
            f.write("{also torn")               # must not kill the report
    rc = request_report.main([str(tmp_path)])
    out = capsys.readouterr().out
    if damage == "missing":
        assert rc == 1 and "no request_trace.jsonl records" in out
    else:
        assert rc == 0
        rep = request_report.build_report(str(tmp_path))
        assert rep["completed"] == (2 if damage == "garbage" else 1)
        assert rep["tenants"]["paid"]["completed"] == rep["completed"]
        assert rep["exemplars"] == {}           # torn snapshot: degraded


# -- e2e: span-tree invariants + ON/OFF parity --------------------------------


def test_span_tree_invariants_and_on_off_token_parity(setup, tmp_path):
    """One seeded Poisson trace replayed twice — tracing ON and the OFF
    twin — must produce bit-identical tokens; the ON run's records must
    satisfy the span-tree invariants. The pool is sized so nothing sheds
    (shedding is wall-clock-dependent and would make the twin runs
    incomparable); the shed-record path is pinned separately below."""
    from llama_pipeline_parallel_tpu.serve import RequestRejected

    cfg, params = setup
    trace_reqs = serve_traffic.poisson_trace(
        3, 200.0, 6, serve_traffic.parse_mix("6:0.5,20:0.5"),
        serve_traffic.parse_mix("3:0.5,6:0.5"),
        tenant_mix=serve_traffic.parse_tenant_mix("free:0.7,paid:0.3"))

    tokens = {}
    for mode in ("on", "off"):
        rec = (RequestTraceRecorder(str(tmp_path), exemplar_k=4)
               if mode == "on" else None)
        engine = chunked_engine(cfg, params, num_pages=64, reqtrace=rec)
        summary = serve_traffic.run_trace(engine, trace_reqs,
                                          time_scale=0.02,
                                          collect_tokens=True)
        if mode == "on":
            # a synchronous rejection leaves a shed record (the request
            # never reaches the loop, so the terminal event IS its trace)
            with pytest.raises(RequestRejected):
                engine.submit(ServeRequest(
                    input_ids=[3] * 40,
                    gen=GenerationConfig(max_new_tokens=4)))
        engine.shutdown()
        if rec is not None:
            rec.close()
        tokens[mode] = summary["tokens"]
        if mode == "off":
            # OFF is structurally free: no recorder, no listener, no
            # builder dict entries ever created
            assert engine._reqtrace is None
            assert engine._rt == {}
            assert engine.slots.alloc_listener is None
    assert None not in tokens["on"]             # nothing shed
    assert tokens["on"] == tokens["off"]        # THE parity pin

    records = load_records(str(tmp_path))
    completed = [r for r in records if r["outcome"] == "completed"]
    assert len(completed) == 6                  # every request completed
    shed = [r for r in records if r["outcome"] == "shed"]
    assert [r["reason"] for r in shed] == ["rejected"]
    assert len(shed[0]["trace_id"]) == 32       # shed requests traced too
    for r in completed:
        names = [s["name"] for s in r["spans"]]
        assert names[0] == "queue_wait" and names[1] == "admission"
        assert "prefill_chunk" in names and "first_token" in names
        assert len(r["trace_id"]) == 32 and len(r["span_id"]) == 16
        assert r["tenant"] in ("free", "paid")
        # queue-wait span == the retroactive queue_wait_s measurement
        qspan = next(s for s in r["spans"] if s["name"] == "queue_wait")
        assert qspan["dur"] == pytest.approx(r["queue_wait_s"], abs=1e-5)
        # a request's own chunks can't exceed its TTFT, TTFT its wall
        assert r["prefill_s"] <= r["ttft_s"] + 1e-6
        assert r["ttft_s"] <= r["wall_s"] + 1e-6
        # timed child spans sum within the request wall
        assert sum(s.get("dur", 0.0) for s in r["spans"]) \
            <= r["wall_s"] + 1e-6
        # chunk offsets advance monotonically to the bucket
        chunks = [s for s in r["spans"] if s["name"] == "prefill_chunk"]
        offs = [c["offset"] for c in chunks]
        assert offs == sorted(offs)
        assert sum(c["tokens"] for c in chunks) == r["bucket"]
        d = r.get("decode")
        if d:                                   # ticks are contiguous
            assert d["ticks"] == d["last_tick"] - d["first_tick"] + 1
            assert sum(d["shared_with"].values()) == d["ticks"]
        assert r.get("pages_reserved", 0) >= r.get("pages_allocated", 0)


def test_note_abandoned_live_and_late(setup, tmp_path):
    cfg, params = setup
    rec = RequestTraceRecorder(str(tmp_path))
    engine = chunked_engine(cfg, params, reqtrace=rec)
    try:
        r = ServeRequest(input_ids=[4, 5, 6], tenant="free",
                         gen=GenerationConfig(max_new_tokens=4))
        h = engine.submit(r)
        engine.step()                          # admitted: builder is live
        engine.note_abandoned(r)               # disconnect mid-stream
        engine.drain(timeout_s=120)
        # cancelled at the next step boundary: the stream ends early and
        # the slot/pages were reclaimed instead of decoding for nobody
        assert len(h.result(timeout=1)) < 4

        done = ServeRequest(input_ids=[4, 5], tenant="paid",
                            gen=GenerationConfig(max_new_tokens=1))
        h2 = engine.submit(done)
        engine.drain(timeout_s=120)
        h2.result(timeout=1)
        engine.note_abandoned(done)            # disconnect AFTER completion
        snap = engine.stats.snapshot()
        assert snap["requests_abandoned"] == 2
        assert snap["tenants"]["free"]["requests_abandoned"] == 1
        assert snap["tenants"]["paid"]["requests_abandoned"] == 1
    finally:
        engine.shutdown()
        rec.close()
    records = load_records(str(tmp_path))
    live = next(x for x in records if x["request_id"] == r.request_id)
    assert live["outcome"] == "abandoned" and live["abandoned"] is True
    assert live["tokens_discarded"] == live["tokens"]
    assert any(s["name"] == "abandoned" for s in live["spans"])
    late = [x for x in records if x["request_id"] == done.request_id]
    assert [x["outcome"] for x in late] == ["completed", "abandoned"]
    assert late[1]["event"] == "late_disconnect"
    assert late[1]["trace_id"] == late[0]["trace_id"]


# -- THE e2e acceptance -------------------------------------------------------


def test_slow_chunked_request_is_p99_exemplar_with_capture(setup, tmp_path,
                                                           capsys):
    """Mixed-tenant run with one deliberately slow long-prompt chunked
    request B: B's waterfall attributes its TTFT to prefill chunks, the
    report names B the slowest-TTFT exemplar with per-tenant tables, and
    the SLO-breach capture meta carries B's trace id."""
    from llama_pipeline_parallel_tpu.serve.telemetry import SLOThresholds
    from llama_pipeline_parallel_tpu.utils.profiler import (
        CaptureConfig,
        TriggeredProfiler,
    )

    cfg, params = setup
    rs = np.random.RandomState(5)
    short = rs.randint(3, cfg.vocab_size, (5,)).tolist()
    long_p = rs.randint(3, cfg.vocab_size, (20,)).tolist()
    # warm both program shapes on a throwaway engine so compile time
    # skews neither TTFT (it would otherwise dwarf the chunk phases and
    # hand the warming request both the capture and the p99)
    warm = chunked_engine(cfg, params)
    for prompt in (short, long_p):
        warm.submit(ServeRequest(input_ids=prompt,
                                 gen=GenerationConfig(max_new_tokens=2)))
    warm.drain(timeout_s=300)
    warm.shutdown()

    rec = RequestTraceRecorder(str(tmp_path), exemplar_k=4)
    prof = TriggeredProfiler(
        CaptureConfig(zscore=0.0, window_steps=2, max_captures=1),
        str(tmp_path))
    engine = chunked_engine(cfg, params, reqtrace=rec, profiler=prof,
                            slo=SLOThresholds(ttft_s=0.0))
    try:
        ga = GenerationConfig(max_new_tokens=20)
        a = engine.submit(ServeRequest(input_ids=short, gen=ga, seed=1,
                                       tenant="paid"))
        engine.step()                      # A's one-shot prefill: TTFT ~1 tick
        gb = GenerationConfig(max_new_tokens=2)
        b_req = ServeRequest(input_ids=long_p, gen=gb, seed=2, tenant="free")
        b = engine.submit(b_req)           # 4 chunks behind A's live decode
        engine.drain(timeout_s=300)
        # parity under tracing ON: B bit-matches its generate() reference
        assert b.result(timeout=1) == reference_tokens(
            params, cfg, long_p, gb, 2, bucket=32)
        a.result(timeout=1)
    finally:
        engine.shutdown()
        rec.close()

    records = load_records(str(tmp_path))
    by_id = {x["request_id"]: x for x in records}
    rb = by_id[b_req.request_id]
    # B finished first (budget 2 vs A's 20), so the single capture is B's
    assert rb["slo_breach"] == ["ttft"]
    assert rb["capture"]
    with open(os.path.join(rb["capture"], "capture_meta.json")) as f:
        meta = json.load(f)
    assert meta["trace_id"] == rb["trace_id"] == b_req.trace.trace_id
    assert meta["tenant"] == "free"
    assert meta["request_id"] == b_req.request_id
    # the waterfall attributes B's TTFT to its 4 interleaved chunks, not
    # queue wait (B was admitted immediately)
    assert len([s for s in rb["spans"] if s["name"] == "prefill_chunk"]) == 4
    bd = request_report.ttft_breakdown(rb)
    assert bd["prefill_pct"] + bd["interleave_pct"] > bd["queue_pct"]

    rep = request_report.build_report(str(tmp_path))
    assert rep["p99_exemplar"]["request_id"] == b_req.request_id
    assert set(rep["tenants"]) == {"paid", "free"}
    assert rep["exemplars"]["ttft"][0] == b_req.request_id
    assert request_report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert b_req.request_id in out and "per-tenant" in out
    assert "prefill-behind-chunked-neighbor" in out

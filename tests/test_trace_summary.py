"""tools/trace_summary.py: the offline per-op breakdown for profiler traces
(the first thing run after a live-chip BENCH_PROFILE capture)."""

import jax
import jax.numpy as jnp
import pytest

import trace_summary  # importable via conftest's tools/ path insert


@pytest.fixture(scope="module")
def trace_dir(tmp_path_factory, devices):
    d = str(tmp_path_factory.mktemp("trace"))
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((64, 64))
    float(f(x))  # compile outside the capture
    jax.profiler.start_trace(d)
    for _ in range(3):
        float(f(x))
    jax.profiler.stop_trace()
    return d


def test_summarize_finds_the_jit_ops(trace_dir, capsys):
    # --top large enough to list every event: the assertion is about the
    # jitted computation APPEARING, not about its rank (which varies with
    # process warm-up noise in the host-side events)
    trace_summary.main([trace_dir, "--top", "100"])
    out = capsys.readouterr().out
    assert "ms total" in out
    assert "%" in out
    # the jitted computation must appear on some track
    assert "PjitFunction" in out or "dot_general" in out


def test_track_filter_and_missing_dir(trace_dir):
    path, trace = trace_summary.load_latest_trace(trace_dir)
    assert path.endswith(".trace.json.gz")
    totals, op_dur, _ = trace_summary.summarize(trace, track_filter="cpu")
    assert totals and all("cpu" in t.lower() for t in totals)
    totals_none, _, _ = trace_summary.summarize(trace, track_filter="tpu-v9")
    assert not totals_none
    with pytest.raises(FileNotFoundError, match="trace.json.gz"):
        trace_summary.load_latest_trace(trace_dir + "-missing")

"""tools/trace_summary.py: the offline per-op breakdown for profiler traces
(the first thing run after a live-chip BENCH_PROFILE capture)."""

import jax
import jax.numpy as jnp
import pytest

import trace_summary  # importable via conftest's tools/ path insert


@pytest.fixture(scope="module")
def trace_dir(tmp_path_factory, devices):
    d = str(tmp_path_factory.mktemp("trace"))
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((64, 64))
    float(f(x))  # compile outside the capture
    jax.profiler.start_trace(d)
    for _ in range(3):
        float(f(x))
    jax.profiler.stop_trace()
    return d


def test_summarize_finds_the_jit_ops(trace_dir, capsys):
    # --top large enough to list every event: the assertion is about the
    # jitted computation APPEARING, not about its rank (which varies with
    # process warm-up noise in the host-side events)
    trace_summary.main([trace_dir, "--top", "100"])
    out = capsys.readouterr().out
    assert "ms total" in out
    assert "%" in out
    # the jitted computation must appear on some track
    assert "PjitFunction" in out or "dot_general" in out


def test_track_filter_and_missing_dir(trace_dir):
    path, trace = trace_summary.load_latest_trace(trace_dir)
    assert path.endswith(".trace.json.gz")
    totals, op_dur, _ = trace_summary.summarize(trace, track_filter="cpu")
    assert totals and all("cpu" in t.lower() for t in totals)
    totals_none, _, _ = trace_summary.summarize(trace, track_filter="tpu-v9")
    assert not totals_none
    with pytest.raises(FileNotFoundError, match="trace.json.gz"):
        trace_summary.load_latest_trace(trace_dir + "-missing")


def _fake_trace() -> dict:
    return {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 1, "name": "fusion.1", "dur": 120.0},
        {"ph": "X", "pid": 1, "name": "fusion.1", "dur": 80.0},
    ]}


def test_uncompressed_trace_json_accepted(tmp_path, capsys):
    """Hand-saved / exporter-written *.trace.json (no gzip) loads and
    summarizes exactly like the gzipped capture."""
    import json as _json

    p = tmp_path / "plugins" / "profile" / "run1"
    p.mkdir(parents=True)
    (p / "host.trace.json").write_text(_json.dumps(_fake_trace()))
    path, trace = trace_summary.load_latest_trace(str(tmp_path))
    assert path.endswith("host.trace.json")
    totals, op_dur, op_count = trace_summary.summarize(trace)
    assert totals == {"/host:CPU": 200.0}
    assert op_count["/host:CPU"]["fusion.1"] == 2
    trace_summary.main([str(tmp_path)])
    out = capsys.readouterr().out
    assert "fusion.1" in out and "ms total" in out


def test_empty_dir_is_a_readable_message(tmp_path):
    """An empty/partial trace dir exits with a verdict, not a traceback."""
    with pytest.raises(SystemExit) as ei:
        trace_summary.main([str(tmp_path)])
    assert "trace.json" in str(ei.value)


def test_partial_capture_is_a_readable_message(tmp_path):
    """A torn capture (killed mid-profile-window) exits with a pointer to
    the bad file instead of a JSONDecodeError traceback."""
    p = tmp_path / "plugins" / "profile" / "run1"
    p.mkdir(parents=True)
    (p / "torn.trace.json").write_text('{"traceEvents": [{"ph": "X", "du')
    with pytest.raises(SystemExit) as ei:
        trace_summary.load_latest_trace(str(tmp_path))
    assert "torn.trace.json" in str(ei.value)
    assert "partial capture" in str(ei.value)

"""Checkpoint round-trips, topology-change restore, resume detection, converter."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager, find_resume_checkpoint
from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel import train_step as ts
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh


def tree_equal(a, b, atol=0.0):
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), atol=atol), a, b)


@pytest.fixture()
def cfg():
    return LlamaConfig.tiny()


def _trained_state(cfg, pp, dp, steps=2):
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshConfig(pp=pp, dp=dp))
    manifest = StageManifest.for_config(cfg, pp)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=pp, num_microbatches=2)
    tx, sched = make_optimizer(OptimizerConfig(learning_rate=1e-3, total_steps=50,
                                               warmup_steps=5))
    state = ts.init_train_state(stacked, tx, mesh)
    step = ts.make_train_step(mesh, cfg, pcfg, tx, sched, stacked)
    rng = np.random.RandomState(0)
    B = dp * 2 * 2
    ids = rng.randint(3, cfg.vocab_size, size=(B, 16)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids),
             "attention_mask": jnp.ones((B, 16), jnp.int32),
             "position_ids": jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (B, 16)),
             "labels": jnp.asarray(ids)}
    for _ in range(steps):
        state, _ = step(state, batch)
    return state, manifest, tx


@pytest.mark.slow
def test_full_roundtrip_same_topology(tmp_path, cfg, devices):
    state, manifest, tx = _trained_state(cfg, pp=2, dp=2)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(2, state.params, manifest, cfg, opt_state=state.opt_state)

    params2, opt2, step = mgr.load(2, state.params, state.opt_state, manifest)
    assert step == 2
    tree_equal(params2, state.params)
    tree_equal(opt2, state.opt_state)


@pytest.mark.slow
def test_async_save_finalize_and_roundtrip(tmp_path, cfg, devices):
    """blocking=False: commit (meta/tag/on_complete) lands after finalize();
    back-to-back async saves serialize; the result round-trips bit-exactly."""
    state, manifest, tx = _trained_state(cfg, pp=2, dp=2)
    mgr = CheckpointManager(str(tmp_path))
    seen = []
    mgr.save(2, state.params, manifest, cfg, opt_state=state.opt_state,
             blocking=False, on_complete=seen.append)
    mgr.finalize()
    assert seen == [mgr.step_dir(2)]
    assert mgr.is_complete(2) and mgr.latest_step() == 2
    params2, opt2, step = mgr.load(2, state.params, state.opt_state, manifest)
    assert step == 2
    tree_equal(params2, state.params)
    tree_equal(opt2, state.opt_state)

    mgr.save(3, state.params, manifest, cfg, blocking=False)
    mgr.save(4, state.params, manifest, cfg, blocking=False)  # joins save(3)
    mgr.finalize()
    assert mgr.is_complete(3) and mgr.is_complete(4)
    assert mgr.latest_step() == 4


def test_prune_keeps_newest_and_ignores_incomplete(tmp_path, cfg, devices):
    """save_total_limit semantics: oldest COMPLETE checkpoints beyond the
    limit are deleted (incl. async path via keep_last=); incomplete dirs and
    the latest tag are untouched."""
    manifest = StageManifest.for_config(cfg, 1)
    stacked = pl.stack_stages(llama.init_params(jax.random.PRNGKey(0), cfg),
                              manifest)
    mgr = CheckpointManager(str(tmp_path))
    for s in (2, 3):
        mgr.save(s, stacked, manifest, cfg)
    os.makedirs(mgr.step_dir(0))  # incomplete (no meta.json): never pruned
    # complete checkpoint under a NON-canonical dirname (hand-copied style):
    # pruning must delete the actual directory, not a step_dir() respelling
    odd = str(tmp_path / "checkpoint-001")
    os.makedirs(odd)
    open(os.path.join(odd, "meta.json"), "w").write("{}")
    mgr.save(4, stacked, manifest, cfg, blocking=False, keep_last=2)
    mgr.finalize()
    assert mgr.list_steps(complete_only=True) == [3, 4]
    assert not os.path.isdir(odd)
    assert os.path.isdir(mgr.step_dir(0))
    assert mgr.latest_step() == 4


def test_async_save_surfaces_commit_failure(tmp_path, cfg, devices):
    """A background-commit failure must fail the run at finalize(), exactly
    as a blocking save would — not vanish into a daemon-thread traceback."""
    manifest = StageManifest.for_config(cfg, 1)
    stacked = pl.stack_stages(llama.init_params(jax.random.PRNGKey(0), cfg),
                              manifest)
    mgr = CheckpointManager(str(tmp_path))

    def boom(*a, **k):
        raise OSError("disk full")

    mgr._commit = boom
    mgr.save(2, stacked, manifest, cfg, blocking=False)
    with pytest.raises(RuntimeError, match="async checkpoint commit failed"):
        mgr.finalize()
    mgr.finalize()  # error is consumed; manager stays usable


@pytest.mark.slow
def test_topology_change_restore(tmp_path, cfg, devices):
    """Save at PP=2, restore at PP=4 — forbidden by the reference's filename
    arithmetic, enabled by the canonical layout + manifest design."""
    state, manifest2, tx = _trained_state(cfg, pp=2, dp=2)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(2, state.params, manifest2, cfg, opt_state=state.opt_state)

    manifest4 = StageManifest.for_config(cfg, 4)
    params4_tmpl = pl.stack_stages(pl.unstack_stages(state.params, manifest2), manifest4)
    mesh4 = make_mesh(MeshConfig(pp=4, dp=1))
    state4 = ts.init_train_state(params4_tmpl, tx, mesh4)
    params4, opt4, step = mgr.load(2, state4.params, state4.opt_state, manifest4)

    # canonical views must agree exactly
    tree_equal(pl.unstack_stages(params4, manifest4),
               pl.unstack_stages(state.params, manifest2))
    assert np.asarray(params4["layers"]["attn"]["wq"]).shape[:2] == (4, 1)


@pytest.mark.slow
def test_module_only_warm_start_from_full_ckpt(tmp_path, cfg, devices):
    state, manifest, tx = _trained_state(cfg, pp=2, dp=2)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(2, state.params, manifest, cfg, opt_state=state.opt_state)
    params = mgr.load_params(2, state.params, manifest)
    tree_equal(params, state.params)


@pytest.mark.slow
def test_params_only_ckpt_refuses_full_resume(tmp_path, cfg, devices):
    state, manifest, tx = _trained_state(cfg, pp=2, dp=1, steps=1)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(0, state.params, manifest, cfg, opt_state=None)
    with pytest.raises(ValueError, match="no optimizer state"):
        mgr.load(0, state.params, state.opt_state, manifest)
    # but warm start works
    params = mgr.load_params(0, state.params, manifest)
    tree_equal(params, state.params)


@pytest.mark.slow
def test_latest_tag_and_resume_detection(tmp_path, cfg, devices):
    assert find_resume_checkpoint(str(tmp_path / "nope")) is None
    state, manifest, tx = _trained_state(cfg, pp=2, dp=1, steps=1)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, state.params, manifest, cfg)
    mgr.save(5, state.params, manifest, cfg)
    step, path = find_resume_checkpoint(str(tmp_path))
    assert step == 5 and path.endswith("checkpoint-5")
    # corrupt the tag -> directory-scan fallback
    with open(tmp_path / "latest", "w") as f:
        f.write("checkpoint-999")
    assert find_resume_checkpoint(str(tmp_path))[0] == 5


def test_resume_edge_cases_tag_meta_and_quarantine(tmp_path, cfg, devices):
    """Resume-path edge cases (docs/RESILIENCE.md): a corrupt/stale `latest`
    tag falls back to the directory scan; a checkpoint-N dir with no
    meta.json is invisible to every reader; find_resume_checkpoint skips a
    quarantined checkpoint."""
    manifest = StageManifest.for_config(cfg, 1)
    stacked = pl.stack_stages(llama.init_params(jax.random.PRNGKey(0), cfg),
                              manifest)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(2, stacked, manifest, cfg)
    mgr.save(5, stacked, manifest, cfg)

    # tag holding garbage (not even a checkpoint-N name)
    with open(tmp_path / "latest", "w") as f:
        f.write("!!torn write garbage")
    assert mgr.latest_step() == 5

    # tag pointing at a checkpoint that never completed (dir, no meta.json)
    os.makedirs(mgr.step_dir(9))
    with open(tmp_path / "latest", "w") as f:
        f.write("checkpoint-9")
    assert mgr.latest_step() == 5
    assert mgr.list_steps(complete_only=True) == [2, 5]
    assert not mgr.is_complete(9)

    # quarantined newest checkpoint: resume detection falls back past it
    os.rename(mgr.step_dir(5), mgr.step_dir(5) + ".corrupt")
    with open(tmp_path / "latest", "w") as f:
        f.write("checkpoint-5")
    step, path = find_resume_checkpoint(str(tmp_path))
    assert step == 2 and path.endswith("checkpoint-2")


@pytest.mark.slow
def test_hf_export_round_trip(tmp_path, cfg, devices):
    """native ckpt -> HF (tools/export_hf) -> logits parity with our forward."""
    torch = pytest.importorskip("torch")
    state, manifest, tx = _trained_state(cfg, pp=2, dp=1, steps=1)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, state.params, manifest, cfg)

    from tools.export_hf import export
    out = str(tmp_path / "hf")
    export(str(tmp_path), out)

    from transformers import LlamaForCausalLM
    hf_model = LlamaForCausalLM.from_pretrained(out).eval()
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, size=(1, 12))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = np.asarray(llama.forward(
        pl.unstack_stages(jax.device_get(state.params), manifest),
        jnp.asarray(ids), cfg=cfg))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_hf_converter_end_to_end(tmp_path, devices):
    """convert2ckpt.py equivalent: HF model -> native ckpt -> logits parity."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM

    hf_dir = str(tmp_path / "hf")
    hf_cfg = HFLlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2,
        max_position_embeddings=64, attn_implementation="eager",
        tie_word_embeddings=False)
    torch.manual_seed(0)
    hf_model = LlamaForCausalLM(hf_cfg).eval()
    hf_model.save_pretrained(hf_dir)

    from tools.convert_hf import convert
    out_dir = str(tmp_path / "native")
    convert(hf_dir, out_dir, expand_vocab=False)

    # load it back through the normal warm-start path, at PP=2
    cfg = LlamaConfig.from_hf_config(hf_cfg, dtype=jnp.float32)
    manifest = StageManifest.for_config(cfg, 2)
    template = pl.stack_stages(llama.init_params(jax.random.PRNGKey(1), cfg), manifest)
    mgr = CheckpointManager(out_dir)
    assert mgr.latest_step() == 0
    params = pl.unstack_stages(mgr.load_params(0, template, manifest), manifest)

    ids = np.random.RandomState(0).randint(0, 128, size=(1, 10))
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(ids)).logits.numpy()
    ours = np.asarray(llama.forward(params, jnp.asarray(ids), cfg=cfg))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)

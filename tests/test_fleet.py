"""Fleet observatory (utils/fleet.py + tools/fleetd.py +
tools/fleet_report.py — docs/OBSERVABILITY.md "Fleet").

Fast lanes: the registry contract, the incremental tailer's read-bytes
bound (no full-file re-reads — the aggregator scales with bytes WRITTEN,
not bytes accumulated), alert firing/resolved edges + the cross-process
capture trigger, atomic fleet_status.json, the live HTTP endpoint, the
supervisor's own heartbeat + registration, and the offline report's
degrade grid. The kill-a-replica chaos e2e lives in test_fleet_e2e.py."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from llama_pipeline_parallel_tpu.utils import fleet
from llama_pipeline_parallel_tpu.utils.fleet import (
    AlertRules,
    FileWatcher,
    FleetAggregator,
    JsonlTailer,
    latest_verified_step,
    load_registry,
    read_alerts,
    register_member,
)


def write_lines(path, rows, mode="a"):
    with open(path, mode) as f:
        for row in rows:
            f.write((row if isinstance(row, str) else json.dumps(row)) + "\n")


def make_member(fleet_root, out_root, name, role=None, health=None,
                metrics=(), incarnations=(), reg_ts=None,
                health_file="health.json"):
    """One fake fleet member: a registry row + its run-dir artifacts."""
    out = os.path.join(str(out_root), name)
    os.makedirs(out, exist_ok=True)
    row = {"ts": reg_ts if reg_ts is not None else time.time(), "role": role,
           "replica": name, "output_dir": os.path.abspath(out), "pid": 1234,
           "incarnation": 0, "health_file": health_file}
    write_lines(os.path.join(str(fleet_root), fleet.REGISTRY_NAME), [row])
    if health is not None:
        with open(os.path.join(out, health_file), "w") as f:
            json.dump(health, f)
    if metrics:
        write_lines(os.path.join(out, "metrics.jsonl"), list(metrics))
    if incarnations:
        write_lines(os.path.join(out, "incarnations.jsonl"),
                    list(incarnations))
    return out


def write_ckpt(out, step, complete=True):
    d = os.path.join(out, f"checkpoint-{step}")
    os.makedirs(d, exist_ok=True)
    if complete:
        with open(os.path.join(d, "meta.json"), "w") as f:
            json.dump({"step": step}, f)


# ---------------------------------------------------------------------------
# registry contract
# ---------------------------------------------------------------------------

def test_register_member_appends_and_loads(tmp_path):
    row = register_member(str(tmp_path), output_dir=str(tmp_path / "a"),
                          role="serve", pid=42, incarnation=1)
    assert row["replica"] == "a" and row["health_file"] == "health.json"
    register_member(str(tmp_path), output_dir=str(tmp_path / "a"),
                    role="serve", pid=43, incarnation=2, layout="dp1")
    # a torn tail degrades, never tracebacks
    with open(tmp_path / fleet.REGISTRY_NAME, "a") as f:
        f.write('{"output_dir": "/torn')
    rows = load_registry(str(tmp_path))
    assert len(rows) == 2
    assert rows[1]["pid"] == 43 and rows[1]["layout"] == "dp1"


def test_latest_verified_step_requires_meta(tmp_path):
    out = str(tmp_path)
    assert latest_verified_step(out) is None
    write_ckpt(out, 2)
    write_ckpt(out, 6, complete=False)  # arrays landed, no meta commit yet
    assert latest_verified_step(out) == 2
    write_ckpt(out, 6)
    assert latest_verified_step(out) == 6
    assert latest_verified_step(str(tmp_path / "missing")) is None


# ---------------------------------------------------------------------------
# incremental readers: the read-bytes bound
# ---------------------------------------------------------------------------

def test_tailer_reads_each_byte_exactly_once(tmp_path):
    """THE incremental contract: across any number of polls, the tailer
    reads exactly the bytes ever written — never the file again from the
    start. This is what keeps a fleetd refresh O(new data) while
    metrics.jsonl grows without bound."""
    path = str(tmp_path / "m.jsonl")
    t = JsonlTailer(path)
    assert t.poll() == []                       # missing file: no read
    write_lines(path, [{"step": i} for i in range(50)])
    size1 = os.path.getsize(path)
    assert [r["step"] for r in t.poll()] == list(range(50))
    assert t.bytes_read == size1
    assert t.poll() == [] and t.bytes_read == size1   # idle poll: 0 bytes
    write_lines(path, [{"step": 50}])
    size2 = os.path.getsize(path)
    assert [r["step"] for r in t.poll()] == [50]
    # the bound the ISSUE pins: total bytes read == total bytes written
    assert t.bytes_read == size2


def test_tailer_carries_torn_tail_until_completed(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with open(path, "w") as f:
        f.write('{"a": 1}\n{"b": 2')           # writer mid-append
    t = JsonlTailer(path)
    assert t.poll() == [{"a": 1}]              # the tear is carried, not lost
    with open(path, "a") as f:
        f.write('2}\n')                        # writer finishes the line
    assert t.poll() == [{"b": 22}]
    # garbage lines skip without losing later rows (read_jsonl semantics)
    write_lines(path, ["not json", '{"c": 3}'])
    assert t.poll() == [{"c": 3}]


def test_tailer_resets_on_truncation(tmp_path):
    path = str(tmp_path / "m.jsonl")
    write_lines(path, [{"a": 1}, {"a": 2}])
    t = JsonlTailer(path)
    assert len(t.poll()) == 2
    write_lines(path, [{"b": 1}], mode="w")    # rotated/truncated under us
    assert t.poll() == [{"b": 1}]


def test_filewatcher_rereads_only_on_change(tmp_path):
    path = str(tmp_path / "health.json")
    w = FileWatcher(path)
    assert w.poll() is None and w.status == "missing"
    with open(path, "w") as f:
        json.dump({"time": 1.0}, f)
    assert w.poll() == {"time": 1.0} and w.status == "ok"
    n = w.bytes_read
    assert w.poll() == {"time": 1.0}
    assert w.bytes_read == n                   # unchanged stat: zero reads
    # a torn rewrite keeps the last good value, flags corrupt
    with open(path, "w") as f:
        f.write('{"time": 2')
    assert w.poll() == {"time": 1.0} and w.status == "corrupt"


# ---------------------------------------------------------------------------
# alert rules
# ---------------------------------------------------------------------------

def test_alert_rules_reject_unknown_keys():
    with pytest.raises(ValueError, match="unknown alerts"):
        AlertRules.from_cfg({"heartbeat_stale": 3})
    with pytest.raises(ValueError, match="mapping"):
        AlertRules.from_cfg(7)
    rules = AlertRules.from_cfg({"heartbeat_stale_s": 30,
                                 "checkpoint_lag_steps": 4})
    assert rules.heartbeat_stale_s == 30.0
    assert rules.checkpoint_lag_steps == 4
    assert rules.ttft_p95_ms is None
    assert AlertRules.from_cfg(None) == AlertRules()


def test_alert_rules_evaluate_role_and_absence():
    rules = AlertRules(heartbeat_stale_s=10, goodput_floor=0.5,
                       ttft_p95_ms=200, checkpoint_lag_steps=2,
                       nonfinite_steps=0, step_time_p95_s=1.0)
    # a rule whose input is absent is NOT evaluated (no fabricated edges)
    out = rules.evaluate({"role": "serve", "heartbeat_age_s": 3})
    assert out == [("heartbeat_stale", 3.0, 10.0, False)]
    fired = dict((r[0], r[3]) for r in rules.evaluate(
        {"role": "serve", "heartbeat_age_s": 30, "goodput": 0.2,
         "ttft_p95_ms": 500, "checkpoint_lag": 5}))
    assert fired == {"heartbeat_stale": True, "goodput_floor": True,
                     "ttft_p95": True, "checkpoint_lag": True}
    trainer = dict((r[0], r[3]) for r in rules.evaluate(
        {"role": "trainer", "heartbeat_age_s": 1, "goodput": 0.9,
         "step_time_p95": 2.0, "nonfinite_steps": 1}))
    assert trainer == {"heartbeat_stale": False, "goodput_floor": False,
                       "step_time_p95": True, "nonfinite_steps": True}
    # the supervisor's goodput (it has none) is never judged
    assert rules.evaluate({"role": "supervisor", "heartbeat_age_s": 1,
                           "goodput": None}) == \
        [("heartbeat_stale", 1.0, 10.0, False)]


def test_alert_rules_per_tenant_ttft_fanout():
    """One configured `tenant_ttft_p95_ms` threshold fans out to a rule
    INSTANCE per tenant in the member's serving snapshot
    (`tenant_ttft_p95:<tenant>`), all sharing the base rule's threshold
    and damping — the ':' suffix is instance identity, not config."""
    rules = AlertRules(tenant_ttft_p95_ms=100.0,
                       damping={"tenant_ttft_p95": (5.0, 10.0)})
    out = rules.evaluate({"role": "serve", "tenants": {
        "free": {"ttft_p95_ms": 250.0},
        "paid": {"ttft_p95_ms": 40.0},
        "torn": "not a snapshot",          # tolerated, not evaluated
        "silent": {"requests_completed": 3}}})   # no ttft yet: absent
    assert dict((r[0], r[3]) for r in out) == \
        {"tenant_ttft_p95:free": True, "tenant_ttft_p95:paid": False}
    assert all(r[2] == 100.0 for r in out)
    assert rules.damping_for("tenant_ttft_p95:free") == (5.0, 10.0)
    # no threshold configured -> the tenants map is never judged
    assert AlertRules().evaluate(
        {"role": "serve", "tenants": {"free": {"ttft_p95_ms": 9e9}}}) == []


# ---------------------------------------------------------------------------
# the aggregator
# ---------------------------------------------------------------------------

def make_fleet(tmp_path, trainer_step_time=0.1):
    """One trainer (2 checkpoints, metrics, incarnations) + one serve
    replica (serving metrics, checkpoint_step) + its supervisor member."""
    root = str(tmp_path / "fleet")
    os.makedirs(root, exist_ok=True)
    now = time.time()
    trainer = make_member(
        root, tmp_path, "trainer0",
        health={"time": now, "last_step": 8, "goodput": 0.9,
                "clock": {"elapsed": 100.0},
                "topology": {"layout": "pp2dp2"}},
        metrics=[{"step": s, "loss": 2.0, "step_time": trainer_step_time,
                  "bubble_fraction": 0.05,
                  "bubble_fraction_measured": 0.07,
                  "nonfinite_steps": 0, "anomaly_count": 1}
                 for s in range(1, 9)],
        incarnations=[{"incarnation": 0, "outcome": "crash",
                       "duration_s": 5.0, "start": now - 60, "end": now - 55},
                      {"incarnation": 1, "outcome": None, "start": now - 50}])
    write_ckpt(trainer, 4)
    write_ckpt(trainer, 8)
    serve = make_member(
        root, tmp_path, "serve0", role="serve",
        health={"time": now, "last_step": 30, "goodput": 0.6, "role": "serve",
                "checkpoint_step": 4, "clock": {"elapsed": 50.0}},
        metrics=[{"step": 16, "serving": 1, "requests_completed": 16,
                  "ttft_p95_ms": 120.0, "tpot_p50_ms": 30.0,
                  "queue_wait_p95_ms": 15.0, "slo_breaches": 2,
                  "requests_page_refused": 3, "pages_used": 5,
                  "pages_free": 11, "prefill_chunks_total": 7,
                  "prefill_tokens_total": 448}],
        incarnations=[{"incarnation": 0, "outcome": "crash",
                       "duration_s": 3.0, "start": now - 40,
                       "end": now - 37}])
    make_member(root, tmp_path, "serve0", role="supervisor",
                health={"time": now, "role": "supervisor", "restarts": 1,
                        "consecutive_failures": 0, "child_pid": 777},
                health_file="supervisor_health.json")
    return root, trainer, serve


def test_aggregator_composes_fleet_status(tmp_path):
    root, trainer_dir, serve_dir = make_fleet(tmp_path)
    agg = FleetAggregator(root)
    status = agg.refresh()

    assert set(status["members"]) == {"trainer:trainer0", "serve:serve0",
                                      "supervisor:serve0"}
    tr = status["members"]["trainer:trainer0"]
    assert tr["last_step"] == 8 and tr["goodput"] == 0.9
    assert tr["latest_verified_step"] == 8
    assert tr["step_time_p50"] == pytest.approx(0.1)
    assert tr["bubble_fraction_analytic"] == 0.05
    assert tr["bubble_fraction_measured"] == 0.07
    assert tr["anomaly_count"] == 1 and tr["nonfinite_steps"] == 0
    assert tr["incarnations"] == 2 and tr["restarts"] == 1
    assert tr["failed_incarnations"] == 1
    assert tr["heartbeat_age_s"] < 5

    sv = status["members"]["serve:serve0"]
    assert sv["checkpoint_step"] == 4
    assert sv["checkpoint_lag"] == 4          # trainer verified 8, loaded 4
    assert sv["ttft_p95_ms"] == 120.0 and sv["slo_breaches"] == 2
    assert sv["requests_page_refused"] == 3 and sv["pages_free"] == 11
    assert sv["prefill_chunks_total"] == 7

    sup = status["members"]["supervisor:serve0"]
    assert sup["role"] == "supervisor" and sup["restarts"] == 1
    assert sup["child_pid"] == 777
    # the watchdog shares its child's dir but must NOT mirror the child's
    # streams: no serve SLO fields re-attributed to it (a ttft rule would
    # otherwise fire twice), no ledger rows double-counted
    assert "ttft_p95_ms" not in sup and "slo_breaches" not in sup
    assert "incarnations" not in sup

    pod = status["pod"]
    assert pod["trainer_step"] == 8 and pod["members"] == 3
    # elapsed-weighted: (0.9*100 + 0.6*50) / 150
    assert pod["goodput"] == pytest.approx(0.8)
    assert pod["alerts_firing"] == []

    # the status file landed atomically and parses
    with open(os.path.join(root, fleet.STATUS_NAME)) as f:
        on_disk = json.load(f)
    assert on_disk["refresh_count"] == 1
    assert on_disk["members"]["serve:serve0"]["checkpoint_lag"] == 4


def test_aggregator_refreshes_are_incremental(tmp_path):
    """The acceptance bound: a refresh against an IDLE fleet reads zero
    stream bytes, and a refresh after appends reads only the appended
    bytes — pinned via the aggregator's own byte counter."""
    root, trainer_dir, _ = make_fleet(tmp_path)
    agg = FleetAggregator(root)
    agg.refresh()
    first = agg.bytes_read
    status = agg.refresh()
    assert status["bytes_read_last_refresh"] == 0   # idle: stats only
    appended = [{"step": 9, "loss": 1.9, "step_time": 0.2}]
    before = os.path.getsize(os.path.join(trainer_dir, "metrics.jsonl"))
    write_lines(os.path.join(trainer_dir, "metrics.jsonl"), appended)
    after = os.path.getsize(os.path.join(trainer_dir, "metrics.jsonl"))
    status = agg.refresh()
    assert status["bytes_read_last_refresh"] == after - before
    assert agg.bytes_read == first + (after - before)


def test_alert_edges_fire_resolve_and_drop_one_trigger(tmp_path):
    root = str(tmp_path / "fleet")
    os.makedirs(root)
    now = time.time()
    out = make_member(root, tmp_path, "serveA", role="serve",
                      health={"time": now - 100, "role": "serve"},
                      reg_ts=now - 100)
    rules = AlertRules(heartbeat_stale_s=30.0)
    agg = FleetAggregator(root, rules)

    status = agg.refresh()
    assert status["pod"]["alerts_firing"] == ["heartbeat_stale:serve:serveA"]
    trigger = os.path.join(out, fleet.CAPTURE_TRIGGER_NAME)
    assert os.path.exists(trigger)
    with open(trigger) as f:
        payload = json.load(f)
    assert payload["alert"] == "heartbeat_stale"

    # still firing: NO second edge, and an unconsumed trigger not re-dropped
    os_stat = os.stat(trigger).st_mtime_ns
    status = agg.refresh()
    assert status["alert_edges_last_refresh"] == []
    assert os.stat(trigger).st_mtime_ns == os_stat

    # the member comes back: resolved edge, exactly two edges on disk
    with open(os.path.join(out, "health.json"), "w") as f:
        json.dump({"time": time.time(), "role": "serve"}, f)
    status = agg.refresh()
    edges = read_alerts(root)
    assert [e["state"] for e in edges] == ["firing", "resolved"]
    assert edges[0]["member"] == "serve:serveA"
    assert status["pod"]["alerts_firing"] == []
    assert status["alerts"]["heartbeat_stale:serve:serveA"]["state"] == \
        "resolved"


def test_checkpoint_lag_alert_fires_and_resolves(tmp_path):
    root = str(tmp_path / "fleet")
    os.makedirs(root)
    now = time.time()
    trainer = make_member(root, tmp_path, "t0",
                          health={"time": now, "last_step": 10})
    write_ckpt(trainer, 10)
    serve = make_member(root, tmp_path, "s0", role="serve",
                        health={"time": now, "role": "serve",
                                "checkpoint_step": 2})
    agg = FleetAggregator(root, AlertRules(checkpoint_lag_steps=4))
    status = agg.refresh()
    assert status["members"]["serve:s0"]["checkpoint_lag"] == 8
    assert status["pod"]["alerts_firing"] == ["checkpoint_lag:serve:s0"]
    # the serve tier tails the newer verified checkpoint -> resolved
    with open(os.path.join(serve, "health.json"), "w") as f:
        json.dump({"time": time.time(), "role": "serve",
                   "checkpoint_step": 10}, f)
    agg.refresh()
    assert [e["state"] for e in read_alerts(root)] == ["firing", "resolved"]


def test_garbage_registry_row_skipped_not_fatal(tmp_path):
    """A parseable-but-wrong registry line (no output_dir) must degrade
    like a torn one — never a KeyError out of the daemon's refresh."""
    root = str(tmp_path / "fleet")
    os.makedirs(root)
    make_member(root, tmp_path, "ok", health={"time": time.time()})
    write_lines(os.path.join(root, fleet.REGISTRY_NAME),
                [{"note": "not a member"}, "plain garbage"])
    status = FleetAggregator(root).refresh()
    assert sorted(status["members"]) == ["trainer:ok"]


def test_replica_name_collision_keeps_alerts_distinct(tmp_path):
    """Two dirs with the same basename and no --replica label: member ids
    disambiguate ONCE (status map, alert rollup, and edge rows all agree),
    so one replica's resolution can never mask the other's firing."""
    root = str(tmp_path / "fleet")
    os.makedirs(root)
    old = time.time() - 100
    for sub in ("x", "y"):
        out = os.path.join(str(tmp_path), sub, "serve")
        os.makedirs(out)
        write_lines(os.path.join(root, fleet.REGISTRY_NAME),
                    [{"ts": old, "role": "serve", "replica": "serve",
                      "output_dir": out, "health_file": "health.json"}])
        with open(os.path.join(out, "health.json"), "w") as f:
            json.dump({"time": old, "role": "serve"}, f)
    agg = FleetAggregator(root, AlertRules(heartbeat_stale_s=30.0))
    status = agg.refresh()
    assert sorted(status["members"]) == ["serve:serve", "serve:serve+"]
    assert sorted(status["pod"]["alerts_firing"]) == [
        "heartbeat_stale:serve:serve", "heartbeat_stale:serve:serve+"]
    assert sorted(e["member"] for e in read_alerts(root)) == [
        "serve:serve", "serve:serve+"]


def test_registration_vouches_liveness_before_first_health(tmp_path):
    """A just-launched member with a STALE health.json from its previous
    incarnation must not be declared stale: the fresh registry row vouches
    for it, the supervisor's own staleness rule."""
    root = str(tmp_path / "fleet")
    os.makedirs(root)
    make_member(root, tmp_path, "m0",
                health={"time": time.time() - 1000},  # dead incarnation's
                reg_ts=time.time())                   # fresh relaunch
    agg = FleetAggregator(root, AlertRules(heartbeat_stale_s=30.0))
    status = agg.refresh()
    assert status["pod"]["alerts_firing"] == []
    assert status["members"]["trainer:m0"]["heartbeat_age_s"] < 5


# ---------------------------------------------------------------------------
# cross-process capture trigger (the profiler side)
# ---------------------------------------------------------------------------

def test_trigger_file_starts_exactly_one_capture(tmp_path):
    import glob

    from llama_pipeline_parallel_tpu.utils.profiler import (
        CaptureConfig,
        TriggeredProfiler,
    )

    out = str(tmp_path)
    prof = TriggeredProfiler(
        CaptureConfig(zscore=0.0, window_steps=1, trigger_poll_s=0.0), out)
    prof.observe_step(1, 0.01)
    assert prof.captures_taken == 0            # no trigger file: no capture
    fleet.write_json_atomic(os.path.join(out, fleet.CAPTURE_TRIGGER_NAME),
                            {"alert": "heartbeat_stale", "member": "x"})
    prof.observe_step(2, 0.01)
    assert prof.capturing and prof.captures_taken == 1
    assert not os.path.exists(
        os.path.join(out, fleet.CAPTURE_TRIGGER_NAME))  # consumed
    prof.observe_step(3, 0.01)                 # window closes
    prof.observe_step(4, 0.01)
    assert not prof.capturing and prof.captures_taken == 1  # exactly one
    dirs = glob.glob(os.path.join(out, "captures", "*"))
    assert len(dirs) == 1 and "fleet_heartbeat_stale" in dirs[0]
    prof.close()


def test_trigger_file_respects_retention_cap_and_garbage(tmp_path):
    from llama_pipeline_parallel_tpu.utils.profiler import (
        CaptureConfig,
        TriggeredProfiler,
    )

    out = str(tmp_path)
    prof = TriggeredProfiler(
        CaptureConfig(zscore=0.0, window_steps=1, max_captures=1,
                      trigger_poll_s=0.0), out)
    prof.captures_taken = 1                    # cap already reached
    path = os.path.join(out, fleet.CAPTURE_TRIGGER_NAME)
    with open(path, "w") as f:
        f.write("not json")                    # garbage trigger: still consumed
    prof.observe_step(1, 0.01)
    assert not prof.capturing and not os.path.exists(path)
    prof.close()


# ---------------------------------------------------------------------------
# the supervisor's registration + own heartbeat
# ---------------------------------------------------------------------------

def test_supervisor_registers_and_heartbeats(tmp_path):
    import sys

    import supervisor  # tools/ on sys.path via conftest

    out = str(tmp_path / "run")
    root = str(tmp_path / "fleet")
    sup = supervisor.Supervisor(
        [sys.executable, "-c", "pass"],
        supervisor.SupervisorConfig(output_dir=out, max_restarts=1,
                                    poll_s=0.05, fleet_root=root,
                                    role="serve", replica="r0"))
    assert sup.run() == 0
    rows = load_registry(root)
    # the supervisor member + incarnation 0's launch row
    roles = [(r["role"], r["health_file"]) for r in rows]
    assert (("supervisor", fleet.SUPERVISOR_HEALTH_NAME) in roles)
    launch = [r for r in rows if r["role"] == "serve"]
    assert len(launch) == 1 and launch[0]["incarnation"] == 0
    assert launch[0]["replica"] == "r0" and launch[0]["pid"]
    with open(os.path.join(out, fleet.SUPERVISOR_HEALTH_NAME)) as f:
        health = json.load(f)
    assert health["role"] == "supervisor"
    assert health["last_outcome"] == "clean"
    assert health["restarts"] == 0 and health["consecutive_failures"] == 0


def test_supervisor_heartbeat_without_fleet_root(tmp_path):
    """The watchdog heartbeat is unconditional (its staleness is fleet
    business, but labeling the dir is the goodput report's too)."""
    import sys

    import goodput_report
    import supervisor

    out = str(tmp_path)
    sup = supervisor.Supervisor(
        [sys.executable, "-c", "import sys; sys.exit(3)"],
        supervisor.SupervisorConfig(output_dir=out, max_restarts=0,
                                    poll_s=0.05, crash_loop_threshold=9))
    assert sup.run() == 2
    with open(os.path.join(out, fleet.SUPERVISOR_HEALTH_NAME)) as f:
        health = json.load(f)
    assert health["last_outcome"] == "crash"
    assert health["consecutive_failures"] == 1
    summary = goodput_report.supervisor_summary(out)
    assert summary["last_outcome"] == "crash"
    assert summary["consecutive_failures"] == 1


# ---------------------------------------------------------------------------
# fleetd: the live endpoint
# ---------------------------------------------------------------------------

def test_fleetd_http_endpoint(tmp_path):
    import fleetd  # tools/ on sys.path via conftest

    root, _, _ = make_fleet(tmp_path)
    agg = FleetAggregator(root)
    server = fleetd.make_server(agg)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()

    def get(path):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                return r.status, json.load(r)
        except urllib.error.HTTPError as e:
            return e.code, json.load(e)

    try:
        # before the first refresh /fleet is 503, /healthz still answers
        code, _ = get("/fleet")
        assert code == 503
        code, hz = get("/healthz")
        assert code == 200 and hz["refresh_count"] == 0
        agg.refresh()
        code, status = get("/fleet")
        assert code == 200
        assert status["members"]["serve:serve0"]["ttft_p95_ms"] == 120.0
        code, hz = get("/healthz")
        assert code == 200
        assert hz["members"] == 3 and hz["refresh_count"] == 1
        code, _ = get("/nope")
        assert code == 404
    finally:
        server.shutdown()


def test_fleetd_once_cli_and_bad_alerts(tmp_path, capsys):
    import fleetd

    root, _, _ = make_fleet(tmp_path)
    assert fleetd.main(["--fleet-root", root, "--once"]) == 0
    status = json.loads(capsys.readouterr().out)
    assert status["pod"]["trainer_step"] == 8
    with pytest.raises(SystemExit, match="bad --alerts"):
        fleetd.main(["--fleet-root", root, "--once",
                     "--alerts", '{"nope": 1}'])


# ---------------------------------------------------------------------------
# the offline report
# ---------------------------------------------------------------------------

def test_fleet_report_tables_and_degrade(tmp_path, capsys):
    import fleet_report

    root, trainer_dir, serve_dir = make_fleet(tmp_path)
    # an alert timeline for the report to draw
    agg = FleetAggregator(root, AlertRules(checkpoint_lag_steps=2))
    agg.refresh()
    rep = fleet_report.build_report(root)
    assert rep["registered_members"] == 3
    assert rep["checkpoint_lag"]["trainer_step"] == 8
    assert rep["checkpoint_lag"]["replicas"][0]["checkpoint_lag"] == 4
    # serve0's dir hosts BOTH the serve member and its supervisor member:
    # the shared ledger must appear once, labeled as the child
    timeline = [(e["member"], e["incarnation"])
                for e in rep["incarnation_timeline"]]
    assert timeline == [("trainer:trainer0", 0), ("trainer:trainer0", 1),
                        ("serve:serve0", 0)]
    assert rep["alert_timeline"][0]["alert"] == "checkpoint_lag"
    assert rep["slo_table"][0]["slo_breaches"] == 2
    assert fleet_report.main([root]) == 0
    out = capsys.readouterr().out
    assert "incarnation timeline" in out and "alert timeline" in out
    assert "checkpoint lag" in out and "slo_breaches=2" in out

    # empty/garbage fleet root degrades, never tracebacks
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert fleet_report.main([empty]) == 0
    assert "no members registered" in capsys.readouterr().out
    write_lines(os.path.join(empty, fleet.REGISTRY_NAME), ["garbage"])
    assert fleet_report.main([empty]) == 0


# ---------------------------------------------------------------------------
# report satellites (serving counters + role labeling)
# ---------------------------------------------------------------------------

def test_goodput_report_surfaces_serve_counters_and_role(tmp_path, capsys):
    import goodput_report

    out = str(tmp_path)
    now = time.time()
    write_lines(os.path.join(out, "spans.jsonl"),
                [{"name": "serve_decode_step", "ts": now, "dur": 1.0,
                  "end": now + 1.0, "depth": 0, "main_thread": True}])
    write_lines(os.path.join(out, "metrics.jsonl"),
                [{"step": 4, "serving": 1, "requests_completed": 4,
                  "slo_breaches": 1, "requests_page_refused": 2,
                  "requests_failed": 0, "prefill_chunks_total": 3,
                  "prefill_tokens_total": 192, "ttft_p95_ms": 99.0}])
    with open(os.path.join(out, "health.json"), "w") as f:
        json.dump({"time": now, "role": "serve", "goodput": 0.5}, f)
    rep = goodput_report.build_report(out)
    assert rep["role"] == "serve"
    assert rep["serve_counters"]["slo_breaches"] == 1
    assert rep["serve_counters"]["requests_page_refused"] == 2
    assert rep["serve_counters"]["prefill_tokens_total"] == 192
    goodput_report.print_report(rep)
    text = capsys.readouterr().out
    assert "role serve" in text
    assert "slo_breaches=1" in text and "requests_page_refused=2" in text


def test_serving_report_surfaces_breach_and_refusal_counters(tmp_path,
                                                             capsys):
    import serving_report

    write_lines(os.path.join(str(tmp_path), "metrics.jsonl"),
                [{"step": 8, "serving": 1, "requests_completed": 8,
                  "requests_failed": 1, "requests_page_refused": 5,
                  "slo_breaches": 3, "tokens_generated": 64,
                  "active_slots": 2, "kv_cache": "paged", "pages_used": 4,
                  "prefill_chunks_total": 2, "prefill_tokens_total": 128}])
    assert serving_report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "slo_breaches=3" in out and "requests_page_refused=5" in out
    assert "requests_failed=1" in out and "prefill_chunks_total=2" in out


# ---------------------------------------------------------------------------
# alert damping (for_s / cooldown_s) — independent of any actuator
# ---------------------------------------------------------------------------

def test_alert_damping_config_parse_and_reject():
    rules = AlertRules.from_cfg({
        "ttft_p95_ms": {"threshold": 500, "for_s": 10, "cooldown_s": 30},
        "heartbeat_stale_s": 30})
    assert rules.ttft_p95_ms == 500.0
    assert rules.damping_for("ttft_p95") == (10.0, 30.0)
    assert rules.damping_for("heartbeat_stale") == (0.0, 0.0)
    # scalar spelling == dict spelling with zero damping
    assert AlertRules.from_cfg({"ttft_p95_ms": 500}) == \
        AlertRules.from_cfg({"ttft_p95_ms": {"threshold": 500}})
    with pytest.raises(ValueError, match="unknown alerts.ttft_p95_ms"):
        AlertRules.from_cfg({"ttft_p95_ms": {"threshold": 500, "hold_s": 9}})
    with pytest.raises(ValueError, match="threshold"):
        AlertRules.from_cfg({"ttft_p95_ms": {"for_s": 10}})
    with pytest.raises(ValueError, match=">= 0"):
        AlertRules.from_cfg({"ttft_p95_ms": {"threshold": 5, "for_s": -1}})


def _eval_once(agg, value, now):
    """One damped-evaluator pass over a single synthetic serve member."""
    key = ("serve", "/runs/s0")
    member = {"role": "serve", "replica": "s0", "output_dir": "/runs/s0",
              "ttft_p95_ms": value}
    return agg._evaluate_alerts({key: member}, {key: "serve:s0"}, now,
                                write=False)


def test_alert_for_s_delays_the_rising_edge(tmp_path):
    root = str(tmp_path / "fleet")
    os.makedirs(root)
    rules = AlertRules.from_cfg({"ttft_p95_ms": {"threshold": 500,
                                                 "for_s": 10}})
    agg = FleetAggregator(root, rules)
    t0 = time.time()
    # breaching, but not sustained -> no edge yet
    alerts, edges = _eval_once(agg, 900, t0)
    assert edges == [] and alerts == {}
    alerts, edges = _eval_once(agg, 900, t0 + 5)
    assert edges == []
    # a dip resets the continuity clock
    _eval_once(agg, 100, t0 + 6)
    alerts, edges = _eval_once(agg, 900, t0 + 7)
    assert edges == []
    alerts, edges = _eval_once(agg, 900, t0 + 16)   # held 9s < 10s
    assert edges == []
    alerts, edges = _eval_once(agg, 900, t0 + 17.5)  # held 10.5s -> FIRES
    assert [e["state"] for e in edges] == ["firing"]
    assert alerts["ttft_p95:serve:s0"]["state"] == "firing"


def test_alert_cooldown_suppresses_the_refire(tmp_path):
    root = str(tmp_path / "fleet")
    os.makedirs(root)
    rules = AlertRules.from_cfg({"ttft_p95_ms": {"threshold": 500,
                                                 "cooldown_s": 30}})
    agg = FleetAggregator(root, rules)
    t0 = time.time()
    _, edges = _eval_once(agg, 900, t0)              # for_s=0: fires at once
    assert [e["state"] for e in edges] == ["firing"]
    _, edges = _eval_once(agg, 100, t0 + 1)          # resolves
    assert [e["state"] for e in edges] == ["resolved"]
    _, edges = _eval_once(agg, 900, t0 + 5)          # flap inside cooldown
    assert edges == []
    _, edges = _eval_once(agg, 900, t0 + 29)
    assert edges == []
    _, edges = _eval_once(agg, 900, t0 + 32)         # cooled -> re-fires
    assert [e["state"] for e in edges] == ["firing"]


def test_zero_damping_is_bit_identical_to_undamped(tmp_path):
    """{threshold: x} with no for_s/cooldown_s must produce the exact
    edge sequence the scalar spelling always did."""
    t0 = time.time()
    seqs = []
    for spec in (500, {"threshold": 500}):
        root = str(tmp_path / f"fleet-{len(seqs)}")
        os.makedirs(root)
        agg = FleetAggregator(root, AlertRules.from_cfg(
            {"ttft_p95_ms": spec}))
        seq = []
        for dt, val in ((0, 900), (1, 900), (2, 100), (3, 900)):
            _, edges = _eval_once(agg, val, t0 + dt)
            seq.extend((round(e["ts"] - t0, 3), e["state"]) for e in edges)
        seqs.append(seq)
    assert seqs[0] == seqs[1]
    assert [s for _, s in seqs[0]] == ["firing", "resolved", "firing"]


def test_queue_wait_p95_rule_fires(tmp_path):
    root = str(tmp_path / "fleet")
    os.makedirs(root)
    now = time.time()
    make_member(root, tmp_path, "s0", role="serve",
                health={"time": now, "role": "serve"},
                metrics=[{"step": 1, "serving": 1,
                          "queue_wait_p95_ms": 850.0}])
    agg = FleetAggregator(root, AlertRules.from_cfg(
        {"queue_wait_p95_ms": 500}))
    status = agg.refresh()
    assert status["members"]["serve:s0"]["queue_wait_p95_ms"] == 850.0
    assert status["pod"]["alerts_firing"] == ["queue_wait_p95:serve:s0"]


def test_terminal_registry_row_fires_stale_immediately(tmp_path):
    """A supervisor that gave up writes outcome=aborted registry rows;
    the member must alert NOW — a fresh-looking abort row must not vouch
    liveness for the whole staleness window."""
    root = str(tmp_path / "fleet")
    os.makedirs(root)
    now = time.time()
    out = make_member(root, tmp_path, "t0", role="trainer",
                      health={"time": now}, reg_ts=now)
    agg = FleetAggregator(root, AlertRules(heartbeat_stale_s=30.0))
    assert agg.refresh()["pod"]["alerts_firing"] == []
    register_member(root, output_dir=out, role="trainer", pid=99,
                    incarnation=3, outcome="aborted", reason="crash_loop")
    status = agg.refresh()
    assert status["members"]["trainer:t0"]["terminal_outcome"] == "aborted"
    assert status["pod"]["alerts_firing"] == ["heartbeat_stale:trainer:t0"]
    # a relaunch re-registers WITHOUT an outcome -> fresh again, resolves
    register_member(root, output_dir=out, role="trainer", pid=100,
                    incarnation=4)
    status = agg.refresh()
    assert status["pod"]["alerts_firing"] == []
    assert "terminal_outcome" not in status["members"]["trainer:t0"]

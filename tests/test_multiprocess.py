"""Multi-process execution of every `jax.process_count() > 1` branch.

Spawns REAL OS processes (tests/mp_worker.py) that rendezvous through
`jax.distributed.initialize` on CPU — the virtual-pod harness SURVEY.md §4(b)
calls for, taken to its multi-host conclusion (the reference ran 16 GPUs over
2 nodes, reference README.md:11; nothing below ever ran multi-process before
this file existed). Parity baselines are produced by the SAME worker run as a
single process, so distributed vs local is the only variable.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# multi-process spawns: the expensive lane (round gate); `-m 'not slow'` skips
pytestmark = pytest.mark.slow

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "mp_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_workers(scenario: str, tmpdir: str, num_processes: int,
                local_devices: int = 2, timeout: int = 420, **spec_extra) -> list[dict]:
    """Launch `num_processes` workers, wait, and return their result dicts
    (ordered by process id). Any non-zero exit fails the test with that
    worker's stderr tail."""
    workdir = os.path.join(tmpdir, f"{scenario}-{num_processes}p")
    os.makedirs(workdir, exist_ok=True)
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {**os.environ, "PYTHONPATH": _REPO}
    procs, logs = [], []
    for pid in range(num_processes):
        spec = {"scenario": scenario, "dir": workdir, "coordinator": coordinator,
                "num_processes": num_processes, "process_id": pid,
                "local_devices": local_devices, **spec_extra}
        log = open(os.path.join(workdir, f"worker-{pid}.log"), "w+")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER, json.dumps(spec)],
            stdout=log, stderr=subprocess.STDOUT, env=env, cwd=_REPO))
    def _fail(pid: int, why: str):
        logs[pid].seek(0)
        tail = logs[pid].read()
        # Some jax CPU builds refuse cross-process collectives outright
        # ("Multiprocess computations aren't implemented on the CPU
        # backend") — an environment limitation, not a repo regression:
        # skip with the reason instead of failing the suite.
        if "Multiprocess computations aren't implemented" in tail:
            pytest.skip(
                f"jax CPU backend in this environment does not implement "
                f"multiprocess computations (worker {pid} of {scenario!r}); "
                f"run on a backend with cross-process collectives")
        pytest.fail(f"worker {pid}/{num_processes} of {scenario!r} {why}:\n"
                    f"{tail[-4000:]}")

    try:
        # poll round-robin, not in pid order: the first worker to die (any
        # pid) must surface ITS log, instead of the test blocking on pid 0
        # until the deadline hides the actual diagnostic
        import time as _time

        deadline = _time.time() + timeout
        pending = set(range(num_processes))
        while pending:
            for pid in sorted(pending):
                rc = procs[pid].poll()
                if rc is None:
                    continue
                pending.discard(pid)
                if rc != 0:
                    _fail(pid, f"exited {rc}")
            if pending and _time.time() > deadline:
                _fail(min(pending), f"still running after {timeout}s")
            _time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()
    results = []
    for pid in range(num_processes):
        with open(os.path.join(workdir, f"result-{pid}.json")) as f:
            results.append(json.load(f))
    return results


def tiny_train_cfg(output_dir: str, **kw) -> dict:
    cfg = {
        "output_dir": output_dir,
        "mesh": {"pp": 2, "dp": 2},
        "model": {"preset": "tiny", "dtype": "float32"},
        "dataset": {"synthetic": True, "seq_length": 16, "pseudo_dataset_len": 128},
        "seed": 11,
        "per_device_train_batch_size": 2,
        "gradient_accumulation_steps": 2,
        "max_steps": 4,
        "learning_rate": 1e-3,
        "warmup_steps": 1,
        "logging_steps": 2,
        "save_steps": 0,
        "attention": "exact",
    }
    cfg.update(kw)
    return cfg


def test_should_stop_agreement(tmp_path):
    """One host's preemption signal becomes a unanimous stop; no signal stays
    a unanimous go (train.py _should_stop allgather)."""
    results = run_workers("should_stop", str(tmp_path), num_processes=2,
                          local_devices=1)
    assert all(r == {"one_host_flag": True, "no_flags": False} for r in results)


def test_two_process_train_parity(tmp_path):
    """A pp=2 x dp=2 run split over 2 processes matches the identical run on
    one process bit-for-bit-close: form_global_batch's multi-host assembly,
    host_dp_shard, and the jitted step under a cross-process mesh all line up."""
    dist = run_workers(
        "trainer", str(tmp_path), num_processes=2, local_devices=2,
        config=tiny_train_cfg(os.path.join(str(tmp_path), "dist")))
    ref = run_workers(
        "trainer", str(tmp_path), num_processes=1, local_devices=4,
        config=tiny_train_cfg(os.path.join(str(tmp_path), "ref")))
    assert dist[0]["final_step"] == 4
    assert dist[0]["final_loss"] == pytest.approx(dist[1]["final_loss"], rel=1e-6)
    np.testing.assert_allclose(dist[0]["final_loss"], ref[0]["final_loss"],
                               rtol=1e-5)


def test_dp_sharded_loading_and_metering(tmp_path):
    """Pure dp=4 over 2 processes: each host loads ONLY its own dp shards
    (host_dp_shard gives each a disjoint range) yet the loss matches the
    single-process run over the full batch — and the throughput meter scales
    host-local token counts back to the global batch (the pod MFU
    under-report fix)."""
    out = os.path.join(str(tmp_path), "dist")
    dist = run_workers(
        "trainer", str(tmp_path), num_processes=2, local_devices=2,
        config=tiny_train_cfg(out, mesh={"dp": 4}))
    ref = run_workers(
        "trainer", str(tmp_path), num_processes=1, local_devices=4,
        config=tiny_train_cfg(os.path.join(str(tmp_path), "ref"),
                              mesh={"dp": 4}))
    np.testing.assert_allclose(dist[0]["final_loss"], ref[0]["final_loss"],
                               rtol=1e-5)
    # each host owns a DISJOINT half of the dp range (this is what feeds the
    # meter's global_scale = dp/local = 2; the scale arithmetic itself is
    # pinned by test_metrics.py::test_throughput_global_scale)
    assert dist[0]["dp_range"] == [0, 2] and dist[1]["dp_range"] == [2, 2]
    assert ref[0]["dp_range"] == [0, 4]
    # metrics.jsonl is written by process 0 only: exactly one line per
    # logging boundary (4 steps / logging_steps=2), no interleaved duplicates
    dist_lines = [json.loads(l) for l in open(os.path.join(out, "metrics.jsonl"))]
    assert len(dist_lines) == 2
    assert all("tokens_per_sec" in l for l in dist_lines)


def test_sp_sharded_training_two_process(tmp_path):
    """Sequence parallelism across REAL processes: hosts load full-length
    rows, form_global_batch reshards the sequence dim over sp on-device
    (the distributed.py sp>1 device_put branch — never executed
    multi-process before this test), and the pp=2 x sp=2 ring loss matches
    the identical single-process run."""
    base = dict(tiny_train_cfg("", mesh={"pp": 2, "sp": 2},
                               sequence_parallel="ring"))
    dist = run_workers(
        "trainer", str(tmp_path), num_processes=2, local_devices=2,
        config=dict(base, output_dir=os.path.join(str(tmp_path), "dist")))
    ref = run_workers(
        "trainer", str(tmp_path), num_processes=1, local_devices=4,
        config=dict(base, output_dir=os.path.join(str(tmp_path), "ref")))
    assert dist[0]["final_step"] == 4
    # both hosts must report the identical loss (cross-process agreement)...
    assert dist[0]["final_loss"] == pytest.approx(dist[1]["final_loss"],
                                                  rel=1e-6)
    # ...and match the single-process run
    np.testing.assert_allclose(dist[0]["final_loss"], ref[0]["final_loss"],
                               rtol=1e-5)


def test_preemption_signal_two_process(tmp_path):
    """SIGTERM delivered to ONE process mid-run: the jax runtime's C++
    notifier consumes it, the coordination service's sync point stops both
    processes at the same step (train._preemption_notice), they write one
    complete checkpoint together (commit barriers), and exit 0. The worker
    only signals after the first metrics line proves training started."""
    out = os.path.join(str(tmp_path), "preempt")
    cfg = tiny_train_cfg(out, max_steps=100000, total_steps=100000,
                         preempt_check_every=1, logging_steps=1,
                         save_final=True)
    results = run_workers("trainer_preempt", str(tmp_path), num_processes=2,
                          local_devices=2, config=cfg, signal_seed=7)
    step0, step1 = results[0]["ckpt_step"], results[1]["ckpt_step"]
    assert step0 is not None and step0 == step1
    assert 0 < step0 < 100000
    # per-process observed stop steps prove the pod agreed on ONE step
    # (ckpt_step is a shared filesystem read and can't show disagreement)
    assert results[0]["stop_step"] == results[1]["stop_step"] == step0
    # the checkpoint is complete and resumable: meta.json written once by
    # process 0 after every process's arrays landed
    meta = json.load(open(os.path.join(out, f"checkpoint-{step0}", "meta.json")))
    assert meta["step"] == step0 and meta["has_optimizer_state"]


def test_async_checkpoint_stays_async_multiprocess(tmp_path):
    """At process_count=2 an async save must keep its background commit
    thread (round 2 demoted it to blocking) and still produce a complete,
    latest-tagged checkpoint via the RPC barriers."""
    results = run_workers("ckpt_async", str(tmp_path), num_processes=2,
                          local_devices=2)
    for r in results:
        assert r["async_alive"], "async save was demoted to blocking"
        assert r["complete"]
        assert r["latest"] == 9


def test_offload_zero2_two_process_dp4(tmp_path):
    """ZeRO-2 offload across REAL processes with dp spanning hosts (dp=4
    over 2 processes): masters/moments live dp-sharded so each host stores
    and updates ONLY its own dp range, grads leave the device
    reduce-scattered across hosts, the loss matches the identical
    single-process run, AND the dp-sharded checkpoint round-trips across
    processes (interrupted + resumed equals straight — the docs'
    cross-host pin for the z2 layout)."""
    base = dict(tiny_train_cfg("", mesh={"dp": 4}, optimizer_offload=True,
                               optimizer_offload_zero2=True,
                               learning_rate=1e-2, total_steps=4))
    dist = run_workers(
        "trainer", str(tmp_path), num_processes=2, local_devices=2,
        config=dict(base, output_dir=os.path.join(str(tmp_path), "dist")))
    ref = run_workers(
        "trainer", str(tmp_path), num_processes=1, local_devices=4,
        config=dict(base, output_dir=os.path.join(str(tmp_path), "ref")))
    assert dist[0]["final_loss"] == pytest.approx(dist[1]["final_loss"],
                                                  rel=1e-6)
    np.testing.assert_allclose(dist[0]["final_loss"], ref[0]["final_loss"],
                               rtol=1e-5)

    # cross-host z2 resume: each host restores its own dp-sharded
    # master/moment range from the checkpoint written by the first leg
    resume_dir = os.path.join(str(tmp_path), "resume")
    run_workers("trainer", str(tmp_path), num_processes=2, local_devices=2,
                config=dict(base, output_dir=resume_dir, max_steps=2))
    resumed = run_workers(
        "trainer", str(tmp_path), num_processes=2, local_devices=2,
        config=dict(base, output_dir=resume_dir))
    assert resumed[0]["final_step"] == 4
    np.testing.assert_allclose(resumed[0]["final_loss"],
                               dist[0]["final_loss"], rtol=1e-5)


def test_offload_trainer_two_process_resume(tmp_path):
    """The 65B config-of-record lifecycle at tiny scale across real
    processes: host-offloaded optimizer (cross-process grad-norm allgather),
    streamed offload checkpoint, THEN a second 2-process run restores
    masters+moments through the sharded templates (the round-2
    NotImplementedError gate, now lifted) and matches the uninterrupted run."""
    base = dict(tiny_train_cfg("", optimizer_offload=True, learning_rate=1e-2,
                               max_steps=8, total_steps=8))
    straight = run_workers(
        "trainer", str(tmp_path), num_processes=2, local_devices=2,
        config=dict(base, output_dir=os.path.join(str(tmp_path), "straight")))

    interrupted_dir = os.path.join(str(tmp_path), "interrupted")
    run_workers("trainer", str(tmp_path), num_processes=2, local_devices=2,
                config=dict(base, output_dir=interrupted_dir, max_steps=4))
    resumed = run_workers(
        "trainer", str(tmp_path), num_processes=2, local_devices=2,
        config=dict(base, output_dir=interrupted_dir))

    assert resumed[0]["final_step"] == 8
    np.testing.assert_allclose(resumed[0]["final_loss"],
                               straight[0]["final_loss"], rtol=1e-5)

"""Numerics observatory: in-graph stats, nonfinite guard, anomaly stream.

Covers the ISSUE 3 acceptance contract end to end: an injected nonfinite
gradient (the `grad_nonfinite` fault op) is detected the SAME step, the
update is where-skipped in-graph, the anomaly lands in numerics.jsonl +
health.json, and tools/numerics_report.py localizes it to the right
pipeline stage — plus the steady-state guarantee that the stats are
computed in-graph (no host callbacks in the lowered step, no extra step
inputs beyond state/batch).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel import train_step as ts
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh
from llama_pipeline_parallel_tpu.utils import faults, numerics


# ---------------------------------------------------------------------------
# Host-side units
# ---------------------------------------------------------------------------

def test_anomaly_detector_flags_spike_not_steady():
    det = numerics.AnomalyDetector(window=16, min_history=4)
    zs = [det.push(2.0 + 0.01 * (i % 3)) for i in range(10)]
    assert all(z is None or abs(z) < 6.0 for z in zs)
    z = det.push(50.0)
    assert z is not None and z > 6.0


def test_anomaly_detector_nan_does_not_poison_window():
    det = numerics.AnomalyDetector(window=16, min_history=4)
    for _ in range(6):
        det.push(1.0)
    det.push(float("nan"))  # must not enter the baseline
    z = det.push(1.0)
    assert z is not None and abs(z) < 1.0  # baseline still the steady 1.0s


def test_config_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown numerics config keys"):
        numerics.NumericsConfig.from_cfg({"windw": 10})
    cfg = numerics.NumericsConfig.from_cfg({"window": 5, "halt_on_nonfinite": True})
    assert cfg.window == 5 and cfg.halt_on_nonfinite


def test_monitor_counts_and_writes_jsonl(tmp_path):
    cfg = numerics.NumericsConfig(window=8, min_history=2, zscore=5.0)
    mon = numerics.NumericsMonitor(str(tmp_path), cfg)
    for step in range(1, 8):
        mon.observe(step, loss=2.0, grad_norm=1.0, stats=None)
    mon.observe(8, loss=200.0, grad_norm=1000.0, stats=None)  # finite spike
    mon.observe(9, loss=2.0, grad_norm=float("inf"), stats=None)  # nonfinite
    mon.flush()
    mon.close()
    recs = [json.loads(l) for l in open(tmp_path / "numerics.jsonl")]
    assert [r["step"] for r in recs] == list(range(1, 10))
    assert not recs[7]["nonfinite"]
    assert {"loss_spike", "grad_spike"} <= set(recs[7]["anomaly"])
    assert recs[8]["nonfinite"] and recs[8]["anomaly"] == ["nonfinite"]
    assert mon.nonfinite_steps == 1 and mon.anomaly_count == 2
    assert mon.health_fields["nonfinite_steps"] == 1
    assert mon.health_fields["grad_norm"] == "inf"


def test_monitor_halt_on_nonfinite(tmp_path):
    cfg = numerics.NumericsConfig(halt_on_nonfinite=True)
    mon = numerics.NumericsMonitor(str(tmp_path), cfg)
    mon.observe(1, loss=2.0, grad_norm=float("nan"), stats=None)
    with pytest.raises(numerics.NonfiniteHaltError) as ei:
        mon.flush()
    assert ei.value.step == 1
    mon.close()


# ---------------------------------------------------------------------------
# In-graph stats + the nonfinite guard
# ---------------------------------------------------------------------------

PP, DP = 2, 2


@pytest.fixture(scope="module")
def step_setup(devices):
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    mesh = make_mesh(MeshConfig(pp=PP, dp=DP))
    manifest = StageManifest.for_config(cfg, PP)
    pcfg = pl.PipelineConfig(num_stages=PP, num_microbatches=2)
    tx, schedule = make_optimizer(OptimizerConfig(
        learning_rate=1e-3, total_steps=10, warmup_steps=1))
    params = ts.init_params_sharded(jax.random.PRNGKey(0), cfg, mesh, manifest)
    state = ts.init_train_state(params, tx, mesh)
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": jnp.asarray(rng.randint(0, cfg.vocab_size, (4 * DP, 16))),
        "attention_mask": jnp.ones((4 * DP, 16), jnp.int32),
        "position_ids": jnp.broadcast_to(jnp.arange(16), (4 * DP, 16)),
        "labels": jnp.asarray(rng.randint(0, cfg.vocab_size, (4 * DP, 16))),
    }
    return cfg, mesh, manifest, pcfg, tx, schedule, params, state, batch


def _fresh_state(step_setup):
    cfg, mesh, manifest, pcfg, tx, schedule, params, state, batch = step_setup
    params = ts.init_params_sharded(jax.random.PRNGKey(0), cfg, mesh, manifest)
    return ts.init_train_state(params, tx, mesh)


def test_step_stats_shapes_and_values(step_setup):
    cfg, mesh, manifest, pcfg, tx, schedule, params, state, batch = step_setup
    step = ts.make_train_step(mesh, cfg, pcfg, tx, schedule, params,
                              collect_stats=True)
    state2 = _fresh_state(step_setup)
    state2, _ = step(state2, batch)  # step 0: warmup lr=0 -> zero updates
    new_state, metrics = step(state2, batch)
    stats = metrics["numerics"]
    for key in ("grad_norm_per_stage", "param_norm_per_stage",
                "update_norm_per_stage", "act_rms_per_stage",
                "act_absmax_per_stage"):
        arr = np.asarray(stats[key])
        assert arr.shape == (PP,), key
        assert np.all(np.isfinite(arr)) and np.all(arr > 0), key
    assert not bool(stats["nonfinite"])
    assert np.asarray(stats["grad_absmax_per_layer"]).shape == (
        PP, manifest.max_layers_per_stage)
    assert set(stats["grad_absmax_per_group"]) == {
        "attn.wq", "attn.wk", "attn.wv", "attn.wo",
        "mlp.gate", "mlp.up", "mlp.down", "input_norm", "post_norm"}
    assert set(stats["replicated_groups"]) == {"embed", "norm", "lm_head"}
    # the per-stage grad norms must compose to the (clip-input) global norm
    # over the layers subtree: sqrt(sum of per-stage squares) is a lower
    # bound of the full-tree norm reported in metrics
    layers_norm = float(np.sqrt((np.asarray(stats["grad_norm_per_stage"]) ** 2).sum()))
    assert layers_norm <= float(metrics["grad_norm"]) + 1e-4


@pytest.mark.slow  # PR 10 rebalance: the 1f1b stats test is the fast gate;
# gpipe folds the same value_and_grad aux path
def test_gpipe_schedule_collects_stats_too(step_setup):
    cfg, mesh, manifest, pcfg, tx, schedule, params, state, batch = step_setup
    import dataclasses

    gpcfg = dataclasses.replace(pcfg, schedule="gpipe")
    step = ts.make_train_step(mesh, cfg, gpcfg, tx, schedule, params,
                              collect_stats=True)
    _, metrics = step(_fresh_state(step_setup), batch)
    stats = metrics["numerics"]
    arr = np.asarray(stats["act_rms_per_stage"])
    assert arr.shape == (PP,) and np.all(np.isfinite(arr)) and np.all(arr > 0)


def test_nonfinite_guard_skips_update_same_step(step_setup):
    """A poisoned stage makes that step's grads nonfinite; params and
    optimizer state must come out bit-identical to the pre-step state, the
    flag must say so, and the NEXT (clean) step must train normally."""
    cfg, mesh, manifest, pcfg, tx, schedule, params, state, batch = step_setup
    step = ts.make_train_step(mesh, cfg, pcfg, tx, schedule, params,
                              collect_stats=True, poison=True)
    state2 = _fresh_state(step_setup)
    before = jax.tree.map(np.asarray, state2.params)
    poisoned, metrics = step(state2, batch, 1)  # poison stage 1
    stats = metrics["numerics"]
    assert bool(stats["nonfinite"])
    per_stage = np.asarray(stats["grad_norm_per_stage"])
    assert np.isfinite(per_stage[0]) and not np.isfinite(per_stage[1])
    after = jax.tree.map(np.asarray, poisoned.params)
    jax.tree.map(np.testing.assert_array_equal, before, after)
    assert int(poisoned.step) == 1  # step counter still advances

    # the skip also preserved the optimizer's internal count (0), so the
    # next clean step still sees the warmup lr=0 — two clean steps prove
    # training resumes: the first re-arms the schedule, the second moves
    clean, metrics2 = step(poisoned, batch, -1)  # -1 = no poison
    assert not bool(metrics2["numerics"]["nonfinite"])
    clean2, _ = step(clean, batch, -1)
    changed = jax.tree.leaves(jax.tree.map(
        lambda a, b: bool(np.any(a != b)), after,
        jax.tree.map(np.asarray, clean2.params)))
    assert any(changed)


def test_stats_are_in_graph_no_callbacks(step_setup):
    """The steady-state contract: numerics stats add device-side reductions
    only — no host callbacks / infeed / outfeed in the lowered step, and no
    change to the step's input signature (state, batch)."""
    cfg, mesh, manifest, pcfg, tx, schedule, params, state, batch = step_setup
    step = ts.make_train_step(mesh, cfg, pcfg, tx, schedule, params,
                              collect_stats=True)
    lowered = step.lower(state, batch)
    text = lowered.as_text()
    for marker in ("callback", "infeed", "outfeed", "SendToHost", "RecvFromHost"):
        assert marker not in text, f"host round-trip marker {marker!r} in HLO"
    # the only custom_calls allowed are GSPMD sharding annotations — any
    # other target would be a host round-trip or an op the stats smuggled in
    import re

    targets = set(re.findall(r"custom_call @(\w+)", text))
    assert targets <= {"Sharding", "SPMDFullToShardShape",
                       "SPMDShardToFullShape"}, targets
    # stats appear in the jaxpr's outputs (in-graph, not post-hoc)
    jaxpr_text = str(jax.make_jaxpr(
        lambda s, b: step(s, b), static_argnums=())(state, batch))
    assert "isfinite" in jaxpr_text or "is_finite" in jaxpr_text


def test_collect_stats_off_is_signature_compatible(step_setup):
    """collect_stats=False keeps the pre-observatory contract: metrics
    carries no numerics key and the update is NOT nonfinite-guarded."""
    cfg, mesh, manifest, pcfg, tx, schedule, params, state, batch = step_setup
    step = ts.make_train_step(mesh, cfg, pcfg, tx, schedule, params)
    _, metrics = step(_fresh_state(step_setup), batch)
    assert "numerics" not in metrics


# ---------------------------------------------------------------------------
# Offload-path skip
# ---------------------------------------------------------------------------

def test_host_offload_skip_nonfinite(devices):
    from llama_pipeline_parallel_tpu.optim.offload import HostOffloadAdamW

    ocfg = OptimizerConfig(learning_rate=1e-2, total_steps=10, warmup_steps=1)
    host = HostOffloadAdamW(ocfg, skip_nonfinite=True, device_norm=False)
    tree = {"w": jnp.ones((4, 4), jnp.float32)}
    host.init(tree)
    nan_grads = {"w": jnp.full((4, 4), jnp.nan)}
    host.update(nan_grads)
    assert host.last_nonfinite and host.nonfinite_count == 1
    assert host.step_count == 0  # moments/step untouched
    np.testing.assert_array_equal(
        np.asarray(host.masters_tree()["w"]), np.ones((4, 4), np.float32))
    host.update({"w": jnp.ones((4, 4), jnp.float32)})
    assert not host.last_nonfinite and host.step_count == 1
    # two clean steps: the first burns the warmup lr=0, the second moves
    host.update({"w": jnp.ones((4, 4), jnp.float32)})
    assert host.step_count == 2 and host.nonfinite_count == 1
    assert not np.allclose(np.asarray(host.masters_tree()["w"]), 1.0)


# ---------------------------------------------------------------------------
# The chaos e2e: inject -> detect -> skip -> record -> localize
# ---------------------------------------------------------------------------

def _tiny_cfg(tmp_path, **kw):
    cfg = {
        "output_dir": str(tmp_path / "out"),
        "mesh": {"pp": 2, "dp": 2},
        "model": {"preset": "tiny", "dtype": "float32"},
        "dataset": {"synthetic": True, "seq_length": 16, "pseudo_dataset_len": 128},
        "seed": 7,
        "per_device_train_batch_size": 2,
        "gradient_accumulation_steps": 2,
        "max_steps": 4,
        "learning_rate": 1e-3,
        "warmup_steps": 1,
        "logging_steps": 2,
        "save_steps": 0,
        "save_final": False,
        "attention": "exact",
    }
    cfg.update(kw)
    return cfg


def test_chaos_grad_nonfinite_detect_skip_localize(tmp_path, devices):
    """The ISSUE 3 acceptance scenario: a grad_nonfinite fault at step 2
    (stage 1) is detected that same step, the update is skipped (training
    continues finite), the anomaly is in numerics.jsonl AND health.json,
    and numerics_report localizes it to stage 1."""
    from llama_pipeline_parallel_tpu.train import run_training

    cfg = _tiny_cfg(tmp_path, fault_plan={
        "faults": [{"site": "step", "op": "grad_nonfinite",
                    "at_step": 2, "stage": 1}]})
    summary = run_training(cfg)
    assert summary["final_step"] == 4
    assert np.isfinite(summary["final_loss"])  # the skip held the line
    out = cfg["output_dir"]

    recs = {r["step"]: r for r in
            (json.loads(l) for l in open(os.path.join(out, "numerics.jsonl")))}
    assert set(recs) == {1, 2, 3, 4}
    # loop step 2 logs as record step 3 (records are 1-based like metrics)
    assert recs[3]["nonfinite"] and "nonfinite" in recs[3]["anomaly"]
    assert not recs[2]["nonfinite"] and not recs[4]["nonfinite"]
    per_stage = recs[3]["grad_norm_per_stage"]
    assert per_stage[1] in ("inf", "nan") and isinstance(per_stage[0], float)
    # the skipped update left the next step finite
    assert isinstance(recs[4]["grad_norm"], float)

    health = json.load(open(os.path.join(out, "health.json")))
    assert health["nonfinite_steps"] == 1 and health["anomaly_count"] == 1

    metrics = [json.loads(l) for l in open(os.path.join(out, "metrics.jsonl"))]
    assert metrics[-1]["nonfinite_steps"] == 1
    assert metrics[-1]["anomaly_count"] == 1

    import numerics_report  # importable via conftest's tools/ path insert

    rep = numerics_report.build_report(out)
    assert rep["nonfinite_steps"] == 1
    loc = rep["first_nonfinite"]
    assert loc["step"] == 3 and loc["stages"] == [1]
    assert any(g.startswith(("attn", "mlp")) for g in loc.get("groups", []))
    # the anomaly snapshot was dumped
    assert os.path.exists(os.path.join(out, "numerics-snapshot-3.json"))

    # goodput_report folds the anomaly timeline in
    import goodput_report

    grep = goodput_report.build_report(out)
    assert grep["numerics"]["nonfinite_steps"] == 1
    assert grep["numerics"]["first_nonfinite_step"] == 3


def test_halt_on_nonfinite_checkpoints_and_raises(tmp_path, devices):
    """halt_on_nonfinite escalates the skip: the run raises out of
    run_training (-> nonzero exit) AFTER committing a final checkpoint of
    the last-finite state through the PR 2 path."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.train import run_training

    cfg = _tiny_cfg(
        tmp_path,
        numerics={"halt_on_nonfinite": True},
        fault_plan={"faults": [{"site": "step", "op": "grad_nonfinite",
                                "at_step": 2, "stage": 0}]})
    with pytest.raises(numerics.NonfiniteHaltError):
        run_training(cfg)
    mgr = CheckpointManager(cfg["output_dir"])
    step = mgr.latest_step()
    # fault at loop step 2 -> record 3 is nonfinite; the lag-1 monitor
    # raises during record 4's step, whose (clean) update is already in the
    # live state — the checkpoint must carry THAT label, or a resume would
    # re-apply batch 4 (the review-fixed off-by-one)
    assert step == 4
    mgr.verify(step)  # integrity-complete commit, not a torn save


def test_grad_nonfinite_plan_requires_numerics(tmp_path, devices):
    """A grad_nonfinite rule with the observatory disabled would poison
    params with no guard/skip/record — rejected at config time."""
    from llama_pipeline_parallel_tpu.train import run_training

    cfg = _tiny_cfg(
        tmp_path,
        numerics={"enabled": False},
        fault_plan={"faults": [{"site": "step", "op": "grad_nonfinite",
                                "at_step": 1}]})
    with pytest.raises(ValueError, match="numerics.enabled"):
        run_training(cfg)


def test_numerics_disabled_writes_no_stream(tmp_path, devices):
    from llama_pipeline_parallel_tpu.train import run_training

    cfg = _tiny_cfg(tmp_path, numerics={"enabled": False})
    run_training(cfg)
    assert not os.path.exists(os.path.join(cfg["output_dir"], "numerics.jsonl"))
    metrics = [json.loads(l) for l in
               open(os.path.join(cfg["output_dir"], "metrics.jsonl"))]
    assert "nonfinite_steps" not in metrics[-1]


def test_pipeline_stats_under_tp(devices):
    """collect_stats composes with tensor parallelism: the stat reductions'
    dp/sp/tp collectives stay stage-uniform and the [S] outputs are finite."""
    from llama_pipeline_parallel_tpu.parallel.pipeline import (
        make_pipeline_loss_and_grad,
        stack_stages,
    )

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    mesh = make_mesh(MeshConfig(pp=2, tp=2))
    manifest = StageManifest.for_config(cfg, 2)
    stacked = pl.stack_stages(llama.init_params(jax.random.PRNGKey(0), cfg),
                              manifest)
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=2)
    fn = jax.jit(make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked,
                                             collect_stats=True))
    rng = np.random.RandomState(0)
    ids = rng.randint(3, cfg.vocab_size, size=(2, 16)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids),
             "attention_mask": jnp.ones((2, 16), jnp.int32),
             "position_ids": jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32),
                                              (2, 16)),
             "labels": jnp.asarray(ids)}
    loss, grads, stats = fn(stacked, batch)
    assert np.isfinite(float(loss))
    for key in ("act_rms_per_stage", "act_absmax_per_stage"):
        arr = np.asarray(stats[key])
        assert arr.shape == (2,) and np.all(np.isfinite(arr)) and np.all(arr > 0)


def test_grad_nonfinite_stage_out_of_range_rejected(tmp_path, devices):
    """A poison stage past num_stages would be an all-ones mask — the drill
    would 'pass' while exercising nothing. Rejected at config time."""
    from llama_pipeline_parallel_tpu.train import run_training

    cfg = _tiny_cfg(tmp_path, fault_plan={
        "faults": [{"site": "step", "op": "grad_nonfinite",
                    "at_step": 1, "stage": 7}]})
    with pytest.raises(ValueError, match="out of range"):
        run_training(cfg)


@pytest.mark.slow
def test_chaos_grad_nonfinite_offload_path(tmp_path, devices):
    """The host-offload optimizer path: the poison forces the separate
    stats dispatch, the nonfinite global norm skips the masters update
    (HostOffloadAdamW.skip_nonfinite), and the stream records it.
    Slow-marked (PR 10 rebalance): the fused-path chaos e2e stays the fast
    detect/skip/localize gate; this re-runs it through the offload
    optimizer only."""
    from llama_pipeline_parallel_tpu.train import run_training

    cfg = _tiny_cfg(
        tmp_path, optimizer_offload=True,
        fault_plan={"faults": [{"site": "step", "op": "grad_nonfinite",
                                "at_step": 2, "stage": 0}]})
    summary = run_training(cfg)
    assert summary["final_step"] == 4
    assert np.isfinite(summary["final_loss"])  # the skip held the line
    out = cfg["output_dir"]
    recs = {r["step"]: r for r in
            (json.loads(l) for l in open(os.path.join(out, "numerics.jsonl")))}
    assert recs[3]["nonfinite"] and not recs[4]["nonfinite"]
    assert recs[3]["grad_norm_per_stage"][0] in ("inf", "nan")
    health = json.load(open(os.path.join(out, "health.json")))
    assert health["nonfinite_steps"] == 1


def test_numerics_report_dedups_incarnations(tmp_path):
    """A resume re-runs steps past its checkpoint and appends fresh records
    for them; the offline readers keep only the surviving timeline (last
    record per step), so a recovered nonfinite step stops being reported."""
    import goodput_report
    import numerics_report

    rows = [
        {"step": 1, "loss": 1.0, "grad_norm": 1.0, "nonfinite": False},
        {"step": 2, "loss": 9.9, "grad_norm": "inf", "nonfinite": True,
         "anomaly": ["nonfinite"]},
        # crash + resume from checkpoint-1: step 2 re-runs clean
        {"step": 2, "loss": 1.1, "grad_norm": 1.0, "nonfinite": False},
        {"step": 3, "loss": 1.0, "grad_norm": 1.0, "nonfinite": False},
    ]
    with open(tmp_path / "numerics.jsonl", "w") as f:
        f.write("".join(json.dumps(r) + "\n" for r in rows))
    rep = numerics_report.build_report(str(tmp_path))
    assert rep["records"] == 3
    assert rep["nonfinite_steps"] == 0 and rep["first_nonfinite"] is None
    summary = goodput_report.numerics_summary(str(tmp_path))
    assert summary["records"] == 3 and summary["nonfinite_steps"] == 0

"""One "host" of the multi-process CPU pod harness.

tests/test_multiprocess.py spawns N of these as REAL OS processes, each with
its own jax runtime and a few virtual CPU devices, rendezvousing through
`jax.distributed.initialize` — the closest single-machine analogue of the
reference's 2-node/16-GPU deployment (reference README.md:11). Every
`jax.process_count() > 1` branch in the package executes here for real:
`form_global_batch`'s multi-host assembly, `host_dp_shard`, the preemption
allgather, the checkpoint commit barriers, the offload optimizer's
cross-process grad norm, and the attention-choice broadcast.

Invocation: python mp_worker.py '<json spec>'. The spec carries the scenario
name, rendezvous info, and scenario arguments; the worker writes its result
as JSON to `<spec[dir]>/result-<process_id>.json` (exit code 0 iff the
scenario ran clean).
"""

import json
import os
import re
import sys


def _setup(spec: dict):
    """Pin the CPU platform + device count, then rendezvous. Must run before
    jax initializes its backend, hence before any scenario import."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={spec['local_devices']}"
    ).strip()
    if spec["num_processes"] > 1:
        os.environ["JAX_COORDINATOR_ADDRESS"] = spec["coordinator"]
        os.environ["JAX_NUM_PROCESSES"] = str(spec["num_processes"])
        os.environ["JAX_PROCESS_ID"] = str(spec["process_id"])
    else:  # the single-process parity reference must not try to rendezvous
        for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                  "JAX_PROCESS_ID"):
            os.environ.pop(k, None)

    import jax

    # the image's sitecustomize force-registers the TPU platform; re-pin
    jax.config.update("jax_platforms", "cpu")

    from llama_pipeline_parallel_tpu.parallel.distributed import (
        initialize_distributed,
    )

    initialize_distributed()
    assert jax.process_count() == spec["num_processes"], (
        jax.process_count(), spec["num_processes"])


# ---------------------------------------------------------------------------
# Scenarios
# ---------------------------------------------------------------------------

def scenario_trainer(spec: dict) -> dict:
    """The full trainer on this virtual pod — whatever the config asks for
    (fused or offloaded optimizer, saves, resume, eval)."""
    from llama_pipeline_parallel_tpu.parallel.distributed import host_dp_shard
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh
    from llama_pipeline_parallel_tpu.train import run_training

    summary = run_training(spec["config"])
    dp_range = host_dp_shard(make_mesh(MeshConfig(**spec["config"]["mesh"])))
    return {"final_loss": summary["final_loss"],
            "final_step": summary["final_step"],
            "dp_range": list(dp_range)}


def scenario_trainer_preempt(spec: dict) -> dict:
    """Preemption e2e: ONLY the last process gets SIGTERM, mid-run. Under the
    jax distributed runtime the C++ notifier consumes the signal and the
    coordination service's sync point (train._preemption_notice) must stop
    every process at the same step; the save barriers then commit one
    agreed-on checkpoint.

    The signal fires only AFTER training observably progressed (first
    metrics.jsonl line, written by process 0 at logging_steps boundaries)
    plus a spec-seeded random extra delay — a fixed timer lands in
    setup/compile on a loaded machine and turns the test into a race
    (round-3 advisor finding)."""
    import random
    import signal
    import threading
    import time

    import jax

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.train import run_training

    if jax.process_index() == jax.process_count() - 1:
        metrics = os.path.join(spec["config"]["output_dir"], "metrics.jsonl")
        rng = random.Random(spec.get("signal_seed", 0))
        lo, hi = spec.get("signal_delay_range_s", [0.2, 1.5])

        def _signal_after_progress():
            deadline = time.time() + 300
            while time.time() < deadline:
                if os.path.exists(metrics) and os.path.getsize(metrics) > 0:
                    time.sleep(rng.uniform(lo, hi))
                    os.kill(os.getpid(), signal.SIGTERM)
                    return
                time.sleep(0.1)
            # a SIGTERM here would only feed the notifier of a process that
            # is wedged BEFORE the step loop (nothing polls the notice) — hard
            # -exit instead so the test fails fast with this line in the log
            print("progress gate expired: no metrics line within 300s; "
                  "aborting worker", flush=True)
            os._exit(3)

        threading.Thread(target=_signal_after_progress, daemon=True).start()
    summary = run_training(spec["config"])
    step = CheckpointManager(spec["config"]["output_dir"]).latest_step()
    # stop_step is the step THIS process observed its own loop break at —
    # the cross-process agreement evidence (ckpt_step alone is one shared
    # filesystem read and would match even if the processes disagreed)
    return {"ckpt_step": step, "stop_step": summary["preempted_at"]}


def scenario_ckpt_async(spec: dict) -> dict:
    """Async save at process_count > 1 stays async (no blocking demotion) and
    commits durably through the coordination-service barriers."""
    import jax

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.parallel import train_step as ts
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg = LlamaConfig.tiny(dtype="float32")
    mesh = make_mesh(MeshConfig.from_world(jax.device_count(), pp=2))
    manifest = StageManifest.for_config(cfg, 2)
    params = ts.init_params_sharded(jax.random.PRNGKey(0), cfg, mesh, manifest)

    mgr = CheckpointManager(os.path.join(spec["dir"], "ckpt"))
    mgr.save(7, params, manifest, cfg, blocking=False)
    # captured BEFORE finalize: a demoted (blocking) save leaves no thread
    async_alive = mgr._pending is not None
    mgr.finalize()
    complete = mgr.is_complete(7) and mgr.latest_step() == 7

    # second async save: unique barrier keys + previous-commit join
    mgr.save(9, params, manifest, cfg, blocking=False)
    mgr.finalize()
    return {"async_alive": async_alive, "complete": complete,
            "latest": mgr.latest_step()}


def scenario_should_stop(spec: dict) -> dict:
    """The preemption vote in isolation: one local signal => global stop."""
    import jax

    from llama_pipeline_parallel_tpu.train import _should_stop

    one_host_flag = _should_stop(jax.process_index() == 1)
    no_flags = _should_stop(False)
    return {"one_host_flag": bool(one_host_flag), "no_flags": bool(no_flags)}


SCENARIOS = {
    "trainer": scenario_trainer,
    "trainer_preempt": scenario_trainer_preempt,
    "ckpt_async": scenario_ckpt_async,
    "should_stop": scenario_should_stop,
}


def main() -> None:
    spec = json.loads(sys.argv[1])
    _setup(spec)
    result = SCENARIOS[spec["scenario"]](spec)
    out = os.path.join(spec["dir"], f"result-{spec['process_id']}.json")
    with open(out + ".tmp", "w") as f:
        json.dump(result, f)
    os.replace(out + ".tmp", out)


if __name__ == "__main__":
    main()

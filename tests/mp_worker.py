"""One "host" of the multi-process CPU pod harness.

tests/test_multiprocess.py spawns N of these as REAL OS processes, each with
its own jax runtime and a few virtual CPU devices, rendezvousing through
`jax.distributed.initialize` — the closest single-machine analogue of the
reference's 2-node/16-GPU deployment (reference README.md:11). Every
`jax.process_count() > 1` branch in the package executes here for real:
`form_global_batch`'s multi-host assembly, `host_dp_shard`, the preemption
allgather, the checkpoint commit barriers, the offload optimizer's
cross-process grad norm, and the attention-choice broadcast.

Invocation: python mp_worker.py '<json spec>'. The spec carries the scenario
name, rendezvous info, and scenario arguments; the worker writes its result
as JSON to `<spec[dir]>/result-<process_id>.json` (exit code 0 iff the
scenario ran clean).
"""

import json
import os
import re
import sys


def _setup(spec: dict):
    """Pin the CPU platform + device count, then rendezvous. Must run before
    jax initializes its backend, hence before any scenario import."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={spec['local_devices']}"
    ).strip()
    if spec["num_processes"] > 1:
        os.environ["JAX_COORDINATOR_ADDRESS"] = spec["coordinator"]
        os.environ["JAX_NUM_PROCESSES"] = str(spec["num_processes"])
        os.environ["JAX_PROCESS_ID"] = str(spec["process_id"])
    else:  # the single-process parity reference must not try to rendezvous
        for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                  "JAX_PROCESS_ID"):
            os.environ.pop(k, None)

    import jax

    # the image's sitecustomize force-registers the TPU platform; re-pin
    jax.config.update("jax_platforms", "cpu")

    from llama_pipeline_parallel_tpu.parallel.distributed import (
        initialize_distributed,
    )

    initialize_distributed()
    assert jax.process_count() == spec["num_processes"], (
        jax.process_count(), spec["num_processes"])


# ---------------------------------------------------------------------------
# Scenarios
# ---------------------------------------------------------------------------

def scenario_trainer(spec: dict) -> dict:
    """The full trainer on this virtual pod — whatever the config asks for
    (fused or offloaded optimizer, saves, resume, eval)."""
    from llama_pipeline_parallel_tpu.parallel.distributed import host_dp_shard
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh
    from llama_pipeline_parallel_tpu.train import run_training

    summary = run_training(spec["config"])
    dp_range = host_dp_shard(make_mesh(MeshConfig(**spec["config"]["mesh"])))
    return {"final_loss": summary["final_loss"],
            "final_step": summary["final_step"],
            "dp_range": list(dp_range)}


def scenario_trainer_preempt(spec: dict) -> dict:
    """Preemption e2e: ONLY the last process gets SIGTERM, mid-run. The
    allgather in `_should_stop` must stop every process at the same step and
    the save barriers must commit one agreed-on checkpoint."""
    import signal
    import threading

    import jax

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.train import run_training

    if jax.process_index() == jax.process_count() - 1:
        threading.Timer(spec["signal_after_s"],
                        lambda: os.kill(os.getpid(), signal.SIGTERM)).start()
    run_training(spec["config"])
    step = CheckpointManager(spec["config"]["output_dir"]).latest_step()
    return {"ckpt_step": step}


def scenario_ckpt_async(spec: dict) -> dict:
    """Async save at process_count > 1 stays async (no blocking demotion) and
    commits durably through the coordination-service barriers."""
    import jax

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.parallel import train_step as ts
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg = LlamaConfig.tiny(dtype="float32")
    mesh = make_mesh(MeshConfig.from_world(jax.device_count(), pp=2))
    manifest = StageManifest.for_config(cfg, 2)
    params = ts.init_params_sharded(jax.random.PRNGKey(0), cfg, mesh, manifest)

    mgr = CheckpointManager(os.path.join(spec["dir"], "ckpt"))
    mgr.save(7, params, manifest, cfg, blocking=False)
    # captured BEFORE finalize: a demoted (blocking) save leaves no thread
    async_alive = mgr._pending is not None
    mgr.finalize()
    complete = mgr.is_complete(7) and mgr.latest_step() == 7

    # second async save: unique barrier keys + previous-commit join
    mgr.save(9, params, manifest, cfg, blocking=False)
    mgr.finalize()
    return {"async_alive": async_alive, "complete": complete,
            "latest": mgr.latest_step()}


def scenario_should_stop(spec: dict) -> dict:
    """The preemption vote in isolation: one local signal => global stop."""
    import jax

    from llama_pipeline_parallel_tpu.train import _should_stop

    one_host_flag = _should_stop(jax.process_index() == 1)
    no_flags = _should_stop(False)
    return {"one_host_flag": bool(one_host_flag), "no_flags": bool(no_flags)}


SCENARIOS = {
    "trainer": scenario_trainer,
    "trainer_preempt": scenario_trainer_preempt,
    "ckpt_async": scenario_ckpt_async,
    "should_stop": scenario_should_stop,
}


def main() -> None:
    spec = json.loads(sys.argv[1])
    _setup(spec)
    result = SCENARIOS[spec["scenario"]](spec)
    out = os.path.join(spec["dir"], f"result-{spec['process_id']}.json")
    with open(out + ".tmp", "w") as f:
        json.dump(result, f)
    os.replace(out + ".tmp", out)


if __name__ == "__main__":
    main()

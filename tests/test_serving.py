"""Continuous-batching serving subsystem (serve/, tools/serve.py,
docs/SERVING.md).

The two acceptance contracts live here:
- e2e: staggered requests through the scheduler return TOKEN-IDENTICAL
  outputs to independent generate() calls with the same per-request seeds,
  with slot reuse (one cache allocation, a slot serving two requests) and
  TTFT/TPOT/queue-wait records in the spans + metrics streams.
- multi-replica: two serve processes under tools/supervisor.py, one
  SIGKILLed mid-decode, restarted from the same checkpoint by the
  watchdog, serving again; the incarnation ledger records the restart.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.decode import (
    GenerationConfig,
    generate,
)
from llama_pipeline_parallel_tpu.serve import (
    RequestRejected,
    ServeConfig,
    ServeEngine,
    ServeLoop,
    ServeOverloaded,
    ServeRequest,
    SlotKVCache,
)
from llama_pipeline_parallel_tpu.serve.telemetry import (
    SLOStats,
    percentile,
    percentiles_ms,
)
from llama_pipeline_parallel_tpu.utils import trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUCKET = 8


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def make_engine(cfg, params, **kw):
    defaults = dict(max_slots=2, max_len=BUCKET + 8, prompt_buckets=(BUCKET,),
                    max_queue=8, metrics_every=1, decode_span_every=1)
    defaults.update(kw)
    return ServeEngine(params, cfg, ServeConfig(**defaults))


def reference_tokens(params, cfg, prompt, gen, seed):
    """What the served request must emit: an independent generate() call
    with the prompt left-padded to the engine's bucket."""
    pad = BUCKET - len(prompt)
    ids = np.concatenate([np.zeros(pad, np.int32),
                          np.asarray(prompt, np.int32)])[None]
    mask = np.asarray([[0] * pad + [1] * len(prompt)], np.int32)
    out = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, gen,
                   rng=jax.random.PRNGKey(seed))
    return np.asarray(out["tokens"])[0].tolist()


# -- the e2e acceptance test -------------------------------------------------


def test_continuous_batching_token_parity_and_telemetry(setup, tmp_path):
    """Staggered arrivals through 2 slots: every request's stream matches
    its independent generate() call; slot reuse is proven (one allocation,
    slots serving two requests each); TTFT/TPOT/queue-wait land in both
    telemetry streams."""
    from llama_pipeline_parallel_tpu.utils.metrics import MetricsWriter

    cfg, params = setup
    trace.configure(str(tmp_path))
    writer = MetricsWriter(str(tmp_path))
    try:
        engine = make_engine(cfg, params)
        engine._metrics_writer = writer
        rs = np.random.RandomState(0)
        gens = [GenerationConfig(max_new_tokens=6),                       # greedy
                GenerationConfig(max_new_tokens=4, temperature=0.8, top_k=5),
                GenerationConfig(max_new_tokens=6, temperature=0.7, top_p=0.9),
                GenerationConfig(max_new_tokens=5, temperature=1.1)]
        prompts = [rs.randint(3, cfg.vocab_size, (n,)).tolist()
                   for n in (5, 8, 3, 7)]

        # staggered arrivals: two up front, two more mid-flight (they join
        # the running batch at a later step boundary)
        handles = [engine.submit(ServeRequest(input_ids=p, gen=g, seed=i))
                   for i, (p, g) in enumerate(zip(prompts[:2], gens[:2]))]
        engine.step()
        engine.step()
        handles += [engine.submit(ServeRequest(input_ids=p, gen=g, seed=i + 2))
                    for i, (p, g) in enumerate(zip(prompts[2:], gens[2:]))]
        engine.drain(timeout_s=120)

        for i, (h, p, g) in enumerate(zip(handles, prompts, gens)):
            assert h.result(timeout=1) == reference_tokens(params, cfg, p, g, i), \
                f"request {i} diverged from its independent generate() call"

        # slot reuse: the cache was allocated once and at least one slot
        # served two requests (4 requests > 2 slots force it)
        assert engine.slots.allocations == 1
        assert engine.slots.reused_slot_count() >= 1
        assert len(engine.slots.assignments) == 4
        assert engine.slots.free_count == 2  # all released

        snap = engine.metrics_snapshot()
        assert snap["requests_completed"] == 4
        assert snap["slot_allocations"] == 1
    finally:
        writer.close()
        trace.configure(None)

    # SLO records in the spans stream
    with open(tmp_path / "spans.jsonl") as f:
        spans = [json.loads(l) for l in f]
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    assert len(by_name["serve_ttft"]) == 4
    assert len(by_name["serve_queue_wait"]) == 4
    assert len(by_name["serve_prefill"]) == 4
    decode_spans = by_name["serve_decode_step"]
    assert sum(s["ticks"] for s in decode_spans) >= 5  # every tick accounted
    requests = by_name["serve_request"]
    assert len(requests) == 4
    for r in requests:
        assert r["ttft"] >= r["queue_wait"] >= 0.0
        assert r["tpot"] > 0.0 and r["tokens"] >= 4

    # ... and in the metrics stream
    with open(tmp_path / "metrics.jsonl") as f:
        lines = [json.loads(l) for l in f if l.strip()]
    serving = [m for m in lines if m.get("serving")]
    assert serving, "no serving metrics line written"
    last = serving[-1]
    for key in ("ttft_p50_ms", "tpot_p50_ms", "queue_wait_p50_ms",
                "ttft_p99_ms"):
        assert key in last, f"metrics line missing {key}"
    assert last["requests_completed"] == 4
    assert last["tokens_generated"] == sum(g.max_new_tokens for g in gens)


@pytest.mark.slow  # the paged grid's eos row (test_paged_serving.py::
# test_paged_eos_finishes_row_early_and_frees_pages) pins the same early-
# free semantics every tier-1 run; this dense twin stays in the round gate
def test_eos_finishes_row_early_and_frees_slot(setup):
    """A request hitting eos frees its slot before the budget; the emitted
    stream ends with the eos token, matching generate()'s pre-pad prefix."""
    cfg, params = setup
    engine = make_engine(cfg, params, max_slots=1)
    prompt = np.random.RandomState(2).randint(3, cfg.vocab_size, (4,)).tolist()

    free = engine.submit(ServeRequest(
        input_ids=prompt, gen=GenerationConfig(max_new_tokens=8), seed=0))
    engine.drain(timeout_s=60)
    eos = free.result(timeout=1)[0]  # force eos on the very first token

    gen = GenerationConfig(max_new_tokens=8, eos_token_id=eos, pad_token_id=17)
    h = engine.submit(ServeRequest(input_ids=prompt, gen=gen, seed=0))
    engine.drain(timeout_s=60)
    got = h.result(timeout=1)
    assert got == [eos]                      # stream stops AT eos
    assert engine.slots.free_count == 1      # slot freed immediately
    ref = reference_tokens(params, cfg, prompt, gen, 0)
    assert ref[0] == eos and all(t == 17 for t in ref[1:])  # generate pads


# -- scheduler / slot units --------------------------------------------------


def test_backpressure_and_rejection(setup):
    cfg, params = setup
    engine = make_engine(cfg, params, max_queue=2)

    # shape that can never be served -> rejected outright
    with pytest.raises(RequestRejected):
        engine.submit(ServeRequest(input_ids=list(range(BUCKET + 1)),
                                   gen=GenerationConfig(max_new_tokens=2)))
    with pytest.raises(RequestRejected):  # budget overflows the slot
        engine.submit(ServeRequest(input_ids=[5],
                                   gen=GenerationConfig(max_new_tokens=100)))
    with pytest.raises(RequestRejected):
        engine.submit(ServeRequest(input_ids=[]))

    # bounded wait queue -> overload is backpressure, not OOM
    small = GenerationConfig(max_new_tokens=2)
    for i in range(2):
        engine.submit(ServeRequest(input_ids=[3 + i], gen=small))
    with pytest.raises(ServeOverloaded):
        engine.submit(ServeRequest(input_ids=[9], gen=small))
    # all 4 refusals count: 3 unservable shapes + 1 overload
    assert engine.stats.snapshot()["requests_rejected"] == 4
    engine.drain(timeout_s=120)  # the queued two still complete
    assert engine.queue_depth() == 0


def test_shutdown_fails_pending_and_blocks_late_submits(setup):
    """shutdown() fails queued handles and flips the engine closed: a late
    submit raises EngineShutdown instead of queueing into a dead engine
    (its handle would otherwise block its caller forever)."""
    from llama_pipeline_parallel_tpu.serve import EngineShutdown

    cfg, params = setup
    engine = make_engine(cfg, params)
    small = GenerationConfig(max_new_tokens=2)
    h = engine.submit(ServeRequest(input_ids=[5], gen=small))
    engine.shutdown()
    with pytest.raises(EngineShutdown):
        h.result(timeout=1)
    with pytest.raises(EngineShutdown):
        engine.submit(ServeRequest(input_ids=[6], gen=small))


def test_slot_manager_acquire_release():
    cache = SlotKVCache(LlamaConfig.tiny(), max_slots=2, max_len=4)
    a = cache.acquire("r1")
    b = cache.acquire("r2")
    assert {a, b} == {0, 1}
    assert cache.acquire("r3") is None       # full
    cache.release(a)
    assert cache.acquire("r4") == a          # lowest free slot, reused
    with pytest.raises(ValueError):
        cache.release(7)                     # never held
    cache.release(a)
    with pytest.raises(ValueError):
        cache.release(a)                     # double free
    assert cache.reused_slot_count() == 1
    assert cache.allocations == 1


def test_serve_config_validation():
    with pytest.raises(ValueError):
        ServeConfig(prompt_buckets=())
    with pytest.raises(ValueError):
        ServeConfig(prompt_buckets=(64, 32))         # not ascending
    with pytest.raises(ValueError):
        ServeConfig(prompt_buckets=(64,), max_len=64)  # no room to generate
    with pytest.raises(ValueError):
        ServeConfig(max_queue=0)


def test_pick_bucket_prefers_smallest_fitting(setup):
    cfg, params = setup
    engine = ServeEngine(params, cfg, ServeConfig(
        max_slots=1, max_len=40, prompt_buckets=(8, 16, 32)))
    assert engine.pick_bucket(5, 4) == 8
    assert engine.pick_bucket(9, 4) == 16
    # 8-token budget pushes a 30-prompt past max_len on bucket 32 -> reject
    with pytest.raises(RequestRejected):
        engine.pick_bucket(30, 16)


def test_decode_span_aggregation(setup, tmp_path):
    """Decode-tick spans aggregate (decode_span_every) so a long-lived
    replica doesn't grow spans.jsonl at token rate; the aggregate's dur is
    the exact sum of its ticks and the idle boundary flushes the tail."""
    cfg, params = setup
    trace.configure(str(tmp_path))
    try:
        engine = make_engine(cfg, params, decode_span_every=1000)
        engine.submit(ServeRequest(
            input_ids=[5, 6], gen=GenerationConfig(max_new_tokens=5)))
        engine.drain(timeout_s=60)
        assert engine.step() is False  # idle boundary flushes the aggregate
    finally:
        trace.configure(None)
    with open(tmp_path / "spans.jsonl") as f:
        spans = [json.loads(l) for l in f]
    decode_spans = [s for s in spans if s["name"] == "serve_decode_step"]
    assert len(decode_spans) == 1              # 4 ticks, ONE line
    assert decode_spans[0]["ticks"] == 4       # max_new 5 -> 4 decode ticks
    assert decode_spans[0]["dur"] > 0.0


def test_percentile_helpers():
    assert percentile([], 50) is None
    assert percentile([3.0], 99) == 3.0
    vals = list(range(1, 102))       # 1..101: median unambiguous
    assert percentile(vals, 50) == 51
    assert percentile(vals, 100) == 101
    assert percentile(vals, 0) == 1
    out = percentiles_ms([0.1, 0.2], "ttft")
    assert set(out) == {"ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms"}
    assert percentiles_ms([], "x") == {}
    stats = SLOStats()
    stats.record(ttft=0.5, tpot=None, queue_wait=0.1, tokens=1)
    snap = stats.snapshot()
    assert snap["requests_completed"] == 1
    assert "tpot_p50_ms" not in snap  # single-token request: TPOT undefined


# -- in-process loop + HTTP front-end ---------------------------------------


@pytest.mark.slow  # ServeLoop streaming now runs every tier-1 lane under
# real load via test_serve_traffic.py::test_run_trace_against_chunked_paged_
# engine (plus the HTTP test below); this focused dense rep joins the round
# gate
def test_serve_loop_streams_tokens(setup):
    """ServeLoop drives the engine in the background; the handle streams
    tokens as they are produced and the stream matches the result."""
    cfg, params = setup
    engine = make_engine(cfg, params)
    with ServeLoop(engine, idle_wait_s=0.005):
        h = engine.submit(ServeRequest(
            input_ids=[5, 6, 7],
            gen=GenerationConfig(max_new_tokens=5, temperature=0.9), seed=4))
        streamed = list(h.tokens(timeout=60))
    assert len(streamed) == 5
    assert streamed == h.result(timeout=1)
    assert streamed == reference_tokens(
        params, cfg, [5, 6, 7],
        GenerationConfig(max_new_tokens=5, temperature=0.9), 4)


def test_http_frontend_inprocess(setup):
    from llama_pipeline_parallel_tpu.serve.frontend import make_server

    cfg, params = setup
    engine = make_engine(cfg, params)
    server = make_server(engine)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        with ServeLoop(engine, idle_wait_s=0.005):
            def post(body, headers=None):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/generate",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json",
                             **(headers or {})})
                return urllib.request.urlopen(req, timeout=60)

            resp = post({"input_ids": [5, 6], "max_new_tokens": 3,
                         "seed": 1})
            # correlation contract (docs/SERVING.md "Request tracing"):
            # ids in the body AND the response headers, joined by the
            # incoming W3C traceparent when the caller sent one
            assert resp.headers["X-Request-Id"]
            assert resp.headers["X-Trace-Id"]
            assert resp.headers["traceparent"].startswith("00-")
            out = json.load(resp)
            assert out["request_id"] == resp.headers["X-Request-Id"]
            assert out["trace_id"] == resp.headers["X-Trace-Id"]
            assert out["tokens"] == reference_tokens(
                params, cfg, [5, 6], GenerationConfig(max_new_tokens=3), 1)

            parent = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
            joined = post({"input_ids": [5, 6], "max_new_tokens": 1},
                          headers={"traceparent": parent})
            assert joined.headers["X-Trace-Id"] == "ab" * 16  # adopted
            assert joined.headers["traceparent"] != parent    # our span id

            stream = post({"input_ids": [4, 5], "max_new_tokens": 4,
                           "temperature": 0.8, "top_p": 0.9, "seed": 2,
                           "stream": True})
            assert stream.headers["X-Trace-Id"]
            lines = [json.loads(l) for l in stream.read().decode().splitlines()]
            assert [l["token"] for l in lines[:-1]] == lines[-1]["tokens"]
            assert lines[-1]["done"] is True
            # the FIRST streamed line carries the correlation ids (a client
            # can join a waterfall without waiting for the tail line);
            # later token lines stay minimal
            assert lines[0]["request_id"] == stream.headers["X-Request-Id"]
            assert lines[0]["trace_id"] == stream.headers["X-Trace-Id"]
            assert all(set(l) == {"token"} for l in lines[1:-1])
            assert lines[-1]["trace_id"] == stream.headers["X-Trace-Id"]

            health = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10))
            assert health["serving"] == 1 and health["requests_completed"] == 3

            with pytest.raises(urllib.error.HTTPError) as err:
                post({"input_ids": "nope"})
            assert err.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as err:
                post({"input_ids": list(range(BUCKET + 1))})
            assert err.value.code == 400
    finally:
        server.shutdown()


def test_serving_report_builds_from_run_dir(tmp_path):
    import serving_report  # tools/ on sys.path via conftest

    spans = [
        {"name": "serve_request", "ts": 100.0, "end": 101.0, "dur": 1.0,
         "ttft": 0.3, "tpot": 0.05, "queue_wait": 0.1, "tokens": 15},
        {"name": "serve_request", "ts": 100.5, "end": 102.0, "dur": 1.5,
         "ttft": 0.6, "tpot": 0.07, "queue_wait": 0.2, "tokens": 5},
        {"name": "serve_decode_step", "ts": 100.0, "dur": 0.01},
    ]
    with open(tmp_path / "spans.jsonl", "w") as f:
        for s in spans:
            f.write(json.dumps(s) + "\n")
        f.write("{torn")  # torn tail must not kill the report
    with open(tmp_path / "metrics.jsonl", "w") as f:
        f.write(json.dumps({"step": 2, "serving": 1, "requests_completed": 2,
                            "ttft_p50_ms": 300.0, "active_slots": 0,
                            "slot_allocations": 1}) + "\n")

    rep = serving_report.build_report(str(tmp_path))
    assert rep["requests"] == 2 and rep["tokens"] == 20
    assert rep["ttft"]["ttft_p50_ms"] == 300.0
    assert rep["tpot"]["tpot_p99_ms"] == 70.0
    assert rep["tokens_per_sec"] == pytest.approx(20 / 2.0)
    assert rep["last_metrics"]["slot_allocations"] == 1
    assert serving_report.main([str(tmp_path)]) == 0
    # empty dir degrades, nonzero exit, no traceback
    empty = tmp_path / "empty"
    empty.mkdir()
    assert serving_report.main([str(empty)]) == 1


# -- multi-replica serving under the supervisor ------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_for_replica(out_dir: str, old_pid: int | None = None,
                      timeout_s: float = 120.0) -> dict:
    """Poll serve.json until a (new) replica is up and /healthz answers."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(os.path.join(out_dir, "serve.json")) as f:
                info = json.load(f)
            if old_pid is not None and info["pid"] == old_pid:
                raise OSError("still the old incarnation")
            urllib.request.urlopen(
                f"http://127.0.0.1:{info['port']}/healthz", timeout=5)
            return info
        except Exception:
            time.sleep(0.25)
    raise TimeoutError(f"no live replica in {out_dir} within {timeout_s}s")


def _post(port: int, body: dict, timeout: float = 120.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return json.load(urllib.request.urlopen(req, timeout=timeout))


@pytest.mark.slow  # ~40 s of real process spawns/kills — the heavyweight
# chaos leg the CI gate note already earmarks for the round gate; its
# machinery (supervisor restart, serve.json discovery, role ledger) is
# untouched by the paged-cache work that funds this rebalance
def test_multi_replica_supervised_restart(setup, tmp_path):
    """Two serve replicas under tools/supervisor.py from ONE checkpoint;
    replica A is SIGKILLed mid-decode, the watchdog restarts it from the
    same checkpoint, and it serves again — the incarnation ledger records
    the crash, the restart, and the serve role."""
    import supervisor  # tools/ on sys.path via conftest
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.parallel.pipeline import stack_stages

    cfg, params = setup
    ckpt = str(tmp_path / "ckpt")
    manifest = StageManifest.for_config(cfg, 1)
    CheckpointManager(ckpt).save(0, stack_stages(params, manifest), manifest,
                                 cfg)

    replicas, sups, threads = {}, {}, {}
    try:
        for name in ("a", "b"):
            out = str(tmp_path / name)
            port = _free_port()
            cmd = [sys.executable, os.path.join(REPO, "tools", "serve.py"),
                   "--checkpoint_dir", ckpt, "--output_dir", out,
                   "--host", "127.0.0.1", "--port", str(port),
                   "--platform", "cpu", "--max_slots", "2",
                   "--max_len", "320", "--buckets", "8",
                   "--metrics_every", "1"]
            env = dict(os.environ)
            # stretch decode steps so the kill lands mid-decode deterministically
            env["LPT_SERVE_STEP_DELAY_S"] = "0.05" if name == "a" else "0"
            sup = supervisor.Supervisor(cmd, supervisor.SupervisorConfig(
                output_dir=out, max_restarts=3, hang_timeout_s=300.0,
                grace_s=5.0, crash_loop_threshold=3, crash_loop_window_s=0.0,
                poll_s=0.1), env=env)
            t = threading.Thread(target=sup.run, daemon=True)
            t.start()
            replicas[name], sups[name], threads[name] = out, sup, t

        info = {n: _wait_for_replica(replicas[n]) for n in ("a", "b")}

        # both replicas serve, and token-identically: same checkpoint,
        # same seed -> same stream, whichever replica handles it
        body = {"input_ids": [5, 6, 7], "max_new_tokens": 4, "seed": 3}
        out_a = _post(info["a"]["port"], body)["tokens"]
        out_b = _post(info["b"]["port"], body)["tokens"]
        assert out_a == out_b
        assert out_a == reference_tokens(params, cfg, [5, 6, 7],
                                         GenerationConfig(max_new_tokens=4), 3)

        # a long streaming request on A, killed mid-decode
        def doomed():
            try:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{info['a']['port']}/v1/generate",
                    data=json.dumps({"input_ids": [9, 10],
                                     "max_new_tokens": 300,
                                     "stream": True}).encode()),
                    timeout=300).read()
            except Exception:
                pass  # the point: the replica dies under it

        t_doomed = threading.Thread(target=doomed, daemon=True)
        t_doomed.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:  # wait until decode is underway
            health = supervisor.read_health(replicas["a"]) or {}
            if (health.get("last_step") or 0) >= 3:
                break
            time.sleep(0.1)
        else:
            pytest.fail("replica a never started decoding the doomed request")
        os.kill(info["a"]["pid"], signal.SIGKILL)

        # the watchdog relaunches from the same checkpoint; it serves again
        new_info = _wait_for_replica(replicas["a"], old_pid=info["a"]["pid"])
        assert new_info["checkpoint_step"] == 0
        out_a2 = _post(new_info["port"], body)["tokens"]
        assert out_a2 == out_a  # same checkpoint, same seed, same tokens

        # the goodput ledger recorded the crash + serve role
        with open(os.path.join(replicas["a"], "incarnations.jsonl")) as f:
            rows = [json.loads(l) for l in f]
        assert rows[0]["outcome"] == "crash" and rows[0]["exit_code"] != 0
        assert rows[0]["role"] == "serve"
        assert rows[0]["incarnation"] == 0
    finally:
        # clean stop: SIGTERM the children -> serve exits 0 -> supervisors
        # return; anything still alive gets killed so the test never leaks
        for name, out in replicas.items():
            try:
                with open(os.path.join(out, "serve.json")) as f:
                    os.kill(json.load(f)["pid"], signal.SIGTERM)
            except (OSError, ValueError):
                pass
        for name, t in threads.items():
            t.join(timeout=60)
        for name, out in replicas.items():
            try:
                with open(os.path.join(out, "serve.json")) as f:
                    os.kill(json.load(f)["pid"], signal.SIGKILL)
            except (OSError, ValueError):
                pass

    # B was never restarted; its supervisor saw a clean exit
    with open(os.path.join(replicas["b"], "incarnations.jsonl")) as f:
        rows_b = [json.loads(l) for l in f]
    assert [r["outcome"] for r in rows_b] == ["clean"]
    assert rows_b[0]["role"] == "serve"


# -- degraded-mode admission (docs/RESILIENCE.md "Actuation") ----------------


def test_degraded_mode_sheds_and_recovers(setup):
    """A degraded engine (draining / mid-resize) refuses NEW admissions
    with an honest retry hint, keeps decoding what it already admitted,
    advertises the reason in its metrics, and recovers the moment the
    degradation clears."""
    cfg, params = setup
    engine = make_engine(cfg, params)
    gen = GenerationConfig(max_new_tokens=3)
    h = engine.submit(ServeRequest(input_ids=[5, 6], gen=gen, seed=1))
    engine.set_degraded("draining")
    with pytest.raises(ServeOverloaded) as exc:
        engine.submit(ServeRequest(input_ids=[7, 8], gen=gen))
    assert "degraded (draining)" in str(exc.value)
    assert exc.value.retry_after_s > 0
    assert engine.metrics_snapshot()["degraded"] == "draining"
    # the admitted request still decodes through the degraded window
    engine.drain(timeout_s=120)
    assert h.result(timeout=1) == reference_tokens(params, cfg, [5, 6],
                                                   gen, 1)
    engine.clear_degraded()
    assert "degraded" not in engine.metrics_snapshot()
    h2 = engine.submit(ServeRequest(input_ids=[7, 8], gen=gen, seed=2))
    engine.drain(timeout_s=120)
    assert h2.result(timeout=1) == reference_tokens(params, cfg, [7, 8],
                                                    gen, 2)


def test_degraded_maps_to_429_with_pinned_retry_after(setup):
    """HTTP contract pin: a degraded replica answers 429 with a
    Retry-After measured from its OWN backlog and drain rate. 2 queued
    requests draining at 1 completion / 30 s window -> 90 s, clamped to
    the 60 s cap — jitter cannot move a clamped value, so the header is
    exactly "60" for any request id."""
    from llama_pipeline_parallel_tpu.serve.frontend import make_server

    cfg, params = setup
    engine = make_engine(cfg, params)
    server = make_server(engine)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    gen = GenerationConfig(max_new_tokens=2)
    try:
        for i in range(2):
            engine.submit(ServeRequest(input_ids=[5, 6], gen=gen, seed=i))
        engine.stats.finished_at.append(time.monotonic())
        engine.set_degraded("draining")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate",
                data=json.dumps({"input_ids": [3, 4],
                                 "max_new_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"}), timeout=60)
        assert err.value.code == 429
        assert err.value.headers["Retry-After"] == "60"
        assert "degraded (draining)" in json.loads(err.value.read())["error"]
    finally:
        engine.clear_degraded()
        engine.drain(timeout_s=120)  # the queued admissions still finish
        server.shutdown()


def test_shutdown_maps_to_503_with_pinned_retry_after(setup):
    """HTTP contract pin for the gateway's failover signal: a shut-down
    replica answers 503 + Retry-After so the gateway reroutes instead of
    hot-retrying a dying process. With no measured completions the hint
    is the 1.0 s fallback, and "rid-301" has zero deterministic jitter
    (crc32 % 1000 == 0) — the header is exactly "1"."""
    from llama_pipeline_parallel_tpu.serve.frontend import make_server

    cfg, params = setup
    engine = make_engine(cfg, params)
    server = make_server(engine)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        engine.shutdown()
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate",
                data=json.dumps({"input_ids": [3, 4], "max_new_tokens": 2,
                                 "request_id": "rid-301"}).encode(),
                headers={"Content-Type": "application/json"}), timeout=60)
        assert err.value.code == 503
        assert err.value.headers["Retry-After"] == "1"
        payload = json.loads(err.value.read())
        assert "shut down" in payload["error"]
        assert payload["request_id"] == "rid-301"
    finally:
        server.shutdown()

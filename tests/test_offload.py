"""Host-offloaded AdamW (C++ kernel, shard-aware) vs optax numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer
from llama_pipeline_parallel_tpu.optim import offload as off
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh


@pytest.fixture(scope="module")
def tree():
    rng = np.random.RandomState(0)
    return {"a": jnp.asarray(rng.randn(64, 32), jnp.float32),
            "b": {"c": jnp.asarray(rng.randn(128), jnp.float32)}}


def grads_like(tree, seed):
    rng = np.random.RandomState(seed)
    return jax.tree.map(lambda x: jnp.asarray(rng.randn(*x.shape) * 2, jnp.float32), tree)


def optax_reference(tree, cfg, n_steps):
    tx, _ = make_optimizer(cfg)
    opt_state = tx.init(tree)
    params = tree
    import optax

    for step in range(n_steps):
        updates, opt_state = tx.update(grads_like(tree, step), opt_state, params)
        params = optax.apply_updates(params, updates)
    return params


def test_native_kernel_compiles():
    assert off._load_native() is not None, "g++ compile of csrc/host_adamw.cpp failed"


@pytest.mark.parametrize("force_numpy", [False, True])
def test_matches_optax(tree, force_numpy, monkeypatch):
    if force_numpy:
        monkeypatch.setattr(off, "_lib", None)
        monkeypatch.setattr(off, "_lib_failed", True)
    cfg = OptimizerConfig(learning_rate=1e-2, weight_decay=0.1, beta1=0.9,
                          beta2=0.95, max_grad_norm=1.0, total_steps=100,
                          warmup_steps=10)
    params_ref = optax_reference(tree, cfg, 5)

    host = off.HostOffloadAdamW(cfg)
    host.init(tree)
    for step in range(5):
        host.update(grads_like(tree, step))

    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        params_ref, host.masters_tree())
    assert host.last_grad_norm > 0
    assert host.last_timings["update_ms"] >= 0


def test_sharded_masters_match_optax(tree, devices):
    """Masters stored per mesh shard (pp x dp sharded + replicated leaves)
    must step to the same values as the unsharded optax chain."""
    mesh = make_mesh(MeshConfig(pp=2, dp=2))
    shard_specs = {"a": P("pp"), "b": {"c": P()}}  # sharded + replicated leaf
    put = lambda t: jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), t, shard_specs)
    cfg = OptimizerConfig(learning_rate=1e-2, weight_decay=0.1,
                          max_grad_norm=1.0, total_steps=100, warmup_steps=10)
    params_ref = optax_reference(tree, cfg, 3)

    host = off.HostOffloadAdamW(cfg)
    host.init(put(tree))
    assert len(host._leaves[0].shards) == 2   # "a" split over pp
    assert len(host._leaves[1].shards) == 1   # replicated "c": one distinct shard
    for step in range(3):
        host.update(put(grads_like(tree, step)))

    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        params_ref, host.masters_tree())

    # the bf16 working copy keeps the mesh sharding and the master values
    dev = host.device_params(jnp.bfloat16)
    assert dev["a"].sharding.spec == NamedSharding(mesh, P("pp")).spec
    np.testing.assert_allclose(np.asarray(dev["a"], np.float32),
                               np.asarray(host.masters_tree()["a"]),
                               rtol=8e-3, atol=1e-5)


def test_update_and_refresh_matches_separate_phases(tree, devices):
    """The fused per-leaf AdamW + cast + H2D pipeline (update_and_refresh,
    the trainer's hot path) is bit-identical to the separate
    update() + device_params() phases — same kernels, same order — while
    returning the same sharded working copy."""
    mesh = make_mesh(MeshConfig(pp=2, dp=2))
    shard_specs = {"a": P("pp"), "b": {"c": P()}}
    put = lambda t: jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), t, shard_specs)
    cfg = OptimizerConfig(learning_rate=1e-2, weight_decay=0.1,
                          max_grad_norm=1.0, total_steps=100, warmup_steps=10)

    h_sep = off.HostOffloadAdamW(cfg)
    h_sep.init(put(tree))
    h_fused = off.HostOffloadAdamW(cfg)
    h_fused.init(put(tree))

    for step in range(3):
        g = put(grads_like(tree, step))
        h_sep.update(g)
        dev_sep = h_sep.device_params(jnp.bfloat16)
        dev_fused = h_fused.update_and_refresh(g, jnp.bfloat16)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32)),
            dev_sep, dev_fused)
        assert dev_fused["a"].sharding.spec == NamedSharding(mesh, P("pp")).spec
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)),
        h_sep.masters_tree(), h_fused.masters_tree())
    assert h_fused.last_timings["update_h2d_ms"] >= 0
    assert h_fused.last_grad_norm == h_sep.last_grad_norm


def test_state_dict_roundtrip(tree):
    cfg = OptimizerConfig(learning_rate=1e-2, total_steps=50, warmup_steps=2)
    h1 = off.HostOffloadAdamW(cfg)
    h1.init(tree)
    h1.update(grads_like(tree, 0))

    h2 = off.HostOffloadAdamW(cfg)
    h2.init(tree)
    h2.load_state_dict(h1.state_dict())
    h2.load_masters(h1.masters_tree())

    h1.update(grads_like(tree, 1))
    h2.update(grads_like(tree, 1))
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), rtol=0, atol=0),
        h1.masters_tree(), h2.masters_tree())


def test_bf16_host_cast_matches_device_cast(tree):
    """The native round-to-nearest-even f32->bf16 must agree with XLA's."""
    cfg = OptimizerConfig(total_steps=10, warmup_steps=1)
    host = off.HostOffloadAdamW(cfg)
    host.init(tree)
    dev = host.device_params(jnp.bfloat16)
    expected = jax.tree.map(lambda x: jnp.asarray(x).astype(jnp.bfloat16), tree)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a, np.float32), np.asarray(b, np.float32)), dev, expected)


def test_offload_checkpoint_restores_sharded(tmp_path, devices):
    """save_offload -> load with the host's sharded abstract template: the
    restored params keep the pp sharding end to end (at 65B an unsharded
    restore would funnel whole canonical leaves through one device)."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.parallel import train_step as ts

    mcfg = LlamaConfig.tiny()
    mesh = make_mesh(MeshConfig(pp=4))
    man = StageManifest.for_config(mcfg, 4)
    stacked = ts.init_params_sharded(jax.random.PRNGKey(0), mcfg, mesh, man)

    cfg = OptimizerConfig(learning_rate=1e-2, total_steps=10, warmup_steps=1)
    host = off.HostOffloadAdamW(cfg)
    host.init(stacked)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save_offload(3, host, man, mcfg)

    template = host.abstract_tree()
    assert tuple(template["layers"]["attn"]["wq"].sharding.spec)[0] == "pp"
    restored = mgr.load_params(3, template, man)
    wq = restored["layers"]["attn"]["wq"]
    assert tuple(wq.sharding.spec)[0] == "pp"  # never funneled to one device
    np.testing.assert_array_equal(
        np.asarray(wq), np.asarray(stacked["layers"]["attn"]["wq"]))
    m, v, step_count = mgr.load_offload_moments(3, template, man)
    assert step_count == 0
    np.testing.assert_array_equal(np.asarray(m["norm"]), 0.0)


def test_mismatched_tree_raises(tree):
    cfg = OptimizerConfig(total_steps=10, warmup_steps=1)
    h = off.HostOffloadAdamW(cfg)
    h.init(tree)
    with pytest.raises(ValueError, match="does not match"):
        h.update({"a": jnp.zeros((64, 32))})


def test_device_norm_streaming_matches_host_norm(tree, devices):
    """The streaming fused step (device-side global norm, the trainer's
    default) matches the host-norm path within fp32-vs-fp64 norm-accumulation
    tolerance — WITH clipping active (grads_like's *2 against clip 1.0), so
    the grad_scale actually depends on the norm under test."""
    mesh = make_mesh(MeshConfig(pp=2, dp=2))
    shard_specs = {"a": P("pp"), "b": {"c": P()}}
    put = lambda t: jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), t, shard_specs)
    cfg = OptimizerConfig(learning_rate=1e-2, weight_decay=0.1,
                          max_grad_norm=1.0, total_steps=100, warmup_steps=10)

    h_host = off.HostOffloadAdamW(cfg)
    h_host.init(put(tree))
    h_dev = off.HostOffloadAdamW(cfg, device_norm=True)
    h_dev.init(put(tree))

    for step in range(3):
        g = put(grads_like(tree, step))
        if step == 2:  # gpipe can hand the optimizer bf16 grads: the device
            # norm must cast to fp32 before accumulating (8 mantissa bits
            # would move the clip factor ~0.4%)
            g = jax.tree.map(lambda x: x.astype(jnp.bfloat16), g)
        dev_a = h_host.update_and_refresh(g, jnp.float32)
        dev_b = h_dev.update_and_refresh(g, jnp.float32)
        assert "stream_d2h_update_h2d_ms" in h_dev.last_timings
        assert "d2h_norm_ms" in h_host.last_timings
        np.testing.assert_allclose(h_dev.last_grad_norm, h_host.last_grad_norm,
                                   rtol=1e-6)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-6, atol=1e-7),
            dev_a, dev_b)

"""Host-offloaded AdamW (C++ kernel) vs optax numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.optim import OptimizerConfig, make_optimizer
from llama_pipeline_parallel_tpu.optim import offload as off


@pytest.fixture(scope="module")
def tree():
    rng = np.random.RandomState(0)
    return {"a": jnp.asarray(rng.randn(64, 32), jnp.float32),
            "b": {"c": jnp.asarray(rng.randn(128), jnp.float32)}}


def grads_like(tree, seed):
    rng = np.random.RandomState(seed)
    return jax.tree.map(lambda x: jnp.asarray(rng.randn(*x.shape) * 2, jnp.float32), tree)


def test_native_kernel_compiles():
    assert off._load_native() is not None, "g++ compile of csrc/host_adamw.cpp failed"


@pytest.mark.parametrize("force_numpy", [False, True])
def test_matches_optax(tree, force_numpy, monkeypatch):
    if force_numpy:
        monkeypatch.setattr(off, "_lib", None)
        monkeypatch.setattr(off, "_lib_failed", True)
    cfg = OptimizerConfig(learning_rate=1e-2, weight_decay=0.1, beta1=0.9,
                          beta2=0.95, max_grad_norm=1.0, total_steps=100,
                          warmup_steps=10)
    tx, _ = make_optimizer(cfg)
    opt_state = tx.init(tree)
    params_ref = tree

    host = off.HostOffloadAdamW(cfg)
    host.init(tree)

    for step in range(5):
        g = grads_like(tree, step)
        updates, opt_state = tx.update(g, opt_state, params_ref)
        import optax

        params_ref = optax.apply_updates(params_ref, updates)
        params_host = host.update(g)

    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        params_ref, params_host)
    assert host.last_grad_norm > 0


def test_state_dict_roundtrip(tree):
    cfg = OptimizerConfig(learning_rate=1e-2, total_steps=50, warmup_steps=2)
    h1 = off.HostOffloadAdamW(cfg)
    h1.init(tree)
    h1.update(grads_like(tree, 0))
    state = h1.state_dict()

    h2 = off.HostOffloadAdamW(cfg)
    h2.init(tree)
    h2.load_state_dict(state)
    p1 = h1.update(grads_like(tree, 1))
    # h2 params must be synced to h1's before the next step for equality
    h2._params = [p.copy() for p in h1._params]
    # re-do: start both from identical params/moments
    h1b = off.HostOffloadAdamW(cfg); h1b.init(tree)
    h1b.update(grads_like(tree, 0))
    h2b = off.HostOffloadAdamW(cfg); h2b.init(tree)
    h2b.load_state_dict(h1b.state_dict())
    h2b._params = [p.copy() for p in h1b._params]
    a = h1b.update(grads_like(tree, 1))
    b = h2b.update(grads_like(tree, 1))
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), rtol=0, atol=0), a, b)


def test_mismatched_tree_raises(tree):
    cfg = OptimizerConfig(total_steps=10, warmup_steps=1)
    h = off.HostOffloadAdamW(cfg)
    h.init(tree)
    with pytest.raises(ValueError, match="does not match"):
        h.update({"a": jnp.zeros((64, 32))})

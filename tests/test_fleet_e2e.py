"""Fleet observatory chaos e2e (docs/OBSERVABILITY.md "Fleet").

The acceptance scenario: one pod — a supervised trainer plus two
supervised serve replicas serving from the trainer's checkpoint dir —
aggregated by an in-process FleetAggregator with alert rules armed.

- SIGKILL replica A mid-decode: the heartbeat-stale alert FIRES within
  the window, the firing edge drops a capture trigger into A's dir,
  the watchdog relaunches A, the relaunched process consumes the trigger
  (EXACTLY one capture lands in that member), the alert RESOLVES, and A
  serves token-identically again.
- Checkpoint lag: a second training leg writes a newer VERIFIED
  checkpoint while replica B still serves the old step — the
  checkpoint-lag alert fires; B's relaunch tails the newer checkpoint
  and the alert resolves.

Process-spawn heavy (two serve replicas + two training legs on CPU), so
slow-marked for the round gate like the other chaos e2es; the fast
aggregation/alert/tailer lanes live in tests/test_fleet.py."""

import glob
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from llama_pipeline_parallel_tpu.utils import fleet
from llama_pipeline_parallel_tpu.utils.fleet import (
    AlertRules,
    FleetAggregator,
    read_alerts,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_for_replica(out_dir: str, old_pid: int | None = None,
                      timeout_s: float = 180.0) -> dict:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(os.path.join(out_dir, "serve.json")) as f:
                info = json.load(f)
            if old_pid is not None and info["pid"] == old_pid:
                raise OSError("still the old incarnation")
            urllib.request.urlopen(
                f"http://127.0.0.1:{info['port']}/healthz", timeout=5)
            return info
        except Exception:
            time.sleep(0.25)
    raise TimeoutError(f"no live replica in {out_dir} within {timeout_s}s")


def _post(port: int, body: dict, timeout: float = 180.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return json.load(urllib.request.urlopen(req, timeout=timeout))


def _refresh_until(agg, cond, what: str, timeout_s: float = 120.0,
                   every_s: float = 0.25) -> dict:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        status = agg.refresh()
        if cond(status):
            return status
        time.sleep(every_s)
    pytest.fail(f"fleet never reached: {what}")


def _train_leg(trainer_out: str, fleet_root: str, max_steps: int) -> None:
    """One supervised training leg via the CLI (--fleet-root coverage):
    writes checkpoint-<max_steps> into trainer_out and registers the
    trainer member; a later leg resumes from the earlier checkpoint."""
    env = {**os.environ,
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    r = subprocess.run(
        [sys.executable, "tools/supervisor.py", "--output-dir", trainer_out,
         "--max-restarts", "1", "--hang-timeout-s", "600",
         "--poll-s", "0.2", "--fleet-root", fleet_root,
         "--role", "trainer", "--replica", "trainer",
         "--", sys.executable, "train.py", "--config",
         "conf/tiny_smoke.yaml", "--platform", "cpu",
         f"output_dir={trainer_out}", f"max_steps={max_steps}",
         "total_steps=4", "save_steps=0", "save_final=true",
         "logging_steps=1", "attention=exact"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, \
        f"training leg failed:\n{r.stdout[-3000:]}\n{r.stderr[-3000:]}"


@pytest.mark.slow  # two training legs + two serve replicas + kills: the
# heavyweight acceptance run, round-gate material like the other chaos e2es
def test_fleet_chaos_stale_alert_capture_and_checkpoint_lag(tmp_path):
    import supervisor  # tools/ on sys.path via conftest

    root = str(tmp_path / "fleet")
    trainer_out = str(tmp_path / "trainer")
    os.makedirs(root, exist_ok=True)

    # ---- phase 0: first training leg -> checkpoint-2 ---------------------
    _train_leg(trainer_out, root, max_steps=2)
    assert fleet.latest_verified_step(trainer_out) == 2

    replicas, sups, threads = {}, {}, {}
    agg = None
    try:
        # ---- phase 1: two supervised serve replicas off checkpoint-2 -----
        for name in ("a", "b"):
            out = str(tmp_path / name)
            cmd = [sys.executable, os.path.join(REPO, "tools", "serve.py"),
                   "--checkpoint_dir", trainer_out, "--output_dir", out,
                   "--host", "127.0.0.1", "--port", str(_free_port()),
                   "--platform", "cpu", "--max_slots", "2",
                   "--max_len", "320", "--buckets", "8",
                   "--metrics_every", "1", "--health_interval", "0.5"]
            env = dict(os.environ)
            # stretch decode steps so the kill lands mid-decode
            env["LPT_SERVE_STEP_DELAY_S"] = "0.05" if name == "a" else "0"
            sup = supervisor.Supervisor(cmd, supervisor.SupervisorConfig(
                output_dir=out, max_restarts=3, hang_timeout_s=600.0,
                grace_s=5.0, crash_loop_threshold=3, crash_loop_window_s=0.0,
                poll_s=0.2, fleet_root=root, role="serve", replica=name),
                env=env)
            t = threading.Thread(target=sup.run, daemon=True)
            t.start()
            replicas[name], sups[name], threads[name] = out, sup, t
        info = {n: _wait_for_replica(replicas[n]) for n in ("a", "b")}
        assert info["a"]["checkpoint_step"] == 2

        # the aggregator arms its rules only against a HEALTHY baseline
        # (a replica's own startup window must not pre-fire the alert
        # whose exactly-one-capture count the kill is about)
        agg = FleetAggregator(root, AlertRules(heartbeat_stale_s=2.0,
                                               checkpoint_lag_steps=1))
        status = agg.refresh()
        for member_id in ("serve:a", "serve:b", "trainer:trainer",
                          "supervisor:a", "supervisor:b"):
            assert member_id in status["members"], \
                f"{member_id} not discovered: {sorted(status['members'])}"
        assert status["members"]["serve:a"]["checkpoint_step"] == 2
        assert status["members"]["serve:a"]["checkpoint_lag"] == 0
        assert "heartbeat_stale:serve:a" not in \
            status["pod"]["alerts_firing"]

        # both replicas serve token-identically off the shared checkpoint
        body = {"input_ids": [5, 6, 7], "max_new_tokens": 4, "seed": 3}
        baseline = _post(info["a"]["port"], body)["tokens"]
        assert _post(info["b"]["port"], body)["tokens"] == baseline

        # ---- phase 2: SIGKILL replica A mid-decode -----------------------
        def doomed():
            try:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{info['a']['port']}/v1/generate",
                    data=json.dumps({"input_ids": [9, 10],
                                     "max_new_tokens": 300,
                                     "stream": True}).encode()),
                    timeout=300).read()
            except Exception:
                pass  # the point: the replica dies under it
        threading.Thread(target=doomed, daemon=True).start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            health = supervisor.read_health(replicas["a"]) or {}
            if (health.get("last_step") or 0) >= 3:
                break
            time.sleep(0.1)
        else:
            pytest.fail("replica a never started decoding")
        os.kill(info["a"]["pid"], signal.SIGKILL)

        # the stale alert fires within the window and drops the trigger
        _refresh_until(
            agg, lambda s: "heartbeat_stale:serve:a"
            in s["pod"]["alerts_firing"],
            "heartbeat_stale firing for serve:a", timeout_s=60)
        trigger = os.path.join(replicas["a"], fleet.CAPTURE_TRIGGER_NAME)
        captures = os.path.join(replicas["a"], "captures", "*")
        assert os.path.exists(trigger) or glob.glob(captures)

        # the watchdog relaunches; once the new incarnation heartbeats,
        # the alert resolves and A serves token-identically again
        new_info = _wait_for_replica(replicas["a"], old_pid=info["a"]["pid"])
        _refresh_until(
            agg, lambda s: "heartbeat_stale:serve:a"
            not in s["pod"]["alerts_firing"],
            "heartbeat_stale resolved after relaunch", timeout_s=60)
        assert _post(new_info["port"], body)["tokens"] == baseline

        # the relaunched member consumed the trigger: EXACTLY one capture
        deadline = time.monotonic() + 30
        while not glob.glob(captures) and time.monotonic() < deadline:
            time.sleep(0.25)
        assert len(glob.glob(captures)) == 1, glob.glob(captures)
        assert not os.path.exists(trigger)
        edges = [e for e in read_alerts(root)
                 if e["alert"] == "heartbeat_stale"
                 and e["member"] == "serve:a"]
        assert edges[0]["state"] == "firing"
        assert edges[-1]["state"] == "resolved"

        # ---- phase 3: checkpoint lag fires and resolves ------------------
        _train_leg(trainer_out, root, max_steps=4)  # resumes 2 -> ckpt-4
        assert fleet.latest_verified_step(trainer_out) == 4
        status = _refresh_until(
            agg, lambda s: "checkpoint_lag:serve:b"
            in s["pod"]["alerts_firing"],
            "checkpoint_lag firing for serve:b", timeout_s=60)
        assert status["members"]["serve:b"]["checkpoint_lag"] == 2
        assert status["pod"]["trainer_step"] == 4

        # B's relaunch tails the newer verified checkpoint -> resolved
        os.kill(info["b"]["pid"], signal.SIGKILL)
        status = _refresh_until(
            agg, lambda s:
            s["members"]["serve:b"].get("checkpoint_step") == 4
            and "checkpoint_lag:serve:b" not in s["pod"]["alerts_firing"],
            "checkpoint_lag resolved on the newer checkpoint",
            timeout_s=180)
        lag_edges = [e for e in read_alerts(root)
                     if e["alert"] == "checkpoint_lag"
                     and e["member"] == "serve:b"]
        assert lag_edges[0]["state"] == "firing"
        assert lag_edges[-1]["state"] == "resolved"

        # the atomic rollup on disk matches the acceptance picture
        with open(os.path.join(root, fleet.STATUS_NAME)) as f:
            on_disk = json.load(f)
        assert on_disk["members"]["serve:b"]["checkpoint_lag"] == 0
        assert on_disk["members"]["trainer:trainer"][
            "latest_verified_step"] == 4
    finally:
        for name, out in replicas.items():
            try:
                with open(os.path.join(out, "serve.json")) as f:
                    os.kill(json.load(f)["pid"], signal.SIGTERM)
            except (OSError, ValueError):
                pass
        for name, t in threads.items():
            t.join(timeout=90)
        for name, out in replicas.items():
            try:
                with open(os.path.join(out, "serve.json")) as f:
                    os.kill(json.load(f)["pid"], signal.SIGKILL)
            except (OSError, ValueError):
                pass

    # the offline story renders from the same root (degrade contract
    # exercised live: every stream has torn/append history by now)
    import fleet_report

    rep = fleet_report.build_report(root)
    assert rep["checkpoint_lag"]["trainer_step"] == 4
    members = {e["member"] for e in rep["incarnation_timeline"]}
    assert "serve:a" in members and "serve:b" in members

"""Interleaved 1F1B (virtual pipeline stages) correctness.

The schedule-parity suite the CI `schedule-parity` step runs: interleaved
loss AND gradients must match the flat 1f1b schedule BIT-exactly on the
dryrun grid topologies (pp=2 v=2, pp=4 v=2) — the two schedules reorder
only zero-padded accumulation, so any drift is a scheduling bug, not
float noise. Plus: the round-robin stacked layout's bit-exact round trip
(PR-2 checkpoints and the HF converter ride on it), the [S, v] activation
stats, the eval path, the full-trainer plumbing, and every new validation
error."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
from llama_pipeline_parallel_tpu.parallel import pipeline as pl
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny(num_hidden_layers=8)  # 8 layers: pp*v up to 8


@pytest.fixture(scope="module")
def params(cfg):
    return llama.init_params(jax.random.PRNGKey(0), cfg)


def make_batch(cfg, batch_size=8, seqlen=16, seed=42):
    rng = np.random.RandomState(seed)
    ids = rng.randint(3, cfg.vocab_size, size=(batch_size, seqlen)).astype(np.int32)
    mask = np.ones((batch_size, seqlen), np.int32)
    mask[:, -3:] = 0
    labels = ids.copy()
    labels[mask == 0] = llama.IGNORE_INDEX
    labels[:, :2] = llama.IGNORE_INDEX
    pos = np.broadcast_to(np.arange(seqlen, dtype=np.int32), (batch_size, seqlen)).copy()
    return {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.asarray(mask),
        "position_ids": jnp.asarray(pos),
        "labels": jnp.asarray(labels),
    }


def run_schedule(params, batch, cfg, pp, schedule, v=1, dp=1, tp=1, sp=1,
                 microbatches=4, chunks=1, collect_stats=False):
    mesh = make_mesh(MeshConfig(pp=pp, dp=dp, tp=tp, sp=sp))
    manifest = StageManifest.for_config(cfg, pp, virtual_stages=v)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=pp, num_microbatches=microbatches,
                             schedule=schedule, virtual_stages=v,
                             accum_chunks=chunks)
    fn = jax.jit(pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg, stacked,
                                                collect_stats=collect_stats))
    out = fn(stacked, batch)
    loss, grads = out[0], pl.unstack_stages(out[1], manifest)
    return (loss, grads, out[2]) if collect_stats else (loss, grads, None)


def assert_tree_bitexact(a, b):
    jax.tree.map(lambda x, y: np.testing.assert_array_equal(
        np.asarray(x), np.asarray(y)), a, b)


# ---------------------------------------------------------------------------
# Schedule parity: interleaved == flat, bit for bit
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("pp,v,microbatches", [
    (2, 2, 4),                  # the dryrun_multichip acceptance grid
    # one fast representative is enough for the tier-1 budget (PR 10
    # rebalance); the deeper rings / v=4 / bigger-M rows are round-gate
    pytest.param(4, 2, 4, marks=pytest.mark.slow),
    pytest.param(2, 4, 4, marks=pytest.mark.slow),   # deeper interleaving
    pytest.param(2, 2, 8, marks=pytest.mark.slow),
    pytest.param(4, 2, 8, marks=pytest.mark.slow),
])
def test_interleaved_matches_flat_bitexact(cfg, params, devices, pp, v,
                                           microbatches):
    """Loss AND unstacked gradients identical to the flat schedule: both
    accumulate each layer's per-microbatch gradients in the same order, and
    the only extra terms are exact zeros (masked vjp cotangents, the
    dynamic-slice scatter's untouched chunks)."""
    batch = make_batch(cfg, batch_size=microbatches * 2)
    l_flat, g_flat, _ = run_schedule(params, batch, cfg, pp, "1f1b",
                                     microbatches=microbatches)
    l_int, g_int, _ = run_schedule(params, batch, cfg, pp, "interleaved_1f1b",
                                   v=v, microbatches=microbatches)
    assert float(l_int) == float(l_flat)
    assert_tree_bitexact(g_int, g_flat)


@pytest.mark.parametrize("dp,tp,sp,chunks", [
    (2, 1, 1, 1),               # one fast hybrid rep (PR 10 rebalance)
    pytest.param(1, 2, 1, 1, marks=pytest.mark.slow),
    pytest.param(1, 1, 2, 1, marks=pytest.mark.slow),
    pytest.param(1, 1, 1, 2, marks=pytest.mark.slow),
])
def test_interleaved_hybrid_grids_bitexact(cfg, params, devices, dp, tp, sp,
                                           chunks):
    """Interleaving composes with dp/tp/sp sharding and chunked
    accumulation without losing the bit-exact flat equivalence (the tp head
    gating, sp label shift, and accum fold are all shared code paths)."""
    m = 4
    batch = make_batch(cfg, batch_size=dp * m * 2)
    l_flat, g_flat, _ = run_schedule(params, batch, cfg, 2, "1f1b", dp=dp,
                                     tp=tp, sp=sp, microbatches=m, chunks=chunks)
    l_int, g_int, _ = run_schedule(params, batch, cfg, 2, "interleaved_1f1b",
                                   v=2, dp=dp, tp=tp, sp=sp, microbatches=m,
                                   chunks=chunks)
    assert float(l_int) == float(l_flat)
    assert_tree_bitexact(g_int, g_flat)


@pytest.mark.slow  # PR 11: under the one interpreter this follows from the
# fast interleaved-vs-flat rep + test_pipeline's flat-vs-single-device
# anchor by transitivity; runs in the round gate
def test_interleaved_matches_single_device_reference(cfg, params, devices):
    """And the flat schedule itself is pinned to the plain forward, so the
    interleaved grads are the true ones, not merely self-consistent."""
    batch = make_batch(cfg)

    def loss(p):
        logits = llama.forward(p, batch["input_ids"], batch["attention_mask"],
                               batch["position_ids"], cfg=cfg)
        return llama.loss_fn(logits, batch["labels"])

    ref_loss, ref_grads = jax.value_and_grad(loss)(params)
    l_int, g_int, _ = run_schedule(params, batch, cfg, 4, "interleaved_1f1b",
                                   v=2, microbatches=4)
    np.testing.assert_allclose(float(l_int), float(ref_loss), rtol=1e-5)
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), rtol=2e-5, atol=1e-6), g_int, ref_grads)


@pytest.mark.parametrize("pp,microbatches", [
    # (2,4) slow since PR 11: same v1-degenerate segment structure as the
    # fast (4,2) M<S row under the one interpreter — its fast-lane slot
    # funds the solver-sequence tests (test_unit_schedule.py)
    pytest.param(2, 4, marks=pytest.mark.slow),
    (4, 2),   # M < S: the pipe never fills — pure warmup+drain masking
    pytest.param(4, 1, marks=pytest.mark.slow),   # M == 1 (sub-case of M<S)
])
def test_interleaved_v1_degenerates_to_flat(cfg, params, devices, pp,
                                            microbatches):
    """virtual_stages=1 runs the interleaved code path on the flat stacked
    layout and must still be bit-identical — the degenerate case that keeps
    the two schedules mutually testable (including M < S, where the steady
    phase shrinks to nothing and masking carries the whole schedule)."""
    batch = make_batch(cfg, batch_size=max(microbatches * 2, 2))
    l_flat, g_flat, _ = run_schedule(params, batch, cfg, pp, "1f1b",
                                     microbatches=microbatches)
    l_int, g_int, _ = run_schedule(params, batch, cfg, pp, "interleaved_1f1b",
                                   v=1, microbatches=microbatches)
    assert float(l_int) == float(l_flat)
    assert_tree_bitexact(g_int, g_flat)


def test_interleaved_eval_matches(cfg, params, devices):
    """make_pipeline_eval_fn understands the interleaved layout (the
    forward-only loop walks the v*S virtual ring)."""
    batch = make_batch(cfg)
    mesh = make_mesh(MeshConfig(pp=2))
    manifest = StageManifest.for_config(cfg, 2, virtual_stages=2)
    stacked = pl.stack_stages(params, manifest)
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=4,
                             schedule="interleaved_1f1b", virtual_stages=2)
    loss_sum, count = jax.jit(pl.make_pipeline_eval_fn(
        mesh, cfg, pcfg, stacked))(stacked, batch)
    l_flat, _, _ = run_schedule(params, batch, cfg, 2, "1f1b")
    np.testing.assert_allclose(float(loss_sum) / float(count), float(l_flat),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# Stacked layout: round-robin assignment + bit-exact round trip
# ---------------------------------------------------------------------------

def test_interleaved_stack_roundtrip_bitexact(cfg, params):
    man = StageManifest.for_config(cfg, 2, virtual_stages=2)
    rt = pl.unstack_stages(pl.stack_stages(params, man), man)
    assert_tree_bitexact(rt, params)


def test_interleaved_stack_is_round_robin(cfg, params):
    """stacked[s, j] holds exactly the layers manifest.layers_of_chunk(s, j)
    names — the layout and the manifest's layer->(stage, chunk) map agree."""
    man = StageManifest.for_config(cfg, 2, virtual_stages=2)
    stacked = pl.stack_stages(params, man)
    wq_c = np.asarray(params["layers"]["attn"]["wq"])  # [n, d, d]
    wq_s = np.asarray(stacked["layers"]["attn"]["wq"])  # [S, v, k, d, d]
    assert wq_s.shape[:3] == (2, 2, man.layers_per_chunk)
    for s in range(man.num_stages):
        for j in range(man.virtual_stages):
            layers = list(man.layers_of_chunk(s, j))
            np.testing.assert_array_equal(wq_s[s, j], wq_c[layers])
    # and the inverse maps agree with it
    for layer in range(man.num_layers):
        s, j = man.chunk_of_layer(layer)
        assert layer in list(man.layers_of_chunk(s, j))
        assert man.stage_of_layer(layer) == s
    # per-stage view: sorted union of the stage's chunks
    assert list(man.layers_of_stage(0)) == [0, 1, 4, 5]
    assert list(man.layers_of_stage(1)) == [2, 3, 6, 7]


def test_interleaved_manifest_json_roundtrip(cfg):
    man = StageManifest.for_config(cfg, 2, virtual_stages=2)
    assert StageManifest.from_json(man.to_json()) == man
    # pre-interleaving metadata (no virtual_stages key) still deserializes
    legacy = json.loads(StageManifest.for_config(cfg, 2).to_json())
    del legacy["virtual_stages"]
    assert StageManifest(**legacy).virtual_stages == 1


def test_checkpoint_roundtrips_across_schedules(cfg, params, tmp_path, devices):
    """A checkpoint written under the INTERLEAVED layout restores into the
    flat layout (and vice versa) unchanged: the canonical [num_layers, ...]
    on-disk layout is the interchange, so PR-2 checkpoints and the HF
    converter keep working with no migration."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig as LC

    man_i = StageManifest.for_config(cfg, 2, virtual_stages=2)
    man_f = StageManifest.for_config(cfg, 4)
    stacked_i = pl.stack_stages(params, man_i)

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(3, stacked_i, man_i, cfg)

    # restore the interleaved-written checkpoint into a flat pp=4 topology
    restored_f = mgr.load_params(3, pl.stack_stages(params, man_f), man_f)
    assert_tree_bitexact(pl.unstack_stages(restored_f, man_f), params)
    # and back into the interleaved layout itself
    restored_i = mgr.load_params(3, stacked_i, man_i)
    assert_tree_bitexact(restored_i, stacked_i)
    # meta carries the virtual manifest
    assert StageManifest(**mgr.load_meta(3)["manifest"]) == man_i


# ---------------------------------------------------------------------------
# Stats: [S, v] activation reductions
# ---------------------------------------------------------------------------

def test_interleaved_collect_stats_shapes(cfg, params, devices):
    _, _, stats = run_schedule(params, make_batch(cfg), cfg, 2,
                               "interleaved_1f1b", v=2, collect_stats=True)
    assert np.asarray(stats["act_absmax_per_chunk"]).shape == (2, 2)
    assert np.asarray(stats["act_rms_per_chunk"]).shape == (2, 2)
    assert np.asarray(stats["act_absmax_per_stage"]).shape == (2,)
    assert np.asarray(stats["act_rms_per_stage"]).shape == (2,)
    for v in stats.values():
        assert np.all(np.isfinite(np.asarray(v)))
        assert np.all(np.asarray(v) > 0)
    # the per-stage view is the chunk view reduced over the chunk axis
    np.testing.assert_allclose(
        np.asarray(stats["act_absmax_per_stage"]),
        np.asarray(stats["act_absmax_per_chunk"]).max(axis=1), rtol=1e-6)


def test_step_stats_flatten_chunk_axis(cfg, params, devices):
    """numerics.step_stats on the interleaved [S, v, k, ...] layout: the
    per-stage vectors keep length S and the per-layer grid flattens the
    chunk axis to [S, v*k] chunk-major slots."""
    from llama_pipeline_parallel_tpu.utils import numerics

    man = StageManifest.for_config(cfg, 2, virtual_stages=2)
    stacked = pl.stack_stages(params, man)
    stats = jax.jit(lambda p: numerics.step_stats(p, p, virtual_stages=2))(stacked)
    assert np.asarray(stats["grad_norm_per_stage"]).shape == (2,)
    assert np.asarray(stats["grad_absmax_per_layer"]).shape == (2, 4)
    assert not bool(stats["nonfinite"])
    # flat vs interleaved layouts agree on the per-stage norm (same layers
    # per stage, different slot order)
    man_f = StageManifest.for_config(cfg, 2)
    flat = jax.jit(lambda p: numerics.step_stats(p, p))(
        pl.stack_stages(params, man_f))
    # stage 0 holds layers {0,1,4,5} interleaved vs {0,1,2,3} flat — norms
    # differ; the TOTAL over stages must match exactly either way
    np.testing.assert_allclose(
        float(jnp.sum(jnp.square(stats["grad_norm_per_stage"]))),
        float(jnp.sum(jnp.square(flat["grad_norm_per_stage"]))), rtol=1e-5)


# ---------------------------------------------------------------------------
# Validation
# ---------------------------------------------------------------------------

def test_virtual_stages_require_interleaved_schedule():
    with pytest.raises(ValueError, match="interleaved_1f1b"):
        pl.PipelineConfig(num_stages=2, num_microbatches=4, virtual_stages=2)


def test_interleaved_requires_divisible_microbatches():
    with pytest.raises(ValueError, match="divisible by num_stages"):
        pl.PipelineConfig(num_stages=4, num_microbatches=6,
                          schedule="interleaved_1f1b", virtual_stages=2)
    # ...per FLUSH: chunking can break divisibility even when M satisfies it
    with pytest.raises(ValueError, match="divisible by num_stages"):
        pl.PipelineConfig(num_stages=4, num_microbatches=8, accum_chunks=4,
                          schedule="interleaved_1f1b", virtual_stages=2)


def test_interleaved_rejects_uneven_partition(cfg):
    with pytest.raises(ValueError, match="even"):
        pl.PipelineConfig(num_stages=2, num_microbatches=4,
                          schedule="interleaved_1f1b", virtual_stages=2,
                          layer_counts=(5, 3))
    with pytest.raises(ValueError, match="even partition"):
        StageManifest(num_layers=8, num_stages=2, virtual_stages=2,
                      layer_counts=(5, 3))
    with pytest.raises(ValueError, match="not divisible"):
        StageManifest(num_layers=6, num_stages=2, virtual_stages=2)


def test_layout_schedule_mismatch_fails_at_build(cfg, params, devices):
    """Flat-stacked params with an interleaved pcfg (and the converse) fail
    loudly at build time, not as a shape error inside shard_map."""
    mesh = make_mesh(MeshConfig(pp=2))
    flat = pl.stack_stages(params, StageManifest.for_config(cfg, 2))
    inter = pl.stack_stages(params,
                            StageManifest.for_config(cfg, 2, virtual_stages=2))
    pcfg_i = pl.PipelineConfig(num_stages=2, num_microbatches=4,
                               schedule="interleaved_1f1b", virtual_stages=2)
    pcfg_f = pl.PipelineConfig(num_stages=2, num_microbatches=4)
    with pytest.raises(ValueError, match="stack_stages"):
        pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg_i, flat)
    with pytest.raises(ValueError, match="virtual_stages manifest"):
        pl.make_pipeline_loss_and_grad(mesh, cfg, pcfg_f, inter)


def test_trainer_rejects_virtual_stages_without_schedule(cfg):
    from llama_pipeline_parallel_tpu.train import build_manifest

    with pytest.raises(ValueError, match="pipeline_schedule"):
        build_manifest({"virtual_stages": 2}, cfg, 2)
    with pytest.raises(ValueError, match="round-robin"):
        build_manifest({"virtual_stages": 2,
                        "pipeline_schedule": "interleaved_1f1b",
                        "stage_balance": "cost"}, cfg, 2)


# ---------------------------------------------------------------------------
# bubble_fraction: schedule x accum_chunks x virtual_stages grid
# ---------------------------------------------------------------------------

def _pcfg(schedule, s, m, c=1, v=1):
    return pl.PipelineConfig(num_stages=s, num_microbatches=m, accum_chunks=c,
                             schedule=schedule, virtual_stages=v)


@pytest.mark.parametrize("schedule,s,m,c,v,expected", [
    # flat 1f1b: 2c(S-1) / (M + 2c(S-1))
    ("1f1b", 4, 8, 1, 1, 6 / 14),
    ("1f1b", 8, 256, 1, 1, 14 / 270),
    ("1f1b", 4, 8, 2, 1, 12 / 20),
    # m per flush == 1 (m == accum_chunks): every flush is pure fill+drain
    ("1f1b", 4, 4, 4, 1, 24 / 28),
    # gpipe: c(S-1) / (M + c(S-1))
    ("gpipe", 4, 8, 1, 1, 3 / 11),
    ("gpipe", 4, 8, 4, 1, 12 / 20),
    ("gpipe", 4, 4, 4, 1, 12 / 16),
    # interleaved: c(S-1) / (Mv + c(S-1))
    ("interleaved_1f1b", 4, 8, 1, 2, 3 / 19),
    ("interleaved_1f1b", 8, 256, 1, 2, 7 / 519),
    ("interleaved_1f1b", 4, 8, 2, 2, 6 / 22),
    ("interleaved_1f1b", 4, 8, 1, 1, 3 / 11),
    ("interleaved_1f1b", 2, 8, 4, 4, 4 / 36),
    # m per flush == accum chunks degenerate under interleaving: flush m=S
    ("interleaved_1f1b", 2, 4, 2, 2, 2 / 10),
    # zb1 (split B/W backward): 2c(S-1) / (3Mv + 2c(S-1)) — unit terms,
    # F=B=W (docs/SCHEDULES.md; test_zero_bubble.py pins the derivation
    # and the zb1 <= interleaved <= flat ordering across the grid)
    ("zb1", 4, 8, 1, 2, 6 / 54),
    ("zb1", 8, 256, 1, 2, 14 / 1550),   # the 65B shape: 0.90% vs 1.35%
    ("zb1", 4, 8, 2, 2, 12 / 60),
    ("zb1", 4, 8, 1, 1, 6 / 30),        # flat zero-bubble form
    ("zb1", 2, 4, 2, 2, 4 / 28),        # m per flush == accum chunks
    ("zb1", 4, 2, 1, 1, 6 / 12),        # M < S
    # S=1: no pipeline, no bubble, any schedule/chunking/interleaving
    ("1f1b", 1, 8, 1, 1, 0.0),
    ("1f1b", 1, 8, 8, 1, 0.0),
    ("gpipe", 1, 8, 2, 1, 0.0),
    ("interleaved_1f1b", 1, 8, 1, 4, 0.0),
    ("zb1", 1, 8, 1, 4, 0.0),
])
def test_bubble_fraction_grid(schedule, s, m, c, v, expected):
    assert pl.bubble_fraction(_pcfg(schedule, s, m, c, v)) == pytest.approx(expected)


def test_bubble_fraction_interleaved_reduction():
    """The acceptance claim: at the same (S, m), interleaving with v chunks
    cuts the reported bubble by >= v (measured ~2v for m >> S: v from the
    shorter fill, 2 from the fwd-only/bwd-only phase pairing)."""
    for s, m in [(2, 4), (4, 8), (8, 256)]:
        flat = pl.bubble_fraction(_pcfg("1f1b", s, m))
        for v in (2, 4):
            if m % s:
                continue
            inter = pl.bubble_fraction(
                _pcfg("interleaved_1f1b", s, m, v=v))
            assert inter <= flat / v, (s, m, v, flat, inter)


def test_bubble_fraction_monotone_in_v():
    vals = [pl.bubble_fraction(_pcfg("interleaved_1f1b", 4, 8, v=v))
            for v in (1, 2, 4, 8)]
    assert vals == sorted(vals, reverse=True)
    assert all(0.0 < b < 1.0 for b in vals)


# ---------------------------------------------------------------------------
# Full-trainer plumbing (the CI schedule-parity gate's artifact producer)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # PR 14 rebalance: since PR 11 the trainer runs every
# schedule through ONE unit interpreter, and the Observatory timeline e2e
# exercises that trainer path (zb1-v2) every fast run — the interleaved
# parity reps above keep this schedule's fast coverage
def test_trainer_interleaved_end_to_end(tmp_path, devices):
    """run_training with schedule: interleaved_1f1b + virtual_stages: 2 —
    metrics carry the interleaved bubble_fraction, numerics.jsonl resolves
    activations per [S, v] chunk, and the final loss matches the flat
    schedule bit-for-bit.

    Both runs warm-start from ONE canonical-layout checkpoint (the PR-2
    format; written here with a flat manifest, restored into both layouts):
    fresh inits go through `init_params_sharded`, whose in-jit RNG draws are
    sharding-LAYOUT-dependent (a pre-existing quirk of partitioned threefry,
    not a schedule property), so identical weights — the real 65B warm-start
    situation — are the honest baseline for schedule equality."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.train import run_training

    model_cfg = LlamaConfig.tiny(dtype=jnp.float32)
    man = StageManifest.for_config(model_cfg, 2)
    warm_dir = str(tmp_path / "warm")
    CheckpointManager(warm_dir).save(
        0, pl.stack_stages(llama.init_params(jax.random.PRNGKey(7), model_cfg),
                           man), man, model_cfg)

    def cfg_for(out, **kw):
        base = {
            "output_dir": str(tmp_path / out),
            "mesh": {"pp": 2, "dp": 2},
            "model": {"preset": "tiny", "dtype": "float32"},
            "model_name_or_path": warm_dir,
            "dataset": {"synthetic": True, "seq_length": 16,
                        "pseudo_dataset_len": 128},
            "seed": 7,
            "per_device_train_batch_size": 2,
            "gradient_accumulation_steps": 2,
            "max_steps": 3,
            "learning_rate": 1e-3,
            "warmup_steps": 1,
            "logging_steps": 1,
            "save_steps": 0,
            "save_final": False,
        }
        base.update(kw)
        return base

    flat = run_training(cfg_for("flat"))
    inter = run_training(cfg_for("inter", pipeline_schedule="interleaved_1f1b",
                                 virtual_stages=2))
    assert inter["final_loss"] == flat["final_loss"]

    lines = [json.loads(l) for l in
             open(os.path.join(str(tmp_path / "inter"), "metrics.jsonl"))]
    pcfg = pl.PipelineConfig(num_stages=2, num_microbatches=2,
                             schedule="interleaved_1f1b", virtual_stages=2)
    assert lines[0]["bubble_fraction"] == round(pl.bubble_fraction(pcfg), 4)
    flat_lines = [json.loads(l) for l in
                  open(os.path.join(str(tmp_path / "flat"), "metrics.jsonl"))]
    assert lines[0]["bubble_fraction"] < flat_lines[0]["bubble_fraction"]

    nrec = [json.loads(l) for l in
            open(os.path.join(str(tmp_path / "inter"), "numerics.jsonl"))]
    per_chunk = np.asarray(nrec[0]["act_rms_per_chunk"])
    assert per_chunk.shape == (2, 2) and np.all(per_chunk > 0)


@pytest.mark.slow
def test_trainer_interleaved_offload_zero2(tmp_path, devices):
    """The 65B run-of-record combination (conf/llama_65b_pp8_v2_tp2_dp2.yaml):
    interleaved 1F1B under the ZeRO-2 host-offloaded optimizer — the
    [S, v, k, ...] layout must stream through host masters/moments, the
    dp-sharded grad outputs, and the numerics stats dispatch. Slow-marked
    (PR 10 rebalance): the plain interleaved trainer e2e stays fast, and
    test_trainer/test_offload keep the zero2 machinery's own fast gates."""
    from llama_pipeline_parallel_tpu.train import run_training

    summary = run_training({
        "output_dir": str(tmp_path / "out"),
        "mesh": {"pp": 2, "dp": 2},
        "model": {"preset": "tiny", "dtype": "float32"},
        "dataset": {"synthetic": True, "seq_length": 16,
                    "pseudo_dataset_len": 128},
        "seed": 7,
        "per_device_train_batch_size": 2,
        "gradient_accumulation_steps": 2,
        "pipeline_schedule": "interleaved_1f1b",
        "virtual_stages": 2,
        "optimizer_offload": True,
        "optimizer_offload_zero2": True,
        "max_steps": 2,
        "learning_rate": 1e-3,
        "warmup_steps": 1,
        "logging_steps": 1,
        "save_steps": 0,
        "save_final": True,
    })
    assert summary["final_step"] == 2
    assert np.isfinite(summary["final_loss"])
    # the offload checkpoint wrote the canonical layout via the interleaved
    # manifest (save_offload -> unstack_stages)
    meta = json.load(open(os.path.join(str(tmp_path / "out"),
                                       "checkpoint-2", "meta.json")))
    assert meta["manifest"]["virtual_stages"] == 2
    assert meta["opt_layout"] == "offload_parts"

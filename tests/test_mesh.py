import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from llama_pipeline_parallel_tpu.utils.compat import shard_map

from llama_pipeline_parallel_tpu.parallel import mesh as mesh_lib
from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh


def test_mesh_shapes(devices):
    m = make_mesh(MeshConfig(pp=4, dp=2))
    assert m.shape == {"pp": 4, "dp": 2, "sp": 1, "tp": 1}
    m2 = make_mesh(MeshConfig(pp=2, dp=2, tp=2))
    assert m2.shape["tp"] == 2


def test_from_world():
    cfg = MeshConfig.from_world(8, pp=4)
    assert cfg.dp == 2 and cfg.world_size == 8
    with pytest.raises(ValueError):
        MeshConfig.from_world(6, pp=4)


def test_too_many_devices(devices):
    with pytest.raises(ValueError):
        make_mesh(MeshConfig(pp=16))


def test_ep_axis_is_a_reserved_hook():
    """SURVEY §2.2: the expert-parallel axis NAME exists for a future MoE
    block, but sharding over it is rejected until one does."""
    assert mesh_lib.AXIS_EP == "ep"
    assert MeshConfig(ep=1).world_size == 1  # accepted, inert
    with pytest.raises(NotImplementedError, match="expert parallelism"):
        MeshConfig(ep=2)


def test_stage_index_inside_shard_map(devices):
    m = make_mesh(MeshConfig(pp=4, dp=2))

    def f():
        return (
            mesh_lib.stage_index()[None],
            mesh_lib.dp_index()[None],
            mesh_lib.is_last_stage()[None],
        )

    sm = shard_map(
        f, mesh=m, in_specs=(), out_specs=(P("pp"), P("dp"), P("pp")), check_vma=False
    )
    stages, dps, last = jax.jit(sm)()
    np.testing.assert_array_equal(np.asarray(stages), [0, 1, 2, 3])
    np.testing.assert_array_equal(np.asarray(dps), [0, 1])
    np.testing.assert_array_equal(np.asarray(last), [False, False, False, True])


def test_underuse_warning_once_per_layout(devices):
    """The 'mesh uses N of M devices' warning fires once per distinct
    layout, not on every mesh build (it used to repeat dozens of times in a
    dryrun sweep — MULTICHIP_r05)."""
    import logging

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    layout_a = MeshConfig(pp=3)
    layout_b = MeshConfig(dp=3)
    # hermetic: an earlier build of these layouts (or an in-process re-run
    # of this test) must not pre-latch the warn-once set
    mesh_lib._UNDERUSE_WARNED.discard((3, 8, 3, 1, 1, 1))
    mesh_lib._UNDERUSE_WARNED.discard((3, 8, 1, 3, 1, 1))

    handler = Capture(level=logging.WARNING)
    logger = logging.getLogger("llama_pipeline_parallel_tpu.parallel.mesh")
    logger.addHandler(handler)
    try:
        def warnings_for(cfg):
            records.clear()
            make_mesh(cfg)
            return [m for m in records if "available devices" in m]

        assert len(warnings_for(layout_a)) == 1
        assert len(warnings_for(layout_a)) == 0   # repeat build: silent
        assert len(warnings_for(layout_b)) == 1   # a NEW layout still warns
        assert len(warnings_for(layout_b)) == 0
    finally:
        logger.removeHandler(handler)

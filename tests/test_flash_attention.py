"""Flash attention vs the exact reference path (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.ops import flash_attention as fa
from llama_pipeline_parallel_tpu.ops.attention import attention


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setattr(fa, "_INTERPRET", True)


def rand_qkv(b=2, sq=128, skv=128, h=4, h_kv=None, hd=32, seed=0):
    rng = np.random.RandomState(seed)
    h_kv = h_kv or h
    q = jnp.asarray(rng.randn(b, sq, h, hd), jnp.float32)
    k = jnp.asarray(rng.randn(b, skv, h_kv, hd), jnp.float32)
    v = jnp.asarray(rng.randn(b, skv, h_kv, hd), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("h_kv", [4, 2])
@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(h_kv, causal):
    q, k, v = rand_qkv(h_kv=h_kv)
    ref = attention(q, k, v, None, causal=causal)
    out = fa.flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("h_kv", [4, 2])
def test_gradients_match_reference(h_kv):
    q, k, v = rand_qkv(sq=64, skv=64, h_kv=h_kv, hd=16)

    def loss_ref(q, k, v):
        return (attention(q, k, v, None, causal=True) ** 2).sum()

    def loss_fa(q, k, v):
        return (fa.flash_attention(q, k, v, causal=True,
                                   block_q=32, block_k=32) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fa = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_fa, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3, err_msg=f"d{name}")


def test_offsets_slice_of_larger_causal():
    """q/kv offsets reproduce a slab of a bigger causal computation — the
    contract ring attention depends on."""
    q, k, v = rand_qkv(b=1, sq=128, skv=128, hd=16)
    full = attention(q, k, v, None, causal=True)
    # second half of queries against first half of keys: fully visible slab
    out = fa.flash_attention(q[:, 64:], k[:, :64], v[:, :64],
                             causal=True, q_offset=64, kv_offset=0,
                             block_q=32, block_k=32)
    # compare against reference with same offsets
    ref = attention(q[:, 64:], k[:, :64], v[:, :64], None, causal=True,
                    q_offset=64, kv_offset=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_fully_masked_rows_are_zero_not_nan():
    """kv entirely in the future -> empty softmax rows must yield 0, not NaN."""
    q, k, v = rand_qkv(b=1, sq=32, skv=32, hd=16)
    out = fa.flash_attention(q, k, v, causal=True, q_offset=0, kv_offset=1000,
                             block_q=32, block_k=32)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_array_equal(np.asarray(out), 0.0)


def test_right_padding_equivalence_through_loss():
    """flash (no mask) and reference (masked) agree on the loss with
    right-padded batches — the property the training path relies on."""
    from llama_pipeline_parallel_tpu.models.llama import model as llama
    from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(3, cfg.vocab_size, (2, 32)), jnp.int32)
    mask = np.ones((2, 32), np.int32)
    mask[:, -8:] = 0
    labels = np.asarray(ids).copy()
    labels[mask == 0] = llama.IGNORE_INDEX
    mask, labels = jnp.asarray(mask), jnp.asarray(labels)

    def fa_fn(q, k, v, pad, **kw):
        return fa.flash_attention(q, k, v, pad, block_q=32, block_k=32,
                                  **{k_: v_ for k_, v_ in kw.items()
                                     if k_ in ("causal", "q_offset", "kv_offset")})

    loss_ref = llama.loss_fn(llama.forward(params, ids, mask, cfg=cfg), labels)
    loss_fa = llama.loss_fn(llama.forward(params, ids, mask, cfg=cfg, attn_fn=fa_fn), labels)
    np.testing.assert_allclose(float(loss_fa), float(loss_ref), rtol=1e-5)


def test_bad_block_divisibility():
    q, k, v = rand_qkv(sq=100, skv=100)
    with pytest.raises(ValueError, match="divisible"):
        fa.flash_attention(q, k, v, block_q=64, block_k=64)


def test_auto_block_selection():
    """Adaptive tiling (round-3 verdict #5): the largest 128-aligned block
    <= 1024 that divides the length; tiling blocks and short sequences pass
    through unchanged."""
    assert fa._auto_block(2048) == 1024
    assert fa._auto_block(1536) == 768   # largest 128-multiple dividing 1536
    assert fa._auto_block(1536 // 4) == 384  # < 1024: clamps to the length
    assert fa._auto_block(1280) == 640
    assert fa._auto_block(512) == 512
    assert fa._auto_block(100) == 100
    assert fa._auto_block(1537) == 128   # nothing divides; _block_sizes raises


def test_seq_1536_runs_flash_with_adaptive_blocks():
    """seq 1536 (not a 1024 multiple — the round-3 silent fallback case) now
    tiles with auto-selected 768 blocks: fwd + grads parity vs exact."""
    q, k, v = rand_qkv(b=1, sq=1536, skv=1536, h=1, hd=8)
    ref = attention(q, k, v, None, causal=True)
    out = fa.flash_attention(q, k, v, causal=True)  # blocks auto-selected
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    g_ref = jax.grad(lambda q: (attention(q, k, v, None, causal=True) ** 2).sum())(q)
    g_fa = jax.grad(lambda q: (fa.flash_attention(q, k, v, causal=True) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g_fa), np.asarray(g_ref),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("seq", [512, 384])
def test_short_sequences_tile_with_default_blocks(seq):
    """The kernel's real divisibility rule: blocks CLAMP to the sequence, so
    the bench workload (seq 512, the reference's shape, conf yaml:32) and any
    sub-1024 length run with the DEFAULT block sizes — the gate train.py's
    `auto` previously over-restricted (VERDICT weak #4). fwd + grads parity."""
    q, k, v = rand_qkv(b=1, sq=seq, skv=seq, h=2, hd=16)
    ref = attention(q, k, v, None, causal=True)
    out = fa.flash_attention(q, k, v, causal=True)  # default 1024 blocks clamp
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    g_ref = jax.grad(lambda q: (attention(q, k, v, None, causal=True) ** 2).sum())(q)
    g_fa = jax.grad(lambda q: (fa.flash_attention(q, k, v, causal=True) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g_fa), np.asarray(g_ref),
                               rtol=2e-3, atol=2e-3)


def test_select_attention_tiling_rule(devices):
    """`auto` applies the adaptive-block rule against the per-slab length."""
    from llama_pipeline_parallel_tpu.parallel.mesh import MeshConfig, make_mesh
    from llama_pipeline_parallel_tpu.train import select_attention

    mesh = make_mesh(MeshConfig(sp=4))
    # CPU mesh -> always exact, but the call must accept every shape/strategy
    # including the previously-rejected non-1024-multiple slabs (6144/sp=4 ->
    # 1536-long ring slabs now tile with 768 blocks)
    for seq, strategy in ((512, "ring"), (4096, "ring"), (6144, "ring"),
                          (1536, "ulysses"), (6144, "ulysses")):
        assert select_attention("auto", seq, mesh, strategy) is attention
    assert select_attention("flash", 512, mesh) is fa.flash_attention


def test_measure_attention_packed_shapes(devices):
    """The auto measurement runs at the REAL (microbatch, seq) shape with
    segment streams when packed (round-3 weak #6: it used to time batch=1
    unpacked and could pick the wrong winner for packed runs): exercise the
    measurement path end to end on CPU and check the cache keys by shape."""
    from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
    from llama_pipeline_parallel_tpu.train import (
        _AUTO_ATTN_CACHE,
        _measure_attention,
        _measure_segments,
    )

    seg = np.asarray(_measure_segments(2, 32))
    assert seg.shape == (2, 32)
    # 4 equal segments AND a genuine pad tail (the kernels' segment-0 skip
    # path must be part of the timing)
    assert set(np.unique(seg)) == {0, 1, 2, 3, 4}
    monotone_then_pad = seg[:, :-8]
    assert (np.diff(monotone_then_pad, axis=1) >= 0).all()
    assert (seg[:, -2:] == 0).all()

    cfg = LlamaConfig.tiny()
    _AUTO_ATTN_CACHE.clear()
    winner = _measure_attention(cfg, 32, micro_batch=2, packed=True)
    assert winner in (attention, fa.flash_attention)
    assert (32, 2, True, cfg.num_attention_heads, cfg.kv_heads,
            cfg.head_dim) in _AUTO_ATTN_CACHE
    # distinct shapes measure independently (packed and unpacked never share)
    _measure_attention(cfg, 32, micro_batch=2, packed=False)
    assert len(_AUTO_ATTN_CACHE) == 2

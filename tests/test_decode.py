"""KV-cache generation: parity with the cache-free forward, padding
invariance, and eos semantics (models/llama/decode.py).

The reference has NO predict/generate path (its prediction_cfg names an
absent class, reference conf yaml:107-115; SURVEY.md §2.4) — these tests pin
the surface this framework adds in its place.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.decode import (
    GenerationConfig,
    generate,
)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def greedy_no_cache(params, cfg, ids, mask, n_new):
    """Reference decoder: full forward over the growing sequence each step."""
    ids = np.asarray(ids)
    mask = np.asarray(mask)
    out = []
    for _ in range(n_new):
        positions = np.clip(np.cumsum(mask, axis=1) - 1, 0, None)
        logits = llama.forward(params, jnp.asarray(ids), jnp.asarray(mask),
                               jnp.asarray(positions.astype(np.int32)), cfg=cfg)
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1), np.int32)
        out.append(nxt)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
        mask = np.concatenate([mask, np.ones_like(nxt[:, None])], axis=1)
    return np.stack(out, axis=1)  # [b, n_new]


@pytest.mark.slow
def test_greedy_matches_cache_free_forward(setup):
    cfg, params = setup
    rng = np.random.RandomState(0)
    ids = rng.randint(3, cfg.vocab_size, (2, 7)).astype(np.int32)
    mask = np.ones_like(ids)
    gen = GenerationConfig(max_new_tokens=6)

    got = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, gen)
    want = greedy_no_cache(params, cfg, ids, mask, 6)
    np.testing.assert_array_equal(np.asarray(got["tokens"]), want)


def test_left_padded_batch_matches_unpadded_rows(setup):
    """Rows of different prompt lengths, left-padded together, generate the
    same tokens as each row alone — padding must be invisible."""
    cfg, params = setup
    rng = np.random.RandomState(1)
    a = rng.randint(3, cfg.vocab_size, (1, 5)).astype(np.int32)
    b = rng.randint(3, cfg.vocab_size, (1, 8)).astype(np.int32)
    gen = GenerationConfig(max_new_tokens=5)

    pad_a = np.concatenate([np.zeros((1, 3), np.int32), a], axis=1)
    batch_ids = np.concatenate([pad_a, b], axis=0)
    batch_mask = np.asarray([[0] * 3 + [1] * 5, [1] * 8], np.int32)

    together = np.asarray(generate(params, jnp.asarray(batch_ids),
                                   jnp.asarray(batch_mask), cfg, gen)["tokens"])
    alone_a = np.asarray(generate(params, jnp.asarray(a),
                                  jnp.asarray(np.ones_like(a)), cfg, gen)["tokens"])
    alone_b = np.asarray(generate(params, jnp.asarray(b),
                                  jnp.asarray(np.ones_like(b)), cfg, gen)["tokens"])
    np.testing.assert_array_equal(together[0:1], alone_a)
    np.testing.assert_array_equal(together[1:2], alone_b)


def test_eos_stops_row_and_pads(setup):
    """After a row emits eos, it emits pad_token_id; `done` reports it."""
    cfg, params = setup
    rng = np.random.RandomState(2)
    ids = rng.randint(3, cfg.vocab_size, (1, 4)).astype(np.int32)
    mask = np.ones_like(ids)

    free = np.asarray(generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg,
                               GenerationConfig(max_new_tokens=8))["tokens"])[0]
    eos = int(free[0])  # the first generated token becomes "eos"
    got = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg,
                   GenerationConfig(max_new_tokens=8, eos_token_id=eos,
                                    pad_token_id=17))
    toks = np.asarray(got["tokens"])[0]
    assert toks[0] == eos  # the eos token itself is emitted
    assert (toks[1:] == 17).all()
    assert bool(np.asarray(got["done"])[0])


def test_generate_with_tp_sharded_params(setup, devices):
    """Generation needs no shard_map: Megatron-sharding the params over a tp
    mesh and calling the same jitted generate() lets GSPMD insert the
    collectives — tokens match the unsharded run exactly. (How a model too
    big for one chip serves: shard, same code.)"""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cfg, params = setup
    ids = np.random.RandomState(5).randint(3, cfg.vocab_size, (2, 6)).astype(np.int32)
    mask = np.ones_like(ids)
    gen = GenerationConfig(max_new_tokens=5)
    ref = np.asarray(generate(params, jnp.asarray(ids), jnp.asarray(mask),
                              cfg, gen)["tokens"])

    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(4), ("tp",))
    col, row = P(None, None, "tp"), P(None, "tp", None)
    specs = jax.tree.map(lambda _: P(), params)
    specs["layers"]["attn"] = {"wq": col, "wk": col, "wv": col, "wo": row}
    specs["layers"]["mlp"] = {"gate": col, "up": col, "down": row}
    specs["lm_head"] = P(None, "tp")
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)
    out = np.asarray(generate(sharded, jnp.asarray(ids), jnp.asarray(mask),
                              cfg, gen)["tokens"])
    np.testing.assert_array_equal(out, ref)


def test_generate_tool_end_to_end(setup, tmp_path):
    """tools/generate.py: checkpoint + tokenizer on disk -> decoded text."""
    import argparse

    from tokenizers import SentencePieceUnigramTokenizer
    from transformers import PreTrainedTokenizerFast

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.parallel.pipeline import stack_stages
    from tools import generate as gen_tool

    _, params = setup
    # the checkpoint meta pins the vocab size; train the tokenizer to match
    spm = SentencePieceUnigramTokenizer()
    spm.train_from_iterator(["the quick brown fox jumps over the lazy dog"] * 8,
                            vocab_size=40, unk_token="<unk>",
                            special_tokens=["<unk>", "<s>", "</s>"])
    tok = PreTrainedTokenizerFast(tokenizer_object=spm._tokenizer,
                                  bos_token="<s>", eos_token="</s>",
                                  unk_token="<unk>")
    cfg_small = LlamaConfig.tiny(vocab_size=tok.vocab_size)
    params_small = llama.init_params(jax.random.PRNGKey(0), cfg_small)

    ckpt_dir = str(tmp_path / "ckpt")
    manifest = StageManifest.for_config(cfg_small, 1)
    CheckpointManager(ckpt_dir).save(
        0, stack_stages(params_small, manifest), manifest, cfg_small)
    tok.save_pretrained(ckpt_dir)

    texts = gen_tool.run(argparse.Namespace(
        checkpoint_dir=ckpt_dir, tokenizer_path=None, step=None,
        prompt=["the quick brown", "dog"], max_new_tokens=4,
        temperature=0.0, top_k=0, seed=0))
    assert len(texts) == 2 and all(isinstance(t, str) for t in texts)


def test_sampling_seeded_and_in_vocab(setup):
    """Temperature sampling is deterministic under a fixed key and emits
    valid token ids; top_k restricts support."""
    cfg, params = setup
    ids = np.random.RandomState(3).randint(3, cfg.vocab_size, (2, 5)).astype(np.int32)
    mask = np.ones_like(ids)
    gen = GenerationConfig(max_new_tokens=4, temperature=0.8, top_k=5)

    r1 = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, gen,
                  rng=jax.random.PRNGKey(7))
    r2 = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, gen,
                  rng=jax.random.PRNGKey(7))
    t1 = np.asarray(r1["tokens"])
    np.testing.assert_array_equal(t1, np.asarray(r2["tokens"]))
    assert ((t1 >= 0) & (t1 < cfg.vocab_size)).all()


def test_top_p_sampling_seeded_and_in_vocab(setup):
    """Nucleus sampling: deterministic under a fixed key, in-vocab, and a
    top_p below the top token's own probability degrades to greedy (the
    filter always keeps the argmax)."""
    cfg, params = setup
    ids = np.random.RandomState(6).randint(3, cfg.vocab_size, (2, 5)).astype(np.int32)
    mask = np.ones_like(ids)
    gen = GenerationConfig(max_new_tokens=4, temperature=0.9, top_p=0.8)

    r1 = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, gen,
                  rng=jax.random.PRNGKey(11))
    r2 = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, gen,
                  rng=jax.random.PRNGKey(11))
    t1 = np.asarray(r1["tokens"])
    np.testing.assert_array_equal(t1, np.asarray(r2["tokens"]))
    assert ((t1 >= 0) & (t1 < cfg.vocab_size)).all()

    # a vanishingly small nucleus leaves only the argmax: greedy, any key
    tiny = GenerationConfig(max_new_tokens=4, temperature=0.9, top_p=1e-9)
    nucleus = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, tiny,
                       rng=jax.random.PRNGKey(3))
    greedy = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg,
                      GenerationConfig(max_new_tokens=4))
    np.testing.assert_array_equal(np.asarray(nucleus["tokens"]),
                                  np.asarray(greedy["tokens"]))

    with pytest.raises(ValueError):
        GenerationConfig(top_p=0.0)
    with pytest.raises(ValueError):
        GenerationConfig(top_p=1.5)


def test_max_new_tokens_one_empty_scan(setup):
    """max_new_tokens=1: the decode scan is empty; the single token is the
    prefill-sampled one (argmax of the cache-free forward's last logits)."""
    cfg, params = setup
    rng = np.random.RandomState(4)
    ids = rng.randint(3, cfg.vocab_size, (2, 6)).astype(np.int32)
    mask = np.ones_like(ids)

    got = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg,
                   GenerationConfig(max_new_tokens=1))
    want = greedy_no_cache(params, cfg, ids, mask, 1)
    assert np.asarray(got["tokens"]).shape == (2, 1)
    np.testing.assert_array_equal(np.asarray(got["tokens"]), want)
    assert not np.asarray(got["done"]).any()  # no eos configured


def test_eos_none_runs_full_budget(setup):
    """eos_token_id=None: no row ever finishes early, done stays False, and
    every budgeted token is a real sample (no pad substitution)."""
    cfg, params = setup
    ids = np.random.RandomState(8).randint(3, cfg.vocab_size, (2, 4)).astype(np.int32)
    mask = np.ones_like(ids)
    got = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg,
                   GenerationConfig(max_new_tokens=6, eos_token_id=None))
    toks = np.asarray(got["tokens"])
    assert toks.shape == (2, 6)
    assert not np.asarray(got["done"]).any()
    assert ((toks >= 0) & (toks < cfg.vocab_size)).all()


def test_all_pad_row_stays_finite(setup):
    """A fully-padded row (mask all zero) must not poison the batch: its
    own tokens are garbage-but-valid ids, and the REAL row generates
    exactly what it generates alone."""
    cfg, params = setup
    rng = np.random.RandomState(9)
    real = rng.randint(3, cfg.vocab_size, (1, 5)).astype(np.int32)
    ids = np.concatenate([np.zeros((1, 5), np.int32), real], axis=0)
    mask = np.asarray([[0] * 5, [1] * 5], np.int32)
    gen = GenerationConfig(max_new_tokens=4)

    got = np.asarray(generate(params, jnp.asarray(ids), jnp.asarray(mask),
                              cfg, gen)["tokens"])
    assert ((got >= 0) & (got < cfg.vocab_size)).all()
    alone = np.asarray(generate(params, jnp.asarray(real),
                                jnp.asarray(np.ones_like(real)), cfg,
                                gen)["tokens"])
    np.testing.assert_array_equal(got[1:2], alone)


def test_train_checkpoint_to_serve_handoff(setup, tmp_path):
    """Train->serve handoff: a TRAINING checkpoint (stacked pp=2 layout)
    loads through load_module_checkpoint (unstack_stages + manifest) and
    generates valid tokens — no conversion step between the workloads."""
    from llama_pipeline_parallel_tpu.ckpt.checkpoint import (
        CheckpointManager,
        load_module_checkpoint,
    )
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.parallel.pipeline import stack_stages

    cfg, params = setup
    manifest = StageManifest.for_config(cfg, 2)
    CheckpointManager(str(tmp_path)).save(
        3, stack_stages(params, manifest), manifest, cfg)

    loaded, loaded_cfg, _, step = load_module_checkpoint(str(tmp_path))
    assert step == 3 and loaded_cfg.vocab_size == cfg.vocab_size
    ids = np.random.RandomState(10).randint(3, cfg.vocab_size, (2, 5)).astype(np.int32)
    mask = np.ones_like(ids)
    gen = GenerationConfig(max_new_tokens=4)
    got = np.asarray(generate(loaded, jnp.asarray(ids), jnp.asarray(mask),
                              loaded_cfg, gen)["tokens"])
    assert ((got >= 0) & (got < cfg.vocab_size)).all()
    # the checkpoint round trip is the identity: tokens match the source
    want = np.asarray(generate(params, jnp.asarray(ids), jnp.asarray(mask),
                               cfg, gen)["tokens"])
    np.testing.assert_array_equal(got, want)


def test_generate_tool_bucketing():
    """tools/generate.py pads prompts to a BUCKET length so distinct prompt
    lengths reuse one compiled shape."""
    from generate import DEFAULT_BUCKETS, bucket_length  # tools/ on sys.path

    assert bucket_length(1) == DEFAULT_BUCKETS[0]
    assert bucket_length(16) == 16
    assert bucket_length(17) == 32
    assert bucket_length(1000) == 1024
    # past the largest bucket: fall back to the exact length
    assert bucket_length(5000) == 5000
    assert bucket_length(9, buckets=(4, 12)) == 12

"""KV-cache generation: parity with the cache-free forward, padding
invariance, and eos semantics (models/llama/decode.py).

The reference has NO predict/generate path (its prediction_cfg names an
absent class, reference conf yaml:107-115; SURVEY.md §2.4) — these tests pin
the surface this framework adds in its place.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llama_pipeline_parallel_tpu.models.llama import model as llama
from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
from llama_pipeline_parallel_tpu.models.llama.decode import (
    GenerationConfig,
    generate,
)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def greedy_no_cache(params, cfg, ids, mask, n_new):
    """Reference decoder: full forward over the growing sequence each step."""
    ids = np.asarray(ids)
    mask = np.asarray(mask)
    out = []
    for _ in range(n_new):
        positions = np.clip(np.cumsum(mask, axis=1) - 1, 0, None)
        logits = llama.forward(params, jnp.asarray(ids), jnp.asarray(mask),
                               jnp.asarray(positions.astype(np.int32)), cfg=cfg)
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1), np.int32)
        out.append(nxt)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
        mask = np.concatenate([mask, np.ones_like(nxt[:, None])], axis=1)
    return np.stack(out, axis=1)  # [b, n_new]


@pytest.mark.slow
def test_greedy_matches_cache_free_forward(setup):
    cfg, params = setup
    rng = np.random.RandomState(0)
    ids = rng.randint(3, cfg.vocab_size, (2, 7)).astype(np.int32)
    mask = np.ones_like(ids)
    gen = GenerationConfig(max_new_tokens=6)

    got = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, gen)
    want = greedy_no_cache(params, cfg, ids, mask, 6)
    np.testing.assert_array_equal(np.asarray(got["tokens"]), want)


def test_left_padded_batch_matches_unpadded_rows(setup):
    """Rows of different prompt lengths, left-padded together, generate the
    same tokens as each row alone — padding must be invisible."""
    cfg, params = setup
    rng = np.random.RandomState(1)
    a = rng.randint(3, cfg.vocab_size, (1, 5)).astype(np.int32)
    b = rng.randint(3, cfg.vocab_size, (1, 8)).astype(np.int32)
    gen = GenerationConfig(max_new_tokens=5)

    pad_a = np.concatenate([np.zeros((1, 3), np.int32), a], axis=1)
    batch_ids = np.concatenate([pad_a, b], axis=0)
    batch_mask = np.asarray([[0] * 3 + [1] * 5, [1] * 8], np.int32)

    together = np.asarray(generate(params, jnp.asarray(batch_ids),
                                   jnp.asarray(batch_mask), cfg, gen)["tokens"])
    alone_a = np.asarray(generate(params, jnp.asarray(a),
                                  jnp.asarray(np.ones_like(a)), cfg, gen)["tokens"])
    alone_b = np.asarray(generate(params, jnp.asarray(b),
                                  jnp.asarray(np.ones_like(b)), cfg, gen)["tokens"])
    np.testing.assert_array_equal(together[0:1], alone_a)
    np.testing.assert_array_equal(together[1:2], alone_b)


def test_eos_stops_row_and_pads(setup):
    """After a row emits eos, it emits pad_token_id; `done` reports it."""
    cfg, params = setup
    rng = np.random.RandomState(2)
    ids = rng.randint(3, cfg.vocab_size, (1, 4)).astype(np.int32)
    mask = np.ones_like(ids)

    free = np.asarray(generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg,
                               GenerationConfig(max_new_tokens=8))["tokens"])[0]
    eos = int(free[0])  # the first generated token becomes "eos"
    got = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg,
                   GenerationConfig(max_new_tokens=8, eos_token_id=eos,
                                    pad_token_id=17))
    toks = np.asarray(got["tokens"])[0]
    assert toks[0] == eos  # the eos token itself is emitted
    assert (toks[1:] == 17).all()
    assert bool(np.asarray(got["done"])[0])


def test_generate_with_tp_sharded_params(setup, devices):
    """Generation needs no shard_map: Megatron-sharding the params over a tp
    mesh and calling the same jitted generate() lets GSPMD insert the
    collectives — tokens match the unsharded run exactly. (How a model too
    big for one chip serves: shard, same code.)"""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cfg, params = setup
    ids = np.random.RandomState(5).randint(3, cfg.vocab_size, (2, 6)).astype(np.int32)
    mask = np.ones_like(ids)
    gen = GenerationConfig(max_new_tokens=5)
    ref = np.asarray(generate(params, jnp.asarray(ids), jnp.asarray(mask),
                              cfg, gen)["tokens"])

    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(4), ("tp",))
    col, row = P(None, None, "tp"), P(None, "tp", None)
    specs = jax.tree.map(lambda _: P(), params)
    specs["layers"]["attn"] = {"wq": col, "wk": col, "wv": col, "wo": row}
    specs["layers"]["mlp"] = {"gate": col, "up": col, "down": row}
    specs["lm_head"] = P(None, "tp")
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)
    out = np.asarray(generate(sharded, jnp.asarray(ids), jnp.asarray(mask),
                              cfg, gen)["tokens"])
    np.testing.assert_array_equal(out, ref)


def test_generate_tool_end_to_end(setup, tmp_path):
    """tools/generate.py: checkpoint + tokenizer on disk -> decoded text."""
    import argparse

    from tokenizers import SentencePieceUnigramTokenizer
    from transformers import PreTrainedTokenizerFast

    from llama_pipeline_parallel_tpu.ckpt.checkpoint import CheckpointManager
    from llama_pipeline_parallel_tpu.models.llama.manifest import StageManifest
    from llama_pipeline_parallel_tpu.parallel.pipeline import stack_stages
    from tools import generate as gen_tool

    _, params = setup
    # the checkpoint meta pins the vocab size; train the tokenizer to match
    spm = SentencePieceUnigramTokenizer()
    spm.train_from_iterator(["the quick brown fox jumps over the lazy dog"] * 8,
                            vocab_size=40, unk_token="<unk>",
                            special_tokens=["<unk>", "<s>", "</s>"])
    tok = PreTrainedTokenizerFast(tokenizer_object=spm._tokenizer,
                                  bos_token="<s>", eos_token="</s>",
                                  unk_token="<unk>")
    cfg_small = LlamaConfig.tiny(vocab_size=tok.vocab_size)
    params_small = llama.init_params(jax.random.PRNGKey(0), cfg_small)

    ckpt_dir = str(tmp_path / "ckpt")
    manifest = StageManifest.for_config(cfg_small, 1)
    CheckpointManager(ckpt_dir).save(
        0, stack_stages(params_small, manifest), manifest, cfg_small)
    tok.save_pretrained(ckpt_dir)

    texts = gen_tool.run(argparse.Namespace(
        checkpoint_dir=ckpt_dir, tokenizer_path=None, step=None,
        prompt=["the quick brown", "dog"], max_new_tokens=4,
        temperature=0.0, top_k=0, seed=0))
    assert len(texts) == 2 and all(isinstance(t, str) for t in texts)


def test_sampling_seeded_and_in_vocab(setup):
    """Temperature sampling is deterministic under a fixed key and emits
    valid token ids; top_k restricts support."""
    cfg, params = setup
    ids = np.random.RandomState(3).randint(3, cfg.vocab_size, (2, 5)).astype(np.int32)
    mask = np.ones_like(ids)
    gen = GenerationConfig(max_new_tokens=4, temperature=0.8, top_k=5)

    r1 = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, gen,
                  rng=jax.random.PRNGKey(7))
    r2 = generate(params, jnp.asarray(ids), jnp.asarray(mask), cfg, gen,
                  rng=jax.random.PRNGKey(7))
    t1 = np.asarray(r1["tokens"])
    np.testing.assert_array_equal(t1, np.asarray(r2["tokens"]))
    assert ((t1 >= 0) & (t1 < cfg.vocab_size)).all()

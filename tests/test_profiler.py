"""Triggered profiler capture (utils/profiler.py —
docs/OBSERVABILITY.md "Triggered capture").

Unit level: config parsing/rejection, the at_step / z-score / span
triggers, the bounded window, and the retention cap. E2E level: a
fault-plan `slow` rule at the step site fires the z-score trigger during
a real tiny training run — exactly once under a cap of 1 even though a
second slow step follows — and the written capture is readable by
tools/trace_summary.py; the serving SLO-breach trigger does the same
under the synthetic traffic generator."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import trace_summary  # tools/ on sys.path via conftest

from llama_pipeline_parallel_tpu.utils.profiler import (
    CaptureConfig,
    TriggeredProfiler,
)


def _burn():
    x = jnp.ones((128, 128))
    jax.block_until_ready(jnp.tanh(x @ x))


def _capture_dirs(output_dir) -> list[str]:
    return sorted(glob.glob(os.path.join(str(output_dir), "captures", "*")))


# ---------------------------------------------------------------------------
# Config + unit triggers
# ---------------------------------------------------------------------------

def test_capture_config_parse():
    assert CaptureConfig.from_cfg(None) is None
    c = CaptureConfig.from_cfg({"at_step": 5, "window_steps": 1,
                                "max_captures": 2})
    assert c.at_step == (5,) and c.window_steps == 1 and c.max_captures == 2
    assert CaptureConfig.from_cfg({}).zscore == 4.0
    with pytest.raises(ValueError, match="unknown profiler"):
        CaptureConfig.from_cfg({"atstep": [1]})
    with pytest.raises(ValueError, match="mapping"):
        CaptureConfig.from_cfg(7)
    with pytest.raises(ValueError, match="max_captures"):
        CaptureConfig.from_cfg({"max_captures": 0})


def test_at_step_deferred_semantics(tmp_path, monkeypatch):
    """at_step 4 lands INSIDE the step-3 capture window: it must fire at
    the first free boundary after the window closes, not silently drop.
    Capture start/stop are stubbed — the state machine is the contract
    here; the real-trace rep below stays in the round gate."""
    prof = TriggeredProfiler(
        CaptureConfig(at_step=(3, 4), window_steps=2, zscore=0.0),
        str(tmp_path))
    started = []

    def fake_start(path, reason, step=None, meta=None):
        prof._active_dir = path
        prof._remaining = prof.cfg.window_steps
        prof.captures_taken += 1
        started.append((reason, path))
        return True

    monkeypatch.setattr(prof, "_start", fake_start)
    monkeypatch.setattr(prof, "_stop",
                        lambda: setattr(prof, "_active_dir", None))
    for step in range(1, 9):
        prof.observe_step(step, 0.01)
    assert not prof.capturing  # windows closed
    assert prof.captures_taken == 2
    # step 3 fired at 3; step 4's landed inside that window and fired at
    # the first free boundary (step 5), never dropped
    assert [r for r, _ in started] == ["at_step", "at_step"]
    assert "step3" in started[0][1] and "step5" in started[1][1]


@pytest.mark.slow  # two real jax trace captures (~20 s); the deferral
# state machine is pinned fast above, and capture-dir/trace readability
# fast by the zscore test — this rep funds the fleet fast lanes
def test_at_step_trigger_bounded_window(tmp_path):
    prof = TriggeredProfiler(
        CaptureConfig(at_step=(3, 4), window_steps=2, zscore=0.0),
        str(tmp_path))
    for step in range(1, 9):
        prof.observe_step(step, 0.01)
        if prof.capturing:
            _burn()  # give the open window device work to record
    assert not prof.capturing  # windows closed
    assert prof.captures_taken == 2
    dirs = _capture_dirs(tmp_path)
    assert len(dirs) == 2 and all("at_step" in d for d in dirs)
    path, trace = trace_summary.load_latest_trace(dirs[0])
    assert trace.get("traceEvents")


def test_zscore_trigger_and_retention_cap(tmp_path):
    prof = TriggeredProfiler(
        CaptureConfig(zscore=4.0, zscore_min_history=8, window_steps=1,
                      max_captures=1), str(tmp_path))
    for step in range(1, 11):
        prof.observe_step(step, 0.01 + 0.0001 * (step % 3))
    assert prof.captures_taken == 0  # steady walls: no trigger
    prof.observe_step(11, 1.0)  # the outlier
    assert prof.capturing and prof.captures_taken == 1
    _burn()
    prof.observe_step(12, 0.01)  # closes the 1-step window
    assert not prof.capturing
    # a second outlier is dropped by the retention cap
    assert prof.trigger("zscore-again", step=13) is False
    assert len(_capture_dirs(tmp_path)) == 1


def test_numerics_anomaly_span_listener(tmp_path):
    prof = TriggeredProfiler(CaptureConfig(window_steps=1, zscore=0.0),
                             str(tmp_path))
    prof.on_span({"name": "data_wait", "dur": 1.0})
    assert not prof.capturing
    prof.on_span({"name": "numerics_anomaly", "step": 7})
    assert prof.capturing
    prof.close()
    dirs = _capture_dirs(tmp_path)
    assert len(dirs) == 1 and "numerics_anomaly" in dirs[0]


# ---------------------------------------------------------------------------
# E2E: the fault-plan leg
# ---------------------------------------------------------------------------

def test_slow_step_fault_fires_zscore_capture_once(tmp_path):
    """A `slow` fault at the step site inflates one iteration's wall; the
    z-score trigger captures a bounded window EXACTLY once (a second slow
    step at step 12 is dropped by max_captures=1), and the trace is
    readable by trace_summary."""
    from llama_pipeline_parallel_tpu.train import run_training

    out = tmp_path / "run"
    cfg = {
        "output_dir": str(out),
        "mesh": {"pp": 2},
        "model": {"preset": "tiny", "dtype": "float32"},
        "dataset": {"synthetic": True, "seq_length": 32,
                    "pseudo_dataset_len": 64},
        "seed": 0, "per_device_train_batch_size": 2,
        "gradient_accumulation_steps": 2, "max_steps": 14,
        "logging_steps": 7, "save_steps": 0, "save_final": False,
        "attention": "exact", "numerics": {"enabled": False},
        "profiler": {"zscore": 4.0, "zscore_min_history": 6,
                     "window_steps": 2, "max_captures": 1},
        "fault_plan": {"faults": [
            {"site": "step", "op": "slow", "seconds": 2.0, "at_step": 10},
            {"site": "step", "op": "slow", "seconds": 2.0, "at_step": 12},
        ]},
    }
    summary = run_training(cfg)
    assert summary["final_step"] == 14
    dirs = _capture_dirs(out)
    assert len(dirs) == 1, dirs  # exactly once; cap honored
    assert "zscore" in os.path.basename(dirs[0])
    path, trace = trace_summary.load_latest_trace(dirs[0])
    assert trace.get("traceEvents")


# ---------------------------------------------------------------------------
# E2E: serving SLO breach under the traffic generator
# ---------------------------------------------------------------------------

def test_serve_slo_breach_capture_under_traffic(tmp_path):
    import serve_traffic

    from llama_pipeline_parallel_tpu.models.llama import model as llama
    from llama_pipeline_parallel_tpu.models.llama.config import LlamaConfig
    from llama_pipeline_parallel_tpu.serve import ServeConfig, ServeEngine
    from llama_pipeline_parallel_tpu.serve.telemetry import SLOThresholds

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prof = TriggeredProfiler(
        CaptureConfig(zscore=0.0, window_steps=2, max_captures=1),
        str(tmp_path))
    eng = ServeEngine(
        params, cfg,
        ServeConfig(max_slots=2, max_len=64, prompt_buckets=(16,),
                    max_queue=32),
        profiler=prof,
        slo=SLOThresholds(ttft_s=0.0))  # every completion breaches
    trace_reqs = serve_traffic.poisson_trace(
        0, 50.0, 6, serve_traffic.parse_mix("8:1.0"),
        serve_traffic.parse_mix("3:1.0"))
    summary = serve_traffic.run_trace(eng, trace_reqs)
    eng.shutdown()
    assert summary["requests_completed"] == 6
    snap = eng.stats.snapshot()
    assert snap["slo_breaches"] >= 1
    dirs = _capture_dirs(tmp_path)
    assert len(dirs) == 1, dirs  # cap of 1 despite 6 breaching requests
    assert "serve_slo_ttft" in os.path.basename(dirs[0])

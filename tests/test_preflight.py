"""tools/preflight.py end to end: the AOT memory check must keep working
(it gates the big-config ladder, docs/PREFLIGHT.md) — run as a real
subprocess because the tool must pin XLA_FLAGS before jax's first import."""

import json

import pytest

# each case AOT-compiles a big config in a subprocess
pytestmark = pytest.mark.slow
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_preflight(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "preflight.py"), *args],
        capture_output=True, text=True, cwd=_REPO, timeout=600,
        env={**os.environ, "PYTHONPATH": _REPO})


def test_preflight_tiny_config_passes():
    res = _run_preflight("--config", "conf/tiny_smoke.yaml")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "preflight OK" in res.stdout
    assert "fused_train_step" in res.stdout


def test_preflight_all_sweep():
    """--all GLOB runs every matching config in a subprocess and prints the
    summary table (one command reproduces docs/PREFLIGHT.md)."""
    res = _run_preflight("--all", "conf/tiny*.yaml")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "verdict" in res.stdout and "OK" in res.stdout
    res_fail = _run_preflight("--all", "conf/tiny*.yaml",
                              "--hbm-gb", "0.0000001")
    assert res_fail.returncode == 1
    assert "FAIL" in res_fail.stdout


def test_preflight_fails_on_absurd_budget():
    """The gate must actually gate: an impossible budget exits 1 with the
    FAIL verdict (and the offload override compiles the offload path)."""
    res = _run_preflight("--config", "conf/tiny_smoke.yaml",
                         "--hbm-gb", "0.000001", "optimizer_offload=true")
    assert res.returncode == 1, res.stdout + res.stderr
    assert "preflight FAIL" in res.stdout
    assert "offload_loss_and_grad" in res.stdout
    assert "host_dram_total_gib" in res.stdout

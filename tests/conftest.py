"""Force an 8-device virtual CPU mesh before jax is imported anywhere.

This is the TPU-world analogue of a fake NCCL backend: multi-chip PP/DP/TP/SP
paths run on one host (SURVEY.md §4 test strategy)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The image's sitecustomize force-registers the 'axon' TPU platform and
# overwrites jax_platforms; re-pin to cpu for the virtual 8-device mesh.
jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402

import pytest  # noqa: E402

# repo tools/ are plain scripts, not a package: make them importable once
# for every test that drives one (inspect_ckpt, trace_summary, ...)
sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir, "tools"))


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
